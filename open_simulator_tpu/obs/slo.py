"""SLO engine: declarative objectives + multi-window burn-rate alerts.

Thresholding an instantaneous gauge pages on noise; averaging over a
day pages a week late. The production answer (SRE-workbook style) is
BURN RATE over two windows: how fast is the error budget being spent,
measured over a FAST window (reacts in minutes) AND a SLOW window
(filters blips). An alert fires only when both windows burn past the
threshold, and clears as soon as the fast window recovers — fast to
page, fast to stand down, hard to flap.

Objectives are declarative records (JSON or YAML, ``--slo-config``),
evaluated over the resident series rings (obs/telemetry.py) on the
sampling cadence. Four kinds, all reduced to one vocabulary — a
``bad_ratio(window)`` against an error budget ``eb``, with
``burn = bad_ratio / eb``:

- ``availability``: Δbad / Δtotal of two cumulative counters over the
  window; ``eb = 1 - target`` (target e.g. 0.999).
- ``latency``: fraction of window samples whose tracked percentile
  series (``histo/<site>/p95_ms``) exceeded ``threshold_ms``;
  ``eb = budget`` (allowed violating fraction).
- ``gauge_min``: fraction of window samples of a gauge below ``min``
  (agreement rate, mirror freshness); ``eb = budget``.
- ``counter_budget``: Δcounter over the fast window against an
  absolute ``maxPerWindow`` allowance (recompile budget: 0 means ANY
  growth burns).

Three FLEET kinds judge the router as one service (the router runs
its own engine over the aggregated ``fleet_*`` counters — PR 18):

- ``fleet_availability``: availability over the router's counters,
  defaulting to ``fleet_requests_total`` / ``fleet_shed_total`` — a
  rerouted-but-answered request is GOOD (reroutes are the fleet doing
  its job), only an exhaustion shed spends budget.
- ``fleet_imbalance``: fraction of window samples of the
  ``fleet_slot_imbalance`` gauge ABOVE ``max`` (hottest slot's load
  over the fleet mean, minus one); ``eb = budget``.
- ``fleet_failover``: seconds of audited failover time
  (``fleet_failover_ms_total``, fleet/audit.py) per fast window
  against a ``maxSecondsPerWindow`` allowance.

Alert states export as ``simon_slo_*`` metrics on ``/metrics``, surface
in ``/healthz`` ``reasons[]``, and ride ``/v1/obs/snapshot`` and the
``/debug/dump`` body. The PR-11 inject seams drive them in chaos CI:
an armed fault storm must flip a declared SLO to burning, and the
alert must clear after the faults stop (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import json
import re
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..models.validation import InputError
from ..utils.trace import COUNTERS
from . import telemetry

KINDS = (
    "availability",
    "latency",
    "gauge_min",
    "counter_budget",
    "fleet_availability",
    "fleet_imbalance",
    "fleet_failover",
)

DEFAULT_FAST_WINDOW_S = 300.0
DEFAULT_SLOW_WINDOW_S = 3600.0
DEFAULT_BURN_THRESHOLD = 1.0
DEFAULT_BUDGET = 0.05

#: burn value exported when the budget is zero and violations exist —
#: "infinitely burning" must stay JSON- and Prometheus-representable
BURN_SATURATED = 1e9


@dataclass
class Objective:
    """One declared SLO. Field relevance depends on ``kind`` (the
    loader validates the combination)."""

    name: str
    kind: str
    target: float = 0.0  # availability: good fraction (e.g. 0.999)
    total: str = ""  # availability: cumulative counter of all events
    bad: str = ""  # availability: cumulative counter of bad events
    site: str = ""  # latency: histogram site (serve/request, ...)
    percentile: int = 95  # latency: which tracked percentile series
    threshold_ms: float = 0.0  # latency: bad past this
    gauge: str = ""  # gauge_min: gauge name (twin_agreement_rate, ...)
    min_value: float = 0.0  # gauge_min: bad below this
    counter: str = ""  # counter_budget/fleet_failover: counter name
    max_per_window: float = 0.0  # counter_budget: fast-window allowance
    max_value: float = 0.0  # fleet_imbalance: bad above this
    budget: float = DEFAULT_BUDGET  # latency/gauge_min error budget
    fast_window_s: float = DEFAULT_FAST_WINDOW_S
    slow_window_s: float = DEFAULT_SLOW_WINDOW_S
    burn_threshold: float = DEFAULT_BURN_THRESHOLD

    def series_name(self) -> str:
        """The ring series this objective's bad-ratio reads."""
        if self.kind in ("availability", "fleet_availability"):
            return f"counter/{self.bad}"
        if self.kind == "latency":
            return f"histo/{self.site}/p{self.percentile}_ms"
        if self.kind in ("gauge_min", "fleet_imbalance"):
            return f"gauge/{self.gauge}"
        return f"counter/{self.counter}"

    def error_budget(self) -> float:
        if self.kind in ("availability", "fleet_availability"):
            return max(1.0 - self.target, 1e-9)
        return max(self.budget, 1e-9)

    # -- evaluation ---------------------------------------------------------

    def burn(
        self, series: "telemetry.SeriesStore", window_s: float, now: float
    ) -> Optional[float]:
        """Burn rate over one window; None until enough data exists
        (an objective with no history neither fires nor clears)."""
        if self.kind in ("availability", "fleet_availability"):
            total = series.delta(f"counter/{self.total}", window_s, now)
            bad = series.delta(f"counter/{self.bad}", window_s, now)
            if total is None:
                return None
            if bad is None:
                bad = 0.0
            if total <= 0:
                # no traffic: an empty window spends no budget
                return 0.0 if bad <= 0 else BURN_SATURATED
            return min((bad / total) / self.error_budget(), BURN_SATURATED)
        if self.kind == "fleet_imbalance":
            frac = series.frac_beyond(
                self.series_name(), self.max_value, window_s, now
            )
            if frac is None:
                return None
            return min(frac / self.error_budget(), BURN_SATURATED)
        if self.kind == "fleet_failover":
            # the audited-failover counter is milliseconds (Counters
            # increments are integral); the allowance is seconds
            delta_ms = series.delta(self.series_name(), window_s, now)
            if delta_ms is None:
                return None
            spent_s = delta_ms / 1e3
            if self.max_per_window <= 0:
                return 0.0 if spent_s <= 0 else BURN_SATURATED
            return min(spent_s / self.max_per_window, BURN_SATURATED)
        if self.kind == "latency":
            frac = series.frac_beyond(
                self.series_name(), self.threshold_ms, window_s, now
            )
            if frac is None:
                return None
            return min(frac / self.error_budget(), BURN_SATURATED)
        if self.kind == "gauge_min":
            frac = series.frac_beyond(
                self.series_name(), self.min_value, window_s, now, below=True
            )
            if frac is None:
                return None
            return min(frac / self.error_budget(), BURN_SATURATED)
        # counter_budget: absolute allowance per window
        delta = series.delta(self.series_name(), window_s, now)
        if delta is None:
            return None
        if self.max_per_window <= 0:
            return 0.0 if delta <= 0 else BURN_SATURATED
        return min(delta / self.max_per_window, BURN_SATURATED)

    def as_dict(self) -> dict:
        out = {
            "name": self.name,
            "kind": self.kind,
            "series": self.series_name(),
            "fastWindowSeconds": self.fast_window_s,
            "slowWindowSeconds": self.slow_window_s,
            "burnThreshold": self.burn_threshold,
        }
        if self.kind in ("availability", "fleet_availability"):
            out.update(target=self.target, total=self.total, bad=self.bad)
        elif self.kind == "latency":
            out.update(
                site=self.site,
                percentile=self.percentile,
                thresholdMs=self.threshold_ms,
                budget=self.budget,
            )
        elif self.kind == "gauge_min":
            out.update(
                gauge=self.gauge, min=self.min_value, budget=self.budget
            )
        elif self.kind == "fleet_imbalance":
            out.update(
                gauge=self.gauge, max=self.max_value, budget=self.budget
            )
        elif self.kind == "fleet_failover":
            out.update(
                counter=self.counter,
                maxSecondsPerWindow=self.max_per_window,
            )
        else:
            out.update(
                counter=self.counter, maxPerWindow=self.max_per_window
            )
        return out


@dataclass
class AlertState:
    """One objective's live verdict after the latest evaluation."""

    objective: Objective
    burn_fast: Optional[float] = None
    burn_slow: Optional[float] = None
    alerting: bool = False
    since: Optional[float] = None
    fired_total: int = 0
    cleared_total: int = 0
    last_eval: float = field(default_factory=time.time)

    def as_dict(self) -> dict:
        return {
            "objective": self.objective.as_dict(),
            "burnFast": self.burn_fast,
            "burnSlow": self.burn_slow,
            "alerting": self.alerting,
            "since": self.since,
            "firedTotal": self.fired_total,
            "clearedTotal": self.cleared_total,
        }


# ---------------------------------------------------------------- the engine


class SLOEngine:
    """Evaluates every declared objective over the series rings; holds
    the alert state machine (fire: fast AND slow burning; clear: fast
    recovered). Evaluation rides the telemetry sampler's cadence;
    ``/metrics`` and ``/healthz`` read the held state without
    re-evaluating."""

    def __init__(self, objectives: List[Objective], series=None, clock=time.time):
        self._lock = threading.Lock()
        self._clock = clock
        self.series = series if series is not None else telemetry.SERIES
        self._states: Dict[str, AlertState] = {
            o.name: AlertState(objective=o) for o in objectives
        }

    @property
    def objectives(self) -> List[Objective]:
        with self._lock:
            return [s.objective for s in self._states.values()]

    def evaluate(self, now: Optional[float] = None) -> List[AlertState]:
        """One evaluation pass over every objective; returns the
        resulting states (copies are cheap; callers mutate nothing)."""
        now = self._clock() if now is None else now
        with self._lock:
            states = list(self._states.values())
        for st in states:
            o = st.objective
            bf = o.burn(self.series, o.fast_window_s, now)
            bs = o.burn(self.series, o.slow_window_s, now)
            with self._lock:
                st.burn_fast, st.burn_slow, st.last_eval = bf, bs, now
                if (
                    not st.alerting
                    and bf is not None
                    and bs is not None
                    and bf >= o.burn_threshold
                    and bs >= o.burn_threshold
                ):
                    st.alerting = True
                    st.since = now
                    st.fired_total += 1
                    COUNTERS.inc("slo_alerts_fired_total")
                elif st.alerting and (bf is None or bf < o.burn_threshold):
                    st.alerting = False
                    st.since = None
                    st.cleared_total += 1
                    COUNTERS.inc("slo_alerts_cleared_total")
        return states

    # -- reads --------------------------------------------------------------

    def states(self) -> List[AlertState]:
        with self._lock:
            return list(self._states.values())

    def alerting(self) -> List[str]:
        with self._lock:
            return [n for n, s in self._states.items() if s.alerting]

    def reasons(self) -> List[str]:
        """/healthz ``reasons[]`` lines for burning objectives."""
        out = []
        with self._lock:
            for name, st in self._states.items():
                if not st.alerting:
                    continue
                bf = -1.0 if st.burn_fast is None else st.burn_fast
                bs = -1.0 if st.burn_slow is None else st.burn_slow
                out.append(
                    f"slo burning: {name} (burn fast {bf:.2f} / "
                    f"slow {bs:.2f} >= {st.objective.burn_threshold:g})"
                )
        return out

    def as_dict(self) -> dict:
        with self._lock:
            return {
                "objectives": len(self._states),
                "alerting": [
                    n for n, s in self._states.items() if s.alerting
                ],
                "states": [s.as_dict() for s in self._states.values()],
            }

    def prometheus_lines(self) -> List[str]:
        """The ``simon_slo_*`` exposition block (one family each for
        target-ish info, burn rates, and the alert bit)."""
        with self._lock:
            states = [
                (name, st, st.objective) for name, st in self._states.items()
            ]
        if not states:
            return []
        lines = [
            "# HELP simon_slo_burn_rate Error-budget burn rate per "
            "objective and window (>= threshold in BOTH windows fires).",
            "# TYPE simon_slo_burn_rate gauge",
        ]
        for name, st, _o in states:
            for window, burn in (("fast", st.burn_fast), ("slow", st.burn_slow)):
                if burn is None:
                    continue
                lines.append(
                    f'simon_slo_burn_rate{{slo="{name}",window="{window}"}} '
                    f"{round(burn, 6)}"
                )
        lines.append(
            "# HELP simon_slo_burn_threshold Burn rate at/past which an "
            "objective fires."
        )
        lines.append("# TYPE simon_slo_burn_threshold gauge")
        for name, _st, o in states:
            lines.append(
                f'simon_slo_burn_threshold{{slo="{name}"}} {o.burn_threshold}'
            )
        lines.append(
            "# HELP simon_slo_alert 1 while the objective's multi-window "
            "burn alert is firing."
        )
        lines.append("# TYPE simon_slo_alert gauge")
        for name, st, _o in states:
            lines.append(f'simon_slo_alert{{slo="{name}"}} {int(st.alerting)}')
        snap = COUNTERS.snapshot()["counts"]
        for key, help_text in (
            ("slo_alerts_fired_total", "SLO alerts fired (state transitions)."),
            ("slo_alerts_cleared_total", "SLO alerts cleared."),
        ):
            lines.append(f"# HELP simon_{key} {help_text}")
            lines.append(f"# TYPE simon_{key} counter")
            lines.append(f"simon_{key} {snap.get(key, 0)}")
        return lines


# ---------------------------------------------------------------- the loader

_NAME_OK = re.compile(r"^[A-Za-z0-9_.:-]{1,64}$")


def parse_objective(rec: dict) -> Objective:
    """One config record as a validated Objective; raises InputError
    with the offending field on anything malformed."""
    if not isinstance(rec, dict):
        raise InputError("slo record is not an object")
    name = str(rec.get("name") or "")
    if not _NAME_OK.match(name):
        raise InputError(
            f"slo name {name!r} must be 1-64 chars of [A-Za-z0-9_.:-] "
            "(it becomes a metric label)"
        )
    kind = str(rec.get("kind") or "")
    if kind not in KINDS:
        raise InputError(
            f"slo {name!r}: unknown kind {kind!r} (one of {', '.join(KINDS)})"
        )

    def num(key, default=None, lo=None, hi=None):
        v = rec.get(key, default)
        if v is None:
            return None
        try:
            v = float(v)
        except (TypeError, ValueError):
            raise InputError(f"slo {name!r}: {key} must be a number") from None
        if lo is not None and v < lo:
            raise InputError(f"slo {name!r}: {key} must be >= {lo}")
        if hi is not None and v > hi:
            raise InputError(f"slo {name!r}: {key} must be <= {hi}")
        return v

    o = Objective(name=name, kind=kind)
    o.fast_window_s = num("fastWindowSeconds", DEFAULT_FAST_WINDOW_S, lo=1.0)
    o.slow_window_s = num("slowWindowSeconds", DEFAULT_SLOW_WINDOW_S, lo=1.0)
    if o.slow_window_s < o.fast_window_s:
        raise InputError(
            f"slo {name!r}: slowWindowSeconds ({o.slow_window_s:g}) must "
            f"be >= fastWindowSeconds ({o.fast_window_s:g})"
        )
    o.burn_threshold = num("burnThreshold", DEFAULT_BURN_THRESHOLD, lo=0.0)
    if kind in ("availability", "fleet_availability"):
        # fleet_availability defaults to the router's own counters: a
        # rerouted-but-answered request never spends budget, only an
        # exhaustion shed does
        dflt_total = "fleet_requests_total" if kind.startswith("fleet") else ""
        dflt_bad = "fleet_shed_total" if kind.startswith("fleet") else ""
        o.total = str(rec.get("total") or dflt_total)
        o.bad = str(rec.get("bad") or dflt_bad)
        if not o.total or not o.bad:
            raise InputError(
                f"slo {name!r}: availability needs 'total' and 'bad' "
                "counter names"
            )
        o.target = num("target", None, lo=0.0, hi=1.0)
        if o.target is None or o.target >= 1.0:
            raise InputError(
                f"slo {name!r}: {kind} needs target in [0, 1)"
            )
    elif kind == "fleet_imbalance":
        o.gauge = str(rec.get("gauge") or "fleet_slot_imbalance")
        v = num("max", None, lo=0.0)
        if v is None:
            raise InputError(f"slo {name!r}: fleet_imbalance needs 'max'")
        o.max_value = v
        o.budget = num("budget", DEFAULT_BUDGET, lo=1e-9, hi=1.0)
    elif kind == "fleet_failover":
        o.counter = str(rec.get("counter") or "fleet_failover_ms_total")
        v = num("maxSecondsPerWindow", None, lo=0.0)
        if v is None:
            raise InputError(
                f"slo {name!r}: fleet_failover needs 'maxSecondsPerWindow'"
            )
        o.max_per_window = v
    elif kind == "latency":
        o.site = str(rec.get("site") or "")
        if not o.site:
            raise InputError(f"slo {name!r}: latency needs a 'site'")
        pct = num("percentile", 95.0)
        if int(pct) not in (50, 95, 99):
            raise InputError(
                f"slo {name!r}: percentile must be 50, 95, or 99 (the "
                "tracked percentile series)"
            )
        o.percentile = int(pct)
        o.threshold_ms = num("thresholdMs", None, lo=0.0)
        if o.threshold_ms is None:
            raise InputError(f"slo {name!r}: latency needs 'thresholdMs'")
        o.budget = num("budget", DEFAULT_BUDGET, lo=1e-9, hi=1.0)
    elif kind == "gauge_min":
        o.gauge = str(rec.get("gauge") or "")
        if not o.gauge:
            raise InputError(f"slo {name!r}: gauge_min needs a 'gauge'")
        v = num("min", None)
        if v is None:
            raise InputError(f"slo {name!r}: gauge_min needs 'min'")
        o.min_value = v
        o.budget = num("budget", DEFAULT_BUDGET, lo=1e-9, hi=1.0)
    else:  # counter_budget
        o.counter = str(rec.get("counter") or "")
        if not o.counter:
            raise InputError(f"slo {name!r}: counter_budget needs 'counter'")
        o.max_per_window = num("maxPerWindow", 0.0, lo=0.0)
    return o


def parse_objectives(doc) -> List[Objective]:
    if isinstance(doc, dict):
        doc = doc.get("slos")
    if not isinstance(doc, list) or not doc:
        raise InputError(
            'slo config must be a non-empty list (or {"slos": [...]})'
        )
    objectives = [parse_objective(rec) for rec in doc]
    seen = set()
    for o in objectives:
        if o.name in seen:
            raise InputError(f"duplicate slo name {o.name!r}")
        seen.add(o.name)
    return objectives


def load_slo_config(path: str) -> List[Objective]:
    """Objectives from a JSON or YAML file (--slo-config). The
    documented grammar lives in docs/OBSERVABILITY.md."""
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except OSError as e:
        raise InputError(f"cannot read slo config {path!r}: {e}") from e
    try:
        doc = json.loads(text)
    except ValueError:
        import yaml

        try:
            doc = yaml.safe_load(text)
        except yaml.YAMLError as e:
            raise InputError(
                f"slo config {path!r} is neither JSON nor YAML: {e}"
            ) from e
    return parse_objectives(doc)
