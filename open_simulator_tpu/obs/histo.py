"""Log-bucketed streaming latency histograms.

``utils.trace.Counters.percentile`` answers "p95 of the last 2048
samples" from a bounded reservoir — good enough for a serve dashboard,
but it forgets history (a burst of fast requests evicts the slow tail)
and a percentile read sorts the window under the lock. This module is
the long-memory complement: a fixed 64-bucket base-geometric histogram
per site, O(1) to record (one lock, one increment), never evicting,
with percentile reads that interpolate inside the landing bucket.

The precision contract is explicit: a percentile answer is exact to
within one bucket, i.e. a relative error bounded by ``RATIO - 1``
(~30% with the default 1e-5s..100s span). That is the right trade for
latency observability — "p99 is ~3ms vs ~300ms" is the question, not
the fourth significant digit — and it is pinned against a numpy
reference in tests/test_observatory.py.

Sites: every ``InstrumentedJit`` dispatch records under ``jit/<site>``
(obs/profile.py), and the serve request path records queue-wait /
evaluate / end-to-end phases (serve/coalescer.py). All of it is
exported as Prometheus histogram exposition plus p50/p95/p99 gauges in
``/metrics`` (serve/server.py), as a ``histograms`` sub-block in every
bench obs line (bench.py), and in the serve drain dump (cli.py).

Stdlib-only on purpose: obs/spans.py may reach this module from the
export path, and utils.trace loads obs.spans at import time.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional

N_BUCKETS = 64
# bucket 0 is the underflow bin [0, LOW); bucket 63 the overflow bin
# [HIGH, inf); 62 geometric buckets span LOW..HIGH
LOW = 1e-5
HIGH = 100.0
RATIO = (HIGH / LOW) ** (1.0 / (N_BUCKETS - 2))
_LOG_RATIO = math.log(RATIO)

# bucket i (1 <= i <= 62) covers [LOW * RATIO**(i-1), LOW * RATIO**i)
_UPPER: List[float] = [LOW * RATIO ** i for i in range(N_BUCKETS - 1)] + [
    math.inf
]


def bucket_of(value: float) -> int:
    """The bucket index a (non-negative) observation lands in."""
    if value < LOW:
        return 0
    if value >= HIGH:
        return N_BUCKETS - 1
    # floor can land one off at exact bucket boundaries (float log);
    # nudge into the bucket whose bounds actually contain the value
    i = 1 + int(math.log(value / LOW) / _LOG_RATIO)
    i = min(max(i, 1), N_BUCKETS - 2)
    if value < _UPPER[i - 1]:
        i -= 1
    elif value >= _UPPER[i]:
        i += 1
    return min(max(i, 0), N_BUCKETS - 1)


class Histogram:
    """One thread-safe fixed-64-bucket streaming histogram. Recording
    is O(1) under the lock (an index computation, three adds); reads
    copy the counts under the lock and interpolate outside it."""

    __slots__ = ("_lock", "counts", "count", "sum", "min", "max")

    def __init__(self):
        self._lock = threading.Lock()
        self.counts = [0] * N_BUCKETS
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = 0.0

    def record(self, value: float) -> None:
        v = float(value)
        if v < 0.0 or v != v:  # negative or NaN: clock skew, not data
            return
        idx = bucket_of(v)
        with self._lock:
            self.counts[idx] += 1
            self.count += 1
            self.sum += v
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v

    def _snapshot(self):
        with self._lock:
            return (
                list(self.counts), self.count, self.sum, self.min, self.max
            )

    def percentile(self, q: float) -> float:
        """q in [0, 100]. Nearest-rank walk over the cumulative bucket
        counts, linearly interpolated inside the landing bucket and
        clamped to the observed min/max (so p0/p100 are exact). 0.0
        when empty."""
        counts, total, _s, lo_seen, hi_seen = self._snapshot()
        if not total:
            return 0.0
        rank = max(1, min(total, int(math.ceil(q / 100.0 * total))))
        cum = 0
        for i, c in enumerate(counts):
            if not c:
                continue
            if cum + c >= rank:
                lo = 0.0 if i == 0 else _UPPER[i - 1]
                hi = _UPPER[i]
                if math.isinf(hi):
                    return hi_seen
                frac = (rank - cum - 0.5) / c
                v = lo + (hi - lo) * min(max(frac, 0.0), 1.0)
                return min(max(v, lo_seen), hi_seen)
            cum += c
        return hi_seen

    def mean(self) -> float:
        _c, total, s, _lo, _hi = self._snapshot()
        return s / total if total else 0.0

    def as_dict(self) -> dict:
        counts, total, s, lo_seen, hi_seen = self._snapshot()
        return {
            "count": total,
            "sum_seconds": round(s, 6),
            "min_ms": round(lo_seen * 1e3, 3) if total else 0.0,
            "max_ms": round(hi_seen * 1e3, 3),
            "p50_ms": round(self.percentile(50) * 1e3, 3),
            "p95_ms": round(self.percentile(95) * 1e3, 3),
            "p99_ms": round(self.percentile(99) * 1e3, 3),
            "buckets": counts,
        }


class HistogramRegistry:
    """Process-wide name -> Histogram map. ``observe`` is the hot path:
    one dict lookup (creating on first sight) and one O(1) record."""

    def __init__(self):
        self._lock = threading.Lock()
        self._histos: Dict[str, Histogram] = {}

    def get(self, name: str) -> Histogram:
        with self._lock:
            h = self._histos.get(name)
            if h is None:
                h = self._histos[name] = Histogram()
            return h

    def observe(self, name: str, value: float) -> None:
        self.get(name).record(value)

    def peek(self, name: str) -> Optional[Histogram]:
        with self._lock:
            return self._histos.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._histos)

    def reset(self) -> None:
        with self._lock:
            self._histos.clear()

    def summary(self, with_buckets: bool = False) -> dict:
        """{site: {count, p50_ms, p95_ms, p99_ms, ...}} for bench obs
        blocks and the serve drain dump. Buckets are included only when
        asked (observatory blocks in bench lines and trace artifacts,
        where tools/validate_trace.py checks bucket-sum arithmetic) —
        the serve drain dump stays readable without them."""
        out = {}
        for name in self.names():
            h = self.peek(name)
            if h is None or not h.count:
                continue
            d = h.as_dict()
            if not with_buckets:
                d.pop("buckets")
            out[name] = d
        return out


HISTOS = HistogramRegistry()


def percentile_from_counts(counts: List[int], q: float) -> float:
    """Percentile (seconds) from a raw 64-bucket count vector — the
    telemetry sampler's INTERVAL percentiles are computed from bucket
    DELTAS between two samples of a cumulative histogram, so a
    latency regression shows up at full strength in the next sample
    instead of being diluted into the process-lifetime distribution.
    Same landing-bucket interpolation as Histogram.percentile (without
    the observed min/max clamp — deltas carry no min/max); the
    overflow bucket answers its lower bound. 0.0 when empty."""
    total = sum(counts)
    if total <= 0:
        return 0.0
    rank = max(1, min(total, int(math.ceil(q / 100.0 * total))))
    cum = 0
    for i, c in enumerate(counts):
        if not c:
            continue
        if cum + c >= rank:
            lo = 0.0 if i == 0 else _UPPER[i - 1]
            hi = _UPPER[i]
            if math.isinf(hi):
                return _UPPER[i - 1]
            frac = (rank - cum - 0.5) / c
            return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
        cum += c
    return _UPPER[-2]

# the subset of bucket boundaries exported as Prometheus `le` labels
# (cumulative, so any subset stays correct); every 4th + +Inf keeps
# the exposition ~17 lines per site instead of 65
_EXPO_BUCKETS = list(range(3, N_BUCKETS - 1, 4))


def prometheus_lines(prefix: str = "simon_latency_seconds") -> List[str]:
    """Prometheus histogram exposition for every registered site:
    `<prefix>_bucket{site="...",le="..."}` cumulative counts plus
    `_sum`/`_count`, and p50/p95/p99 gauges derived from the buckets."""
    lines: List[str] = []
    names = HISTOS.names()
    if not names:
        return lines
    lines.append(f"# HELP {prefix} Latency distribution per site.")
    lines.append(f"# TYPE {prefix} histogram")
    quantiles: Dict[int, List[str]] = {50: [], 95: [], 99: []}
    qname = prefix.replace("_seconds", "")
    for name in names:
        h = HISTOS.peek(name)
        if h is None:
            continue
        counts, total, s, _lo, _hi = h._snapshot()
        cum = 0
        emitted = 0
        for i, c in enumerate(counts):
            cum += c
            if i in _EXPO_BUCKETS and cum > emitted:
                lines.append(
                    f'{prefix}_bucket{{site="{name}",le="{_UPPER[i]:.6g}"}} {cum}'
                )
                emitted = cum
        lines.append(f'{prefix}_bucket{{site="{name}",le="+Inf"}} {total}')
        lines.append(f'{prefix}_sum{{site="{name}"}} {round(s, 6)}')
        lines.append(f'{prefix}_count{{site="{name}"}} {total}')
        for q in quantiles:
            quantiles[q].append(
                f'{qname}_p{q}_seconds{{site="{name}"}} '
                f"{round(h.percentile(q), 6)}"
            )
    for q, qlines in quantiles.items():
        if qlines:
            lines.append(
                f"# HELP {qname}_p{q}_seconds Per-site p{q} latency "
                "(bucket-interpolated)."
            )
            lines.append(f"# TYPE {qname}_p{q}_seconds gauge")
            lines.extend(qlines)
    return lines
