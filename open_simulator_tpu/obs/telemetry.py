"""Production telemetry: request correlation + the resident series store.

PRs 4/11/12 made the simulator resident (serve, twin, shadow tail) but
left its observability batch-shaped: spans dump at exit, ``/metrics``
exports only instantaneous values, and a request that joins a coalesced
dispatch loses its identity. This module is the telemetry layer a
production scheduler assumes:

- **Request correlation**: every request carries an ID — accepted from
  the ``X-Simon-Request-Id`` header (sanitized), else minted — held in
  a ``contextvars.ContextVar`` so every span recorded while handling
  the request is stamped with it automatically (obs/spans.py asks this
  module through a provider hook). The coalescer synthesizes
  per-request span subtrees (queue_wait / evaluate) from measured
  timestamps, so a batch of N requests yields N traceable subtrees at
  zero extra device work.
- **Resident time-series store** (``SERIES``): a fixed-size ring per
  signal — O(1) append, bounded memory — with seeded-DETERMINISTIC
  downsampling into coarser rings (each bucket of ``AGG`` points keeps
  one hash-chosen representative plus the bucket min/max/mean), so a
  daemon holds hours of history in a few MB and two runs with the same
  samples downsample identically. ``TelemetryRuntime`` samples every
  ``Counters`` counter/gauge, histogram percentile, and ledger
  watermark on a cadence, and drives the SLO engine (obs/slo.py).
- **Query surface**: ``/v1/obs/series`` + ``/v1/obs/snapshot`` on the
  serve and twin daemons (`simon top` renders them live), and
  ``POST /debug/dump`` — a spans+series+SLO snapshot from a live
  daemon, shaped so ``simon doctor`` can diff two dumps.

Stdlib-only at import time on purpose: ``obs.spans`` must stay
importable from ``utils.trace`` without cycles, so everything that
touches the counter/histogram/ledger registries imports lazily.
"""

from __future__ import annotations

import contextvars
import hashlib
import json
import re
import threading
import time
import uuid
from contextlib import contextmanager
from typing import Dict, List, Optional
from urllib.parse import parse_qs, urlparse

from . import spans as _spans

# ---------------------------------------------------------------- request ids

REQUEST_ID_HEADER = "X-Simon-Request-Id"
#: charset a caller-supplied ID must fit (counter/label/JSON-safe); a
#: non-conforming character is replaced, never rejected — the caller's
#: correlation still works as long as their ID was sane
_RID_RE = re.compile(r"[^A-Za-z0-9_.:-]")
MAX_REQUEST_ID_LEN = 128

_request_id: contextvars.ContextVar = contextvars.ContextVar(
    "simon_request_id", default=None
)


def new_request_id() -> str:
    """Mint a request ID: 16 hex chars of a UUID4, ``req-`` prefixed
    so generated IDs are distinguishable from caller-supplied ones."""
    return "req-" + uuid.uuid4().hex[:16]


def sanitize_request_id(raw: Optional[str]) -> Optional[str]:
    """A header value as a safe ID, or None when absent/empty."""
    if not raw:
        return None
    rid = _RID_RE.sub("_", str(raw))[:MAX_REQUEST_ID_LEN]
    return rid or None


def ensure_request_id(raw: Optional[str] = None) -> str:
    """The caller-supplied ID when one came in, else a fresh one."""
    return sanitize_request_id(raw) or new_request_id()


def current_request_id() -> Optional[str]:
    return _request_id.get()


@contextmanager
def request_scope(rid: str):
    """Bind ``rid`` as the context's request ID: every span recorded
    inside (on this thread / context) is stamped with it."""
    token = _request_id.set(rid)
    try:
        yield rid
    finally:
        _request_id.reset(token)


# spans recorded anywhere in a request scope carry the ID — the hook
# keeps obs/spans.py stdlib-only and cycle-free
_spans.set_request_id_provider(current_request_id)


# ------------------------------------------------------------- trace context

#: cross-process trace propagation (fleet router -> replica): the
#: router stamps its forward span's id plus the fleet hop count on
#: every forwarded request; the replica records the id as a
#: ``remote_parent`` ATTRIBUTE on its ``serve/request`` root (span ids
#: are process-local, so a remote parent can never be a structural
#: ``parent_id`` — the stitcher in fleet/trace.py remaps both id
#: spaces into one tree). Format: ``parent=<span_id>;hop=<n>``.
TRACE_CONTEXT_HEADER = "X-Simon-Trace-Context"
#: hop ceiling: a forwarded request that has already crossed this many
#: fleet hops is parsed as context-free (a loop or a forged header
#: must not grow unbounded attrs)
MAX_TRACE_HOPS = 8

_TRACE_CTX_RE = re.compile(r"^parent=(\d{1,19});hop=(\d{1,3})$")


def format_trace_context(parent_span_id: int, hop: int = 1) -> str:
    """Header value carrying the router-side parent span id and the
    fleet hop count of the receiving process."""
    return f"parent={int(parent_span_id)};hop={int(hop)}"


def parse_trace_context(raw: Optional[str]) -> tuple:
    """``(parent_span_id, hop)`` from a header value, or ``(None, 0)``
    on absence or ANY malformation — a garbled trace context degrades
    to an uncorrelated request, it never fails the request."""
    if not raw:
        return None, 0
    m = _TRACE_CTX_RE.match(str(raw).strip())
    if m is None:
        return None, 0
    parent, hop = int(m.group(1)), int(m.group(2))
    if hop < 1 or hop > MAX_TRACE_HOPS:
        return None, 0
    return parent, hop


# ---------------------------------------------------------------- series ring


#: raw points folded into one coarser point per AGG appends
AGG = 8
#: ring levels: raw, x8, x64 — at a 1s cadence that is ~8.5 min of raw
#: history, ~68 min at x8, ~9 h at x64, in (cap x levels) slots total
LEVELS = 3
DEFAULT_CAPACITY = 512
#: distinct series a store will hold; a label-cardinality accident in
#: the counter registry must not grow the resident set without bound
MAX_SERIES = 4096

RESOLUTIONS = tuple(AGG ** lvl for lvl in range(LEVELS))  # (1, 8, 64)


def _pick_index(seed: int, name: str, level: int, bucket_seq: int) -> int:
    """The seeded-deterministic representative choice: which of the
    AGG points in one downsample bucket survives into the coarser
    ring. A hash, not a PRNG stream: two runs sampling the same series
    pick the same representatives regardless of sampling interleaving
    across series."""
    digest = hashlib.sha256(
        f"{seed}:{name}:{level}:{bucket_seq}".encode()
    ).hexdigest()
    return int(digest[:8], 16) % AGG


class _Ring:
    """Fixed-capacity point ring: O(1) append overwrites the oldest.
    Points are [t, value, vmin, vmax] rows (raw rows carry
    vmin == vmax == value)."""

    __slots__ = ("cap", "rows", "head", "count")

    def __init__(self, cap: int):
        self.cap = cap
        self.rows: List[Optional[list]] = [None] * cap
        self.head = 0  # next write slot
        self.count = 0

    def append(self, row: list) -> None:
        self.rows[self.head] = row
        self.head = (self.head + 1) % self.cap
        if self.count < self.cap:
            self.count += 1

    def points(self) -> List[list]:
        """Chronological copy (oldest first)."""
        if self.count < self.cap:
            return [r for r in self.rows[: self.count]]
        return [
            self.rows[(self.head + i) % self.cap] for i in range(self.cap)
        ]

    def last(self) -> Optional[list]:
        if not self.count:
            return None
        return self.rows[(self.head - 1) % self.cap]


class _Series:
    """One named signal across every resolution level, plus the
    in-progress downsample buckets between levels."""

    __slots__ = ("rings", "pending", "bucket_seq")

    def __init__(self, cap: int):
        self.rings = [_Ring(cap) for _ in range(LEVELS)]
        # pending[lvl] accumulates rows awaiting the fold into lvl+1
        self.pending: List[List[list]] = [[] for _ in range(LEVELS - 1)]
        self.bucket_seq = [0] * (LEVELS - 1)


class SeriesStore:
    """Process-wide name -> ring-set map. ``record`` is the sampler's
    hot path: one lock, one O(1) append, and (every AGG appends per
    level) one O(AGG) fold."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY, seed: int = 0):
        self._lock = threading.Lock()
        self._capacity = capacity
        self._seed = seed
        self._series: Dict[str, _Series] = {}
        self._overflowed = 0

    # -- write --------------------------------------------------------------

    def record(self, name: str, t: float, value: float) -> None:
        row = [t, float(value), float(value), float(value)]
        with self._lock:
            s = self._series.get(name)
            if s is None:
                if len(self._series) >= MAX_SERIES:
                    self._overflowed += 1
                    return
                s = self._series[name] = _Series(self._capacity)
            self._record_level(name, s, 0, row)

    # audited: record() invokes this (and it recurses) WITH self._lock
    # held — the fold must be atomic with the raw append
    def _record_level(self, name, s, level, row):  # simonlint: disable=CONC001
        # caller holds the lock; recursion depth is LEVELS (3)
        s.rings[level].append(row)
        if level >= LEVELS - 1:
            return
        pend = s.pending[level]
        pend.append(row)
        if len(pend) < AGG:
            return
        seq = s.bucket_seq[level]
        s.bucket_seq[level] = seq + 1
        keep = pend[_pick_index(self._seed, name, level, seq)]
        folded = [
            pend[-1][0],  # bucket closes at its newest sample's time
            keep[1],
            min(r[2] for r in pend),
            max(r[3] for r in pend),
        ]
        s.pending[level] = []
        self._record_level(name, s, level + 1, folded)

    # -- read ---------------------------------------------------------------

    def names(self, prefix: str = "") -> List[str]:
        with self._lock:
            return sorted(n for n in self._series if n.startswith(prefix))

    def query(
        self,
        name: str,
        *,
        resolution: int = 1,
        since_s: Optional[float] = None,
        now: Optional[float] = None,
        max_points: Optional[int] = None,
    ) -> List[list]:
        """Chronological [t, value, vmin, vmax] rows of one series at
        one resolution (1, 8, or 64 raw-cadence steps per point)."""
        from ..models.validation import InputError

        try:
            level = RESOLUTIONS.index(int(resolution))
        except ValueError:
            raise InputError(
                f"unknown resolution {resolution!r}; pick one of "
                f"{list(RESOLUTIONS)}"
            ) from None
        with self._lock:
            s = self._series.get(name)
            pts = s.rings[level].points() if s is not None else []
        if since_s is not None:
            cutoff = (now if now is not None else time.time()) - since_s
            pts = [p for p in pts if p[0] >= cutoff]
        if max_points is not None and len(pts) > max_points:
            pts = pts[-max_points:]
        return pts

    def last(self, name: str) -> Optional[list]:
        with self._lock:
            s = self._series.get(name)
            return None if s is None else s.rings[0].last()

    # -- derived reads (the SLO engine's vocabulary) ------------------------

    def window(
        self,
        name: str,
        window_s: float,
        now: Optional[float] = None,
        anchor: bool = False,
    ) -> List[list]:
        """Rows inside the trailing window, read from the FINEST
        resolution whose retained history still covers the whole
        window — a 1 h slow window on a 1 s cadence overflows the raw
        ring (~512 s) and must fall back to the ×8/×64 rings instead
        of silently evaluating the last few minutes as if they were
        the hour. With ``anchor=True`` the newest pre-window row is
        prepended (cumulative-counter deltas anchor at the window edge
        instead of losing the oldest increment); fraction reads leave
        it off — a stale out-of-window sample must not count toward a
        window's bad ratio."""
        now = time.time() if now is None else now
        cutoff = now - window_s
        pts: List[list] = []
        for resolution in RESOLUTIONS:
            level_pts = self.query(name, resolution=resolution)
            if not level_pts:
                continue
            if level_pts[0][0] <= cutoff:
                pts = level_pts
                break  # finest level retaining the whole window
            if not pts or level_pts[0][0] < pts[0][0]:
                # no full coverage yet: remember the level reaching
                # furthest back (a window longer than ALL retention
                # answers from the deepest history, never from nothing)
                pts = level_pts
        inside = [p for p in pts if p[0] >= cutoff]
        if anchor:
            before = [p for p in pts if p[0] < cutoff]
            if before:
                inside.insert(0, before[-1])
        return inside

    def delta(
        self, name: str, window_s: float, now: Optional[float] = None
    ) -> Optional[float]:
        """Increase of a cumulative counter over the trailing window
        (None until two samples exist). Negative deltas (a counter
        reset) clamp to 0 rather than crediting the window."""
        pts = self.window(name, window_s, now, anchor=True)
        if len(pts) < 2:
            return None
        return max(pts[-1][1] - pts[0][1], 0.0)

    def frac_beyond(
        self,
        name: str,
        threshold: float,
        window_s: float,
        now: Optional[float] = None,
        below: bool = False,
    ) -> Optional[float]:
        """Fraction of window samples strictly beyond ``threshold``
        (above by default; ``below=True`` flips). None with no data."""
        pts = self.window(name, window_s, now)
        if not pts:
            return None
        if below:
            bad = sum(1 for p in pts if p[1] < threshold)
        else:
            bad = sum(1 for p in pts if p[1] > threshold)
        return bad / len(pts)

    # -- bookkeeping --------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "series": len(self._series),
                "capacity": self._capacity,
                "resolutions": list(RESOLUTIONS),
                "overflowed": self._overflowed,
            }

    def latest(self, prefix: str = "") -> Dict[str, float]:
        """{name: newest value} for snapshot endpoints."""
        out: Dict[str, float] = {}
        with self._lock:
            for name, s in self._series.items():
                if prefix and not name.startswith(prefix):
                    continue
                row = s.rings[0].last()
                if row is not None:
                    out[name] = row[1]
        return out

    def reset(self) -> None:
        with self._lock:
            self._series.clear()
            self._overflowed = 0


SERIES = SeriesStore()


# ---------------------------------------------------------------- the sampler


class TelemetryRuntime:
    """One daemon's telemetry loop: sample every counter/gauge,
    histogram percentile, and ledger level into ``SERIES`` on a
    cadence, then let the SLO engine evaluate over the fresh rings.
    Pure host bookkeeping — a sample never touches the device beyond
    the ledger's (rate-limited) memory poll, so arming telemetry costs
    zero jit-cache misses by construction."""

    def __init__(
        self,
        cadence_s: float = 1.0,
        slo_engine=None,
        series: Optional[SeriesStore] = None,
        clock=time.time,
    ):
        if cadence_s <= 0:
            from ..models.validation import InputError

            raise InputError(
                f"--obs-cadence must be > 0 seconds, got {cadence_s}"
            )
        self.cadence_s = float(cadence_s)
        self.slo_engine = slo_engine
        self.series = series if series is not None else SERIES
        self._clock = clock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.started_at = clock()
        # last-seen cumulative bucket counts per histogram site: the
        # sampled percentile series are INTERVAL percentiles (of the
        # observations since the previous sample), not process-lifetime
        # ones — a long-lived daemon's regression must move the series
        # now, not after it outweighs a day of history. Sampler-thread
        # confined (start()/stop() serialize around the thread).
        self._histo_counts: Dict[str, list] = {}

    # -- one sample ---------------------------------------------------------

    def sample_once(self, now: Optional[float] = None) -> int:
        """Record one sample of everything; returns the number of
        series touched. Exposed for tests and the drain path (one
        final sample so the dump sees the end state)."""
        from ..utils.trace import COUNTERS

        now = self._clock() if now is None else now
        series = self.series
        n = 0
        snap = COUNTERS.snapshot()
        for key, value in snap["counts"].items():
            series.record(f"counter/{key}", now, value)
            n += 1
        for key, value in snap["gauges"].items():
            series.record(f"gauge/{key}", now, value)
            n += 1
        try:
            from .histo import HISTOS, percentile_from_counts
            from .ledger import LEDGER

            LEDGER.poll()  # refreshes the device_mem_* gauges (rate-limited)
            series.record("ledger/peak_bytes", now, LEDGER.peak_bytes)
            n += 1
            for site in HISTOS.names():
                h = HISTOS.peek(site)
                if h is None:
                    continue
                counts, total, _sum, _lo, _hi = h._snapshot()
                prev = self._histo_counts.get(site)
                self._histo_counts[site] = counts
                if prev is None:
                    delta = counts
                else:
                    delta = [c - p for c, p in zip(counts, prev)]
                if sum(delta) <= 0:
                    # no observations this interval: record nothing —
                    # an idle interval has no percentile, and a gap is
                    # honest where repeating the old value would let a
                    # stale regression (or recovery) linger in every
                    # window that follows
                    continue
                for q in (50, 95, 99):
                    series.record(
                        f"histo/{site}/p{q}_ms",
                        now,
                        percentile_from_counts(delta, q) * 1e3,
                    )
                series.record(f"histo/{site}/count", now, total)
                n += 4
        except Exception:  # noqa: BLE001 - a broken observatory must degrade sampling, never kill the daemon's loop
            COUNTERS.inc("telemetry_sample_errors_total")
        series.record(
            "recorder/spans_dropped", now, _spans.RECORDER.dropped
        )
        if self.slo_engine is not None:
            self.slo_engine.evaluate(now=now)
        return n + 1

    # -- lifecycle ----------------------------------------------------------

    def _run(self):
        while not self._stop.wait(timeout=self.cadence_s):
            try:
                self.sample_once()
            except Exception:  # noqa: BLE001 - the sampling loop must outlive any one bad sample
                from ..utils.trace import COUNTERS

                COUNTERS.inc("telemetry_sample_errors_total")

    def start(self) -> None:
        if self._thread is not None:
            return
        self.started_at = self._clock()
        self.sample_once()  # history exists from the first instant
        self._thread = threading.Thread(
            target=self._run, name="simon-telemetry", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        try:
            self.sample_once()  # the dump sees the drain-time state
        except Exception:  # noqa: BLE001,S110 - best-effort final sample on a dying process
            pass

    def uptime_s(self) -> float:
        return max(self._clock() - self.started_at, 0.0)


def arm_flight_recorder(max_spans: int = 100_000) -> None:
    """Continuous flight recorder for resident daemons: force RING
    mode (overwrite-oldest under a dropped counter) and enable the
    recorder if no CLI flag armed it already. A daemon's recorder is
    ALWAYS a ring — even when ``--trace-out`` armed it first (at the
    one-shot CLI's larger capacity): for a long-lived process the
    recent window is the useful artifact, a keep-the-startup-prefix
    trace is not, and the drain export carries the truncation marker
    either way. ``/debug/dump`` then always has recent spans, with
    bounded memory, without ``--trace-out``."""
    rec = _spans.RECORDER
    rec.ring = True
    if not rec.enabled:
        rec.max_spans = max_spans
        rec.enable()


# ---------------------------------------------------------- endpoint payloads


def series_endpoint(path: str) -> tuple:
    """GET /v1/obs/series handler body. Query params: ``name`` (exact,
    repeatable) or ``prefix``, ``sinceSeconds``, ``resolution`` (1 |
    8 | 64 raw steps per point), ``maxPoints``. Without name/prefix,
    answers the name catalog. Returns (status, payload dict)."""
    from ..models.validation import InputError

    q = parse_qs(urlparse(path).query)

    def one(key, cast, default):
        vals = q.get(key)
        if not vals:
            return default
        try:
            return cast(vals[-1])
        except (TypeError, ValueError):
            raise InputError(f"bad {key!r} value {vals[-1]!r}") from None

    try:
        names = q.get("name") or []
        prefix = one("prefix", str, "")
        since = one("sinceSeconds", float, None)
        resolution = one("resolution", int, 1)
        max_points = one("maxPoints", int, None)
        if not names and prefix:
            names = SERIES.names(prefix)
        if not names:
            return 200, {
                "names": SERIES.names(),
                "stats": SERIES.stats(),
            }
        out = {}
        for name in names[:256]:
            out[name] = SERIES.query(
                name,
                resolution=resolution,
                since_s=since,
                max_points=max_points,
            )
    except InputError as e:
        return 400, {"error": str(e)}
    return 200, {
        "now": time.time(),
        "resolution": resolution,
        "series": out,
    }


def snapshot_doc(
    slo_engine=None, runtime: Optional[TelemetryRuntime] = None, extra=None
) -> dict:
    """GET /v1/obs/snapshot payload: the daemon's live telemetry at one
    instant — newest value of every series, SLO states, recorder and
    store stats. `simon top` renders exactly this."""
    rec = _spans.RECORDER
    doc = {
        "now": time.time(),
        "latest": SERIES.latest(),
        "seriesStats": SERIES.stats(),
        "recorder": {
            "enabled": rec.enabled,
            "ring": rec.ring,
            "spans": rec.count,
            "dropped": rec.dropped,
        },
        "slo": slo_engine.as_dict() if slo_engine is not None else None,
    }
    if runtime is not None:
        doc["uptimeSeconds"] = round(runtime.uptime_s(), 3)
        doc["cadenceSeconds"] = runtime.cadence_s
    if extra:
        doc.update(extra)
    return doc


#: spans included inline in a debug dump; the full ring can be 100k+
#: spans and the dump must stay curl-able from a live daemon
DUMP_MAX_SPANS = 20_000


def debug_dump_doc(
    slo_engine=None,
    runtime: Optional[TelemetryRuntime] = None,
    label: str = "daemon",
) -> dict:
    """POST /debug/dump payload: spans + series + SLO + observatory
    state of a LIVE daemon, no restart. Shaped as a bench record
    (``metric``/``value``/``unit``/``obs``) so ``simon doctor`` can
    diff two dumps of the same daemon — dispatches, recompiles, peak
    HBM, per-site p95s all ride the standard obs block."""
    from ..utils.trace import COUNTERS

    rec = _spans.RECORDER
    all_spans = rec.snapshot()
    spans_out = all_spans[-DUMP_MAX_SPANS:]
    counters = COUNTERS.snapshot()
    obs = {
        "jax_dispatches": counters["counts"].get("jax_dispatches_total", 0),
        "jax_recompiles": counters["counts"].get("jax_recompiles_total", 0),
        "spans_dropped": rec.dropped,
    }
    obs.update(_spans.observatory_block())
    doc = {
        "kind": "simon-debug-dump",
        "metric": f"{label}-debug-dump",
        "value": round(runtime.uptime_s(), 3) if runtime is not None else 0.0,
        "unit": "s",
        "counters": counters,
        "obs": obs,
        "slo": slo_engine.as_dict() if slo_engine is not None else None,
        "series": {
            name: SERIES.query(name, max_points=SERIES.stats()["capacity"])
            for name in SERIES.names()
        },
        "spans": {
            "total": len(all_spans),
            "included": len(spans_out),
            "dropped": rec.dropped,
            "top": _spans.top_spans(all_spans, 10),
            "events": [s.as_dict() for s in spans_out],
        },
    }
    return doc


# ------------------------------------------------------------- simon top

#: series `simon top` shows by default, existence-filtered against the
#: daemon's catalog (serve and twin names both listed; absent ones are
#: simply not rendered) — counters render as per-interval deltas
TOP_DEFAULT_SERIES = (
    "counter/serve_requests_total",
    "counter/serve_shed_total",
    "gauge/serve_queue_depth",
    "histo/serve/request/p95_ms",
    "histo/serve/evaluate/p95_ms",
    "counter/twin_deltas_applied_total",
    "gauge/twin_agreement_rate",
    "gauge/twin_mirror_lag_seconds",
    "histo/twin/query/p95_ms",
    "gauge/device_mem_bytes_in_use",
    "counter/jax_recompiles_total",
    "counter/spans_dropped_total",
)

#: fleet-router series `simon top --fleet` shows by default (same
#: existence-filtering as TOP_DEFAULT_SERIES — a router that has not
#: failed over yet simply has no failover gauges to draw)
FLEET_TOP_DEFAULT_SERIES = (
    "counter/fleet_requests_total",
    "counter/fleet_reroutes_total",
    "counter/fleet_shed_total",
    "counter/fleet_forward_failures_total",
    "counter/fleet_failovers_total",
    "counter/fleet_failover_ms_total",
    "gauge/fleet_slot_imbalance",
    "gauge/fleet_metrics_cache_age_seconds",
    "gauge/fleet_failover_seconds",
)

_SPARK_CHARS = "▁▂▃▄▅▆▇█"


def sparkline(values: List[float], width: int = 40) -> str:
    """Unicode block sparkline of the trailing ``width`` values."""
    vals = [float(v) for v in values][-width:]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return _SPARK_CHARS[0] * len(vals)
    span = hi - lo
    return "".join(
        _SPARK_CHARS[
            min(int((v - lo) / span * len(_SPARK_CHARS)), len(_SPARK_CHARS) - 1)
        ]
        for v in vals
    )


def _fmt_value(name: str, v: float) -> str:
    if "bytes" in name:
        for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
            if abs(v) < 1024 or unit == "TiB":
                return f"{v:.1f}{unit}"
            v /= 1024
    if abs(v) >= 1000:
        return f"{v:.0f}"
    return f"{v:.3g}"


def render_top_frame(
    snapshot: dict, series_doc: dict, url: str, width: int = 40
) -> str:
    """One `simon top` frame from a /v1/obs/snapshot payload and a
    /v1/obs/series payload — pure rendering, testable without a
    daemon. Counters draw their per-sample DELTAS (the rate shape);
    gauges and percentile series draw raw values."""
    lines = []
    health = snapshot.get("health", "?")
    uptime = snapshot.get("uptimeSeconds")
    head = (
        f"simon top — {snapshot.get('daemon', 'daemon')} @ {url} "
        f"[{health}]"
    )
    if uptime is not None:
        head += f"  up {uptime:.0f}s"
    rec = snapshot.get("recorder") or {}
    head += (
        f"  spans {rec.get('spans', 0)}"
        + (f" (dropped {rec['dropped']})" if rec.get("dropped") else "")
        + f"  series {((snapshot.get('seriesStats') or {}).get('series', 0))}"
    )
    lines.append(head)
    slo = snapshot.get("slo")
    if slo:
        alerting = set(slo.get("alerting") or ())
        lines.append("")
        lines.append(f"{'SLO':<28} {'burn fast':>10} {'burn slow':>10}  state")
        for st in slo.get("states") or ():
            name = (st.get("objective") or {}).get("name", "?")
            bf, bs = st.get("burnFast"), st.get("burnSlow")
            lines.append(
                f"{name:<28} "
                f"{('-' if bf is None else f'{bf:.2f}'):>10} "
                f"{('-' if bs is None else f'{bs:.2f}'):>10}  "
                + ("BURNING" if name in alerting else "ok")
            )
    series = series_doc.get("series") or {}
    if series:
        lines.append("")
        lines.append(f"{'signal':<40} {'last':>10}  history")
        for name in sorted(series):
            pts = series[name]
            if not pts:
                continue
            vals = [p[1] for p in pts]
            if name.startswith("counter/"):
                vals = [
                    max(b - a, 0.0) for a, b in zip(vals, vals[1:])
                ] or [0.0]
                last = vals[-1]
                label = name[len("counter/"):] + " Δ"
            else:
                last = vals[-1]
                label = name.split("/", 1)[1] if "/" in name else name
            lines.append(
                f"{label[:40]:<40} {_fmt_value(name, last):>10}  "
                f"{sparkline(vals, width)}"
            )
    return "\n".join(lines)


def fleet_slot_series(slot: str) -> List[str]:
    """The per-slot series names a fleet top frame reads (the caller
    URL-encodes them for the query string — slot labels ride inside
    series names)."""
    return [
        f"counter/fleet_replica_requests:{slot}",
        f"histo/fleet/forward/{slot}/p95_ms",
    ]


def render_fleet_top_frame(
    snapshot: dict, series_doc: dict, url: str, width: int = 40
) -> str:
    """One `simon top --fleet` frame from the ROUTER'S snapshot and
    series payloads: the fleet header + SLO burn table (shared with
    render_top_frame), then a per-slot pane — up/degraded/down, the
    slot's per-interval request rate, its forward p95 — and the
    fleet-wide signal sparklines. Tolerant BY CONSTRUCTION: a slot
    whose series are missing (TTL-cached scrape not refreshed yet, a
    replica that answered nothing this window) renders gaps ('-'),
    never a crash."""
    lines = [render_top_frame(snapshot, {"series": {}}, url, width=width)]
    series = series_doc.get("series") or {}
    replicas = snapshot.get("replicas") or {}
    if replicas:
        lines.append("")
        lines.append(
            f"{'slot':<12} {'state':<9} {'req Δ':>8} {'p95 ms':>8}  history"
        )
        for slot in sorted(replicas):
            reqs = series.get(f"counter/fleet_replica_requests:{slot}") or []
            p95 = series.get(f"histo/fleet/forward/{slot}/p95_ms") or []
            vals = [p[1] for p in reqs]
            deltas = [max(b - a, 0.0) for a, b in zip(vals, vals[1:])]
            rate = _fmt_value("", deltas[-1]) if deltas else "-"
            p95_last = _fmt_value("", p95[-1][1]) if p95 else "-"
            lines.append(
                f"{str(slot)[:12]:<12} {str(replicas[slot])[:9]:<9} "
                f"{rate:>8} {p95_last:>8}  "
                f"{sparkline(deltas, width) if deltas else ''}"
            )
    fleet_series = {
        name: pts
        for name, pts in series.items()
        if not name.startswith("counter/fleet_replica_requests:")
        and not name.startswith("histo/fleet/forward/")
    }
    if fleet_series:
        body = render_top_frame(
            {"daemon": "", "recorder": {}},
            {"series": fleet_series},
            url,
            width=width,
        )
        # drop the duplicate header line; keep the signal table
        lines.append("")
        lines.extend(body.splitlines()[1:])
    return "\n".join(lines)


def _confine_dump_path(path: str):
    """Resolve a server-side dump path, confined: RELATIVE to the
    daemon's working directory only (no absolute paths, no `..`
    escapes), and never overwriting an existing file. /debug/dump is
    reachable by anything that can reach the HTTP port — it must not
    be an arbitrary-file-write primitive (a client that wants the
    bytes elsewhere takes the inline dump and writes it itself).
    Returns the resolved path or raises InputError."""
    import os

    from ..models.validation import InputError

    p = str(path)
    if os.path.isabs(p):
        raise InputError(
            "dump path must be relative to the daemon's working "
            "directory (absolute paths refused); omit 'path' to get "
            "the dump inline"
        )
    root = os.path.realpath(os.getcwd())
    resolved = os.path.realpath(os.path.join(root, p))
    if resolved != root and not resolved.startswith(root + os.sep):
        raise InputError(
            f"dump path {path!r} escapes the daemon's working directory"
        )
    if os.path.exists(resolved):
        raise InputError(
            f"dump path {path!r} already exists (overwrite refused)"
        )
    return resolved


def handle_debug_dump(raw_body: bytes, **kwargs) -> tuple:
    """POST /debug/dump: optional JSON body ``{"path": "..."}`` writes
    the dump to a fresh file UNDER THE DAEMON'S WORKING DIRECTORY
    (relative paths only, no overwrite — see ``_confine_dump_path``)
    and answers a summary; without it the full dump is the response
    body. Returns (status, payload dict)."""
    path = None
    if raw_body and raw_body.strip():
        try:
            body = json.loads(raw_body.decode("utf-8"))
        except (UnicodeDecodeError, ValueError) as e:
            return 400, {"error": f"body is not valid JSON: {e}"}
        if not isinstance(body, dict):
            return 400, {"error": "body must be a JSON object"}
        path = body.get("path")
    if path:
        try:
            resolved = _confine_dump_path(path)
        except ValueError as e:
            return 400, {"error": str(e)}
    doc = debug_dump_doc(**kwargs)
    if path:
        try:
            with open(resolved, "w", encoding="utf-8") as f:
                json.dump(doc, f)
        except OSError as e:
            return 400, {"error": f"cannot write dump to {path!r}: {e}"}
        return 200, {
            "written": resolved,
            "spans": doc["spans"]["total"],
            "series": len(doc["series"]),
            "sloAlerts": (doc["slo"] or {}).get("alerting", []),
        }
    return 200, doc
