"""Perf-regression doctor: diff two bench records, gate on thresholds.

The BENCH_r*.json trajectory was a pile of JSON files a human eyeballed
("is 202 dispatches still 202?"). This module makes it an enforced
contract: load a baseline bench record and a candidate (fresh) one,
diff the headline value plus every observatory dimension — device
dispatches, XLA recompiles, peak HBM from the memory ledger, per-site
latency p95s from the histograms — against per-dimension thresholds,
and report regressions machine-readably. ``simon doctor OLD NEW``
(cli.py) and ``bench.py --against OLD`` both ride this; CI runs the
doctor over the checked-in trajectory so a regression fails the build
instead of landing in the next BENCH file.

Threshold semantics (docs/OBSERVABILITY.md):

- counts (dispatches, recompiles): ABSOLUTE slack, default 0 — these
  are semantic on a fixed scenario, so "one more dispatch" is a real
  behavior change, not noise;
- times/rates/bytes (value, peak HBM, p95): FRACTIONAL slack, default
  0.5 (±50%) — wall-clock on shared CPU runners is noisy, so only a
  step change trips. Direction comes from the unit: seconds-like
  values regress UP, rate-like values (pods/s, req/s, steps/s)
  regress DOWN.

A dimension missing from EITHER record is skipped (older BENCH files
predate the observatory blocks) — the doctor compares what both sides
measured, never invents a zero.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import List, Optional

from ..models.validation import InputError

# units whose headline value is better when LARGER; everything else
# (s, mismatches, bytes) regresses upward
_RATE_UNITS = {"pods/s", "req/s", "steps/s", "qps", "rows/s", "deltas/s"}


@dataclass
class Thresholds:
    value_frac: float = 0.5
    dispatch_abs: int = 0
    recompile_abs: int = 0
    hbm_frac: float = 0.5
    p95_frac: float = 0.5
    # incremental families (r15): the suffix fraction regresses UP (a
    # bigger fraction = re-scanning rows the journal should have
    # reused); the store hit rate regresses DOWN (cold starts paying
    # compiles a warm store should have served)
    suffix_frac: float = 0.5
    store_frac: float = 0.5
    store_reject_abs: int = 0
    # fleet families (r16): qps_scaling regresses DOWN (lost
    # horizontal scaling), failover_seconds regresses UP (slower
    # recovery after a replica kill)
    fleet_frac: float = 0.5
    # checkpoint family (r17): restore_seconds on the AGED failover
    # cells regresses UP — recovery time growing with absorbed-delta
    # age means the checkpoint + compacted-suffix bound broke
    ckpt_frac: float = 0.5

    @classmethod
    def from_args(cls, args) -> "Thresholds":
        return cls(
            value_frac=getattr(args, "time_tolerance", 0.5),
            dispatch_abs=getattr(args, "dispatch_tolerance", 0),
            recompile_abs=getattr(args, "recompile_tolerance", 0),
            hbm_frac=getattr(args, "hbm_tolerance", 0.5),
            p95_frac=getattr(args, "p95_tolerance", 0.5),
            suffix_frac=getattr(args, "suffix_tolerance", 0.5),
            store_frac=getattr(args, "store_tolerance", 0.5),
            store_reject_abs=getattr(args, "store_reject_tolerance", 0),
            fleet_frac=getattr(args, "fleet_tolerance", 0.5),
            ckpt_frac=getattr(args, "ckpt_tolerance", 0.5),
        )


@dataclass
class DiffRow:
    dimension: str
    baseline: float
    candidate: float
    threshold: str
    regressed: bool
    note: str = ""

    def as_dict(self) -> dict:
        return {
            "dimension": self.dimension,
            "baseline": self.baseline,
            "candidate": self.candidate,
            "threshold": self.threshold,
            "regressed": self.regressed,
            "note": self.note,
        }


@dataclass
class DoctorReport:
    rows: List[DiffRow] = field(default_factory=list)
    skipped: List[str] = field(default_factory=list)

    @property
    def regressions(self) -> List[DiffRow]:
        return [r for r in self.rows if r.regressed]

    @property
    def ok(self) -> bool:
        return not self.regressions

    def as_dict(self) -> dict:
        return {
            "ok": self.ok,
            "regressions": len(self.regressions),
            "rows": [r.as_dict() for r in self.rows],
            "skipped": self.skipped,
        }


def load_bench_record(path: str) -> dict:
    """Load a bench record from any of its on-disk shapes: the raw
    one-line JSON bench.py prints, a file of several such lines (last
    wins — the bench prints progress lines first), or the checked-in
    BENCH_r*.json wrapper whose ``tail`` field holds the line. Raises
    InputError with the offending path on anything else."""
    with open(path, encoding="utf-8") as f:
        text = f.read().strip()
    if not text:
        raise InputError(f"{path}: empty file")
    try:
        doc = json.loads(text)
    except ValueError:
        doc = None
    if isinstance(doc, dict) and "tail" in doc and "metric" not in doc:
        text = str(doc["tail"]).strip()
        doc = None
    if doc is None:
        # one record per line; take the last parseable line with a
        # "metric" key (bench progress output precedes the record)
        best = None
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                cand = json.loads(line)
            except ValueError:
                continue
            if isinstance(cand, dict) and "metric" in cand:
                best = cand
        if best is None:
            raise InputError(
                f"{path}: no bench record found (expected a JSON object "
                'with a "metric" key, a JSONL of them, or a BENCH_r*.json '
                "wrapper)"
            )
        doc = best
    if not isinstance(doc, dict) or "metric" not in doc:
        raise InputError(f"{path}: not a bench record (no 'metric' key)")
    return doc


def _num(d: dict, *keys) -> Optional[float]:
    cur = d
    for k in keys:
        if not isinstance(cur, dict) or k not in cur:
            return None
        cur = cur[k]
    return float(cur) if isinstance(cur, (int, float)) else None


def diff_records(
    base: dict, cand: dict, thresholds: Optional[Thresholds] = None
) -> DoctorReport:
    """Diff two bench records dimension by dimension. Regression is
    one-sided: getting FASTER / dispatching LESS never trips."""
    th = thresholds or Thresholds()
    report = DoctorReport()

    def frac_row(dim, b, c, tol, higher_is_better=False, note=""):
        if b is None or c is None:
            report.skipped.append(dim)
            return
        if b == 0:
            regressed = c > 0 and not higher_is_better
        elif higher_is_better:
            regressed = c < b * (1.0 - tol)
        else:
            regressed = c > b * (1.0 + tol)
        report.rows.append(
            DiffRow(dim, b, c, f"±{tol:.0%}", regressed, note)
        )

    def abs_row(dim, b, c, tol, note=""):
        if b is None or c is None:
            report.skipped.append(dim)
            return
        report.rows.append(
            DiffRow(dim, b, c, f"+{tol}", c > b + tol, note)
        )

    unit = str(cand.get("unit") or base.get("unit") or "")
    frac_row(
        f"value ({unit})",
        _num(base, "value"),
        _num(cand, "value"),
        th.value_frac,
        higher_is_better=unit in _RATE_UNITS,
        note=str(base.get("metric", ""))[:60],
    )
    abs_row(
        "jax_dispatches",
        _num(base, "obs", "jax_dispatches"),
        _num(cand, "obs", "jax_dispatches"),
        th.dispatch_abs,
        note="device dispatches are semantic on a fixed scenario",
    )
    abs_row(
        "jax_recompiles",
        _num(base, "obs", "jax_recompiles"),
        _num(cand, "obs", "jax_recompiles"),
        th.recompile_abs,
        note="one per shape-signature; growth = warm-cache regression",
    )
    frac_row(
        "ledger.peak_bytes",
        _num(base, "obs", "ledger", "peak_bytes"),
        _num(cand, "obs", "ledger", "peak_bytes"),
        th.hbm_frac,
        note="peak device memory (obs/ledger.py watermark)",
    )
    # incremental / artifact-store families (r15): optional blocks —
    # absent from BOTH sides means the scenario never exercised them
    # (silently not-applicable, not a noteworthy skip); absent from
    # ONE side reports as skipped like every other dimension
    def opt(row_fn, dim, b, c, tol, **kw):
        if b is None and c is None:
            return
        row_fn(dim, b, c, tol, **kw)

    opt(
        frac_row,
        "incremental.suffix_fraction",
        _num(base, "obs", "incremental", "suffix_fraction"),
        _num(cand, "obs", "incremental", "suffix_fraction"),
        th.suffix_frac,
        note="re-dispatched rows / (re-dispatched + prefix-reused)",
    )
    opt(
        frac_row,
        "aot_store.hit_rate",
        _num(base, "obs", "aot_store", "hit_rate"),
        _num(cand, "obs", "aot_store", "hit_rate"),
        th.store_frac,
        higher_is_better=True,
        note="store loads / (loads + compile misses)",
    )
    opt(
        abs_row,
        "aot_store.rejects",
        _num(base, "obs", "aot_store", "rejects"),
        _num(cand, "obs", "aot_store", "rejects"),
        th.store_reject_abs,
        note="corrupt/stale store entries refused (each one recompiles)",
    )
    opt(
        frac_row,
        "fleet.qps_scaling",
        _num(base, "obs", "fleet", "qps_scaling"),
        _num(cand, "obs", "fleet", "qps_scaling"),
        th.fleet_frac,
        higher_is_better=True,
        note="aggregate fleet QPS at max replicas / single-replica QPS",
    )
    opt(
        frac_row,
        "fleet.failover_seconds",
        _num(base, "obs", "fleet", "failover_seconds"),
        _num(cand, "obs", "fleet", "failover_seconds"),
        th.fleet_frac,
        note="kill-9 to next 200 through the router (reroute latency)",
    )
    # the audited per-phase breakdown of that total (fleet/audit.py):
    # a regressed total names its slow phase instead of one number.
    # Older baselines (BENCH_r13-era) predate the audit — absent from
    # both sides, the rows silently skip and the total is still gated.
    for phase in ("detect", "reclaim", "respawn", "replay", "first_200"):
        opt(
            frac_row,
            f"fleet.failover_phases.{phase}",
            _num(base, "obs", "fleet", "failover_phases", phase),
            _num(cand, "obs", "fleet", "failover_phases", phase),
            th.fleet_frac,
            note="audited failover phase (fleet/audit.py timeline)",
        )
    opt(
        frac_row,
        "ckpt.restore_seconds",
        _num(base, "obs", "ckpt", "restore_seconds"),
        _num(cand, "obs", "ckpt", "restore_seconds"),
        th.ckpt_frac,
        note="aged-failover restore: newest checkpoint + journal suffix",
    )
    # per-site latency p95s: every site present in BOTH records
    bh = base.get("obs", {}).get("histograms")
    ch = cand.get("obs", {}).get("histograms")
    if isinstance(bh, dict) and isinstance(ch, dict):
        for site in sorted(set(bh) & set(ch)):
            frac_row(
                f"p95 {site}",
                _num(bh, site, "p95_ms"),
                _num(ch, site, "p95_ms"),
                th.p95_frac,
            )
    elif bh or ch:
        report.skipped.append("histograms")
    return report


def render_text(report: DoctorReport, base_name: str, cand_name: str) -> str:
    w = max(
        [len(r.dimension) for r in report.rows] + [len("dimension")]
    )
    lines = [
        f"simon doctor: {cand_name} vs baseline {base_name}",
        f"{'dimension':<{w}}  {'baseline':>14}  {'candidate':>14}  "
        f"{'threshold':>9}  verdict",
    ]
    for r in report.rows:
        verdict = "REGRESSED" if r.regressed else "ok"
        lines.append(
            f"{r.dimension:<{w}}  {r.baseline:>14.6g}  "
            f"{r.candidate:>14.6g}  {r.threshold:>9}  {verdict}"
        )
    if report.skipped:
        lines.append(
            f"skipped (absent from one side): {', '.join(report.skipped)}"
        )
    lines.append(
        "RESULT: "
        + (
            "ok — no regression past thresholds"
            if report.ok
            else f"{len(report.regressions)} regression(s): "
            + ", ".join(r.dimension for r in report.regressions)
        )
    )
    return "\n".join(lines)
