"""JAX dispatch / recompile / transfer accounting.

"How many XLA recompiles did this sweep trigger" was previously
unanswerable: the module-level jits in ``scheduler/engine.py``,
``ops/scan.py``, and ``parallel/sweep.py`` compiled (or didn't)
invisibly. This module wraps them in ``InstrumentedJit``, which counts

- ``jax_dispatches_total`` (+ per-site ``jax_dispatches_<site>``):
  every call into a jitted entry point — one device dispatch each;
- ``jax_recompiles_total`` (+ per-site): calls whose jit cache grew
  (``PjitFunction._cache_size`` before/after — a miss means XLA traced
  and compiled a new executable for this shape/static combination);
- ``device_transfer_d2h_bytes_total`` / ``..._h2d_bytes_total``:
  bytes materialized from / shipped to the device at the few sites
  that do it (engine scan outputs, scenario batches).

Everything lands in the existing process-wide ``utils.trace.Counters``
registry, so ``simon serve``'s ``/metrics`` endpoint and the bench
harness report the same numbers with zero extra plumbing. The counters
are always on (one lock + dict-add per DISPATCH, which is rare —
dispatches are per scan round, not per pod), so there is no flag to
forget before asking "did this workload recompile".

The optional ``jax.profiler`` capture (``--profile-dir``) reuses the
``utils.trace.profiled`` machinery via the SIMON_PROFILE_DIR env var.
"""

from __future__ import annotations

import os
from typing import Optional

from ..utils.trace import COUNTERS


class InstrumentedJit:
    """Wraps a jitted callable with dispatch + cache-miss counters and
    (when the span recorder is on) a per-dispatch span. Transparent to
    callers: ``__call__`` only."""

    __slots__ = ("_fn", "name")

    def __init__(self, fn, name: str):
        self._fn = fn
        self.name = name

    def _cache_size(self) -> Optional[int]:
        size = getattr(self._fn, "_cache_size", None)
        if size is None:
            return None
        try:
            return int(size())
        except (TypeError, ValueError):  # non-standard jit wrapper
            return None

    def __call__(self, *args, **kwargs):
        COUNTERS.inc("jax_dispatches_total")
        COUNTERS.inc(f"jax_dispatches_{self.name}")
        before = self._cache_size()
        from .spans import RECORDER

        if RECORDER.enabled:
            with RECORDER.span(f"jit/{self.name}", site=self.name):
                out = self._fn(*args, **kwargs)
        else:
            out = self._fn(*args, **kwargs)
        after = self._cache_size()
        if before is not None and after is not None and after > before:
            COUNTERS.inc("jax_recompiles_total", after - before)
            COUNTERS.inc(f"jax_recompiles_{self.name}", after - before)
        return out


def instrument_jit(fn, name: str) -> InstrumentedJit:
    """Wrap a jitted function for dispatch/recompile accounting. Safe
    to apply to anything callable; cache-miss detection degrades to
    dispatch-only when the wrapper exposes no ``_cache_size``."""
    return InstrumentedJit(fn, name)


# ------------------------------------------------------ transfer gauges


def record_d2h(nbytes: int) -> None:
    """Bytes materialized host-side from device outputs (np.asarray of
    placements and friends)."""
    COUNTERS.inc("device_transfer_d2h_bytes_total", int(nbytes))
    COUNTERS.gauge("device_transfer_d2h_last_bytes", float(nbytes))


def record_h2d(nbytes: int) -> None:
    """Bytes shipped device-wards (encoded batches, scenario masks)."""
    COUNTERS.inc("device_transfer_h2d_bytes_total", int(nbytes))
    COUNTERS.gauge("device_transfer_h2d_last_bytes", float(nbytes))


def nbytes_of(*arrays) -> int:
    """Total nbytes of numpy/jax arrays (anything exposing .nbytes);
    non-arrays count zero — callers pass whatever they just moved."""
    total = 0
    for a in arrays:
        nb = getattr(a, "nbytes", None)
        if isinstance(nb, int):
            total += nb
    return total


# ------------------------------------------------------ profiler capture


def set_profile_dir(path: Optional[str]) -> None:
    """Arm (or disarm with None) the ``utils.trace.profiled`` JAX
    profiler capture — the --profile-dir CLI wiring. Captures land in
    ``<path>/<phase-name>/`` and open in TensorBoard / Perfetto."""
    if path:
        os.makedirs(path, exist_ok=True)
        os.environ["SIMON_PROFILE_DIR"] = path
    else:
        os.environ.pop("SIMON_PROFILE_DIR", None)


# ------------------------------------------------------ snapshot helpers


_KEYS = (
    "jax_dispatches_total",
    "jax_recompiles_total",
    "device_transfer_d2h_bytes_total",
    "device_transfer_h2d_bytes_total",
)


def snapshot() -> dict:
    """Current values of the headline profiling counters."""
    return {k: COUNTERS.get(k) for k in _KEYS}


def delta(since: dict) -> dict:
    """Counter movement since a previous ``snapshot()`` — the bench
    harness stamps each scenario's dispatch/recompile cost with this."""
    now = snapshot()
    return {k: now[k] - since.get(k, 0) for k in _KEYS}
