"""JAX dispatch / recompile / transfer / cost accounting.

"How many XLA recompiles did this sweep trigger" was previously
unanswerable: the module-level jits in ``scheduler/engine.py``,
``ops/scan.py``, and ``parallel/sweep.py`` compiled (or didn't)
invisibly. This module wraps them in ``InstrumentedJit``, which counts

- ``jax_dispatches_total`` (+ per-site ``jax_dispatches_<site>``):
  every call into a jitted entry point — one device dispatch each;
- ``jax_recompiles_total`` (+ per-site): calls that compiled a new
  executable for this shape/static combination — an ahead-of-time
  cache miss on the AOT path, a grown ``PjitFunction._cache_size`` on
  the fallback path;
- ``device_transfer_d2h_bytes_total`` / ``..._h2d_bytes_total``:
  bytes materialized from / shipped to the device at the few sites
  that do it (engine scan outputs, scenario batches).

Since the compiled-cost observatory (docs/OBSERVABILITY.md), each site
also compiles AHEAD OF TIME: the first call of a shape-signature runs
``jit(...).lower(args).compile()``, extracts ``cost_analysis()`` /
``memory_analysis()`` into the cost registry (obs/costs.py), and
REUSES the compiled artifact for this and every later same-signature
dispatch — cost capture adds zero extra compiles, and the executable
becomes a named object keyed by signature (the first step toward
ROADMAP item 4's persisted compile cache). Calls the AOT path cannot
serve — tracer arguments (this site traced inside an outer jit),
committed/sharded inputs (the multichip mesh path), keyword arguments,
signature-cache overflow, or ``SIMON_AOT=0`` — fall back to the plain
jitted call unchanged. Every dispatch additionally records its
latency into the per-site streaming histogram (obs/histo.py) and
polls the device-memory ledger (obs/ledger.py) so the HBM peak is
observed exactly where it moves.

Everything lands in the existing process-wide ``utils.trace.Counters``
registry, so ``simon serve``'s ``/metrics`` endpoint and the bench
harness report the same numbers with zero extra plumbing. The counters
are always on (one lock + dict-add per DISPATCH, which is rare —
dispatches are per scan round, not per pod), so there is no flag to
forget before asking "did this workload recompile".

The optional ``jax.profiler`` capture (``--profile-dir``) reuses the
``utils.trace.profiled`` machinery via the SIMON_PROFILE_DIR env var.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Optional

from ..runtime import inject as _inject
from ..utils.trace import COUNTERS
from . import spans as _spans
from .costs import COSTS, extract_record
from .histo import HISTOS
from .ledger import LEDGER, _span_boundary

log = logging.getLogger(__name__)

# the ledger's top-level-span watermark frames ride the span recorder's
# boundary hook; installed here (not in ledger.py) because this module
# is the first in the obs import order that may safely touch both
_spans.set_boundary_hook(_span_boundary)

_UNSET = object()


def _aot_enabled() -> bool:
    return os.environ.get("SIMON_AOT", "1") != "0"


def _artifact_store():
    """The armed persistent artifact store, or None. Lazy sibling
    import: the obs package must load without incremental/ (and the
    store is consulted only on the rare compile path)."""
    try:
        from ..incremental.store import current_store
    except ImportError:
        return None
    return current_store()


def _ledger_enabled() -> bool:
    return os.environ.get("SIMON_LEDGER", "1") != "0"


class InstrumentedJit:
    """Wraps a jitted callable with dispatch + compile counters, AOT
    cost capture, per-dispatch latency histograms and (when the span
    recorder is on) a per-dispatch span. Transparent to callers:
    ``__call__`` only."""

    # signature-cache bound: a workload churning through more distinct
    # shapes than this is not warm-cacheable anyway — AOT capture
    # retires for the site rather than growing without bound
    MAX_AOT_SIGNATURES = 128

    __slots__ = (
        "_fn", "name", "_static", "_aot", "_aot_on", "_lock",
        "_lead_argnum",
    )

    def __init__(self, fn, name: str, static_argnums=(), lead_argnum=None):
        self._fn = fn
        self.name = name
        self._static = frozenset(int(i) for i in static_argnums)
        self._lead_argnum = lead_argnum
        # signature -> (compiled, CostRecord), or None (signature
        # retired to the plain path)
        self._aot = {}
        self._aot_on = hasattr(fn, "lower")
        self._lock = threading.Lock()

    def _cache_size(self) -> Optional[int]:
        size = getattr(self._fn, "_cache_size", None)
        if size is None:
            return None
        try:
            return int(size())
        except (TypeError, ValueError):  # non-standard jit wrapper
            return None

    # -- AOT path -----------------------------------------------------------

    def _signature(self, args):
        """Hashable shape-signature of a call, or None when the call
        cannot ride the AOT path (tracers, committed shardings,
        unhashable static leaves)."""
        import jax

        try:
            leaves, treedef = jax.tree_util.tree_flatten(args)
        except Exception:  # noqa: BLE001 - unflattenable args: plain path, never an instrumentation failure
            return None
        sig = []
        for leaf in leaves:
            if isinstance(leaf, jax.core.Tracer):
                # this site is being traced inside an outer jit: the
                # dispatch belongs to the outer executable
                return None
            shape = getattr(leaf, "shape", None)
            dtype = getattr(leaf, "dtype", None)
            if shape is not None and dtype is not None:
                if getattr(leaf, "_committed", False):
                    # explicitly placed/sharded input (the multichip
                    # mesh path): the signature would need the sharding
                    # too — stay on the plain jit, which handles it
                    return None
                sig.append(
                    (
                        tuple(shape),
                        str(dtype),
                        bool(getattr(leaf, "weak_type", False)),
                    )
                )
            else:
                sig.append(("static", leaf))
        key = (treedef, tuple(sig))
        try:
            hash(key)
        except TypeError:  # unhashable static leaf
            return None
        return key

    def _lead_dim(self, args) -> int:
        """Row count of the CHUNKED axis for this compile. Sites
        dispatched through guard.run_chunked declare which argument
        carries it (``lead_argnum``) — without that, a site whose
        non-batched arguments have node/pod-sized leading dimensions
        would record those instead, and the cost registry's per-row
        scaling would underestimate chunk workspace by orders of
        magnitude (a chunk of 8 scenarios over 10k nodes is NOT
        8/10000ths of the compiled workspace)."""
        import jax

        search = args
        if self._lead_argnum is not None and self._lead_argnum < len(args):
            search = (args[self._lead_argnum],)
        best = 0
        for leaf in jax.tree_util.tree_leaves(search):
            shape = getattr(leaf, "shape", None)
            if shape:
                best = max(best, int(shape[0]))
        return best

    def _dynamic_args(self, args):
        return [a for i, a in enumerate(args) if i not in self._static]

    def _aot_compile(self, key, args):
        """Lower + compile the signature once, extract its cost/memory
        analysis into the registry, and cache the artifact. Any
        failure retires the signature to the plain path (logged —
        never silent, never fatal). ``_lock`` owns the signature cache
        (`_aot`/`_aot_on`); ``_fn``/``name`` are immutable after
        construction and stay out of the locked region.

        When a persistent artifact store is armed (``--aot-store`` /
        SIMON_AOT_STORE, incremental/store.py), a verified store entry
        is loaded INSTEAD of compiling — the zero-compile cold start:
        the recompile counter does not move, the load is counted
        (``aot_store_hit_total``). Fresh compiles are serialized back
        (outside the lock: the save fsyncs). A rejected/corrupt entry
        was already counted and logged by the store; it lands here as
        a plain compile."""
        fn, name = self._fn, self.name
        with self._lock:
            entry = self._aot.get(key, _UNSET)
        if entry is not _UNSET:
            # raced: another thread already compiled/loaded/retired it —
            # skip the store probe (a second full deserialization would
            # also double-count the hit)
            return entry
        lead_dim = self._lead_dim(args)
        store = _artifact_store()
        loaded = store.load(name, key) if store is not None else None
        to_save = None
        with self._lock:
            entry = self._aot.get(key, _UNSET)
            if entry is not _UNSET:
                return entry  # raced: another thread compiled/retired it
            if len(self._aot) >= self.MAX_AOT_SIGNATURES:
                log.warning(
                    "jit site %s exceeded %d AOT signatures; cost capture "
                    "retired for this site (shape-churning workload)",
                    name, self.MAX_AOT_SIGNATURES,
                )
                self._aot_on = False
                return None
            if loaded is not None:
                compiled, rec = loaded
                COSTS.record(name, key, rec, loaded=True)
                entry = (compiled, rec)
                self._aot[key] = entry
                return entry
            try:
                compiled = fn.lower(*args).compile()
            except Exception as e:  # noqa: BLE001 - AOT is an optimization: any lowering/compile fault falls back to the plain jit call, which surfaces real errors itself
                log.debug(
                    "jit site %s: AOT lower/compile unavailable for this "
                    "signature (%s); falling back to the plain jit path",
                    name, str(e).split("\n", 1)[0][:120],
                )
                self._aot[key] = None
                return None
            COUNTERS.inc("jax_recompiles_total")
            COUNTERS.inc(f"jax_recompiles_{name}")
            rec = extract_record(name, compiled, lead_dim=lead_dim)
            COSTS.record(name, key, rec)
            entry = (compiled, rec)
            self._aot[key] = entry
            to_save = entry
        if store is not None and to_save is not None:
            store.save(name, key, to_save[0], to_save[1])
        return entry

    # -- dispatch -----------------------------------------------------------

    def _dispatch(self, args, kwargs):
        use_aot = False
        if not kwargs and _aot_enabled():
            with self._lock:
                use_aot = self._aot_on
        if use_aot:
            key = self._signature(args)
            if key is not None:
                with self._lock:
                    entry = self._aot.get(key, _UNSET)
                if entry is _UNSET:
                    entry = self._aot_compile(key, args)
                if entry is not None:
                    compiled, rec = entry
                    try:
                        out = compiled(*self._dynamic_args(args))
                    except TypeError as e:
                        # the signature missed a discriminant the
                        # executable is strict about (layout/sharding
                        # drift): retire it and re-dispatch plainly
                        log.warning(
                            "jit site %s: AOT artifact rejected its "
                            "signature (%s); retiring to the plain path",
                            self.name, str(e).split("\n", 1)[0][:120],
                        )
                        with self._lock:
                            self._aot[key] = None
                    else:
                        COSTS.on_dispatch(rec)
                        return out
        before = self._cache_size()
        out = self._fn(*args, **kwargs)
        after = self._cache_size()
        if before is not None and after is not None and after > before:
            COUNTERS.inc("jax_recompiles_total", after - before)
            COUNTERS.inc(f"jax_recompiles_{self.name}", after - before)
        return out

    def __call__(self, *args, **kwargs):
        COUNTERS.inc("jax_dispatches_total")
        COUNTERS.inc(f"jax_dispatches_{self.name}")
        # chaos seam: `jit.<site>` raises the configured device fault
        # at the Nth dispatch of this site — the raw RuntimeError
        # shapes the guard ladder classifies (runtime/inject.py)
        _inject.fire(f"jit.{self.name}")
        from .spans import RECORDER

        t0 = time.perf_counter()
        try:
            if RECORDER.enabled:
                with RECORDER.span(f"jit/{self.name}", site=self.name):
                    out = self._dispatch(args, kwargs)
            else:
                out = self._dispatch(args, kwargs)
        finally:
            HISTOS.observe(f"jit/{self.name}", time.perf_counter() - t0)
            if _ledger_enabled():
                LEDGER.poll()
        return out


def instrument_jit(
    fn, name: str, static_argnums=(), lead_argnum=None
) -> InstrumentedJit:
    """Wrap a jitted function for dispatch/recompile/cost accounting.
    ``static_argnums`` must mirror the wrapped jit's own (the AOT
    artifact is called with the dynamic arguments only).
    ``lead_argnum`` names the argument whose leading dimension is the
    chunked/batched-scenario axis — required for sites driven through
    ``guard.run_chunked`` so the cost registry's per-row estimates
    scale by the right axis. Safe to apply to anything callable; AOT
    capture and cache-miss detection degrade gracefully when the
    wrapper exposes no ``lower``/``_cache_size``."""
    return InstrumentedJit(
        fn, name, static_argnums=static_argnums, lead_argnum=lead_argnum
    )


# ------------------------------------------------------ transfer gauges


def record_d2h(nbytes: int) -> None:
    """Bytes materialized host-side from device outputs (np.asarray of
    placements and friends)."""
    COUNTERS.inc("device_transfer_d2h_bytes_total", int(nbytes))
    COUNTERS.gauge("device_transfer_d2h_last_bytes", float(nbytes))


def record_h2d(nbytes: int) -> None:
    """Bytes shipped device-wards (encoded batches, scenario masks)."""
    COUNTERS.inc("device_transfer_h2d_bytes_total", int(nbytes))
    COUNTERS.gauge("device_transfer_h2d_last_bytes", float(nbytes))


def nbytes_of(*arrays) -> int:
    """Total nbytes of numpy/jax arrays (anything exposing .nbytes);
    non-arrays count zero — callers pass whatever they just moved."""
    total = 0
    for a in arrays:
        nb = getattr(a, "nbytes", None)
        if isinstance(nb, int):
            total += nb
    return total


# ------------------------------------------------------ profiler capture


def set_profile_dir(path: Optional[str]) -> None:
    """Arm (or disarm with None) the ``utils.trace.profiled`` JAX
    profiler capture — the --profile-dir CLI wiring. Captures land in
    ``<path>/<phase-name>/`` and open in TensorBoard / Perfetto."""
    if path:
        os.makedirs(path, exist_ok=True)
        os.environ["SIMON_PROFILE_DIR"] = path
    else:
        os.environ.pop("SIMON_PROFILE_DIR", None)


# ------------------------------------------------------ snapshot helpers


_KEYS = (
    "jax_dispatches_total",
    "jax_recompiles_total",
    "device_transfer_d2h_bytes_total",
    "device_transfer_h2d_bytes_total",
)


def snapshot() -> dict:
    """Current values of the headline profiling counters."""
    return {k: COUNTERS.get(k) for k in _KEYS}


def delta(since: dict) -> dict:
    """Counter movement since a previous ``snapshot()`` — the bench
    harness stamps each scenario's dispatch/recompile cost with this."""
    now = snapshot()
    return {k: now[k] - since.get(k, 0) for k in _KEYS}
