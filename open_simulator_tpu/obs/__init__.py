"""Process-wide flight recorder (docs/OBSERVABILITY.md).

Three cooperating pieces, all off by default and costing nothing on the
hot path until a CLI flag turns them on:

- ``obs.spans``: thread-safe hierarchical wall-clock spans (context
  manager + decorator, contextvar parent tracking so dispatcher threads
  and nested phases nest correctly) with Chrome trace-event JSON and
  streaming JSONL exporters — ``--trace-out``.
- ``obs.explain``: per-pod placement explanations — per-node filter
  verdicts and score vectors captured at commit/failure time on both
  the serial oracle and the scan-replay paths — ``--explain [POD]``.
- ``obs.profile``: JAX dispatch / jit-cache-miss (recompile) / device
  transfer-bytes accounting through the ``utils.trace.Counters``
  registry, plus the ``--profile-dir`` JAX profiler capture.

The compiled-cost & memory observatory (r10) layers four more pieces
on the same registry, all always-on:

- ``obs.costs``: per-site AOT compile cache — ``jit(...).lower()
  .compile()`` per shape-signature with ``cost_analysis()`` /
  ``memory_analysis()`` extracted and the artifact reused for the
  dispatch;
- ``obs.ledger``: device-memory ledger — ``memory_stats()`` /
  live-buffer polling, per-top-level-span HBM watermarks, and
  ``predict_fit`` feeding the guard's predictive degradation ladder;
- ``obs.histo``: fixed-64-bucket streaming latency histograms per jit
  site and serve request phase (p50/p95/p99, Prometheus exposition);
- ``obs.doctor``: the bench-record regression differ behind
  ``simon doctor`` and ``bench.py --against``.

``obs.profile`` (and the cost/ledger/histo trio it wires together) is
deliberately NOT imported here: it imports ``utils.trace`` for the
counter registry, and ``utils.trace`` imports ``obs.spans`` for the
phase shim — importing profile at package level would close that
cycle while ``utils.trace`` is still initializing.
"""

from . import explain, spans
from .explain import EXPLAIN
from .spans import RECORDER, span, traced

__all__ = [
    "EXPLAIN",
    "RECORDER",
    "explain",
    "span",
    "spans",
    "traced",
]
