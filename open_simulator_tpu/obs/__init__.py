"""Process-wide flight recorder (docs/OBSERVABILITY.md).

Three cooperating pieces, all off by default and costing nothing on the
hot path until a CLI flag turns them on:

- ``obs.spans``: thread-safe hierarchical wall-clock spans (context
  manager + decorator, contextvar parent tracking so dispatcher threads
  and nested phases nest correctly) with Chrome trace-event JSON and
  streaming JSONL exporters — ``--trace-out``.
- ``obs.explain``: per-pod placement explanations — per-node filter
  verdicts and score vectors captured at commit/failure time on both
  the serial oracle and the scan-replay paths — ``--explain [POD]``.
- ``obs.profile``: JAX dispatch / jit-cache-miss (recompile) / device
  transfer-bytes accounting through the ``utils.trace.Counters``
  registry, plus the ``--profile-dir`` JAX profiler capture.

``obs.profile`` is deliberately NOT imported here: it imports
``utils.trace`` for the counter registry, and ``utils.trace`` imports
``obs.spans`` for the phase shim — importing profile at package level
would close that cycle while ``utils.trace`` is still initializing.
"""

from . import explain, spans
from .explain import EXPLAIN
from .spans import RECORDER, span, traced

__all__ = [
    "EXPLAIN",
    "RECORDER",
    "explain",
    "span",
    "spans",
    "traced",
]
