"""Hierarchical span tracing — the flight recorder's timeline.

The flat ``utils.trace.Trace`` phase timers answer "how long did encode
take in total"; spans answer "where did these 4.4 seconds go, span by
span": every recorded interval carries its parent, so one `simon apply`
run renders as a tree (command root -> probe search -> per-probe scan
-> device dispatch) loadable in Perfetto / chrome://tracing.

Design:

- ONE process-wide ``Recorder`` (``RECORDER``), disabled by default.
  Disabled cost is a single attribute read per ``span()`` entry —
  the hot path pays nothing until ``--trace-out`` (or a test) enables
  it.
- Parent tracking rides a ``contextvars.ContextVar``: each thread (the
  CLI main thread, serve's dispatcher thread, HTTP handler threads)
  gets its own span stack for free, so concurrent requests nest under
  their own roots instead of interleaving.
- ``utils.trace.phase`` is shimmed to emit each phase as a leaf span
  when the recorder is on, so every existing phase annotation joins
  the tree without touching its call sites.
- Exporters: Chrome trace-event JSON (``export_chrome_trace``; complete
  "X" events, microsecond timestamps — Perfetto nests same-thread
  events by time containment) and streaming JSONL (``JsonlSink``; one
  fsync'd line per completed span, the PR-2 journal append discipline,
  so a crashed run keeps every finished span).

This module is stdlib-only on purpose: ``utils.trace`` imports it at
module load, so it must not pull in anything from the package.
"""

from __future__ import annotations

import contextvars
import json
import os
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, List, Optional

SPAN_SCHEMA_VERSION = 1

# current span id of the calling context (None = root); a ContextVar
# rather than a thread-local so async callers inherit correctly too
_parent: contextvars.ContextVar = contextvars.ContextVar(
    "simon_obs_parent_span", default=None
)

# top-level-span boundary hook (obs/ledger.py: HBM watermark frames).
# A settable slot rather than an import so this module stays
# stdlib-only at load time; obs/profile.py installs the ledger's hook.
# Signature: hook("open", name) -> token; hook("close", name, token).
_BOUNDARY_HOOK = None


def set_boundary_hook(fn) -> None:
    global _BOUNDARY_HOOK
    _BOUNDARY_HOOK = fn


# request-correlation provider (obs/telemetry.py): answers the calling
# context's request ID (or None). A settable slot keeps this module
# stdlib-only; when installed, every recorded span is stamped with a
# `request_id` attr automatically, so a request's whole subtree —
# admission, queue wait, coalesced dispatch, reply — is greppable by
# one ID in any exported trace.
_RID_PROVIDER = None

# dropped-span counter hook (utils/trace.py installs one that feeds
# COUNTERS "spans_dropped_total" and the trace notes): truncation must
# be observable wherever the spans end up
_DROP_HOOK = None


def set_request_id_provider(fn) -> None:
    global _RID_PROVIDER
    _RID_PROVIDER = fn


def set_drop_hook(fn) -> None:
    global _DROP_HOOK
    _DROP_HOOK = fn


def _count_drop(n: int = 1) -> None:
    hook = _DROP_HOOK
    if hook is None:
        return
    try:
        hook(n)
    except Exception:  # noqa: BLE001,S110 - drop accounting must never fail the traced work
        pass


@dataclass
class SpanRecord:
    """One closed span. Times are seconds relative to the recorder's
    enable() epoch (perf_counter domain)."""

    span_id: int
    parent_id: Optional[int]
    name: str
    t0: float
    t1: float
    tid: int
    attrs: dict = field(default_factory=dict)

    @property
    def duration(self) -> float:
        return self.t1 - self.t0

    def as_dict(self) -> dict:
        out = {
            "kind": "span",
            "id": self.span_id,
            "parent": self.parent_id,
            "name": self.name,
            "t0": round(self.t0, 9),
            "t1": round(self.t1, 9),
            "tid": self.tid,
        }
        if self.attrs:
            out["attrs"] = self.attrs
        return out


class JsonlSink:
    """Streaming JSONL span export with the journal's append
    discipline (runtime/journal.py): one line per record, flushed and
    fsync'd per append, header line first — a crash keeps every span
    that finished before it, and a torn final line is the only possible
    damage."""

    def __init__(self, path: str):
        self.path = path
        # own lock, NOT the recorder's: the fsync must never run under
        # the process-wide span lock (it would serialize every thread's
        # span close behind disk latency — see Recorder.span)
        self._lock = threading.Lock()
        self._f = open(path, "w", encoding="utf-8")
        self._emit(
            {
                "kind": "header",
                "version": SPAN_SCHEMA_VERSION,
                "pid": os.getpid(),
                "clock": "perf_counter-relative-seconds",
            }
        )

    # audited: this lock exists ONLY to keep concurrent appends'
    # write+flush+fsync sequences whole (torn lines are worse than
    # queueing); it is single-purpose, leaf in the lock order, and the
    # recorder deliberately never holds its own lock across emit()
    def _emit(self, rec: dict):  # simonlint: disable=CONC002
        with self._lock:
            if self._f is None:  # closed concurrently (recorder disable)
                return
            self._f.write(json.dumps(rec, separators=(",", ":")) + "\n")
            self._f.flush()
            os.fsync(self._f.fileno())

    def emit(self, rec: SpanRecord):
        self._emit(rec.as_dict())

    def close(self):
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


class Recorder:
    """Process-wide span store. enable()/disable() bracket a recording
    session; spans closing while disabled are dropped silently (a
    thread may still be inside a span when the CLI disables at exit).

    Two overflow postures past ``max_spans``:

    - cap mode (``ring=False``, the one-shot CLI default): newest
      spans drop, the recorded prefix stays intact — a bounded trace
      of how the run STARTED;
    - ring mode (``ring=True``, the resident daemons): the OLDEST span
      is overwritten — a continuous flight recorder whose window is
      always the most recent activity, which is what a live
      ``/debug/dump`` needs.

    Either way every lost span increments ``dropped`` and fires the
    drop hook (COUNTERS ``spans_dropped_total`` + a trace note), so a
    truncated trace is detectable, never silent."""

    # default bound so a pathological run cannot grow the recorder
    # without limit; daemons arm smaller rings (obs/telemetry.py)
    MAX_SPANS = 250_000

    def __init__(self):
        self.enabled = False
        self.ring = False
        self.max_spans = self.MAX_SPANS
        self._lock = threading.Lock()
        self._spans: List[SpanRecord] = []
        self._ring_pos = 0
        self._next_id = 1
        self.dropped = 0
        self._epoch = 0.0
        self._sink: Optional[JsonlSink] = None

    def enable(self, sink: Optional[JsonlSink] = None):
        with self._lock:
            self._spans = []
            self._ring_pos = 0
            self._next_id = 1
            self.dropped = 0
            self._epoch = time.perf_counter()
            self._sink = sink
            self.enabled = True

    def disable(self):
        with self._lock:
            self.enabled = False
            if self._sink is not None:
                self._sink.close()
                self._sink = None

    def reset(self):
        with self._lock:
            self._spans = []
            self._ring_pos = 0
            self._next_id = 1
            self.dropped = 0

    @property
    def count(self) -> int:
        """Resident span count, O(1) — /metrics and snapshot polls
        must not copy a 100k-span ring just to report its size."""
        with self._lock:
            return len(self._spans)

    def snapshot(self) -> List[SpanRecord]:
        """Recorded spans, oldest first (ring rotation unwound)."""
        with self._lock:
            if self.ring and len(self._spans) == self.max_spans:
                pos = self._ring_pos
                return self._spans[pos:] + self._spans[:pos]
            return list(self._spans)

    # audited: every caller invokes this WITH self._lock held (span's
    # close path and record_span both take it around the call); the
    # helper exists so the cap-vs-ring posture lives in one place
    def _store(self, rec: SpanRecord) -> Optional[JsonlSink]:  # simonlint: disable=CONC001
        """Append one closed span — caller MUST hold self._lock (cap
        vs ring posture); returns the sink to emit to (outside the
        lock), or None. Caller fires the drop hook when `dropped`
        advanced."""
        if len(self._spans) < self.max_spans:
            self._spans.append(rec)
        elif self.ring:
            self._spans[self._ring_pos] = rec
            self._ring_pos = (self._ring_pos + 1) % self.max_spans
            self.dropped += 1
        else:
            self.dropped += 1
            return None
        return self._sink

    @contextmanager
    def span(self, name: str, **attrs):
        """Record the enclosed block as a span under the context's
        current parent. Yields the span id (None when disabled)."""
        # unlocked fast-path read: `enabled` flips rarely (CLI
        # enable/disable brackets) and a stale read only drops or
        # records one span at the boundary — the close path re-checks
        # under the lock before appending
        if not self.enabled:  # simonlint: disable=CONC001
            yield None
            return
        with self._lock:
            sid = self._next_id
            self._next_id += 1
            # epoch snapshot rides the id-allocation lock: enable()
            # resets it concurrently, and t0/t1 must subtract the SAME
            # epoch or the span's duration is garbage
            epoch = self._epoch
        parent = _parent.get()
        rid_fn = _RID_PROVIDER
        if rid_fn is not None and "request_id" not in attrs:
            try:
                rid = rid_fn()
            except Exception:  # noqa: BLE001 - correlation must never fail the traced work
                rid = None
            if rid is not None:
                attrs["request_id"] = rid
        token = _parent.set(sid)
        hook = _BOUNDARY_HOOK if parent is None else None
        hook_token = None
        if hook is not None:
            try:
                hook_token = hook("open", name)
            except Exception:  # noqa: BLE001 - observability must never fail the traced work
                hook = None
        t0 = time.perf_counter()
        try:
            yield sid
        finally:
            t1 = time.perf_counter()
            _parent.reset(token)
            if hook is not None:
                try:
                    hook("close", name, hook_token)
                except Exception:  # noqa: BLE001,S110 - watermark bookkeeping must never fail (or mask an exception from) the traced work; the open-side hook already disarms itself on error
                    pass
            rec = SpanRecord(
                span_id=sid,
                parent_id=parent,
                name=name,
                t0=t0 - epoch,
                t1=t1 - epoch,
                tid=threading.get_ident(),
                attrs=attrs,
            )
            with self._lock:
                # disabled mid-span: drop, don't resurrect. NOT an
                # early return — a `return` inside this finally would
                # swallow any in-flight exception from the span body
                # (contextlib reads the generator's clean exit as
                # "exception suppressed")
                if self.enabled:
                    before = self.dropped
                    sink = self._store(rec)
                    dropped = self.dropped - before
                else:
                    sink, dropped = None, 0
            if dropped:
                _count_drop(dropped)
            # sink I/O (write+flush+fsync) happens OUTSIDE the recorder
            # lock: concurrent threads closing spans must not queue
            # behind each other's disk syncs. The sink's own lock keeps
            # lines whole; a close() racing in from disable() makes the
            # emit a no-op (the span stays in the in-memory snapshot)
            if sink is not None:
                sink.emit(rec)

    def record_span(
        self,
        name: str,
        t0: float,
        t1: float,
        parent_id: Optional[int] = None,
        tid: Optional[int] = None,
        **attrs,
    ) -> Optional[int]:
        """Append one span with EXPLICIT perf_counter timestamps —
        how the coalescer synthesizes per-request subtrees (queue_wait
        / evaluate) from timings it already measured, instead of
        wrapping work that happened for a whole batch at once. Returns
        the span id (None when disabled) so children can attach."""
        # unlocked fast-path read, same contract as span(): a stale
        # read at the enable/disable boundary loses at most one span,
        # and the store path re-checks under the lock
        if not self.enabled:  # simonlint: disable=CONC001
            return None
        rid_fn = _RID_PROVIDER
        if rid_fn is not None and "request_id" not in attrs:
            try:
                rid = rid_fn()
            except Exception:  # noqa: BLE001 - correlation must never fail the recording
                rid = None
            if rid is not None:
                attrs["request_id"] = rid
        with self._lock:
            if not self.enabled:
                return None
            sid = self._next_id
            self._next_id += 1
            epoch = self._epoch
            rec = SpanRecord(
                span_id=sid,
                parent_id=parent_id,
                name=name,
                t0=t0 - epoch,
                t1=t1 - epoch,
                tid=tid if tid is not None else threading.get_ident(),
                attrs=attrs,
            )
            before = self.dropped
            sink = self._store(rec)
            dropped = self.dropped - before
        if dropped:
            _count_drop(dropped)
        if sink is not None:
            sink.emit(rec)
        return sid

RECORDER = Recorder()


def span(name: str, **attrs):
    """Module-level convenience: ``with span("apply/plan"): ...``"""
    return RECORDER.span(name, **attrs)


def traced(name: Optional[str] = None, **attrs):
    """Decorator form: record every call of the function as a span."""

    def deco(fn):
        import functools

        label = name or fn.__qualname__

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if not RECORDER.enabled:
                return fn(*args, **kwargs)
            with RECORDER.span(label, **attrs):
                return fn(*args, **kwargs)

        return wrapper

    return deco


# ------------------------------------------------------------- exporters


def export_chrome_trace(path: str, spans: Optional[List[SpanRecord]] = None):
    """Write the recorded spans as Chrome trace-event JSON (the
    ``traceEvents`` array of complete "X" events), loadable in Perfetto
    or chrome://tracing. Same-thread events nest by time containment,
    which the parent-tracked spans satisfy by construction."""
    if spans is None:
        spans = RECORDER.snapshot()
    events = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": os.getpid(),
            "tid": 0,
            "args": {"name": "simon"},
        }
    ]
    for s in spans:
        args = {"span_id": s.span_id}
        if s.parent_id is not None:
            args["parent_id"] = s.parent_id
        args.update(s.attrs)
        events.append(
            {
                "name": s.name,
                "cat": "simon",
                "ph": "X",
                "ts": round(s.t0 * 1e6, 3),
                "dur": round(s.duration * 1e6, 3),
                "pid": os.getpid(),
                "tid": s.tid,
                "args": args,
            }
        )
    doc = {"traceEvents": events, "displayTimeUnit": "ms"}
    observatory = observatory_block()
    if observatory:
        doc["simonObservatory"] = observatory
    if RECORDER.dropped:
        # truncation is part of the artifact: validate_trace flags it,
        # and a reader knows the forest is a window, not the whole run
        doc["simonSpansDropped"] = {
            "dropped": RECORDER.dropped,
            "mode": "ring" if RECORDER.ring else "cap",
            "maxSpans": RECORDER.max_spans,
        }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f)


def observatory_block() -> dict:
    """The compiled-cost / memory-ledger / histogram snapshot attached
    to trace artifacts and merged into bench obs lines (both validated
    by tools/validate_trace.py). Lazy sibling imports keep this module
    stdlib-only at load time; an unimportable observatory (partial
    install) degrades to {} rather than failing the trace export."""
    try:
        from .costs import COSTS
        from .histo import HISTOS
        from .ledger import LEDGER
    except Exception:  # noqa: BLE001 - trace export must survive a broken sibling import
        return {}
    out = {}
    costs = COSTS.summary()
    if costs:
        out["costs"] = costs
    ledger = LEDGER.summary()
    if ledger.get("samples"):
        out["ledger"] = ledger
    # buckets included: tools/validate_trace.py cross-checks bucket
    # sums against counts, an arithmetic gate that is dead without them
    histos = HISTOS.summary(with_buckets=True)
    if histos:
        out["histograms"] = histos
    # per-device ledger rows at top level (PR-13 mesh accounting): a
    # mesh-scan bench artifact must record device IMBALANCE, and the
    # tightest device is invisible inside process-total ledger sums —
    # validate_trace.py gates the rows' shape (--require-per-device)
    per_device = LEDGER.device_summary()
    if per_device:
        out["per_device"] = per_device
    # incremental re-simulation + persistent artifact-store counters
    # (incremental/: ROADMAP item 3) — suffix_fraction and hit_rate
    # are doctor-gated dimensions (obs/doctor.py)
    try:
        from ..incremental.store import aot_store_block, incremental_block
    except ImportError:  # pragma: no cover - partial install
        aot_store_block = incremental_block = None
    if incremental_block is not None:
        inc = incremental_block()
        if inc:
            out["incremental"] = inc
        store = aot_store_block()
        if store:
            out["aot_store"] = store
    if RECORDER.dropped:
        out["spans_dropped"] = RECORDER.dropped
    return out


def export_jsonl(path: str, spans: Optional[List[SpanRecord]] = None):
    """One-shot JSONL dump of recorded spans (the streaming form is
    ``JsonlSink`` passed to ``Recorder.enable``)."""
    if spans is None:
        spans = RECORDER.snapshot()
    sink = JsonlSink(path)
    try:
        for s in spans:
            sink.emit(s)
    finally:
        sink.close()


# ------------------------------------------------------------- analysis


def nesting_depth(spans: List[SpanRecord]) -> int:
    """Maximum depth of the span forest (roots are depth 1)."""
    by_id = {s.span_id: s for s in spans}
    best = 0
    for s in spans:
        d, cur = 1, s
        while cur.parent_id is not None and cur.parent_id in by_id:
            cur = by_id[cur.parent_id]
            d += 1
        best = max(best, d)
    return best


def exclusive_times(spans: List[SpanRecord]) -> Dict[str, float]:
    """Per-span-NAME exclusive wall-clock: each span's duration minus
    the durations of its direct children (self-time), summed per name.
    The bench's "top spans" attribution reads this — a parent phase
    that merely contains an expensive child stops looking expensive."""
    child_sum: Dict[int, float] = {}
    for s in spans:
        if s.parent_id is not None:
            child_sum[s.parent_id] = child_sum.get(s.parent_id, 0.0) + s.duration
    out: Dict[str, float] = {}
    for s in spans:
        excl = max(s.duration - child_sum.get(s.span_id, 0.0), 0.0)
        out[s.name] = out.get(s.name, 0.0) + excl
    return out


def top_spans(spans: List[SpanRecord], k: int = 5) -> List[dict]:
    """Top-k span names by exclusive time, for machine-readable
    reports (bench metrics, docs)."""
    excl = exclusive_times(spans)
    ranked = sorted(excl.items(), key=lambda kv: -kv[1])[:k]
    return [
        {"name": name, "exclusive_ms": round(sec * 1e3, 3)}
        for name, sec in ranked
    ]


# cached hot-span table for /metrics: with the daemons' always-armed
# ring, an uncached read would copy the (up to 100k-span) ring and walk
# it on EVERY scrape — stalling concurrent span closes behind the
# recorder lock for the copy's duration. The cache is a benign-race
# dict: worst case two scrapes both recompute one window.
_TOP_CACHE = {"t": -1e18, "top": []}
TOP_SPANS_CACHE_S = 30.0


def top_spans_cached(k: int = 5, max_age_s: float = TOP_SPANS_CACHE_S) -> List[dict]:
    """`top_spans` over the live recorder, recomputed at most once per
    ``max_age_s`` — the /metrics exposition's bounded-cost accessor."""
    now = time.monotonic()
    if now - _TOP_CACHE["t"] < max_age_s:
        return _TOP_CACHE["top"]
    top = top_spans(RECORDER.snapshot(), k)
    _TOP_CACHE["top"] = top
    _TOP_CACHE["t"] = now
    return top
