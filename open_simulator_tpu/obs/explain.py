"""Per-pod placement explanations — "why did pod X land on node Y
(or fail)".

Upstream open-simulator's whole value proposition is an *explained*
placement report; the device-batched reimplementation computes per-node
feasibility and scores and then throws that signal away except for a
single failure reason. This recorder keeps it, on demand:

- serial path: ``Oracle._find_feasible`` records every node's filter
  verdict (the exact reason string + framework status code) and
  ``Oracle._select_and_bind`` records the weighted score vector over
  feasible nodes plus the chosen node — the same walk that made the
  decision, so the explanation can never disagree with it.
- scan path: committed placements replay onto the oracle IN ORDER
  (the engine-replay contract, scheduler/engine.py), so oracle state
  at a pod's replay step equals the serial cycle's state at that step;
  ``capture()`` runs the filter + score walk against that state at
  commit time and records the same data. Failed pods already take a
  serial ``_find_feasible`` pass for their reason — the hook rides it.
- provenance: the tiered priority engine annotates explanations with
  the scan round, tier count, and serial-escape events (PR-3
  machinery), so "this pod went through the serial preemption cycle in
  round 3" is part of the record.

Everything is guarded by ``EXPLAIN.enabled`` (one attribute read on
the hot paths) so a run without ``--explain`` pays nothing.

Stdlib-only at import time: the oracle imports this module at load.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

# per-pod record cap: explanations are for humans; a 100k-pod batch
# with thousands of failures must not hold 100k score vectors
MAX_RECORDS = 200
# per-node verdict rows kept verbatim per pod; larger clusters keep
# counts per reason plus the first rows (the report's aggregate message
# is computed from the full counts either way)
MAX_VERDICT_ROWS = 64


@dataclass
class PodExplanation:
    """Everything recorded about one pod's scheduling decision."""

    namespace: str
    name: str
    # (node, reason-or-None-when-feasible, status code) in node order,
    # truncated at MAX_VERDICT_ROWS (truncated_nodes counts the rest)
    verdicts: List[Tuple[str, Optional[str], str]] = field(default_factory=list)
    truncated_nodes: int = 0
    # full aggregate: reason string -> node count (drives the failure
    # message, identical to the report's)
    reason_counts: Dict[str, int] = field(default_factory=dict)
    feasible_count: int = 0
    total_nodes: int = 0
    # (node, weighted score) for feasible nodes, same truncation
    scores: List[Tuple[str, int]] = field(default_factory=list)
    chosen_node: Optional[str] = None
    # provenance: engine path, scan round, tier count, escape/preemption
    provenance: Dict[str, object] = field(default_factory=dict)

    @property
    def key(self) -> Tuple[str, str]:
        return (self.namespace, self.name)

    def failure_message(self) -> str:
        """The same aggregate message the report carries for an
        unschedulable pod (Oracle._failure_message formula) — computed
        from the recorded per-node verdicts, so the explain block and
        the report can never name different failure reasons."""
        parts = ", ".join(
            f"{n} {r}" for r, n in sorted(self.reason_counts.items())
        )
        total = sum(self.reason_counts.values())
        return (
            f"failed to schedule pod ({self.namespace}/{self.name}): "
            f"Unschedulable: 0/{total} nodes are available: {parts}."
        )

    def as_dict(self) -> dict:
        out = {
            "namespace": self.namespace,
            "name": self.name,
            "scheduled": self.chosen_node is not None,
            "chosenNode": self.chosen_node,
            "feasibleNodes": self.feasible_count,
            "totalNodes": self.total_nodes,
            "verdicts": [
                {"node": n, "verdict": r or "feasible", "code": c}
                for n, r, c in self.verdicts
            ],
            "truncatedNodes": self.truncated_nodes,
        }
        if self.chosen_node is None and self.reason_counts:
            out["reason"] = self.failure_message()
            out["reasonCounts"] = dict(self.reason_counts)
        if self.scores:
            out["scores"] = [{"node": n, "score": s} for n, s in self.scores]
        if self.provenance:
            out["provenance"] = dict(self.provenance)
            # preemption-victim provenance as a first-class structured
            # block: a pod scheduled after an escape round names the
            # node it preempted on and its namespace-qualified victims,
            # so downstream consumers (the shadow auditor's
            # ordering-divergence class) can cite them without parsing
            # the free-form provenance map
            if "preempted" in self.provenance or "preemption_node" in self.provenance:
                out["preemption"] = {
                    "node": self.provenance.get("preemption_node"),
                    "victims": list(self.provenance.get("preempted") or []),
                }
        return out


class ExplainRecorder:
    """Process-wide explanation store. ``enable(target)`` arms it: a
    target of None records UNSCHEDULABLE pods (capped at MAX_RECORDS,
    first-come) plus preemption/escape provenance; a pod name (``name``
    or ``namespace/name``) records that pod's full decision — filter
    verdicts AND the score vector — even when it schedules. ``enabled``
    is a plain attribute so hot-path guards are one read."""

    def __init__(self):
        self.enabled = False
        self.target: Optional[str] = None
        self._lock = threading.Lock()
        self._records: Dict[Tuple[str, str], PodExplanation] = {}
        self._order: List[Tuple[str, str]] = []
        self.dropped = 0
        self._dropped_keys: set = set()
        # round/tier context stamped by the tiered scan engine
        self._context: Dict[str, object] = {}

    # -- lifecycle ----------------------------------------------------------

    def enable(self, target: Optional[str] = None):
        with self._lock:
            self._records = {}
            self._order = []
            self.dropped = 0
            self._dropped_keys = set()
            self._context = {}
            self.target = target or None
            self.enabled = True

    def disable(self):
        with self._lock:
            self.enabled = False
            self.target = None
            self._context = {}

    def reset(self):
        with self._lock:
            self._records = {}
            self._order = []
            self.dropped = 0
            self._dropped_keys = set()
            self._context = {}

    def snapshot(self) -> List[PodExplanation]:
        with self._lock:
            return [self._records[k] for k in self._order]

    # -- matching -----------------------------------------------------------

    @staticmethod
    def _pod_key(pod: dict) -> Tuple[str, str]:
        meta = pod.get("metadata") or {}
        return (meta.get("namespace") or "default", meta.get("name", ""))

    # unlocked `target` reads: set once by enable() before any hook
    # fires and only cleared by disable(); a stale read at the
    # boundary records or skips one pod, never corrupts state
    def wants(self, pod: dict) -> bool:  # simonlint: disable=CONC001
        """Callers guard with ``EXPLAIN.enabled and EXPLAIN.wants(pod)``
        so the disabled path never reaches this call."""
        if self.target is None:
            return True
        ns, name = self._pod_key(pod)
        return self.target == name or self.target == f"{ns}/{name}"

    def _note_dropped(self, key) -> None:  # simonlint: disable=CONC001
        """Caller holds self._lock. One accounting scheme everywhere:
        `dropped` is the count of UNIQUE pods the cap turned away
        (bounded key set so a pathological run cannot grow it)."""
        if len(self._dropped_keys) < (1 << 16):
            self._dropped_keys.add(key)
        self.dropped = len(self._dropped_keys)

    # unlocked `target` read: same boundary-staleness argument as wants()
    def should_record(self, pod: dict) -> bool:  # simonlint: disable=CONC001
        """``wants`` plus the record cap, checked BEFORE the caller
        collects per-node data: once the untargeted recorder is full,
        the hooks stop paying the O(nodes) verdict collection for pods
        that would only be dropped anyway."""
        if not self.wants(pod):
            return False
        if self.target is None:
            key = self._pod_key(pod)
            with self._lock:
                if len(self._records) >= MAX_RECORDS and key not in self._records:
                    self._note_dropped(key)
                    return False
        return True

    def _get(self, pod: dict, create: bool = True) -> Optional[PodExplanation]:  # simonlint: disable=CONC001
        """Caller holds self._lock."""
        key = self._pod_key(pod)
        rec = self._records.get(key)
        if rec is None:
            if not create:
                return None
            if self.target is None and len(self._records) >= MAX_RECORDS:
                self._note_dropped(key)
                return None
            rec = PodExplanation(namespace=key[0], name=key[1])
            self._records[key] = rec
            self._order.append(key)
        return rec

    # -- context (stamped by the scan engine) -------------------------------

    def set_context(self, **ctx):
        """Round/tier provenance merged into every record created while
        the context is in force (the tiered scan sets round=N per
        dispatch round; the replay window inherits it)."""
        with self._lock:
            self._context.update(ctx)

    def clear_context(self):
        with self._lock:
            self._context = {}

    # -- recording hooks ----------------------------------------------------

    def record_filter(self, pod: dict, verdicts, feasible_count: int):
        """From Oracle._find_feasible (or capture()): per-node verdict
        rows ``(node_name, reason_or_None, code)`` in node order.

        Untargeted mode creates records only for pods with NO feasible
        node (the failures the report will name) — a 100k-pod serial
        run must not fill the record cap with its first 200 successes
        and then drop the failures the flag exists to explain. A pod
        that already has a record (an earlier failing pass, a
        preemption retry) keeps updating it."""
        with self._lock:
            create = self.target is not None or feasible_count == 0
            rec = self._get(pod, create=create)
            if rec is None:
                return
            rec.total_nodes = len(verdicts)
            rec.feasible_count = feasible_count
            rec.verdicts = list(verdicts[:MAX_VERDICT_ROWS])
            rec.truncated_nodes = max(len(verdicts) - MAX_VERDICT_ROWS, 0)
            counts: Dict[str, int] = {}
            for _n, reason, _c in verdicts:
                if reason is not None:
                    counts[reason] = counts.get(reason, 0) + 1
            rec.reason_counts = counts
            if self._context:
                rec.provenance.update(self._context)

    def record_scores(self, pod: dict, scores, chosen: Optional[str]):
        """From Oracle._select_and_bind (or capture()): ``(node_name,
        weighted_score)`` over feasible nodes + the selected node.
        Untargeted mode only updates pods already on record (a failed
        pod rescued by preemption gets its final node stamped); full
        score vectors for scheduled pods are targeted-only."""
        with self._lock:
            rec = self._get(pod, create=self.target is not None)
            if rec is None:
                return
            rec.scores = list(scores[:MAX_VERDICT_ROWS])
            rec.chosen_node = chosen
            if self._context:
                rec.provenance.update(self._context)

    def annotate(self, pod: dict, **prov):
        """Merge provenance facts (escape round, preemption victims,
        engine path) into a pod's record, creating it if needed."""
        with self._lock:
            rec = self._get(pod)
            if rec is None:
                return
            rec.provenance.update(prov)

    # -- scan-path capture --------------------------------------------------

    def capture(self, oracle, pod: dict, node_idx: Optional[int]):
        """Record a scan-committed pod's explanation at replay-commit
        time: oracle state here equals the serial cycle's state at this
        pod's step (commits replay in order), so the filter verdicts
        and scores are exactly what the serial scheduler would have
        seen. ``node_idx`` is the scan's placement (None = failed; the
        failure path's own ``_find_feasible`` call records verdicts)."""
        feasible, _reasons, _codes = oracle._find_feasible(pod)
        # ^ the _find_feasible hook recorded the verdict rows
        if node_idx is None or not feasible:
            return
        scores = oracle._prioritize(pod, feasible)
        chosen = oracle.nodes[int(node_idx)].name
        self.record_scores(
            pod, [(ns.name, sc) for ns, sc in zip(feasible, scores)], chosen
        )
        self.annotate(pod, engine="scan-replay")


EXPLAIN = ExplainRecorder()


# ------------------------------------------------------------- rendering


def render_explanations(recorder: Optional[ExplainRecorder] = None) -> str:
    """Human-readable explain block (appended to the apply report).
    Imports the table renderer lazily — report imports models, and this
    module must stay import-light for the oracle."""
    from ..apply.report import render_table

    recorder = recorder or EXPLAIN
    records = recorder.snapshot()
    if not records:
        return (
            "Placement Explanations\n(no pods matched --explain"
            + (f" {recorder.target!r}" if recorder.target else "")
            + ")"
        )
    out = ["Placement Explanations"]
    for rec in records:
        out.append("")
        if rec.chosen_node is not None:
            head = (
                f"pod {rec.namespace}/{rec.name}: scheduled on "
                f"{rec.chosen_node} ({rec.feasible_count}/{rec.total_nodes} "
                "nodes feasible)"
            )
        else:
            head = f"pod {rec.namespace}/{rec.name}: {rec.failure_message()}"
        out.append(head)
        if rec.provenance:
            prov = ", ".join(f"{k}={v}" for k, v in sorted(rec.provenance.items()))
            out.append(f"  provenance: {prov}")
        score_of = dict(rec.scores)
        rows = []
        for node, reason, _code in rec.verdicts:
            verdict = "feasible" if reason is None else reason
            score = score_of.get(node)
            rows.append([node, verdict, "" if score is None else str(score)])
        if rows:
            out.append(render_table(["Node", "Filter Verdict", "Score"], rows))
        if rec.truncated_nodes:
            out.append(
                f"  ... {rec.truncated_nodes} more node(s) omitted "
                f"(per-pod cap {MAX_VERDICT_ROWS}; aggregate counts above "
                "cover all nodes)"
            )
    if recorder.dropped:
        out.append("")
        out.append(
            f"({recorder.dropped} additional pod(s) not recorded — "
            f"record cap {MAX_RECORDS}; pass --explain POD to target one)"
        )
    return "\n".join(out)


def explanations_dict(recorder: Optional[ExplainRecorder] = None) -> List[dict]:
    recorder = recorder or EXPLAIN
    return [rec.as_dict() for rec in recorder.snapshot()]
