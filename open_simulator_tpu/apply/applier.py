"""The capacity planner ("Applier").

Mirrors pkg/apply/apply.go:
- Simon CR config parsing (apiVersion simon/v1alpha1, kind Config;
  pkg/api/v1alpha1/types.go) with path validation (apply.go:249-286)
- cluster from a customConfig dir or from a live cluster via kubeConfig
  (models/kubeclient.py, CreateClusterResourceFromClient semantics)
- app list: plain YAML dirs or Helm charts (pkg/chart rendering)
- the capacity loop (apply.go:186-239): instead of interactively asking
  the user for a node count per iteration, all candidate counts up to
  MaxNumNewNode are evaluated via bisection probes over ONE encoded
  padded cluster (parallel/sweep.py). The reference's ask-per-step
  shell lives in apply/interactive.py (`simon apply -i`), driving the
  same probe machinery one user guess at a time
- utilization caps from MaxCPU/MaxMemory/MaxVG env vars
  (satisfyResourceSetting, apply.go:611-697)
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import List, Optional

import yaml

from ..models import storage as stor
from ..models import workloads as wl
from ..models.chart import process_chart
from ..models.validation import InputError
from ..runtime.errors import ConformanceError
from ..models.cluster import cluster_from_config_dir, match_and_set_local_storage
from ..models.decode import (
    ResourceTypes,
    decode_yaml_content,
    load_directory,
    yaml_content_from_directory,
)
from ..scheduler.core import AppResource, SimulateResult, simulate
from ..utils.memo import clear_all_memos
from .report import report

MAX_NUM_NEW_NODE = wl.MAX_NUM_NEW_NODE


@dataclass
class AppInfo:
    name: str
    path: str
    chart: bool = False


@dataclass
class SimonConfig:
    custom_cluster: Optional[str] = None
    kube_config: Optional[str] = None
    app_list: List[AppInfo] = field(default_factory=list)
    new_node: Optional[str] = None

    @classmethod
    def from_file(cls, path: str) -> "SimonConfig":
        with open(path) as f:
            doc = yaml.safe_load(f)
        if not isinstance(doc, dict) or doc.get("kind") != "Config":
            raise InputError(f"{path}: not a simon Config object")
        spec = doc.get("spec") or {}
        cluster = spec.get("cluster") or {}
        apps = [
            AppInfo(
                name=a.get("name", ""),
                path=a.get("path", ""),
                chart=bool(a.get("chart", False)),
            )
            for a in spec.get("appList") or []
        ]
        return cls(
            custom_cluster=cluster.get("customConfig"),
            kube_config=cluster.get("kubeConfig"),
            app_list=apps,
            new_node=spec.get("newNode"),
        )

    def validate(self):
        """Path validation (apply.go:249-286)."""
        if bool(self.custom_cluster) == bool(self.kube_config):
            raise InputError(
                "only one of values of both kubeConfig and customConfig must exist"
            )
        if self.kube_config and not os.path.exists(os.path.expanduser(self.kube_config)):
            raise InputError(f"invalid path of kubeconfig: {self.kube_config}")
        if self.custom_cluster and not os.path.exists(self.custom_cluster):
            raise InputError(f"invalid path of customConfig: {self.custom_cluster}")
        if self.new_node and not os.path.exists(self.new_node):
            raise InputError(f"invalid path of newNode: {self.new_node}")
        for app in self.app_list:
            if not os.path.exists(app.path):
                raise InputError(f"invalid path of {app.name} app: {app.path}")


def _resource_caps():
    """MaxCPU/MaxMemory/MaxVG env caps, clamped to [0,100] like
    apply.go:611-641."""

    def cap(env):
        raw = os.environ.get(env, "")
        if not raw:
            return 100
        v = int(raw)
        return 100 if v > 100 or v < 0 else v

    return cap("MaxCPU"), cap("MaxMemory"), cap("MaxVG")


def satisfy_resource_setting(node_statuses, oracle=None) -> tuple:
    """satisfyResourceSetting (apply.go:611-697). With `oracle` (the
    replay oracle whose NodeStates back these statuses), per-node
    floor totals come from the commit-time aggregates instead of a
    100k-pod re-walk."""
    from ..models import requests as req
    from .report import _pod_req_summary, matched_node_state, node_state_index

    max_cpu, max_mem, max_vg = _resource_caps()
    total_alloc_cpu = total_alloc_mem = 0
    total_used_cpu = total_used_mem = 0
    vg_cap = vg_req = 0
    by_node = node_state_index(oracle)
    for status in node_statuses:
        node = status.node
        total_alloc_cpu += req.node_alloc_milli_cpu(node)
        total_alloc_mem += req.node_alloc_int(node, req.MEMORY)
        state = matched_node_state(by_node, status)
        if state is not None:
            total_used_cpu += state.req_floor_mcpu
            total_used_mem += state.req_floor_mem
        else:
            for pod in status.pods:
                mcpu, mem = _pod_req_summary(pod)
                total_used_cpu += mcpu
                total_used_mem += mem
        storage = stor.parse_node_storage(node)
        if storage:
            for vg in storage.vgs:
                vg_cap += vg.capacity
                vg_req += vg.requested
    cpu_rate = int(total_used_cpu / total_alloc_cpu * 100) if total_alloc_cpu else 0
    mem_rate = int(total_used_mem / total_alloc_mem * 100) if total_alloc_mem else 0
    if cpu_rate > max_cpu:
        return False, (
            f"the average occupancy rate({cpu_rate}%) of cpu goes beyond the env setting({max_cpu}%)"
        )
    if mem_rate > max_mem:
        return False, (
            f"the average occupancy rate({mem_rate}%) of memory goes beyond the env setting({max_mem}%)"
        )
    if vg_cap:
        vg_rate = int(vg_req / vg_cap * 100)
        if vg_rate > max_vg:
            return False, (
                f"the average occupancy rate({vg_rate}%) of vg goes beyond the env setting({max_vg}%)"
            )
    return True, ""


@dataclass
class ApplyResult:
    success: bool
    new_node_count: int
    result: Optional[SimulateResult]
    report_text: str = ""
    message: str = ""


MAX_DETAILED_REASONS = 50


def replay_scenario(sweep, count: int, placements):
    """Rebuild host-side oracle state from one capacity scenario's scan
    placements (the first `count` candidate nodes enabled). See
    replay_masked for the general form."""
    return replay_masked(sweep, sweep.node_valid(count), placements)


def replay_masked(sweep, valid, placements):
    """Rebuild host-side oracle state from one masked scenario's scan
    placements (the same binding code the serial path uses — the
    engine-replay contract of scheduler/engine.py), producing the
    SimulateResult for reports. `valid[n]` names the nodes that exist
    in the scenario — a capacity prefix for the planner, an arbitrary
    outage mask for the resilience engine. Returns (result, oracle).

    Exact per-node failure reasons cost a full serial filter pass per
    failed pod (O(nodes) Python), so only the first MAX_DETAILED_REASONS
    failures get them; the rest carry a summary reason. A 100k-pod probe
    with thousands of failures must not take hours to explain itself —
    the caller that needs every reason runs the serial engine."""
    import numpy as np

    from ..obs.explain import EXPLAIN
    from ..scheduler.core import NodeStatus, SimulateResult, UnscheduledPod
    from ..scheduler.engine import build_bulk_tables
    from ..scheduler.oracle import ClassCommitCache, Oracle, simple_commit_mask
    from ..utils.trace import profiled

    if EXPLAIN.enabled:
        EXPLAIN.set_context(engine="capacity-replay")
    valid = np.asarray(valid)
    kept = [i for i in range(len(sweep.oracle.nodes)) if valid[i]]
    nodes = [sweep.oracle.nodes[i].node for i in kept]
    oracle = Oracle(nodes)
    # sweep node index -> local replay index, vectorized (-1 unknown)
    local_of_arr = np.full(len(sweep.oracle.nodes) + 1, -1, dtype=np.int64)
    for local_i, sweep_i in enumerate(kept):
        local_of_arr[sweep_i] = local_i
    # classes with no GPU/storage side effects take a minimal bind
    # (nodeName + phase + NodeInfo accounting) — and contiguous runs of
    # them commit in BULK (oracle.commit_simple_bulk: per-node
    # scatter-add of per-class summary deltas), which the general
    # per-pod walk can't touch: the replay used to be most of the
    # 100k-pod capacity plan's host tail
    batch = sweep.batch
    simple_class = simple_commit_mask(batch, bool(sweep.oracle.extenders))
    field_tbl, ports_of, scalars_of, bulk_ok = build_bulk_tables(
        batch, simple_class
    )
    class_of_pod = np.asarray(batch.class_of_pod, dtype=np.int64)
    had_node_name = np.asarray(sweep.had_node_name, dtype=bool)
    place_arr = np.asarray(placements, dtype=np.int64)
    pods = sweep.pods
    failed = []
    commit_cache = ClassCommitCache()
    with profiled("engine/replay"):
        # event pods (inactive / pinned / failed / side-effect classes)
        # take the exact per-pod path in order; runs between them bulk
        bulk_mask = (
            (place_arr >= 0)
            & ~had_node_name
            & simple_class[class_of_pod]
            & bulk_ok[class_of_pod]
        )
        if EXPLAIN.enabled and EXPLAIN.target is not None:
            # a targeted explained pod leaves the bulk run so its
            # filter/score walk is captured against the oracle state of
            # its own commit step (scheduler/core._replay_window has
            # the same carve-out; failed pods explain regardless)
            want = np.fromiter(
                (EXPLAIN.wants(p) for p in pods), dtype=bool, count=len(pods)
            )
            bulk_mask &= ~want

        def bulk(a, b):
            if b <= a:
                return
            local = local_of_arr[place_arr[a:b]]
            if (local < 0).any():
                # a placement names a node outside this scenario's mask
                # — scan invariant violation; fail loudly with the
                # taxonomy's internal-defect error
                bad = int(place_arr[a:b][local < 0][0])
                raise ConformanceError(
                    f"placement on masked-off node index {bad}"
                )
            # prios=None is exact here: CapacitySweep refuses any
            # priority-bearing pod at construction (PrioritySignalError,
            # parallel/sweep.py) and neither oracle carries priority
            # classes, so every effective priority is provably 0 — the
            # documented commit_simple_bulk fast-path contract
            oracle.commit_simple_bulk(
                pods[a:b],
                local,
                class_of_pod[a:b],
                field_tbl, ports_of, scalars_of,
            )

        prev = 0
        for p_i in np.flatnonzero(~bulk_mask).tolist():
            bulk(prev, p_i)
            prev = p_i + 1
            pod = pods[p_i]
            idx = int(place_arr[p_i])
            if idx == -2:  # inactive in this scenario (disabled-node ds pod)
                continue
            # original pins only: a previous replay may have written
            # nodeName/phase into this shared pod dict — clear those so
            # failure reasons (_find_feasible's NodeName filter) and the
            # reported pod see the pre-bind state
            if not had_node_name[p_i]:
                (pod.get("spec") or {}).pop("nodeName", None)
                (pod.get("status") or {}).pop("phase", None)
                name = None
            else:
                name = (pod.get("spec") or {}).get("nodeName")
            if name:
                if name in oracle.node_index:
                    oracle.place_existing_pod(pod)
                # else dangling: kept in the tracker, never scheduled
                # (reference simulator.go:221-229)
            elif idx < 0:
                if len(failed) < MAX_DETAILED_REASONS or (
                    EXPLAIN.enabled and EXPLAIN.should_record(pod)
                ):
                    # an explained pod past the detailed-reason cap
                    # still gets its serial filter pass (the verdict
                    # hook rides _find_feasible). should_record, not
                    # wants: once the untargeted recorder is full this
                    # must NOT widen the detailed-reason cap to every
                    # failure — that O(nodes) walk per failed pod is
                    # the cliff MAX_DETAILED_REASONS exists to prevent
                    _, reasons, _ = oracle._find_feasible(pod)
                    reason = Oracle._failure_message(pod, reasons)
                else:
                    meta = pod.get("metadata") or {}
                    reason = (
                        f"failed to schedule pod ({meta.get('namespace', 'default')}/"
                        f"{meta.get('name', '')}): Unschedulable: "
                        f"0/{len(nodes)} nodes are available"
                    )
                failed.append(UnscheduledPod(pod=pod, reason=reason))
            else:
                local_i = int(local_of_arr[idx])
                if local_i < 0:
                    # same loud failure as the bulk path: a negative
                    # index would silently wrap to the LAST node
                    raise ConformanceError(
                        f"placement on masked-off node index {idx}"
                    )
                if (
                    EXPLAIN.enabled
                    and EXPLAIN.target is not None
                    and EXPLAIN.wants(pod)
                ):
                    # committed-pod captures are targeted-only (the
                    # untargeted recorder explains failures)
                    EXPLAIN.capture(oracle, pod, local_i)
                if simple_class[class_of_pod[p_i]]:
                    commit_cache.commit(
                        oracle, pod, oracle.nodes[local_i], int(class_of_pod[p_i])
                    )
                else:
                    oracle._reserve_and_bind(pod, oracle.nodes[local_i])
        bulk(prev, len(pods))
    status = [NodeStatus(node=ns.node, pods=list(ns.pods)) for ns in oracle.nodes]
    return SimulateResult(unscheduled_pods=failed, node_status=status), oracle


def plan_fingerprint(cluster, apps, new_node, **flags) -> str:
    """Journal fingerprint of one planning problem: the LOADED inputs
    (cluster objects, expanded app resources, newnode spec) plus every
    flag that shapes the work. A resumed journal must describe exactly
    this problem (runtime/journal.py)."""
    from ..runtime.journal import config_fingerprint

    return config_fingerprint(
        {k: getattr(cluster, k) for k in sorted(vars(cluster))},
        [
            (
                a.name,
                {k: getattr(a.resource, k) for k in sorted(vars(a.resource))},
            )
            for a in apps
        ],
        new_node,
        flags,
    )


def probe_plan(
    cluster,
    apps,
    new_node,
    use_greed: bool = False,
    extended_resources: Optional[List[str]] = None,
    max_count: int = MAX_NUM_NEW_NODE,
    score_weights=None,
    tolerate_failures: int = 0,
    chaos_seed: int = 1,
    chaos_trials: int = 32,
    budget=None,
    journal=None,
) -> ApplyResult:
    """Fast capacity plan: encode the padded cluster once, start at the
    aggregate-resource lower bound, bisect over candidate counts (each
    probe = one masked scan), and replay the winning scan's placements
    into host state for the report — no second full simulation
    (replaces the reference's per-guess re-simulation loop,
    pkg/apply/apply.go:186-239). With `tolerate_failures` > 0 the plan
    additionally escalates until it is N+K survivable
    (resilience/chaos.py raise_plan_to_nplusk). `budget` halts the
    search at safe boundaries with a partial payload (runtime/budget);
    `journal` makes probes and scenario verdicts resumable."""
    import gc

    # the plan allocates millions of short-lived dicts (pod expansion,
    # replay, report rows) but frees almost nothing mid-run — cyclic-GC
    # passes are pure overhead and wall-clock jitter at 100k pods.
    # Pause collection for the duration; one collect at the end.
    gc_was_enabled = gc.isenabled()
    if gc_was_enabled:
        gc.disable()
    try:
        return _probe_plan_inner(
            cluster, apps, new_node, use_greed, extended_resources,
            max_count, score_weights, tolerate_failures, chaos_seed,
            chaos_trials, budget, journal,
        )
    finally:
        clear_all_memos()
        if gc_was_enabled:
            gc.enable()
            gc.collect()


def _capacity_feasible():
    max_cpu, max_mem, max_vg = _resource_caps()

    def feasible(res) -> bool:
        # int-truncate like satisfyResourceSetting (apply.go:680-681)
        return (
            res.unscheduled == 0
            and int(res.cpu_util) <= max_cpu
            and int(res.mem_util) <= max_mem
            and int(res.vg_util) <= max_vg
        )

    return feasible, (max_cpu, max_mem, max_vg)


def _finish_plan(
    sweep, best, max_count, extended_resources, fail_message: str = ""
) -> ApplyResult:
    """Replay the winning probe into host state, re-check the caps on
    real state, and render the report — the tail shared by the
    single-spec plan and the multi-spec what-if."""
    from ..utils.trace import phase

    if best is None:
        res = sweep.probe(max_count)
        result, _ = replay_scenario(sweep, max_count, res.placements)
        message = fail_message or (
            f"{len(result.unscheduled_pods)} pod(s) cannot be scheduled "
            f"even with {max_count} new node(s)"
            if result.unscheduled_pods
            else satisfy_resource_setting(result.node_status)[1]
        )
        return ApplyResult(
            success=False, new_node_count=max_count, result=result, message=message
        )
    with phase("apply/replay"):
        result, replay_oracle = replay_scenario(sweep, best.count, best.placements)
    # authoritative host-side check of the caps on real state
    ok, reason = satisfy_resource_setting(result.node_status, oracle=replay_oracle)
    if result.unscheduled_pods or not ok:  # pragma: no cover - defensive
        raise ConformanceError(
            "probe replay disagreed with scan: "
            + (reason or f"{len(result.unscheduled_pods)} unscheduled")
        )
    with phase("apply/report"):
        report_text = report(
            result.node_status, extended_resources or [], oracle=replay_oracle
        )
    return ApplyResult(
        success=True,
        new_node_count=best.count,
        result=result,
        report_text=report_text,
    )


def _probe_plan_inner(
    cluster, apps, new_node, use_greed, extended_resources,
    max_count, score_weights, tolerate_failures=0, chaos_seed=1,
    chaos_trials=32, budget=None, journal=None,
):
    from ..parallel.sweep import CapacitySweep
    from ..utils.trace import phase

    sweep = CapacitySweep(
        cluster,
        apps,
        new_node,
        max_count,
        use_greed=use_greed,
        score_weights=score_weights,
    )
    if journal is not None:
        sweep.attach_journal(journal)
    feasible, (max_cpu, max_mem, max_vg) = _capacity_feasible()
    with phase("apply/lower-bound"):
        start = sweep.lower_bound(max_cpu, max_mem, max_vg)
    with phase("apply/probe-search"):
        best = sweep.find_min_count(feasible, start=start, budget=budget)
    fail_message = ""
    if best is not None and tolerate_failures > 0:
        from ..resilience.chaos import raise_plan_to_nplusk

        with phase("apply/nplusk"):
            best, _chaos = raise_plan_to_nplusk(
                sweep,
                best,
                feasible,
                tolerate_failures,
                seed=chaos_seed,
                trials=chaos_trials,
                budget=budget,
                journal=journal,
            )
        if best is None:
            fail_message = (
                f"plan cannot tolerate {tolerate_failures} node failure(s) "
                f"within {max_count} new node(s)"
            )
    return _finish_plan(
        sweep, best, max_count, extended_resources, fail_message=fail_message
    )


def probe_plan_multi(
    cluster,
    apps,
    new_nodes: List[dict],
    use_greed: bool = False,
    extended_resources: Optional[List[str]] = None,
    max_count: int = MAX_NUM_NEW_NODE,
    score_weights=None,
    budget=None,
) -> List[ApplyResult]:
    """What-if capacity plan over MANY candidate newnode specs: every
    spec's min-count search runs in lockstep and each round's probes
    across ALL specs dispatch in one device sync
    (parallel/sweep.find_min_count_multi) — replacing K sequential
    probe_plan calls whose ~23 relay round-trips dominated the r4
    8-spec bench. Returns one ApplyResult per spec, identical to what
    probe_plan would produce for it."""
    import gc

    gc_was_enabled = gc.isenabled()
    if gc_was_enabled:
        gc.disable()
    try:
        from ..parallel.sweep import CapacitySweep, find_min_count_multi
        from ..utils.trace import phase

        feasible, (max_cpu, max_mem, max_vg) = _capacity_feasible()
        jobs = []
        for new_node in new_nodes:
            sweep = CapacitySweep(
                cluster,
                apps,
                new_node,
                max_count,
                use_greed=use_greed,
                score_weights=score_weights,
                # expansion is spec-independent without daemonsets /
                # greed ordering: later sweeps reuse the first's pods
                share_pods_from=jobs[0][0] if jobs else None,
            )
            with phase("apply/lower-bound"):
                start = sweep.lower_bound(max_cpu, max_mem, max_vg)
            jobs.append((sweep, feasible, start))
        with phase("apply/probe-search"):
            bests = find_min_count_multi(jobs, budget=budget)
        # replay mutates pod dicts (bind writes nodeName/phase and may
        # touch annotations): sweeps that shared the first sweep's
        # expanded pods get their OWN shallow copies from the still-
        # pristine originals before ANY spec replays, so every spec's
        # ApplyResult embeds dicts no later replay rewrites (review r5)
        def own_pod(p):
            q = dict(p)
            q["spec"] = dict(p["spec"])
            meta = dict(p.get("metadata") or {})
            if meta.get("annotations") is not None:
                meta["annotations"] = dict(meta["annotations"])
            q["metadata"] = meta
            if isinstance(q.get("status"), dict):
                q["status"] = dict(q["status"])
            return q

        for sweep, _, _ in jobs:
            if sweep.pods_shared:
                sweep.pods = [own_pod(p) for p in sweep.pods]
        return [
            _finish_plan(sweep, best, max_count, extended_resources)
            for (sweep, _, _), best in zip(jobs, bests)
        ]
    finally:
        clear_all_memos()
        if gc_was_enabled:
            gc.enable()
            gc.collect()


class Applier:
    def __init__(
        self,
        config: SimonConfig,
        interactive: bool = False,
        extended_resources: Optional[List[str]] = None,
        engine: str = "tpu",
        use_sweep: bool = True,
        use_greed: bool = False,
        scheduler_config: str = "",
        tolerate_node_failures: int = 0,
        chaos_seed: int = 1,
        chaos_trials: int = 32,
        journal_path: str = "",
        resume_path: str = "",
    ):
        config.validate()
        self.config = config
        self.interactive = interactive
        self.extended_resources = extended_resources or []
        self.engine = engine
        self.use_sweep = use_sweep
        self.use_greed = use_greed
        self.tolerate_node_failures = tolerate_node_failures
        self.chaos_seed = chaos_seed
        self.chaos_trials = chaos_trials
        # resumable planning journal (runtime/journal.py): --journal
        # appends (creating or continuing), --resume requires the file
        # and refuses a fingerprint mismatch; resume wins when both set
        self.journal_path = journal_path
        self.resume_path = resume_path
        self.extenders = []
        self.score_weights = None  # None = default profile weights
        self.enable_preemption = True
        self.last_cluster = None
        if scheduler_config:
            # full KubeSchedulerConfiguration: extenders + score-plugin
            # enable/disable/weights + percentageOfNodesToScore checks
            from ..scheduler.schedconfig import load_scheduler_config

            cfg = load_scheduler_config(scheduler_config)
            self.extenders = cfg.extenders
            self.score_weights = cfg.score_weights
            self.enable_preemption = cfg.enable_preemption
            if self.extenders:
                # extenders are host RPC per pod: no batched sweep
                self.use_sweep = False

    # -- loading ------------------------------------------------------------

    def load_cluster(self) -> ResourceTypes:
        if self.config.kube_config:
            from ..models.kubeclient import create_cluster_resource_from_client

            return create_cluster_resource_from_client(self.config.kube_config)
        return cluster_from_config_dir(self.config.custom_cluster)

    def load_apps(self) -> List[AppResource]:
        out = []
        for app in self.config.app_list:
            if app.chart:
                content = process_chart(app.name, app.path)
            else:
                content = yaml_content_from_directory(app.path)
            out.append(AppResource(name=app.name, resource=decode_yaml_content(content)))
        return out

    def load_new_node(self) -> Optional[dict]:
        if not self.config.new_node:
            return None
        resources = load_directory(self.config.new_node)
        match_and_set_local_storage(resources.nodes, self.config.new_node)
        if not resources.nodes:
            return None
        return resources.nodes[0]

    # -- planning -----------------------------------------------------------

    def _simulate_with_count(
        self, cluster, apps, new_node, count, budget=None
    ) -> SimulateResult:
        padded = cluster.copy()
        if new_node is not None and count > 0:
            from ..parallel.sweep import _new_nodes

            padded.nodes = list(padded.nodes) + _new_nodes(new_node, count)
        return simulate(
            padded,
            apps,
            engine=self.engine,
            use_greed=self.use_greed,
            extenders=self.extenders,
            score_weights=self.score_weights,
            enable_preemption=self.enable_preemption,
            budget=budget,
        )

    def open_journal(self, cluster, apps, new_node):
        """Open the planning journal when configured (None otherwise),
        keyed by the fingerprint of the loaded inputs + flags."""
        if not (self.journal_path or self.resume_path):
            return None
        from ..runtime.journal import Journal

        fp = plan_fingerprint(
            cluster,
            apps,
            new_node,
            engine=self.engine,
            use_greed=self.use_greed,
            tolerate_node_failures=self.tolerate_node_failures,
            chaos_seed=self.chaos_seed,
            chaos_trials=self.chaos_trials,
        )
        if self.resume_path:
            return Journal.resume(self.resume_path, fp)
        return Journal.open(self.journal_path, fp)

    def run(self, select_apps=None, budget=None) -> ApplyResult:
        # release the identity memos' strong refs to this run's object
        # graph at exit (the serial guesses inside rely on them warm)
        try:
            return self._run_inner(select_apps, budget=budget)
        finally:
            clear_all_memos()

    def _run_inner(self, select_apps=None, budget=None) -> ApplyResult:
        from ..utils.trace import GLOBAL, phase

        # per-run phase times, not cumulative across runs in one process
        GLOBAL.reset()
        with phase("apply/load"):
            cluster = self.load_cluster()
            apps = self.load_apps()
            if select_apps is not None:
                apps = [a for a in apps if a.name in select_apps]
            new_node = self.load_new_node()
        # kept for callers that snapshot the result (cli.py: PDBs and
        # PriorityClasses ride along so a resume behaves identically)
        self.last_cluster = cluster
        journal = self.open_journal(cluster, apps, new_node)
        if journal is not None and journal.replayed:
            GLOBAL.note(
                "journal-resume",
                f"{journal.replayed} record(s) replayed"
                + (f", {journal.dropped} torn record dropped" if journal.dropped else ""),
            )
        try:
            return self._plan(cluster, apps, new_node, budget, journal)
        finally:
            if journal is not None:
                journal.close()

    def _plan(self, cluster, apps, new_node, budget, journal) -> ApplyResult:
        from ..utils.trace import phase

        # N+K needs the batched plan path: the committed placement, the
        # outage sweep, and the escalation all live on the encoded
        # sweep — the serial escalation loop has none of it
        batched_path = (
            self.use_sweep and new_node is not None and self.engine == "tpu"
        )
        if self.tolerate_node_failures > 0 and not batched_path:
            from ..models.validation import InputError

            raise InputError(
                "--tolerate-node-failures requires the batched plan "
                "path: engine tpu, the sweep enabled, and a newNode "
                "spec to escalate with"
            )
        if batched_path:
            fast = self._plan_with_probes(
                cluster, apps, new_node, budget=budget, journal=journal
            )
            if fast is not None:
                return fast
            if self.tolerate_node_failures > 0:
                from ..models.validation import InputError

                raise InputError(
                    "--tolerate-node-failures requires the batched plan, "
                    "but this workload fell back to the serial engine — "
                    "priority/extender workloads cannot ride the sweep, "
                    "and a failed batched plan degrades the same way "
                    "(the logged warning has the underlying cause)"
                )

        start_count = 0
        if self.use_sweep and new_node is not None:
            # the sweep narrows the search; the authoritative serial run
            # below still validates its pick (incl. the VG cap the sweep
            # cannot see) and escalates further if needed
            with phase("apply/sweep"):
                hint = self._sweep_min_count(cluster, apps, new_node)
            if hint is not None:
                start_count = hint

        max_count = 0 if new_node is None else MAX_NUM_NEW_NODE
        result = None
        for count in range(start_count, max_count + 1):
            if budget is not None:
                budget.check(f"serial escalation (count {count})")
            with phase("apply/simulate"):
                result = self._simulate_with_count(
                    cluster, apps, new_node, count, budget=budget
                )
            if result.unscheduled_pods:
                continue
            ok, reason = satisfy_resource_setting(result.node_status)
            if not ok:
                continue
            with phase("apply/report"):
                report_text = report(result.node_status, self.extended_resources)
            return ApplyResult(
                success=True,
                new_node_count=count,
                result=result,
                report_text=report_text,
            )
        if result is not None and result.unscheduled_pods:
            message = (
                f"{len(result.unscheduled_pods)} pod(s) cannot be scheduled "
                f"even with {max_count} new node(s)"
            )
        else:
            _, message = (
                satisfy_resource_setting(result.node_status) if result else (False, "no result")
            )
        return ApplyResult(
            success=False, new_node_count=max_count, result=result, message=message
        )

    def _plan_with_probes(
        self, cluster, apps, new_node, budget=None, journal=None
    ) -> Optional[ApplyResult]:
        """Returns None to fall back to the serial loop (e.g. when the
        batched path cannot encode the input)."""
        import logging

        from ..models.validation import InputError
        from ..parallel.sweep import PrioritySignalError
        from ..runtime.errors import ExecutionHalted

        try:
            return probe_plan(
                cluster,
                apps,
                new_node,
                use_greed=self.use_greed,
                extended_resources=self.extended_resources,
                score_weights=self.score_weights,
                tolerate_failures=self.tolerate_node_failures,
                chaos_seed=self.chaos_seed,
                chaos_trials=self.chaos_trials,
                budget=budget,
                journal=journal,
            )
        except PrioritySignalError as e:
            logging.getLogger(__name__).info(
                "priority workload: planning with the serial engine (%s)", e
            )
            return None
        except ExecutionHalted:
            # the deadline/SIGINT halt carries the partial report up to
            # the CLI — NEVER a silent serial fallback
            raise
        except InputError:
            # malformed user input (e.g. --tolerate-node-failures larger
            # than the node pool): a clean CLI error, not a silent
            # serial fallback
            raise
        except ConformanceError:
            # engines disagreed: an internal defect that must stay LOUD
            # (docs/ROBUSTNESS.md) — degrading to serial would hide the
            # exact evidence the cross-check exists to surface
            raise
        except Exception as e:  # pragma: no cover - diagnostic path
            logging.getLogger(__name__).warning(
                "batched capacity plan failed, falling back to serial escalation: %s", e
            )
            return None

    def _sweep_min_count(self, cluster, apps, new_node) -> Optional[int]:
        """One batched sweep over all candidate counts; returns the
        minimal count that schedules everything within the caps."""
        from ..parallel.sweep import sweep_node_counts

        from ..parallel.sweep import PrioritySignalError

        try:
            counts = list(range(0, MAX_NUM_NEW_NODE + 1))
            res = sweep_node_counts(
                cluster,
                apps,
                new_node,
                counts,
                use_greed=self.use_greed,
                score_weights=self.score_weights,
            )
        except PrioritySignalError:
            return None  # serial loop below handles priority/preemption
        except Exception as e:  # pragma: no cover - diagnostic path
            import logging

            logging.getLogger(__name__).warning(
                "capacity sweep failed, falling back to serial escalation: %s", e
            )
            return None
        max_cpu, max_mem, _ = _resource_caps()
        for s, count in enumerate(res.counts):
            # int-truncate like satisfyResourceSetting (apply.go:680-681)
            if (
                res.unscheduled[s] == 0
                and int(res.cpu_util[s]) <= max_cpu
                and int(res.mem_util[s]) <= max_mem
            ):
                return count
        return None
