"""Placement report tables.

Mirrors pkg/apply/apply.go:309-609 (reportClusterInfo / reportNodeInfo):
node info table, extended-resource tables (local storage VG/device, GPU
per-device), and the per-node pod table. Rendered with a small built-in
ASCII table writer (the reference uses olekukonko/tablewriter).
"""

from __future__ import annotations

from typing import List, Optional

from ..models import requests as req
from ..models import storage as stor
from ..models import workloads as wl
from ..utils.quantity import format_quantity_bin


def render_table(headers: List[str], rows: List[List[str]]) -> str:
    widths = [len(h) for h in headers]
    str_rows = [
        row if all(type(c) is str for c in row) else [str(c) for c in row]
        for row in rows
    ]
    for row in str_rows:
        for i, cell in enumerate(row):
            if len(cell) > widths[i]:
                widths[i] = len(cell)

    def line(ch="-", junction="+"):
        return junction + junction.join(ch * (w + 2) for w in widths) + junction

    # one C-level str.format per row beats per-cell ljust+join at 100k
    # rows (capacity-report host tail)
    row_fmt = "| " + " | ".join(f"{{:<{w}}}" for w in widths) + " |"
    fmt_row = row_fmt.format

    sep = line()  # identical between every row: render once, not per row
    out = [sep, fmt_row(*headers), line("=")]
    for row in str_rows:
        out.append(fmt_row(*row))
        out.append(sep)
    return "\n".join(out)


def node_state_index(oracle):
    """{id(node dict): NodeState} for the oracle-backed fast paths
    (report node table, satisfy_resource_setting). Empty when no
    oracle is in play."""
    if oracle is None:
        return {}
    return {id(ns.node): ns for ns in oracle.nodes}


def matched_node_state(by_node, status):
    """The NodeState backing `status`, or None when the fast path is
    unsound for it. Identity match proves the status was built from
    this oracle's node; the pod-list check guards against a status
    whose pod list was filtered or extended after the fact — length
    alone would accept a same-length rewrite, so the endpoints must
    also be the very same pod objects."""
    state = by_node.get(id(status.node))
    if (
        state is not None
        and len(state.pods) == len(status.pods)
        and (
            not state.pods
            or (
                state.pods[0] is status.pods[0]
                and state.pods[-1] is status.pods[-1]
            )
        )
    ):
        return state
    return None


def _fmt_cpu(mcpu: int) -> str:
    if mcpu % 1000 == 0:
        return str(mcpu // 1000)
    return f"{mcpu}m"


def _pct(numer: float, denom: float) -> int:
    return int(numer / denom * 100) if denom else 0


def _pod_req_summary(pod: dict):
    s = req.pod_request_summary(pod)
    return s.floor_mcpu, s.floor_mem


def report(
    node_statuses,
    extended_resources: Optional[List[str]] = None,
    select_nodes=None,
    oracle=None,
) -> str:
    """Render the result tables. `select_nodes` (a set of node names, or
    None for all) filters the Pod Info table only — the reference's
    interactive node multi-select (reportNodeInfo, apply.go:510-530)
    narrows the pod table while the cluster tables stay complete.
    `oracle` (when the caller just replayed into one) lets the node
    table read per-node floor aggregates instead of re-walking every
    pod (r4 capacity host-tail trim)."""
    extended_resources = extended_resources or []
    out = ["Node Info"]
    out.append(_node_table(node_statuses, extended_resources, oracle=oracle))
    if extended_resources:
        out.append("")
        out.append("Extended Resource Info")
        if "open-local" in extended_resources:
            out.append("Node Local Storage")
            out.append(_storage_table(node_statuses))
        if "gpu" in extended_resources:
            out.append("GPU Node Resource")
            out.append(_gpu_table(node_statuses))
    out.append("")
    out.append("Pod Info")
    pod_statuses = (
        node_statuses
        if select_nodes is None
        else [
            ns
            for ns in node_statuses
            if ((ns.node.get("metadata") or {}).get("name")) in select_nodes
        ]
    )
    out.append(_pod_table(pod_statuses, extended_resources))
    return "\n".join(out)


def _node_table(node_statuses, extended_resources, oracle=None) -> str:
    headers = ["Node", "CPU Allocatable", "CPU Requests", "Memory Allocatable", "Memory Requests"]
    gpu = "gpu" in extended_resources
    if gpu:
        headers += ["GPU Mem Allocatable", "GPU Mem Requests"]
    headers += ["Pod Count", "New Node"]
    # fast path: the replay oracle tracks floor-semantics totals per
    # node (NodeState.req_floor_*), identical to summing the per-pod
    # floors below. NOT used for the gpu column: its per-pod
    # g_mem*g_cnt semantics diverge from the commit-time device
    # accounting on degenerate annotations (mem without count), and
    # the report must render identically on every code path
    by_node = node_state_index(oracle) if not gpu else {}
    rows = []
    for status in node_statuses:
        node = status.node
        alloc_mcpu = req.node_alloc_milli_cpu(node)
        alloc_mem = req.node_alloc_int(node, req.MEMORY)
        used_mcpu = used_mem = 0
        gpu_req = 0
        state = matched_node_state(by_node, status)
        if state is not None:
            used_mcpu = state.req_floor_mcpu
            used_mem = state.req_floor_mem
        else:
            summary = req.pod_request_summary
            for pod in status.pods:
                s = summary(pod)
                used_mcpu += s.floor_mcpu
                used_mem += s.floor_mem
                if gpu:  # column only rendered for the gpu table
                    g_mem, g_cnt = stor.pod_gpu_request(pod)
                    gpu_req += g_mem * g_cnt
        labels = (node.get("metadata") or {}).get("labels") or {}
        row = [
            (node.get("metadata") or {}).get("name", ""),
            _fmt_cpu(alloc_mcpu),
            f"{_fmt_cpu(used_mcpu)}({_pct(used_mcpu, alloc_mcpu)}%)",
            format_quantity_bin(alloc_mem),
            f"{format_quantity_bin(used_mem)}({_pct(used_mem, alloc_mem)}%)",
        ]
        if gpu:
            total = stor.node_total_gpu_memory(node)
            row += [
                format_quantity_bin(total),
                f"{format_quantity_bin(gpu_req)}({_pct(gpu_req, total)}%)",
            ]
        row += [str(len(status.pods)), "√" if wl.LABEL_NEW_NODE in labels else ""]
        rows.append(row)
    return render_table(headers, rows)


def _storage_table(node_statuses) -> str:
    headers = ["Node", "Storage Kind", "Storage Name", "Storage Allocatable", "Storage Requests"]
    rows = []
    for status in node_statuses:
        node = status.node
        storage = stor.parse_node_storage(node)
        if storage is None:
            continue
        name = (node.get("metadata") or {}).get("name", "")
        for vg in storage.vgs:
            rows.append(
                [
                    name,
                    "VG",
                    vg.name,
                    format_quantity_bin(vg.capacity),
                    f"{format_quantity_bin(vg.requested)}({_pct(vg.requested, vg.capacity)}%)",
                ]
            )
        for dev in storage.devices:
            rows.append(
                [
                    name,
                    f"Device({dev.media_type})",
                    dev.name,
                    format_quantity_bin(dev.capacity),
                    "used" if dev.is_allocated else "unused",
                ]
            )
    return render_table(headers, rows)


def _gpu_table(node_statuses) -> str:
    headers = ["Node", "GPU ID", "GPU Request/Capacity", "Pod List"]
    rows = []
    for status in node_statuses:
        node = status.node
        count = stor.node_gpu_count(node)
        if count == 0:
            continue
        name = (node.get("metadata") or {}).get("name", "")
        per_dev = stor.node_gpu_per_device_memory(node)
        used = [0] * count
        pods_per_dev: List[List[str]] = [[] for _ in range(count)]
        for pod in status.pods:
            mem, _cnt = stor.pod_gpu_request(pod)
            if mem <= 0:
                continue
            idx = ((pod.get("metadata") or {}).get("annotations") or {}).get(stor.GPU_INDEX_ANNO)
            if idx is None:
                continue
            for d in str(idx).split("-"):
                d = int(d)
                used[d] += mem
                pods_per_dev[d].append(pod["metadata"]["name"])
        total_used = sum(used)
        rows.append(
            [
                name,
                "ALL",
                f"{format_quantity_bin(total_used)}/{format_quantity_bin(per_dev * count)}",
                "",
            ]
        )
        for d in range(count):
            rows.append(
                [
                    name,
                    str(d),
                    f"{format_quantity_bin(used[d])}/{format_quantity_bin(per_dev)}",
                    ", ".join(pods_per_dev[d]),
                ]
            )
    return render_table(headers, rows)


def _pod_table(node_statuses, extended_resources) -> str:
    headers = ["Node", "Pod", "CPU Requests", "Memory Requests"]
    local = "open-local" in extended_resources
    gpu = "gpu" in extended_resources
    if local:
        headers.append("Volume Request")
    if gpu:
        headers.append("GPU Mem Requests")
    headers.append("APP Name")
    rows = []
    # identical (request, allocatable) pairs repeat across thousands of
    # pods at scale — format each value combination once (value-keyed,
    # so snapshot-loaded pods with per-pod summary objects still hit)
    cell_pair: dict = {}
    summary = req.pod_request_summary
    append = rows.append
    for status in node_statuses:
        node = status.node
        node_name = (node.get("metadata") or {}).get("name", "")
        alloc_mcpu = req.node_alloc_milli_cpu(node)
        alloc_mem = req.node_alloc_int(node, req.MEMORY)
        for pod in status.pods:
            s = summary(pod)
            mcpu, mem = s.floor_mcpu, s.floor_mem
            ck = (mcpu, mem, alloc_mcpu, alloc_mem)
            cells = cell_pair.get(ck)
            if cells is None:
                cells = cell_pair[ck] = (
                    f"{_fmt_cpu(mcpu)}({_pct(mcpu, alloc_mcpu)}%)",
                    f"{format_quantity_bin(mem)}({_pct(mem, alloc_mem)}%)",
                )
            meta = pod.get("metadata") or {}
            row = [
                node_name,
                f"{meta.get('namespace', 'default')}/{meta.get('name', '')}",
                cells[0],
                cells[1],
            ]
            if local:
                lvm, dev = stor.parse_pod_local_volumes(pod)
                vols = [f"{v.kind}:{format_quantity_bin(v.size)}" for v in lvm + dev]
                row.append(", ".join(vols))
            if gpu:
                g_mem, g_cnt = stor.pod_gpu_request(pod)
                idx = (meta.get("annotations") or {}).get(stor.GPU_INDEX_ANNO, "")
                row.append(f"{format_quantity_bin(g_mem)}x{g_cnt}@{idx}" if g_mem else "")
            row.append((meta.get("labels") or {}).get(wl.LABEL_APP_NAME, ""))
            append(row)
    return render_table(headers, rows)
