"""Interactive capacity-planning shell.

Mirrors the reference's survey-driven flow (pkg/apply/apply.go):
- app multi-select before planning (apply.go:157-173)
- the per-iteration capacity loop: simulate with N new nodes; while
  pods stay unschedulable, ask
  {show error event of unscheduled pods | add node(s) | exit}
  (apply.go:186-239, option strings apply.go:33-35)
- node multi-select before the report (reportNodeInfo,
  apply.go:510-530) narrowing the Pod Info table

TPU-first difference: each iteration is NOT a full re-simulation. When
the batched sweep is available, the padded cluster is encoded once
(parallel/sweep.py CapacitySweep) and each user guess is a single
masked scan — the interactive loop just picks which precomputed
scenario to look at. Priority workloads / extenders fall back to a
serial simulate() per guess, exactly the reference's cost model.

Deviation (documented): in the reference, a plan whose pods all fit but
whose utilization caps fail loops forever re-printing the reason
(apply.go:230-238 has no prompt on that path). Here the same
{add node(s) | exit} menu appears so the shell stays usable.

The prompts are plain-text numbered menus over stdin/stdout (the
`survey` TUI has no Python counterpart here), injectable for scripted
tests.
"""

from __future__ import annotations

import sys
from typing import List, Optional

SURVEY_ADD_NODE = "add node(s)"
SURVEY_SHOW_RESULTS = "show error event of unscheduled pods"
SURVEY_EXIT = "exit"


class Shell:
    """Plain-text prompt driver (injectable stdin/stdout for tests)."""

    def __init__(self, fin=None, fout=None):
        self.fin = fin or sys.stdin
        self.fout = fout or sys.stdout

    def say(self, msg: str = ""):
        print(msg, file=self.fout)

    def _read(self) -> str:
        line = self.fin.readline()
        if not line:  # EOF: behave like survey's ^C -> exit
            return ""
        return line.strip()

    def ask_select(self, message: str, options: List[str]) -> str:
        """Single-choice menu; accepts an index or the exact option
        text. EOF or unparseable input selects the last option (exit)."""
        self.say(message)
        for i, opt in enumerate(options):
            self.say(f"  [{i}] {opt}")
        self.fout.write("> ")
        self.fout.flush()
        raw = self._read()
        if raw in options:
            return raw
        try:
            return options[int(raw)]
        except (ValueError, IndexError):
            return options[-1]

    def ask_multiselect(self, message: str, options: List[str]) -> List[str]:
        """Multi-choice: comma-separated indices or names; empty = all."""
        self.say(message)
        for i, opt in enumerate(options):
            self.say(f"  [{i}] {opt}")
        self.fout.write("(comma-separated indices, empty = all) > ")
        self.fout.flush()
        raw = self._read()
        if not raw:
            return list(options)
        picked = []
        for tok in raw.split(","):
            tok = tok.strip()
            if tok in options:
                picked.append(tok)
                continue
            try:
                picked.append(options[int(tok)])
            except (ValueError, IndexError):
                continue
        return picked or list(options)

    def ask_int(self, message: str) -> Optional[int]:
        self.fout.write(f"{message}: ")
        self.fout.flush()
        raw = self._read()
        try:
            return int(raw)
        except ValueError:
            return None


class _ProbeEvaluator:
    """One masked scan per guess over the once-encoded padded cluster."""

    def __init__(self, sweep):
        self.sweep = sweep

    def evaluate(self, count: int):
        from .applier import replay_scenario

        res = self.sweep.probe(count)
        result, _ = replay_scenario(self.sweep, count, res.placements)
        return result


class _SerialEvaluator:
    """Full simulate() per guess (priority workloads, extenders, or
    encode failures) — the reference's per-iteration cost model."""

    def __init__(self, applier, cluster, apps, new_node):
        self.applier = applier
        self.cluster = cluster
        self.apps = apps
        self.new_node = new_node

    def evaluate(self, count: int):
        from ..models.workloads import reset_name_counter

        reset_name_counter()
        return self.applier._simulate_with_count(
            self.cluster, self.apps, self.new_node, count
        )


def _make_evaluator(applier, cluster, apps, new_node):
    if new_node is not None and applier.engine == "tpu" and applier.use_sweep:
        import logging

        from ..parallel.sweep import CapacitySweep, PrioritySignalError
        from ..utils.trace import GLOBAL
        from .applier import MAX_NUM_NEW_NODE

        try:
            return _ProbeEvaluator(
                CapacitySweep(
                    cluster,
                    apps,
                    new_node,
                    MAX_NUM_NEW_NODE,
                    use_greed=applier.use_greed,
                    score_weights=applier.score_weights,
                )
            )
        except PrioritySignalError as e:
            # expected: priority workloads / stateful plugins plan
            # serially per guess, the reference's cost model
            GLOBAL.note("interactive-evaluator", f"serial per guess: {e}")
        except Exception as e:
            # unexpected encode failure: degrade the same way, loudly
            GLOBAL.note(
                "interactive-evaluator", f"serial per guess (encode failed: {e})"
            )
            logging.getLogger(__name__).warning(
                "batched sweep unavailable for the interactive loop, "
                "planning serially per guess: %s", e
            )
    return _SerialEvaluator(applier, cluster, apps, new_node)


def run_interactive(applier, shell: Optional[Shell] = None, max_iterations: int = 1000):
    """The `-i` flow. Returns an ApplyResult."""
    from .applier import ApplyResult, satisfy_resource_setting
    from .report import report

    if getattr(applier, "tolerate_node_failures", 0) > 0:
        from ..models.validation import InputError

        # the guess-a-count loop has no N+K escalation; silently
        # returning an unvetted plan would let the user believe it
        # survives K failures
        raise InputError(
            "--tolerate-node-failures is not available in interactive "
            "mode; run the one-shot plan (drop -i) or `simon chaos`"
        )
    shell = shell or Shell()

    cluster = applier.load_cluster()
    applier.last_cluster = cluster
    apps = applier.load_apps()
    new_node = applier.load_new_node()

    # app multi-select (apply.go:157-173)
    if apps:
        names = [a.name for a in apps]
        chosen = set(shell.ask_multiselect("Confirm your apps :", names))
        apps = [a for a in apps if a.name in chosen]

    evaluator = _make_evaluator(applier, cluster, apps, new_node)

    count = 0
    result = None
    for _ in range(max_iterations):
        result = evaluator.evaluate(count)
        if result.unscheduled_pods:
            choice = shell.ask_select(
                f"there are still {len(result.unscheduled_pods)} pod(s) that "
                f"can not be scheduled when add {count} nodes, you can:",
                [SURVEY_SHOW_RESULTS, SURVEY_ADD_NODE, SURVEY_EXIT],
            )
            if choice == SURVEY_SHOW_RESULTS:
                for i, up in enumerate(result.unscheduled_pods):
                    meta = up.pod.get("metadata") or {}
                    shell.say(
                        f"{i:4d} {meta.get('namespace', 'default')}/"
                        f"{meta.get('name', '')}: {up.reason}"
                    )
                from ..obs.explain import EXPLAIN

                if EXPLAIN.enabled:
                    # `simon apply -i --explain`: the per-node verdict
                    # tables recorded during this iteration's replay
                    from ..obs.explain import render_explanations

                    shell.say(render_explanations())
            elif choice == SURVEY_ADD_NODE:
                if new_node is None:
                    shell.say("no newNode spec configured; cannot add nodes")
                    continue
                num = shell.ask_int("input node number")
                if num is not None and num >= 0:
                    count = num
            else:  # exit
                return ApplyResult(
                    success=False,
                    new_node_count=count,
                    result=result,
                    message="exited by user with unscheduled pods",
                )
            continue
        ok, reason = satisfy_resource_setting(result.node_status)
        if not ok:
            shell.say(reason)
            choice = shell.ask_select(
                f"utilization caps not met with {count} new node(s), you can:",
                [SURVEY_ADD_NODE, SURVEY_EXIT],
            )
            if choice == SURVEY_ADD_NODE and new_node is not None:
                num = shell.ask_int("input node number")
                if num is not None and num >= 0:
                    count = num
                continue
            return ApplyResult(
                success=False, new_node_count=count, result=result, message=reason
            )
        break
    else:  # pragma: no cover - loop bound safety
        return ApplyResult(
            success=False,
            new_node_count=count,
            result=result,
            message="interactive loop exceeded max iterations",
        )

    # node multi-select before the report (apply.go:510-530)
    node_names = [
        (ns.node.get("metadata") or {}).get("name", "") for ns in result.node_status
    ]
    selected = set(
        shell.ask_multiselect("select nodes that you want to report:", node_names)
    )
    report_text = report(
        result.node_status, applier.extended_resources, select_nodes=selected
    )
    return ApplyResult(
        success=True, new_node_count=count, result=result, report_text=report_text
    )
