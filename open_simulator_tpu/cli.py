"""simon-compatible CLI.

Mirrors cmd/simon (cmd/simon/simon.go, cmd/apply/apply.go):

  simon apply -f <simon-config.yaml> [-i] [--extended-resources gpu,open-local]
        [--engine tpu|oracle] [--no-sweep]
  simon version
  simon gen-doc

Log level comes from the LogLevel env var (cmd/simon/simon.go:60-80).
--default-scheduler-config and --use-greed are dead options in the
reference (stored but never forwarded, pkg/apply/apply.go:80-81); here
both are functional: the scheduler config's `extenders:` section is
honored (scheduler/extender.py) and --use-greed applies the GreedQueue
ordering (scheduler/queues.py).

Run as `python -m open_simulator_tpu.cli ...` or via the `simon`
console script.
"""

from __future__ import annotations

import argparse
import logging
import os
import sys
import time

from . import __version__


def _setup_logging():
    level = os.environ.get("LogLevel", "info").lower()
    levels = {
        "debug": logging.DEBUG,
        "info": logging.INFO,
        "warn": logging.WARNING,
        "warning": logging.WARNING,
        "error": logging.ERROR,
    }
    logging.basicConfig(level=levels.get(level, logging.INFO), format="%(levelname)s %(message)s")


def _force_platform():
    # SIMON_FORCE_CPU=1 pins JAX to the CPU backend (config.update is
    # the only override that works after a TPU plugin froze the env)
    if os.environ.get("SIMON_FORCE_CPU") == "1":
        import jax

        jax.config.update("jax_platforms", "cpu")
        return
    # A wedged TPU relay plugin (JAX_PLATFORMS naming a plugin backend
    # that fails to initialize) would otherwise kill the run mid-plan:
    # probe the backend in a subprocess (utils/backend.py, shared with
    # bench.py) and degrade to CPU when it is unhealthy. Only plugin
    # platforms are probed — builtin cpu/tpu initialize in-process —
    # and the probe costs one extra backend init on the healthy path;
    # SIMON_BACKEND_PROBE=0 skips it for operators who prefer the
    # faster cold start over the guard.
    platforms = os.environ.get("JAX_PLATFORMS", "")
    # JAX_PLATFORMS is a comma list; skip the probe only when every
    # entry is a builtin (in-process init). A builtin fallback later in
    # the list does NOT make a leading plugin safe: a wedged plugin
    # hangs inside backend init rather than erroring (utils/backend.py),
    # so jax never reaches the fallback
    entries = [p.strip().lower() for p in platforms.split(",") if p.strip()]
    if not entries or all(p in ("cpu", "tpu") for p in entries):
        return
    if os.environ.get("SIMON_BACKEND_PROBE") == "0":
        return
    if "jax" in sys.modules:
        return  # too late to change the platform; let jax report it
    from .utils.backend import probe_backend

    if not probe_backend():
        logging.warning(
            "JAX platform %r failed to initialize; falling back to CPU",
            platforms,
        )
        os.environ["JAX_PLATFORMS"] = "cpu"


def _obs_begin(args):
    """Arm the flight recorder (obs/) from the shared observability
    flags (--trace-out / --explain / --profile-dir; docs/OBSERVABILITY.md).
    Returns a finish callback that exports the trace and disarms —
    called from _with_obs's finally so every exit path exports."""
    from .obs import profile as obs_profile
    from .obs import spans
    from .obs.explain import EXPLAIN

    trace_out = getattr(args, "trace_out", "")
    explain = getattr(args, "explain", None)
    profile_dir = getattr(args, "profile_dir", "")
    if profile_dir:
        obs_profile.set_profile_dir(profile_dir)
    if trace_out:
        sink = spans.JsonlSink(trace_out) if trace_out.endswith(".jsonl") else None
        spans.RECORDER.enable(sink)
    if explain is not None:
        EXPLAIN.enable(explain or None)

    def finish():
        if trace_out:
            if not trace_out.endswith(".jsonl"):
                spans.export_chrome_trace(trace_out)
            dropped = spans.RECORDER.dropped
            spans.RECORDER.disable()
            note = f" ({dropped} span(s) dropped at cap)" if dropped else ""
            print(f"span trace written to {trace_out}{note}", file=sys.stderr)
        if explain is not None:
            EXPLAIN.disable()
        if profile_dir:
            obs_profile.set_profile_dir(None)
            print(f"JAX profiler capture(s) in {profile_dir}", file=sys.stderr)

    return finish


def _with_obs(name: str):
    """Decorator for the long-running commands: arm the recorder from
    the obs flags, run the command under a root span (`simon <name>` —
    phases and jit dispatches nest under it), export on ANY exit."""

    def deco(fn):
        import functools

        @functools.wraps(fn)
        def wrapper(args):
            from .obs.spans import RECORDER

            finish = _obs_begin(args)
            try:
                with RECORDER.span(f"simon {name}", command=name):
                    return fn(args)
            finally:
                finish()

        return wrapper

    return deco


def _print_explanations(args, out=None):
    """Append the --explain block to the human-readable output."""
    if getattr(args, "explain", None) is None:
        return
    from .obs.explain import render_explanations

    print(render_explanations(), file=out)


def _explanations_payload(args):
    """The --explain block for JSON output (None when off)."""
    if getattr(args, "explain", None) is None:
        return None
    from .obs.explain import explanations_dict

    return explanations_dict()


def _emit_partial(e, args, journal_path: str) -> int:
    """Render an ExecutionHalted (deadline / SIGINT at a safe boundary)
    as a well-formed machine-readable partial report, never a
    traceback, and return its distinct exit code (runtime/errors.py:
    3 deadline, 4 interrupt; docs/ROBUSTNESS.md)."""
    import json

    payload = {
        "partial": True,
        "reason": e.reason,
        "message": str(e),
        "exitCode": e.exit_code,
        "journal": journal_path or None,
        "detail": e.partial,
    }
    if getattr(args, "format", "table") == "json":
        print(json.dumps(payload))
    else:
        print(f"PARTIAL RESULT ({e.reason}): {e}")
        if journal_path:
            print(
                f"completed work journaled in {journal_path}; rerun with "
                f"--resume {journal_path} to continue"
            )
        if e.partial is not None:
            print(json.dumps(e.partial, indent=2))
    return e.exit_code


@_with_obs("apply")
def cmd_apply(args) -> int:
    from .apply.applier import Applier, SimonConfig
    from .models.validation import InputError
    from .runtime import (
        Budget,
        ExecutionHalted,
        ExternalIOError,
        Interrupted,
        sigint_to_budget,
    )

    _force_platform()
    try:
        _configure_mesh(args)
        if args.interactive and args.deadline is not None:
            raise InputError(
                "--deadline is not available in interactive mode (the "
                "shell blocks on user input; press ^C to leave it)"
            )
        config = SimonConfig.from_file(args.simon_config)
        applier = Applier(
            config,
            interactive=args.interactive,
            extended_resources=args.extended_resources,
            engine=args.engine,
            use_sweep=not args.no_sweep,
            use_greed=args.use_greed,
            scheduler_config=args.default_scheduler_config,
            tolerate_node_failures=args.tolerate_node_failures,
            chaos_seed=args.chaos_seed,
            chaos_trials=args.chaos_trials,
            journal_path=args.journal,
            resume_path=args.resume,
        )
        budget = Budget(args.deadline)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    journal_path = args.resume or args.journal
    try:
        if args.interactive:
            # the reference's survey shell: app multi-select, then a
            # per-iteration {show reasons | add node(s) | exit} loop,
            # then node multi-select before the report
            # (apply.go:157-239, 510-530). NOT budget-guarded: the
            # shell blocks on stdin, so ^C must interrupt immediately
            # (KeyboardInterrupt below), not wait for a safe boundary
            from .apply.interactive import run_interactive

            result = run_interactive(applier)
        else:
            with sigint_to_budget(budget):
                result = applier.run(budget=budget)
    except ExecutionHalted as e:
        return _emit_partial(e, args, journal_path)
    except KeyboardInterrupt:
        # SIGINT outside a guarded boundary (interactive shell, or
        # during load): still a clean partial exit, nothing to report
        return _emit_partial(
            Interrupted("interrupted before any safe boundary"),
            args,
            journal_path,
        )
    except ExternalIOError as e:
        # an external dependency (apiserver, credential plugin,
        # extender) failed after retries: clean typed error, exit 2
        print(f"error: {e}", file=sys.stderr)
        return 2
    except (OSError, InputError) as e:
        # malformed input discovered while loading/expanding (e.g. a
        # pod failing k8s validation) exits cleanly like the
        # reference's log.Fatalf path; internal errors (e.g. a JAX
        # shape bug, which also raises ValueError) stay loud
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.trace:
        from .utils.trace import GLOBAL

        print(GLOBAL.as_json(), file=sys.stderr)
    if args.snapshot and result.result is not None:
        from .scheduler.snapshot import save_snapshot

        save_snapshot(
            result.result, args.snapshot, cluster=getattr(applier, "last_cluster", None)
        )
    if args.format == "json":
        print(_result_json(result, explain=_explanations_payload(args)))
        return 0 if result.success else 1
    if not result.success:
        print(result.message)
        if result.result is not None:
            for i, up in enumerate(result.result.unscheduled_pods):
                meta = up.pod.get("metadata") or {}
                print(f"{i:4d} {meta.get('namespace')}/{meta.get('name')}: {up.reason}")
        _print_explanations(args)
        return 1
    print("Simulation success!")
    if result.new_node_count:
        print(f"new nodes added: {result.new_node_count}")
    print(result.report_text)
    _print_explanations(args)
    return 0


def _parse_taint(spec: str):
    """`key[=value]:Effect[@node1,node2]` -> (names_or_None, taint)."""
    body, _, nodes = spec.partition("@")
    kv, sep, effect = body.rpartition(":")
    if not sep or not kv or not effect:
        raise ValueError(
            f"taint {spec!r}: expected key[=value]:Effect[@node1,node2]"
        )
    key, _, value = kv.partition("=")
    taint = {"key": key, "effect": effect}
    if value:
        taint["value"] = value
    return ([n for n in nodes.split(",") if n] or None) if nodes else None, taint


def _parse_degrade(spec: str):
    """`PCT[@node1,node2]` -> (percent, names_or_None)."""
    body, _, nodes = spec.partition("@")
    pct = int(body)
    return pct, ([n for n in nodes.split(",") if n] or None) if nodes else None


@_with_obs("chaos")
def cmd_chaos(args) -> int:
    """Fault-injection survivability of a committed plan
    (resilience/chaos.py; docs/RESILIENCE.md)."""
    import json

    from .apply.applier import (
        MAX_NUM_NEW_NODE,
        Applier,
        SimonConfig,
        _capacity_feasible,
        plan_fingerprint,
    )
    from .models.validation import InputError
    from .parallel.sweep import CapacitySweep, PrioritySignalError
    from .resilience.chaos import ChaosEngine, perturbed_scenario_sweep
    from .runtime import (
        Budget,
        ExecutionHalted,
        ExternalIOError,
        Interrupted,
        Journal,
        sigint_to_budget,
    )
    from .utils.trace import GLOBAL

    _force_platform()
    try:
        _configure_mesh(args)
        config = SimonConfig.from_file(args.simon_config)
        applier = Applier(config, use_greed=args.use_greed)
        cluster = applier.load_cluster()
        apps = applier.load_apps()
        new_node = applier.load_new_node()
        taints = [_parse_taint(t) for t in args.taint or []]
        degrade = _parse_degrade(args.degrade) if args.degrade else None
        cordon = [n for n in (args.cordon or "").split(",") if n]
        budget = Budget(args.deadline)
    except (OSError, ValueError, ExternalIOError) as e:
        # ExternalIOError: a live-cluster import (kubeConfig) whose
        # apiserver/credential plugin failed after retries — typed,
        # clean, exit 2
        print(f"error: {e}", file=sys.stderr)
        return 2

    journal = None
    journal_path = args.resume or args.journal
    GLOBAL.reset()
    try:
        if journal_path:
            fp = plan_fingerprint(
                cluster,
                apps,
                new_node,
                command="chaos",
                use_greed=args.use_greed,
                failures=args.failures,
                seed=args.seed,
                trials=args.trials,
                new_node_count=args.new_node_count,
                cordon=cordon,
                taints=taints,
                degrade=degrade,
            )
            journal = (
                Journal.resume(args.resume, fp)
                if args.resume
                else Journal.open(args.journal, fp)
            )
        # expansion names pods from a process-global counter; reset so
        # repeated in-process runs (and the perturbed re-encoding
        # below) expand the identical pod sequence
        from .models.workloads import reset_name_counter

        reset_name_counter()
        with sigint_to_budget(budget):
            if args.new_node_count is not None:
                count = args.new_node_count
                if count < 0:
                    raise InputError("--new-node-count must be >= 0")
                if count > 0 and new_node is None:
                    # CapacitySweep would silently clamp to 0 and the
                    # report would describe capacity that was never there
                    raise InputError(
                        f"--new-node-count {count} needs a newNode spec in "
                        "the config, which has none"
                    )
                sweep = CapacitySweep(
                    cluster, apps, new_node, count, use_greed=args.use_greed
                )
                if journal is not None:
                    sweep.attach_journal(journal)
                baseline = sweep.probe(count).placements
            else:
                # plan first: the chaos sweep evaluates the committed plan
                max_count = 0 if new_node is None else MAX_NUM_NEW_NODE
                sweep = CapacitySweep(
                    cluster, apps, new_node, max_count, use_greed=args.use_greed
                )
                if journal is not None:
                    sweep.attach_journal(journal)
                feasible, (mc, mm, mv) = _capacity_feasible()
                best = sweep.find_min_count(
                    feasible, start=sweep.lower_bound(mc, mm, mv), budget=budget
                )
                if best is None:
                    print(
                        "error: no feasible plan to inject faults into "
                        f"(infeasible even with {max_count} new node(s)); "
                        "pass --new-node-count to analyze an infeasible "
                        "placement anyway",
                        file=sys.stderr,
                    )
                    return 1
                count, baseline = best.count, best.placements
            scen_sweep = perturbed_scenario_sweep(
                cluster,
                apps,
                new_node,
                sweep.max_count,
                cordon=cordon,
                taints=taints,
                degrade=degrade,
                use_greed=args.use_greed,
            )
            engine = ChaosEngine(
                sweep, count, baseline, scenario_sweep=scen_sweep
            )
            report = engine.run(
                failures=args.failures,
                seed=args.seed,
                trials=args.trials,
                budget=budget,
                journal=journal,
            )
    except ExecutionHalted as e:
        return _emit_partial(e, args, journal_path)
    except KeyboardInterrupt:
        return _emit_partial(
            Interrupted("interrupted before any safe boundary"),
            args,
            journal_path,
        )
    except PrioritySignalError as e:
        print(
            f"error: chaos analysis needs the batched scan path: {e}",
            file=sys.stderr,
        )
        return 2
    except (OSError, InputError, ExternalIOError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    finally:
        if journal is not None:
            journal.close()
    if args.trace:
        print(GLOBAL.as_json(), file=sys.stderr)
    if args.format == "json":
        payload = report.as_dict()
        explain = _explanations_payload(args)
        if explain is not None:
            payload["explain"] = explain
        print(json.dumps(payload))
    else:
        print(report.render_text())
        _print_explanations(args)
    return 0 if report.all_survived else 1


@_with_obs("defrag")
def cmd_defrag(args) -> int:
    import json

    from .parallel.defrag import plan_defrag
    from .scheduler.snapshot import load_snapshot

    _force_platform()
    try:
        snapshot = load_snapshot(args.snapshot)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    protect = None
    if args.keep_new_nodes:
        from .models.workloads import LABEL_NEW_NODE

        def protect(node):
            return LABEL_NEW_NODE in ((node.get("metadata") or {}).get("labels") or {})

    plan = plan_defrag(snapshot, max_drain=args.max_drain, protect=protect)
    if args.format == "json":
        payload = {
            "drainOrder": plan.ranked_nodes,
            "chosenDepth": plan.chosen_depth,
            "drainedNodes": plan.drained_nodes,
            "unscheduledByDepth": [int(x) for x in plan.unscheduled],
            "moves": [
                {
                    "namespace": (m.pod.get("metadata") or {}).get("namespace"),
                    "pod": (m.pod.get("metadata") or {}).get("name"),
                    "from": m.from_node,
                    "to": m.to_node,
                }
                for m in plan.moves
            ],
        }
        explain = _explanations_payload(args)
        if explain is not None:
            payload["explain"] = explain
        print(json.dumps(payload))
        return 0
    if plan.chosen_depth == 0:
        print("no node can be fully drained")
        _print_explanations(args)
        return 0
    print(f"drainable nodes ({plan.chosen_depth}): {', '.join(plan.drained_nodes)}")
    print(f"migrations required: {len(plan.moves)}")
    from .apply.report import render_table

    rows = [
        [
            (m.pod.get("metadata") or {}).get("namespace", ""),
            (m.pod.get("metadata") or {}).get("name", ""),
            m.from_node,
            m.to_node,
        ]
        for m in plan.moves
    ]
    print(render_table(["Namespace", "Pod", "From", "To"], rows))
    _print_explanations(args)
    return 0


def _result_json(result, explain=None) -> str:
    """Structured results (SURVEY.md §5: structured results + optional
    table renderer instead of ASCII-only). `explain` (the --explain
    recorder payload) rides along as an `explain` key when armed."""
    import json

    from .models.workloads import LABEL_NEW_NODE

    out = {
        "success": result.success,
        "newNodeCount": result.new_node_count,
        "message": result.message,
        "nodes": [],
        "unscheduledPods": [],
    }
    if explain is not None:
        out["explain"] = explain
    if result.result is not None:
        for ns in result.result.node_status:
            meta = ns.node.get("metadata") or {}
            out["nodes"].append(
                {
                    "name": meta.get("name"),
                    "newNode": LABEL_NEW_NODE in (meta.get("labels") or {}),
                    "pods": [
                        {
                            "namespace": (p.get("metadata") or {}).get("namespace"),
                            "name": (p.get("metadata") or {}).get("name"),
                            "app": ((p.get("metadata") or {}).get("labels") or {}).get(
                                "simon/app-name"
                            ),
                        }
                        for p in ns.pods
                    ],
                }
            )
        for up in result.result.unscheduled_pods:
            meta = up.pod.get("metadata") or {}
            out["unscheduledPods"].append(
                {
                    "namespace": meta.get("namespace"),
                    "name": meta.get("name"),
                    "reason": up.reason,
                }
            )
    return json.dumps(out)


@_with_obs("serve")
def cmd_serve(args) -> int:
    """Long-lived what-if daemon (serve/; docs/SERVING.md): load the
    cluster once, pre-warm the encode + compiled-scan caches, coalesce
    concurrent POST /v1/simulate requests onto batched device scans.
    Exit 0 after a clean SIGTERM/SIGINT drain, 3 when --drain-timeout
    expired with requests still queued (shed), 2 on input errors."""
    from .apply.applier import Applier, SimonConfig
    from .models.validation import InputError
    from .runtime import ExternalIOError
    from .serve.server import ServeDaemon
    from .serve.session import Session

    _force_platform()
    try:
        # flag validation up front: a bad value must exit 2 BEFORE
        # listening, never crash per request (docs/ROBUSTNESS.md)
        if args.default_deadline is not None and args.default_deadline <= 0:
            raise InputError("--default-deadline must be > 0 seconds")
        if args.drain_timeout < 0:
            raise InputError("--drain-timeout must be >= 0 seconds")
        if args.tick_budget is not None and args.tick_budget <= 0:
            raise InputError("--tick-budget must be > 0 seconds")
        if args.max_request_pods is not None and args.max_request_pods < 1:
            raise InputError("--max-request-pods must be >= 1")
        if args.max_sessions < 1:
            raise InputError("--max-sessions must be >= 1")
        if args.checkpoint_interval is not None and args.checkpoint_interval < 1:
            raise InputError("--checkpoint-interval must be >= 1 delta")
        if args.keep_checkpoints < 1:
            raise InputError("--keep-checkpoints must be >= 1")
        if args.checkpoint_interval and not args.snapshot:
            raise InputError("--checkpoint-interval requires --snapshot PATH")
        # declarative SLOs + telemetry cadence: a bad --slo-config or
        # --obs-cadence raises InputError here (the daemon constructor
        # validates the cadence) -> exit 2 before listening
        slo_engine = _build_slo_engine(args)
        # resident service: circuit breakers get a recovery cooldown so
        # an apiserver/extender flap degrades, not dooms, the daemon.
        # SIMON_BREAKER_COOLDOWN wins when set (0 restores the one-shot
        # stay-open posture); the 30s default applies only without it
        from .runtime.retry import BREAKER_COOLDOWN_ENV, enable_breaker_recovery

        if not os.environ.get(BREAKER_COOLDOWN_ENV):
            enable_breaker_recovery(30.0)
        config = SimonConfig.from_file(args.simon_config)
        applier = Applier(config)
        cluster = applier.load_cluster()
        # the artifact store must be armed BEFORE the warmup request
        # compiles anything: a warm store then serves every warmup
        # shape and the daemon's first answer costs zero new compiles
        _arm_store(args)
        session = Session(cluster, incremental=not args.no_incremental)
        if getattr(args, "replay_snapshot", False) and not args.snapshot:
            raise InputError("--replay-snapshot requires --snapshot PATH")
        daemon = ServeDaemon(
            session,
            host=args.host,
            port=args.port,
            max_batch=args.max_batch,
            queue_depth=args.queue_depth,
            default_deadline_s=args.default_deadline,
            drain_timeout_s=args.drain_timeout,
            tick_budget_s=args.tick_budget,
            max_request_pods=args.max_request_pods,
            max_sessions=args.max_sessions,
            snapshot_path=args.snapshot or None,
            checkpoint_interval=args.checkpoint_interval,
            keep_checkpoints=args.keep_checkpoints,
            slo_engine=slo_engine,
            obs_cadence_s=args.obs_cadence,
        )
    except (OSError, ValueError, ExternalIOError, InputError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    # continuous flight recorder: the resident daemon records into a
    # bounded ring (overwrite-oldest, dropped counted) so /debug/dump
    # always has a recent span window — --trace-out still owns export
    from .obs.telemetry import arm_flight_recorder

    arm_flight_recorder()
    if not args.no_warm:
        # one tiny request through the whole path before we listen:
        # cluster static encode + scenario-scan jit are warm, so the
        # first real request pays traffic-shape compile only
        session.warm()
    replay_summary = None
    if getattr(args, "replay_snapshot", False) and os.path.exists(args.snapshot):
        # failover bootstrap (fleet/replay.py): replay the delta stream
        # a dead replica had absorbed BEFORE listening, so the first
        # answer comes from dict-identical warm state. Deliberately
        # AFTER warm(): warm compiles the pre-delta roster (a shape the
        # dead replica stored), and the post-delta shape loads from the
        # store on the first request — the replacement's compile history
        # mirrors the dead replica's exactly, so a warm shared store
        # makes the whole bootstrap zero-compile. Read-only here; the
        # daemon resumes the same journal for append (truncating any
        # torn tail durably)
        from .fleet.replay import replay_into_session

        replay_summary = replay_into_session(session, args.snapshot)
        if daemon.checkpoints is not None and replay_summary["checkpoint"]:
            # the restored generation is current: the next checkpoint
            # is due one full interval PAST it, not immediately
            daemon.checkpoints.note_restored(
                replay_summary["checkpoint"]["deltaSeq"]
            )
    daemon.start()
    if replay_summary is not None:
        restored = replay_summary.get("checkpoint")
        if restored:
            logging.info(
                "restored checkpoint %s (deltaSeq=%d); %d absorbed "
                "journal record(s) skipped",
                restored["path"],
                restored["deltaSeq"],
                replay_summary["skippedPrefix"],
            )
        logging.info(
            "replayed %d cluster delta(s) from %s "
            "(applied=%d skipped=%d reloads=%d torn-tail-dropped=%d)",
            replay_summary["deltas"],
            args.snapshot,
            replay_summary["applied"],
            replay_summary["skipped"],
            replay_summary["reloads"],
            replay_summary["dropped"],
        )
    if session.force_serial_reason:
        logging.warning(
            "cluster cannot ride the batched scan (%s); every request "
            "will be answered serially",
            session.force_serial_reason,
        )
    # machine-parsable readiness line (tests and the CI smoke step read
    # the bound port from it — --port 0 binds an ephemeral one)
    print(
        f"simon serve listening on http://{daemon.host}:{daemon.port} "
        f"(cluster {session.fingerprint})",
        flush=True,
    )
    code = daemon.run_until_signaled()
    # observatory drain dump: one JSON line on stderr with the per-site
    # latency histograms, the HBM ledger, and the AOT cost table — the
    # daemon's lifetime observability survives the process even when
    # nobody scraped /metrics (per-request output stays untouched)
    import json as _json

    from .obs.spans import observatory_block

    observatory = observatory_block()
    if observatory:
        print(
            "simon serve observatory: " + _json.dumps(observatory),
            file=sys.stderr,
        )
    if args.explain is not None:
        # daemon mode: explanations accumulated across requests land on
        # stderr at drain (per-request output must stay byte-identical
        # to standalone runs — the serve conformance contract)
        _print_explanations(args, out=sys.stderr)
    return code


@_with_obs("fleet")
def cmd_fleet(args) -> int:
    """N-replica serve fleet behind one consistent-hash router
    (fleet/; docs/FLEET.md): spawn N `simon serve` replicas sharing
    one AOT store, route tenant-affine, probe /healthz, and fail over
    on replica death — the replacement resumes its slot's snapshot
    journal and replays the dead replica's delta stream, answering
    its first request at zero new XLA compiles. Exit 0 after a clean
    SIGTERM drain of every replica, 3 when one had to be killed, 2 on
    input/startup errors."""
    from .fleet.replica import DoubleSpawnError, ReplicaProcess, serve_argv
    from .fleet.router import FleetRouter
    from .models.validation import InputError
    from .runtime.errors import GuardError

    replicas = []
    try:
        if args.replicas < 1:
            raise InputError("--replicas must be >= 1")
        if args.probe_interval <= 0:
            raise InputError("--probe-interval must be > 0 seconds")
        if args.probe_timeout <= 0:
            raise InputError("--probe-timeout must be > 0 seconds")
        if args.drain_timeout < 0:
            raise InputError("--drain-timeout must be >= 0 seconds")
        if args.spawn_attempts < 1:
            raise InputError("--spawn-attempts must be >= 1")
        if args.checkpoint_interval is not None and args.checkpoint_interval < 1:
            raise InputError("--checkpoint-interval must be >= 1 delta")
        if args.keep_checkpoints is not None and args.keep_checkpoints < 1:
            raise InputError("--keep-checkpoints must be >= 1")
        slo_engine = _build_slo_engine(args)
        if not os.path.isfile(args.simon_config):
            raise InputError(f"config file not found: {args.simon_config}")
        fleet_dir = os.path.abspath(args.fleet_dir)
        os.makedirs(fleet_dir, exist_ok=True)
        # replicas share ONE content-addressed store: the first spawn
        # populates it, every later spawn (and every failover
        # replacement) boots zero-compile from it
        store = (
            os.path.abspath(args.aot_store)
            if args.aot_store
            else os.path.join(fleet_dir, "aot-store")
        )
        extra = []
        if args.max_batch is not None:
            extra += ["--max-batch", str(args.max_batch)]
        if args.queue_depth is not None:
            extra += ["--queue-depth", str(args.queue_depth)]
        if args.default_deadline is not None:
            extra += ["--default-deadline", str(args.default_deadline)]
        if args.tick_budget is not None:
            extra += ["--tick-budget", str(args.tick_budget)]
        if args.drain_timeout:
            extra += ["--drain-timeout", str(args.drain_timeout)]
        if args.no_incremental:
            extra += ["--no-incremental"]
        config_path = os.path.abspath(args.simon_config)
        for i in range(args.replicas):
            slot = f"r{i}"
            rep = ReplicaProcess(
                slot,
                [],  # argv bound below, once the snapshot path exists
                fleet_dir,
                probe_timeout_s=args.probe_timeout,
            )
            rep.argv = serve_argv(
                config_path,
                aot_store=store,
                snapshot_path=rep.snapshot_path,
                checkpoint_interval=args.checkpoint_interval,
                keep_checkpoints=args.keep_checkpoints,
                extra=extra,
            )
            replicas.append(rep)
        # first replica spawns alone (it pays the compiles that warm
        # the shared store), the rest spawn concurrently and boot warm
        replicas[0].spawn(attempts=args.spawn_attempts)
        if len(replicas) > 1:
            import threading as _threading

            errors = []

            def _spawn(rep):
                try:
                    rep.spawn(attempts=args.spawn_attempts)
                except Exception as e:  # noqa: BLE001 - re-raised below
                    errors.append((rep.slot, e))

            threads = [
                _threading.Thread(target=_spawn, args=(r,))
                for r in replicas[1:]
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            if errors:
                # surface the first concurrent-spawn failure with its
                # original (taxonomy-typed) class intact
                raise errors[0][1]
        # failover audit timeline (fleet/audit.py): fsync'd JSONL in
        # the fleet dir unless pointed elsewhere (or disabled)
        audit = None
        if not args.no_audit_log:
            from .fleet.audit import FailoverAudit

            audit = FailoverAudit(
                args.audit_log
                or os.path.join(fleet_dir, "failover-audit.jsonl")
            )
        router = FleetRouter(
            replicas,
            host=args.host,
            port=args.port,
            probe_interval_s=args.probe_interval,
            drain_timeout_s=args.drain_timeout,
            slo_engine=slo_engine,
            obs_cadence_s=args.obs_cadence,
            spawn_attempts=args.spawn_attempts,
            audit=audit,
        )
    except (
        OSError,
        ValueError,
        RuntimeError,
        GuardError,
        DoubleSpawnError,
        InputError,
    ) as e:
        print(f"error: {e}", file=sys.stderr)
        for rep in replicas:
            rep.kill()
            rep.release()
        return 2
    from .obs.telemetry import arm_flight_recorder

    arm_flight_recorder()
    router.start()
    # machine-parsable readiness line (tests and the CI smoke step
    # read the bound port from it — --port 0 binds an ephemeral one)
    print(
        f"simon fleet listening on http://{router.host}:{router.port} "
        f"({len(replicas)} replicas)",
        flush=True,
    )
    return router.run_until_signaled()


@_with_obs("shadow")
def cmd_shadow(args) -> int:
    """Shadow-scheduler divergence auditor (shadow/;
    docs/OBSERVABILITY.md): record simon's own decisions as a log,
    replay a recorded log of real scheduler decisions against the
    config's cluster, or tail a live cluster — and explain every
    disagreement. Exit 0 on full agreement, 1 when divergences were
    found, 2 on input errors, 3/4 on deadline/interrupt partials."""
    import json

    from .apply.applier import Applier, SimonConfig
    from .models.validation import InputError
    from .runtime import (
        Budget,
        ExecutionHalted,
        ExternalIOError,
        Interrupted,
        sigint_to_budget,
    )
    from .shadow.log import DecisionLogWriter, cluster_fingerprint, read_decision_log
    from .shadow.record import record_simulation
    from .shadow.replay import ShadowReplayer

    _force_platform()
    try:
        modes = sum(bool(m) for m in (args.record, args.decision_log, args.tail))
        if modes != 1:
            raise InputError(
                "pick exactly one mode: --record PATH (write simon's own "
                "decisions), --decision-log PATH (replay a recorded log), "
                "or --tail (poll the config's live cluster)"
            )
        config = SimonConfig.from_file(args.simon_config)
        applier = Applier(config)
        budget = Budget(args.deadline)
        if args.tail and not config.kube_config:
            raise InputError(
                "--tail needs a kubeConfig cluster in the simon config "
                "(customConfig clusters have no scheduler to shadow)"
            )
        if args.max_catchup < 1:
            raise InputError(
                "--max-catchup must be >= 1 (0 would never replay the "
                "backlog and the mirror would stop advancing)"
            )
    except (OSError, ValueError, InputError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    try:
        with sigint_to_budget(budget):
            if args.record:
                cluster = applier.load_cluster()
                apps = applier.load_apps()
                steps = []
                try:
                    record_simulation(
                        cluster, apps, budget=budget, steps_out=steps
                    )
                except ExecutionHalted as e:
                    # a deadline/SIGINT still writes the completed
                    # prefix — a valid, replayable log — and reports it
                    if steps:
                        with DecisionLogWriter(
                            args.record, cluster_fingerprint(cluster)
                        ) as w:
                            for s in steps:
                                w.append(s)
                    e.partial = {
                        "recordedSteps": len(steps),
                        "decisionLog": args.record if steps else None,
                    }
                    raise
                decisions = sum(1 for s in steps if s.kind == "decision")
                scheduled = sum(
                    1 for s in steps if s.kind == "decision" and s.node
                )
                with DecisionLogWriter(
                    args.record, cluster_fingerprint(cluster)
                ) as w:
                    for s in steps:
                        w.append(s)
                print(
                    f"recorded {decisions} decision(s) ({scheduled} "
                    f"scheduled, {decisions - scheduled} failed) across "
                    f"{len(steps)} step(s) to {args.record}"
                )
                return 0
            if args.decision_log:
                cluster = applier.load_cluster()
                fp = cluster_fingerprint(cluster)
                steps, meta = read_decision_log(
                    args.decision_log,
                    fingerprint=None
                    if args.allow_fingerprint_mismatch
                    else fp,
                )
                replayer = ShadowReplayer(cluster, engine=args.engine)
                replayer.report.dropped_records = meta.get("dropped", 0)
                try:
                    report = replayer.run(steps, budget=budget)
                except ExecutionHalted as e:
                    # the audit so far IS the partial result
                    e.partial = {"shadow": replayer.finish().as_dict()}
                    raise
            else:  # --tail
                report = _shadow_tail(args, config, budget)
    except ExecutionHalted as e:
        return _emit_partial(e, args, "")
    except KeyboardInterrupt:
        return _emit_partial(
            Interrupted("interrupted before any safe boundary"), args, ""
        )
    except (OSError, InputError, ExternalIOError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.format == "json":
        payload = report.as_dict()
        explain = _explanations_payload(args)
        if explain is not None:
            payload["explain"] = explain
        print(json.dumps(payload, sort_keys=True))
    else:
        print(report.render_text())
        _print_explanations(args)
    return 0 if report.divergence_count == 0 else 1


def _shadow_tail(args, config, budget):
    """Live shadow loop: bootstrap the mirror from the first LIST, then
    poll-diff-replay until --max-polls / --max-steps / deadline.

    Resident-service hardening (docs/ROBUSTNESS.md): the apiserver's
    circuit breaker gets a recovery cooldown (--breaker-cooldown), a
    failed poll counts a flap and the loop BACKS OFF and continues
    instead of aborting the audit, and a recovered flap's backlog
    replays at most --max-catchup steps per round (bounded catch-up:
    the mirror converges without one giant stop-the-world replay)."""
    import collections
    import time

    from .models.decode import ResourceTypes
    from .models.kubeclient import KubeClient
    from .runtime import ExecutionHalted, ExternalIOError
    from .runtime import inject as _inject
    from .runtime.retry import backoff_delay, enable_breaker_recovery
    from .shadow.ingest import ClusterTailer
    from .shadow.log import DecisionLogWriter, cluster_fingerprint
    from .shadow.replay import ShadowReplayer
    from .utils.trace import COUNTERS, GLOBAL

    if args.breaker_cooldown and args.breaker_cooldown > 0:
        enable_breaker_recovery(args.breaker_cooldown)
    with KubeClient(config.kube_config) as client:
        tailer = ClusterTailer(client)
        nodes, boot_steps = tailer.bootstrap()
        cluster = ResourceTypes()
        cluster.nodes = nodes
        replayer = ShadowReplayer(cluster, engine=args.engine)
        writer = None
        if args.tail_record:
            writer = DecisionLogWriter(
                args.tail_record, cluster_fingerprint(cluster)
            )
        pending = collections.deque()  # observed, not yet replayed

        def apply_step(st):
            if writer is not None:
                writer.append(st)
            replayer.step(st)

        try:
            for st in boot_steps:
                apply_step(st)
            polls = flaps = 0
            while True:
                if budget is not None:
                    budget.check(f"shadow tail (poll {polls})")
                if args.max_polls is not None and polls >= args.max_polls:
                    break
                if (
                    args.max_steps is not None
                    and replayer.report.decisions >= args.max_steps
                ):
                    break
                if polls:
                    time.sleep(args.poll_interval)
                try:
                    # chaos seam: `shadow.poll` faults (reset/timeout/
                    # http:NNN/exio) land like a real apiserver flap
                    _inject.fire("shadow.poll", poll=polls)
                    pending.extend(tailer.poll())
                except (ExternalIOError, OSError) as e:
                    # apiserver flap: count it, note it, back off
                    # (bounded, deterministic), keep the audit alive —
                    # the breaker behind tailer.poll() fails further
                    # calls fast until its cooldown elapses
                    flaps += 1
                    COUNTERS.inc("shadow_tail_flaps_total")
                    GLOBAL.append_note(
                        "shadow-tail-flap",
                        f"poll {polls}: {str(e)[:100]}",
                    )
                    logging.warning(
                        "shadow tail poll failed (%s); continuing", e
                    )
                    time.sleep(
                        min(backoff_delay("shadow-tail", min(flaps, 6)),
                            args.poll_interval)
                    )
                else:
                    flaps = 0
                # bounded catch-up: a big post-flap diff replays across
                # rounds; the backlog depth is observable
                applied = 0
                while pending and applied < args.max_catchup:
                    if budget is not None:
                        budget.check(f"shadow tail (poll {polls}, catch-up)")
                    apply_step(pending.popleft())
                    applied += 1
                if pending:
                    COUNTERS.inc("shadow_tail_deferred_steps_total", len(pending))
                    GLOBAL.append_note(
                        "shadow-tail-catchup",
                        f"poll {polls}: {applied} applied, "
                        f"{len(pending)} deferred to the next round",
                    )
                COUNTERS.gauge("shadow_tail_backlog", float(len(pending)))
                polls += 1
            # drain any deferred backlog before reporting: everything
            # observed is audited (budget still owns the halt) — but
            # --max-steps stays a hard cap: past it the remainder is
            # RECORDED (--tail-record holds every observed step), not
            # replayed, so a recovered flap's giant diff cannot blow
            # through the user's explicit bound
            while pending:
                if (
                    args.max_steps is not None
                    and replayer.report.decisions >= args.max_steps
                ):
                    if writer is not None:
                        for st in pending:
                            writer.append(st)
                    COUNTERS.inc(
                        "shadow_tail_deferred_steps_total", len(pending)
                    )
                    GLOBAL.append_note(
                        "shadow-tail-catchup",
                        f"final drain stopped at --max-steps "
                        f"{args.max_steps}; {len(pending)} observed "
                        "step(s) recorded but not audited",
                    )
                    pending.clear()
                    break
                if budget is not None:
                    budget.check("shadow tail (final catch-up)")
                apply_step(pending.popleft())
        except ExecutionHalted as e:
            # everything audited before the halt is the partial result
            # (the --tail-record log already holds the observed steps)
            e.partial = {"shadow": replayer.finish().as_dict()}
            raise
        finally:
            if writer is not None:
                writer.close()
    return replayer.finish()


@_with_obs("timeline")
def cmd_timeline(args) -> int:
    """Discrete-event cluster timeline (timeline/; docs/TIMELINE.md):
    play a trace of pod arrivals/departures, node churn, and spot
    reclamations through N autoscaler policies as batched scenario rows
    over one encoding, and emit per-step cost/utilization/pending
    curves per policy. Exit 0 on a completed run, 2 on input errors,
    3/4 on deadline/interrupt partials."""
    import json

    from .apply.applier import Applier, SimonConfig
    from .models.validation import InputError
    from .parallel.sweep import PrioritySignalError
    from .runtime import (
        Budget,
        ExecutionHalted,
        ExternalIOError,
        Interrupted,
        Journal,
        sigint_to_budget,
    )
    from .runtime.journal import config_fingerprint
    from .timeline.autoscaler import parse_policies
    from .timeline.compare import run_policies
    from .timeline.events import (
        SyntheticSpec,
        events_from_decision_log,
        generate_synthetic,
        read_trace,
        trace_fingerprint,
        write_trace,
    )
    from .utils.trace import GLOBAL

    _force_platform()
    try:
        _configure_mesh(args)
        sources = sum(
            1 for m in (args.synthetic, args.trace, args.from_decision_log)
            if m
        )
        if sources != 1:
            raise InputError(
                "pick exactly one trace source: --synthetic N (seeded "
                "generator), --trace PATH (timeline-trace JSONL), or "
                "--from-decision-log PATH (shadow decision log)"
            )
        if args.synthetic < 0:
            raise InputError(
                f"--synthetic N must be >= 1, got {args.synthetic}"
            )
        config = SimonConfig.from_file(args.simon_config)
        applier = Applier(config)
        cluster = applier.load_cluster()
        new_node = applier.load_new_node()
        specs = list(args.policy or [])
        for group in args.compare or []:
            specs.extend(s for s in group.split(",") if s)
        policies = parse_policies(specs or ["threshold"])
        budget = Budget(args.deadline)

        if args.synthetic:
            node_names = [
                (n.get("metadata") or {}).get("name") or ""
                for n in cluster.nodes
            ]
            events = generate_synthetic(
                SyntheticSpec(
                    arrivals=args.synthetic,
                    arrival_rate=args.arrival_rate,
                    mean_lifetime_s=args.mean_lifetime,
                    long_running_frac=args.long_running_frac,
                    spot_frac=args.spot_frac,
                    spot_hazard=args.spot_hazard,
                    seed=args.seed,
                ),
                node_names,
            )
        elif args.trace:
            events, meta = read_trace(args.trace)
            if meta.get("dropped"):
                print(
                    f"note: dropped {meta['dropped']} torn trailing trace "
                    "record",
                    file=sys.stderr,
                )
        else:
            from .shadow.log import cluster_fingerprint, read_decision_log

            steps, _meta = read_decision_log(
                args.from_decision_log,
                fingerprint=None
                if args.allow_fingerprint_mismatch
                else cluster_fingerprint(cluster),
            )
            events = events_from_decision_log(steps)
        if args.save_trace:
            fp = write_trace(args.save_trace, events)
            print(
                f"timeline trace ({len(events)} events, fingerprint {fp}) "
                f"written to {args.save_trace}",
                file=sys.stderr,
            )
    except (OSError, ValueError, ExternalIOError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    journal = None
    journal_path = args.resume or args.journal
    GLOBAL.reset()
    try:
        if journal_path:
            from .shadow.log import cluster_fingerprint

            # cluster + newNode identity MUST be in the fingerprint:
            # journaled placements are node indices of one encoding,
            # and replaying them against a different cluster would be
            # silently wrong (the plan_fingerprint rule in apply/chaos)
            fp = config_fingerprint(
                cluster_fingerprint(cluster),
                new_node,
                trace_fingerprint(events),
                [p.name for p in policies],
                {
                    "cadence": args.cadence,
                    "warmup": args.warmup,
                    "maxNodes": args.max_nodes,
                    "windowArrivals": args.window_arrivals,
                    "engine": args.engine,
                },
            )
            journal = (
                Journal.resume(args.resume, fp)
                if args.resume
                else Journal.open(args.journal, fp)
            )
        with sigint_to_budget(budget):
            comparison = run_policies(
                cluster,
                events,
                policies,
                new_node_spec=new_node,
                max_nodes=args.max_nodes,
                cadence_s=args.cadence,
                warmup_s=args.warmup,
                window_arrivals=args.window_arrivals,
                engine=args.engine,
                budget=budget,
                journal=journal,
            )
    except ExecutionHalted as e:
        return _emit_partial(e, args, journal_path)
    except KeyboardInterrupt:
        return _emit_partial(
            Interrupted("interrupted before any safe boundary"),
            args,
            journal_path,
        )
    except PrioritySignalError as e:
        print(
            f"error: the timeline needs the batched scan path: {e}",
            file=sys.stderr,
        )
        return 2
    except (OSError, InputError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    finally:
        if journal is not None:
            journal.close()
    if args.trace_phases:
        print(GLOBAL.as_json(), file=sys.stderr)
    if args.format == "json":
        payload = comparison.as_dict()
        explain = _explanations_payload(args)
        if explain is not None:
            payload["explain"] = explain
        print(json.dumps(payload))
    else:
        print(comparison.render_text())
        _print_explanations(args)
    return 0


@_with_obs("twin")
def cmd_twin(args) -> int:
    """Live digital-twin daemon (twin/; docs/TWIN.md): continuously
    mirror a cluster — a live apiserver tail (--tail) or a recorded
    decision-log feed (--feed) — on the cluster-delta substrate, audit
    every real scheduler decision against the warm mirror, and answer
    what-if / drain-safety / N+K / capacity-forecast queries over HTTP
    against LIVE state. Exit 0 after a clean SIGTERM/SIGINT drain, 2
    on input errors."""
    import json

    from .apply.applier import Applier, SimonConfig
    from .models.validation import InputError
    from .runtime import ExternalIOError
    from .shadow.log import cluster_fingerprint, read_decision_log
    from .twin.mirror import ClusterMirror, FeedSource, LiveSource
    from .twin.server import TwinDaemon

    _force_platform()
    client = None
    try:
        modes = sum(bool(m) for m in (args.feed, args.tail))
        if modes != 1:
            raise InputError(
                "pick exactly one source: --feed LOG (tail a recorded "
                "decision log) or --tail (poll the config's live cluster)"
            )
        if args.poll_interval <= 0:
            raise InputError("--poll-interval must be > 0 seconds")
        if args.drain_timeout < 0:
            raise InputError("--drain-timeout must be >= 0 seconds")
        if args.tick_budget is not None and args.tick_budget <= 0:
            raise InputError("--tick-budget must be > 0 seconds")
        if args.max_request_pods is not None and args.max_request_pods < 1:
            raise InputError("--max-request-pods must be >= 1")
        if args.max_catchup < 1:
            raise InputError(
                "--max-catchup must be >= 1 (0 would never apply the "
                "backlog and the mirror would stop advancing)"
            )
        if getattr(args, "replay_snapshot", False) and not args.snapshot:
            raise InputError("--replay-snapshot requires --snapshot PATH")
        if args.checkpoint_interval is not None and args.checkpoint_interval < 1:
            raise InputError("--checkpoint-interval must be >= 1 step")
        if args.keep_checkpoints < 1:
            raise InputError("--keep-checkpoints must be >= 1")
        if args.checkpoint_interval and not args.snapshot:
            raise InputError("--checkpoint-interval requires --snapshot PATH")
        slo_engine = _build_slo_engine(args)
        # resident service: breakers recover (the serve posture)
        from .runtime.retry import BREAKER_COOLDOWN_ENV, enable_breaker_recovery

        if args.breaker_cooldown and args.breaker_cooldown > 0:
            if not os.environ.get(BREAKER_COOLDOWN_ENV):
                enable_breaker_recovery(args.breaker_cooldown)
        config = SimonConfig.from_file(args.simon_config)
        applier = Applier(config)
        # arm the artifact store before the mirror bootstrap compiles
        # its first warm scan (zero-compile cold start, serve posture)
        _arm_store(args)
        if args.feed:
            cluster = applier.load_cluster()
            fp = cluster_fingerprint(cluster)
            steps, _meta = read_decision_log(
                args.feed,
                fingerprint=None if args.allow_fingerprint_mismatch else fp,
            )
            source = FeedSource(steps, batch=args.feed_batch)
        else:  # --tail
            if not config.kube_config:
                raise InputError(
                    "--tail needs a kubeConfig cluster in the simon config "
                    "(customConfig clusters have no scheduler to mirror)"
                )
            from .models.decode import ResourceTypes
            from .models.kubeclient import KubeClient
            from .shadow.ingest import ClusterTailer

            client = KubeClient(config.kube_config)
            tailer = ClusterTailer(client)
            nodes, boot_steps = tailer.bootstrap()
            cluster = ResourceTypes()
            cluster.nodes = nodes
            source = LiveSource(tailer, boot_steps=boot_steps)
        mirror = ClusterMirror(
            cluster, source, engine=args.engine, max_catchup=args.max_catchup
        )
        twin_replay = None
        if getattr(args, "replay_snapshot", False) and os.path.exists(
            args.snapshot
        ):
            from .twin.mirror import replay_mirror_journal

            twin_replay = replay_mirror_journal(mirror, args.snapshot)
        mirror.bootstrap()
        if args.snapshot:
            # attach AFTER replay: replayed steps must not re-append
            from .twin.mirror import open_twin_snapshot

            mirror.journal = open_twin_snapshot(args.snapshot)
        daemon = TwinDaemon(
            mirror,
            host=args.host,
            port=args.port,
            poll_interval_s=args.poll_interval,
            max_polls=args.max_polls,
            tick_budget_s=args.tick_budget,
            max_request_pods=args.max_request_pods,
            drain_timeout_s=args.drain_timeout,
            slo_engine=slo_engine,
            obs_cadence_s=args.obs_cadence,
            snapshot_path=args.snapshot or None,
            checkpoint_interval=args.checkpoint_interval,
            keep_checkpoints=args.keep_checkpoints,
        )
        if daemon.checkpoints is not None and twin_replay and twin_replay.get(
            "checkpoint"
        ):
            daemon.checkpoints.note_restored(
                twin_replay["checkpoint"]["deltaSeq"]
            )
    except (OSError, ValueError, ExternalIOError, InputError) as e:
        if client is not None:
            client.close()
        print(f"error: {e}", file=sys.stderr)
        return 2
    from .obs.telemetry import arm_flight_recorder

    arm_flight_recorder()
    daemon.start()
    # machine-parsable readiness line (tests and the CI smoke read the
    # bound port from it — --port 0 binds an ephemeral one)
    print(
        f"simon twin listening on http://{daemon.host}:{daemon.port} "
        f"(mirroring {len(mirror.oracle.nodes)} node(s), "
        f"source {'feed' if args.feed else 'tail'})",
        flush=True,
    )
    if twin_replay is not None:
        ckpt = twin_replay.get("checkpoint")
        print(
            f"simon twin replay: {twin_replay['steps']} step(s) replayed, "
            f"{twin_replay['skippedPrefix']} absorbed by checkpoint "
            + (
                f"(restored seq {ckpt['deltaSeq']} from {ckpt['path']})"
                if ckpt
                else "(no usable checkpoint)"
            ),
            file=sys.stderr,
            flush=True,
        )
    try:
        code = daemon.run_until_signaled()
    finally:
        if client is not None:
            client.close()
    # one JSON summary line on stderr at drain: the audit the mirror
    # accumulated (agreement, divergences, lag) survives the process
    print(
        "simon twin mirror: " + json.dumps(mirror.stats(), sort_keys=True),
        file=sys.stderr,
    )
    from .obs.spans import observatory_block

    observatory = observatory_block()
    if observatory:
        print(
            "simon twin observatory: " + json.dumps(observatory),
            file=sys.stderr,
        )
    return code


def cmd_doctor(args) -> int:
    """Perf-regression doctor (obs/doctor.py): diff a candidate bench
    record against a baseline — headline value, device dispatches,
    XLA recompiles, ledger peak HBM, per-site latency p95s — and exit
    1 on any regression past thresholds. CI runs this over the
    checked-in BENCH_r*.json trajectory so the bench history is an
    enforced contract, not a pile of JSON files."""
    import json

    from .models.validation import InputError
    from .obs import doctor

    try:
        base = doctor.load_bench_record(args.baseline)
        cand = doctor.load_bench_record(args.candidate)
    except (OSError, InputError) as e:
        print(f"simon doctor: {e}", file=sys.stderr)
        return 2
    report = doctor.diff_records(
        base, cand, doctor.Thresholds.from_args(args)
    )
    doc = report.as_dict()
    doc["baseline"] = args.baseline
    doc["candidate"] = args.candidate
    if args.format == "json":
        print(json.dumps(doc, indent=2))
    else:
        print(doctor.render_text(report, args.baseline, args.candidate))
    if args.out:
        try:
            with open(args.out, "w", encoding="utf-8") as f:
                json.dump(doc, f, indent=2)
        except OSError as e:
            print(f"simon doctor: cannot write --out: {e}", file=sys.stderr)
            return 2
    return 0 if report.ok else 1


def _fetch_json(url: str, timeout: float):
    """GET a daemon endpoint, decode JSON. Raises ExternalIOError with
    the endpoint on any transport/decode failure (exit 1/2 mapping is
    the caller's)."""
    import json
    import urllib.error
    import urllib.request

    from .runtime import ExternalIOError

    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return json.loads(resp.read().decode("utf-8"))
    except (OSError, urllib.error.URLError, ValueError) as e:
        raise ExternalIOError(f"cannot read {url}: {e}", endpoint=url) from e


def cmd_top(args) -> int:
    """Live terminal dashboard against a RUNNING serve/twin daemon
    (obs/telemetry.py): polls /v1/obs/snapshot + /v1/obs/series and
    renders health, SLO burn rates, and sparklined history — the
    `kubectl top`-shaped view of a resident simon daemon. --once
    prints a single frame (CI smoke); --format json dumps the raw
    snapshot. Exit 0 on a clean stop (Ctrl-C included), 1 when the
    daemon is unreachable, 2 on input errors."""
    import json as _json

    from .obs import telemetry as _tm
    from .runtime import ExternalIOError

    url = (args.url or f"http://{args.host}:{args.port}").rstrip("/")
    if args.interval <= 0:
        print("error: --interval must be > 0 seconds", file=sys.stderr)
        return 2
    names = list(args.series or ())

    fleet = bool(getattr(args, "fleet", False))

    def fetch():
        from urllib.parse import quote

        snapshot = _fetch_json(f"{url}/v1/obs/snapshot", args.timeout)
        if names:
            want = list(names)
        elif fleet:
            # fleet frame: router-wide signals that exist, plus the
            # per-slot panes for every slot the router reports — a
            # slot whose series are missing (stale TTL cache, fresh
            # respawn) renders as gaps, never an error (the series
            # endpoint answers unknown names with empty lists)
            want = [
                n
                for n in _tm.FLEET_TOP_DEFAULT_SERIES
                if n in (snapshot.get("latest") or {})
            ]
            for slot in sorted(snapshot.get("replicas") or {}):
                want.extend(_tm.fleet_slot_series(str(slot)))
        else:
            want = [
                n
                for n in _tm.TOP_DEFAULT_SERIES
                if n in (snapshot.get("latest") or {})
            ]
        # slot-labeled names carry ':' and '/': percent-encode every
        # name so the query string round-trips them verbatim
        qs = "&".join(f"name={quote(n, safe='')}" for n in want)
        series = (
            _fetch_json(
                f"{url}/v1/obs/series?{qs}&sinceSeconds={args.window:g}",
                args.timeout,
            )
            if want
            else {"series": {}}
        )
        return snapshot, series

    try:
        snapshot, series = fetch()
    except ExternalIOError as e:
        print(f"simon top: {e}", file=sys.stderr)
        return 1
    render = _tm.render_fleet_top_frame if fleet else _tm.render_top_frame
    if args.format == "json":
        print(_json.dumps({"snapshot": snapshot, "series": series}, indent=2))
        return 0
    if args.once:
        print(render(snapshot, series, url))
        return 0
    try:
        while True:
            # ANSI home+clear per frame: a live dashboard, not a scroll
            print("\x1b[2J\x1b[H" + render(snapshot, series, url), flush=True)
            time.sleep(args.interval)
            try:
                snapshot, series = fetch()
            except ExternalIOError as e:
                print(f"simon top: {e}", file=sys.stderr)
                return 1
    except KeyboardInterrupt:
        return 0


def _build_slo_engine(args):
    """--slo-config as an SLOEngine (None when unset) — shared by the
    serve and twin daemons. Raises InputError on a bad config; the
    callers' guarded setup blocks turn that into exit 2 before
    listening."""
    if not getattr(args, "slo_config", ""):
        return None
    from .obs.slo import SLOEngine, load_slo_config

    return SLOEngine(load_slo_config(args.slo_config))


def _add_telemetry_flags(p: argparse.ArgumentParser):
    """Resident-telemetry flags shared by the serve and twin daemons
    (docs/OBSERVABILITY.md production-telemetry section)."""
    p.add_argument(
        "--slo-config",
        default="",
        metavar="PATH",
        help="declarative SLO objectives (JSON or YAML; kinds: "
        "availability, latency, gauge_min, counter_budget, plus the "
        "router-side fleet_availability, fleet_imbalance, and "
        "fleet_failover) evaluated over the resident series store "
        "with multi-window burn-rate alerts — alert states export as "
        "simon_slo_* metrics and /healthz reasons",
    )
    p.add_argument(
        "--obs-cadence",
        type=float,
        default=1.0,
        metavar="SECONDS",
        help="telemetry sampling cadence: every counter/gauge, "
        "histogram percentile, and ledger level lands in the ring "
        "store (queryable at /v1/obs/series, rendered by `simon top`) "
        "once per cadence",
    )


def cmd_version(_args) -> int:
    print(f"simon-tpu version {__version__}")
    return 0


def cmd_gen_doc(args) -> int:
    """Markdown CLI docs (cmd/doc/generate_markdown.go -> cobra
    doc.GenMarkdownTree): one page per command — title, synopsis,
    usage, options, SEE ALSO cross-links — not a single dump. We
    create the output directory when missing (the reference instead
    errors on a missing directory — friendlier here, noted)."""
    parser = build_parser()
    out_dir = args.output
    os.makedirs(out_dir, exist_ok=True)

    def page(path: str, title: str, p: argparse.ArgumentParser, see_also):
        desc = (p.description or "").strip()
        lines = [
            f"## {title}",
            "",
            desc,
            "",
            "### Synopsis",
            "",
            desc,
            "",
            "```",
            p.format_usage().strip(),
            "```",
            "",
            "### Options",
            "",
            "```",
        ]
        opts = p.format_help()
        # keep only the options tail of the help text (cobra pages
        # list flags, not the usage/positional preamble)
        for marker in ("options:", "optional arguments:"):
            if marker in opts:
                opts = opts.split(marker, 1)[1]
                break
        lines.append(opts.strip("\n"))
        lines += ["```", "", "### SEE ALSO", ""]
        for target, file_name, blurb in see_also:
            lines.append(f"* [{target}]({file_name})\t - {blurb}")
        lines.append("")
        with open(path, "w") as f:
            f.write("\n".join(lines))

    sub_action = next(
        a for a in parser._actions if isinstance(a, argparse._SubParsersAction)
    )
    helps = {
        a.dest: a.help or "" for a in sub_action._choices_actions
    } if sub_action._choices_actions else {}
    root_desc = (parser.description or "").strip()
    subs = sorted(sub_action.choices.items())
    page(
        os.path.join(out_dir, "simon.md"),
        "simon",
        parser,
        [
            (f"simon {name}", f"simon_{name}.md", helps.get(name, ""))
            for name, _p in subs
        ],
    )
    for name, sp in subs:
        sp.description = sp.description or helps.get(name, "")
        page(
            os.path.join(out_dir, f"simon_{name}.md"),
            f"simon {name}",
            sp,
            [("simon", "simon.md", root_desc)],
        )
    print(f"wrote {len(subs) + 1} pages to {out_dir}")
    return 0


def _add_obs_flags(p: argparse.ArgumentParser):
    """Flight-recorder flags shared by every long-running command
    (docs/OBSERVABILITY.md): span trace export, per-pod placement
    explanations, JAX profiler capture."""
    p.add_argument(
        "--trace-out",
        default="",
        metavar="PATH",
        help="record a hierarchical span trace of the whole run and "
        "write it on exit: a .json path gets Chrome trace-event format "
        "(loadable in Perfetto / chrome://tracing), a .jsonl path gets "
        "streaming JSONL with each span fsync'd as it closes (a crash "
        "keeps every finished span)",
    )
    p.add_argument(
        "--explain",
        nargs="?",
        const="",
        default=None,
        metavar="POD",
        help="record per-pod placement explanations — per-node filter "
        "verdicts, score vectors, and preemption/escape provenance — "
        "and append them to the output (JSON output gains an `explain` "
        "key). With POD (a pod name or namespace/name) the named pod's "
        "full decision is explained even when it schedules; without, "
        "unschedulable pods are explained (capped)",
    )
    p.add_argument(
        "--profile-dir",
        default="",
        metavar="DIR",
        help="capture JAX profiler traces of the device phases into DIR "
        "(viewable in TensorBoard/Perfetto; equivalent to setting "
        "SIMON_PROFILE_DIR)",
    )


def _add_store_flag(p: argparse.ArgumentParser):
    """Persistent compile-artifact store flag shared by the resident
    daemons (incremental/store.py, docs/PERFORMANCE.md): a warm store
    lets a fresh process answer its first request with zero new XLA
    compiles."""
    p.add_argument(
        "--aot-store", default="", metavar="DIR",
        help="persist AOT-compiled executables to this directory and "
        "load them at startup (content-addressed by shape-signature + "
        "toolchain digest; corrupt/stale entries refused loudly and "
        "recompiled; SIMON_AOT_STORE env is the flagless form)",
    )


def _arm_store(args) -> None:
    """Configure the process-wide artifact store from --aot-store
    BEFORE any jit site compiles (cold-start loads happen at the
    daemon's warmup dispatches)."""
    store_dir = getattr(args, "aot_store", "")
    if store_dir:
        from .incremental.store import configure_store

        configure_store(store_dir)


def _add_inject_flag(p: argparse.ArgumentParser):
    """Chaos fault-injection flag shared by every guarded command
    (runtime/inject.py, docs/ROBUSTNESS.md failure-mode matrix)."""
    p.add_argument(
        "--inject",
        default="",
        metavar="SPEC",
        help="arm deterministic fault injection at the named guard "
        "seams (equivalent to SIMON_INJECT). SPEC is ';'-separated "
        "SITE=FAULT[:PARAM][@N][xCOUNT][%%EVERY][~PROB] clauses, e.g. "
        "'jit.scenario_scan=oom@2' (device OOM at the 2nd dispatch) or "
        "'io.kube*=reset@1x3' (3 connection resets). Sites: jit.<site>, "
        "io.<label>, journal.fsync.<subsystem>, budget.check, "
        "ledger.predict_fit, serve.tick, shadow.poll, timeline.tick, "
        "fleet.route, fleet.probe, fleet.replay, fleet.spawn. "
        "Production paths are unmodified when unset "
        "(docs/ROBUSTNESS.md)",
    )


def _arm_injection(args) -> None:
    """Arm the injector from --inject (overriding any SIMON_INJECT the
    process imported with). Bad specs raise InputError -> exit 2,
    including a malformed SIMON_INJECT the import stashed instead of
    crashing on (runtime/inject.py IMPORT_SPEC_ERROR)."""
    from .runtime import inject as _inject

    spec = getattr(args, "inject", "")
    if spec:
        _inject.INJECT.configure(spec)
    elif _inject.IMPORT_SPEC_ERROR is not None:
        # the stashed value IS an InputError (taxonomy-rooted); the
        # lint cannot see through the variable
        raise _inject.IMPORT_SPEC_ERROR  # simonlint: disable=EXC001


def _add_mesh_flag(p: argparse.ArgumentParser):
    p.add_argument(
        "--mesh",
        default=None,
        metavar="auto|off|N",
        help="shard batched scans over a device mesh: auto = every "
        "local device, N = the first N devices, off = single-device "
        "(the default; the SIMON_MESH env var changes it). The layout "
        "planner picks node-axis vs scenario-axis sharding per "
        "dispatch from the cost/memory observatory "
        "(docs/PERFORMANCE.md); faults on the mesh degrade down the "
        "single-device guard ladder",
    )


def _configure_mesh(args) -> None:
    """Wire --mesh into the process-wide mesh (parallel/mesh.py). The
    flag wins; without it the SIMON_MESH env default stands. Resolves
    devices eagerly so a bad device count is a clean exit-2 InputError
    here, not a traceback deep inside a sweep."""
    from .parallel import mesh as mesh_mod

    spec = getattr(args, "mesh", None)
    if spec is not None:
        mesh_mod.configure(spec)
    mesh_mod.current_mesh()


def _add_guard_flags(p: argparse.ArgumentParser):
    """Execution-guard flags shared by the long-running commands
    (docs/ROBUSTNESS.md): wall-clock budget + resumable journal."""
    _add_inject_flag(p)
    p.add_argument(
        "--deadline",
        type=float,
        default=None,
        metavar="SECONDS",
        help="wall-clock budget: on expiry (or SIGINT) the run stops at "
        "the next safe boundary and emits a machine-readable PARTIAL "
        "report (exit 3 deadline / 4 interrupt) instead of a traceback",
    )
    p.add_argument(
        "--journal",
        default="",
        metavar="PATH",
        help="append completed probe results and scenario verdicts to "
        "this crash-safe JSONL journal (created when missing, continued "
        "when it matches this run's config fingerprint)",
    )
    p.add_argument(
        "--resume",
        default="",
        metavar="PATH",
        help="resume from a journal written by --journal: validates the "
        "config fingerprint (mismatch refuses loudly), replays complete "
        "records, re-executes zero journaled work, and keeps appending",
    )


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(prog="simon", description="TPU-native cluster simulator")
    sub = parser.add_subparsers(dest="command")

    p_apply = sub.add_parser("apply", help="simulate deploying applications")
    p_apply.add_argument("-f", "--simon-config", required=True, help="simon config file path")
    p_apply.add_argument("-i", "--interactive", action="store_true", help="interactive mode")
    p_apply.add_argument(
        "--extended-resources",
        type=lambda s: [x for x in s.split(",") if x],
        default=[],
        help="extended resource reports: gpu,open-local",
    )
    p_apply.add_argument(
        "--default-scheduler-config",
        default="",
        help="KubeSchedulerConfiguration file; its `extenders:` section is "
        "honored (HTTP filter/prioritize/bind callbacks; forces the serial "
        "engine). Dead option in the reference, functional here.",
    )
    p_apply.add_argument(
        "--use-greed",
        action="store_true",
        help="order pods by descending dominant-resource share (dead flag in the reference; functional here)",
    )
    p_apply.add_argument("--engine", choices=["tpu", "oracle"], default="tpu")
    p_apply.add_argument(
        "--no-sweep", action="store_true", help="disable the batched capacity sweep"
    )
    p_apply.add_argument(
        "--tolerate-node-failures",
        type=int,
        default=0,
        metavar="K",
        help="raise the plan until it survives any K node failures "
        "(N+K; outage scenarios per docs/RESILIENCE.md, confirmed by a "
        "serial re-simulation of one sampled outage)",
    )
    p_apply.add_argument(
        "--chaos-seed",
        type=int,
        default=1,
        help="seed for the deterministic K-failure scenario sampling",
    )
    p_apply.add_argument(
        "--chaos-trials",
        type=int,
        default=32,
        help="sampled K-failure scenarios per escalation (K >= 2)",
    )
    _add_mesh_flag(p_apply)
    _add_guard_flags(p_apply)
    _add_obs_flags(p_apply)
    p_apply.add_argument(
        "--format", choices=["table", "json"], default="table", help="result output format"
    )
    p_apply.add_argument(
        "--snapshot", default="", help="write the resulting cluster snapshot to this file"
    )
    p_apply.add_argument(
        "--trace",
        action="store_true",
        help="print per-phase wall-clock JSON to stderr (set SIMON_PROFILE_DIR "
        "for a JAX profiler capture of the scan phases)",
    )
    p_apply.set_defaults(func=cmd_apply)

    p_defrag = sub.add_parser(
        "defrag",
        help="pod-migration defragmentation plan from a cluster snapshot",
    )
    p_defrag.add_argument(
        "--snapshot", required=True, help="snapshot file from `simon apply --snapshot`"
    )
    p_defrag.add_argument(
        "--max-drain",
        type=int,
        default=None,
        help="limit the number of nodes considered for draining",
    )
    p_defrag.add_argument(
        "--keep-new-nodes",
        action="store_true",
        help="exempt simon-added new nodes from draining",
    )
    p_defrag.add_argument(
        "--format", choices=["table", "json"], default="table", help="result output format"
    )
    _add_obs_flags(p_defrag)
    p_defrag.set_defaults(func=cmd_defrag)

    p_chaos = sub.add_parser(
        "chaos",
        help="fault-injection survivability report for a committed plan",
        description="Plan (or take --new-node-count as committed), then "
        "evaluate node-outage scenarios against the committed placement: "
        "surviving pods stay put, displaced pods reschedule on the "
        "residual capacity, and the report states which pods fail to "
        "reschedule and why (docs/RESILIENCE.md). Exit 0 when every "
        "scenario survives, 2 otherwise.",
    )
    p_chaos.add_argument("-f", "--simon-config", required=True, help="simon config file path")
    p_chaos.add_argument(
        "--failures",
        type=int,
        default=1,
        metavar="K",
        help="simultaneous node failures: 1 = exhaustive singles; K >= 2 "
        "adds seeded-sampled K-subsets; 0 = replacement study (no outage)",
    )
    p_chaos.add_argument(
        "--seed", type=int, default=1, help="scenario-sampling seed (deterministic)"
    )
    p_chaos.add_argument(
        "--trials", type=int, default=32, help="sampled K-subset scenarios (K >= 2)"
    )
    p_chaos.add_argument(
        "--new-node-count",
        type=int,
        default=None,
        metavar="N",
        help="treat N new nodes as the committed plan instead of planning first",
    )
    p_chaos.add_argument(
        "--cordon",
        default="",
        metavar="NODE[,NODE]",
        help="evaluate scenarios with these nodes cordoned (unschedulable "
        "for rescheduling; their pods stay)",
    )
    p_chaos.add_argument(
        "--taint",
        action="append",
        metavar="key[=value]:Effect[@node1,node2]",
        help="evaluate scenarios with this taint applied (repeatable; no "
        "@nodes = every cluster node)",
    )
    p_chaos.add_argument(
        "--degrade",
        default="",
        metavar="PCT[@node1,node2]",
        help="evaluate scenarios with allocatable cpu/memory reduced PCT%% "
        "on the named nodes (default all)",
    )
    p_chaos.add_argument("--use-greed", action="store_true", help=argparse.SUPPRESS)
    _add_mesh_flag(p_chaos)
    _add_guard_flags(p_chaos)
    _add_obs_flags(p_chaos)
    p_chaos.add_argument(
        "--format", choices=["table", "json"], default="table", help="result output format"
    )
    p_chaos.add_argument(
        "--trace",
        action="store_true",
        help="print per-phase wall-clock JSON to stderr",
    )
    p_chaos.set_defaults(func=cmd_chaos)

    p_serve = sub.add_parser(
        "serve",
        help="long-lived what-if scheduling daemon (JSON-over-HTTP)",
        description="Load the cluster once, pre-warm the encode and "
        "compiled-scan caches, and serve concurrent what-if questions: "
        "POST /v1/simulate with app YAML answers exactly like a "
        "standalone simulation of those apps on the loaded cluster "
        "under the DEFAULT scheduler profile (apply's "
        "--default-scheduler-config / --use-greed customizations are "
        "not served — docs/SERVING.md). Concurrent requests coalesce "
        "onto batched device scans (up to --max-batch per dispatch); "
        "overload sheds with 503 + Retry-After at --queue-depth; "
        "SIGTERM drains in-flight requests then exits 0.",
    )
    p_serve.add_argument(
        "-f", "--simon-config", required=True,
        help="simon config file path (its cluster section is served; "
        "appList is ignored — apps arrive per request)",
    )
    p_serve.add_argument("--host", default="127.0.0.1", help="bind address")
    p_serve.add_argument(
        "--port", type=int, default=8080,
        help="bind port (0 = ephemeral; the readiness line prints it)",
    )
    p_serve.add_argument(
        "--max-batch", type=int, default=16, metavar="B",
        help="max requests coalesced into one batched device scan",
    )
    p_serve.add_argument(
        "--queue-depth", type=int, default=64, metavar="N",
        help="bounded request queue; submits beyond it shed with 503",
    )
    p_serve.add_argument(
        "--default-deadline", type=float, default=None, metavar="SECONDS",
        help="per-request deadline when the request body sets none; a "
        "request whose deadline expires while queued is shed with a "
        "machine-readable PARTIAL 503 body",
    )
    p_serve.add_argument(
        "--drain-timeout", type=float, default=30.0, metavar="SECONDS",
        help="SIGTERM drain bound: queued requests still unanswered "
        "after this are shed and the daemon exits 3 instead of 0",
    )
    p_serve.add_argument(
        "--no-warm", action="store_true",
        help="skip the pre-listen warmup request (faster start, slower "
        "first request)",
    )
    p_serve.add_argument(
        "--tick-budget", type=float, default=None, metavar="SECONDS",
        help="admission latency budget: a request whose predicted wait "
        "(p95 coalescer tick x ticks queued ahead) exceeds this is shed "
        "with 429 + Retry-After before it takes a queue slot "
        "(docs/SERVING.md admission control; default: off)",
    )
    p_serve.add_argument(
        "--max-request-pods", type=int, default=None, metavar="N",
        help="requests whose estimated pod count exceeds N are routed "
        "to the serial oracle instead of the batched scan (one giant "
        "request must not recompile the scan for everyone; default: off)",
    )
    p_serve.add_argument(
        "--max-sessions", type=int, default=8, metavar="N",
        help="warm-session LRU capacity (multi-tenant fleets); the "
        "configured cluster is pinned, secondaries evict LRU-first and "
        "under device-memory ledger pressure",
    )
    p_serve.add_argument(
        "--snapshot", default="", metavar="PATH",
        help="append session admit/evict/drain records to this "
        "crash-safe JSONL snapshot journal (resumed across restarts; "
        "torn tail recovered, interior damage refused)",
    )
    p_serve.add_argument(
        "--replay-snapshot", action="store_true",
        help="before listening, replay the --snapshot journal's "
        "cluster-delta stream into the fresh session (the fleet "
        "failover bootstrap: a replacement replica rejoins with the "
        "dead replica's warm state, dict-identical and — with a warm "
        "--aot-store — at zero new XLA compiles; docs/FLEET.md)",
    )
    p_serve.add_argument(
        "--checkpoint-interval", type=int, default=None, metavar="DELTAS",
        help="write a verified, content-addressed checkpoint of the "
        "committed session every N applied deltas (requires "
        "--snapshot); a restore then replays at most N journal "
        "deltas instead of the daemon's whole history, and the "
        "replayed prefix is compacted away only AFTER the snapshot's "
        "state digest verifies against a fresh materialization "
        "(docs/ROBUSTNESS.md; default: off)",
    )
    p_serve.add_argument(
        "--keep-checkpoints", type=int, default=2, metavar="N",
        help="checkpoint generations retained; a corrupt newest "
        "generation falls back loudly to the previous one plus a "
        "longer journal replay, never a silent wrong state "
        "(default 2)",
    )
    _add_store_flag(p_serve)
    p_serve.add_argument(
        "--no-incremental", action="store_true",
        help="disable delta re-simulation: every tick re-scans the "
        "whole roster instead of dispatching only the request suffix "
        "against the resident committed scan (docs/PERFORMANCE.md)",
    )
    _add_inject_flag(p_serve)
    _add_obs_flags(p_serve)
    _add_telemetry_flags(p_serve)
    p_serve.set_defaults(func=cmd_serve)

    p_fleet = sub.add_parser(
        "fleet",
        help="N-replica serve fleet behind one consistent-hash router",
        description="Spawn N `simon serve` replicas sharing one "
        "content-addressed AOT store and route requests tenant-affine "
        "over a consistent-hash ring (docs/FLEET.md). The router "
        "probes each replica's /healthz, honors degraded Retry-After "
        "hints, and fails over on replica death: in-flight requests "
        "reroute with their ORIGINAL X-Simon-Request-Id (503 + "
        "Retry-After when no replica can answer, never a silent "
        "drop), and the replacement replica resumes its slot's "
        "snapshot journal, replays the dead replica's cluster-delta "
        "stream, and answers its first request at zero new XLA "
        "compiles. Fleet-aggregated /metrics carries per-replica "
        "labels from a cardinality-bounded allowlist. SIGTERM drains "
        "every replica then exits 0.",
    )
    p_fleet.add_argument(
        "-f", "--simon-config", required=True,
        help="simon config file served by every replica",
    )
    p_fleet.add_argument(
        "--replicas", type=int, default=2, metavar="N",
        help="serve replicas to spawn and supervise (default 2)",
    )
    p_fleet.add_argument("--host", default="127.0.0.1", help="bind address")
    p_fleet.add_argument(
        "--port", type=int, default=8080,
        help="router bind port (0 = ephemeral; the readiness line "
        "prints it; replicas always bind ephemeral ports)",
    )
    p_fleet.add_argument(
        "--fleet-dir", default="simon-fleet", metavar="DIR",
        help="fleet state directory: per-slot snapshot journals, "
        "slot lock files, replica logs, and (unless --aot-store is "
        "set) the shared artifact store (default ./simon-fleet)",
    )
    p_fleet.add_argument(
        "--probe-interval", type=float, default=2.0, metavar="SECONDS",
        help="health-probe cadence per replica; a degraded replica's "
        "Retry-After hint stretches its own cadence (default 2.0)",
    )
    p_fleet.add_argument(
        "--probe-timeout", type=float, default=5.0, metavar="SECONDS",
        help="per-probe HTTP timeout (default 5.0)",
    )
    p_fleet.add_argument(
        "--drain-timeout", type=float, default=30.0, metavar="SECONDS",
        help="SIGTERM drain bound per replica; a replica still up "
        "after this is killed and the fleet exits 3 instead of 0",
    )
    p_fleet.add_argument(
        "--spawn-attempts", type=int, default=4, metavar="N",
        help="spawn attempts per replica (capped-exponential backoff "
        "between attempts) before a boot or failover gives up",
    )
    p_fleet.add_argument(
        "--max-batch", type=int, default=None, metavar="B",
        help="forwarded to every replica (see `simon serve`)",
    )
    p_fleet.add_argument(
        "--queue-depth", type=int, default=None, metavar="N",
        help="forwarded to every replica (see `simon serve`)",
    )
    p_fleet.add_argument(
        "--default-deadline", type=float, default=None, metavar="SECONDS",
        help="forwarded to every replica (see `simon serve`)",
    )
    p_fleet.add_argument(
        "--tick-budget", type=float, default=None, metavar="SECONDS",
        help="forwarded to every replica (see `simon serve`)",
    )
    p_fleet.add_argument(
        "--no-incremental", action="store_true",
        help="forwarded to every replica (see `simon serve`)",
    )
    p_fleet.add_argument(
        "--checkpoint-interval", type=int, default=None, metavar="DELTAS",
        help="forwarded to every replica: checkpoint the committed "
        "session every N deltas so a failover replays at most N "
        "journal deltas (bounded recovery; see `simon serve` and "
        "docs/FLEET.md)",
    )
    p_fleet.add_argument(
        "--keep-checkpoints", type=int, default=None, metavar="N",
        help="forwarded to every replica (see `simon serve`)",
    )
    p_fleet.add_argument(
        "--audit-log", default="", metavar="PATH",
        help="failover audit timeline path (fsync'd JSONL: probe_flap "
        "-> declared_dead -> lock_reclaim -> respawn -> replay_progress "
        "-> first_200 per failover, validated by "
        "tools/validate_audit.py; default <fleet-dir>/"
        "failover-audit.jsonl)",
    )
    p_fleet.add_argument(
        "--no-audit-log", action="store_true",
        help="disable the failover audit timeline",
    )
    _add_store_flag(p_fleet)
    _add_inject_flag(p_fleet)
    _add_obs_flags(p_fleet)
    _add_telemetry_flags(p_fleet)
    p_fleet.set_defaults(func=cmd_fleet)

    p_shadow = sub.add_parser(
        "shadow",
        help="shadow-scheduler divergence auditor (replay/tail real decisions)",
        description="Audit simon against a real scheduler's decisions: "
        "replay each recorded (or live-tailed) scheduling decision "
        "through the warm oracle/scan against the same evolving cluster "
        "state, classify every step as agree / node-divergence / "
        "feasibility-divergence / ordering-divergence, and attach "
        "per-node filter verdicts and weighted score vectors to every "
        "disagreement (docs/OBSERVABILITY.md). --record writes a log of "
        "simon's OWN serial decisions (the self-conformance fixture and "
        "trace generator); --decision-log replays a recorded log against "
        "the config's cluster; --tail polls the config's live kubeConfig "
        "cluster. Replay commits the REAL decision after each probe, so "
        "the mirror tracks reality; same-shaped steps re-dispatch warm "
        "compiled scans (zero jit-cache misses after the first step of "
        "each shape — measured in the report). Exit 0 on full agreement, "
        "1 when divergences were found.",
    )
    p_shadow.add_argument(
        "-f", "--simon-config", required=True, help="simon config file path"
    )
    p_shadow.add_argument(
        "--record",
        default="",
        metavar="PATH",
        help="record simon's own serial decisions for the config's "
        "cluster+apps as a fingerprinted decision log (fsync'd JSONL)",
    )
    p_shadow.add_argument(
        "--decision-log",
        default="",
        metavar="PATH",
        help="replay this decision log against the config's cluster and "
        "report the divergence taxonomy (fingerprint mismatch refuses "
        "loudly)",
    )
    p_shadow.add_argument(
        "--tail",
        action="store_true",
        help="poll the config's live kubeConfig cluster and audit its "
        "scheduler's decisions as they appear",
    )
    p_shadow.add_argument(
        "--tail-record",
        default="",
        metavar="PATH",
        help="with --tail: also write every observed step to this "
        "decision log (doubles as an arrival trace; its fingerprint is "
        "the live nodes at bootstrap, and live clusters drift, so "
        "replaying it later usually needs --allow-fingerprint-mismatch)",
    )
    p_shadow.add_argument(
        "--allow-fingerprint-mismatch",
        action="store_true",
        help="replay a decision log whose cluster fingerprint does not "
        "match the config's cluster (needed for --tail-record logs of "
        "drifting live clusters; divergences may then reflect cluster "
        "drift, not scheduler disagreement)",
    )
    p_shadow.add_argument(
        "--engine",
        choices=["tpu", "oracle"],
        default="tpu",
        help="probe engine: tpu = one warm single-pod masked scan per "
        "step, oracle = the serial filter+score walk",
    )
    p_shadow.add_argument(
        "--poll-interval",
        type=float,
        default=10.0,
        metavar="SECONDS",
        help="--tail polling interval",
    )
    p_shadow.add_argument(
        "--max-polls",
        type=int,
        default=None,
        metavar="N",
        help="--tail: stop after N poll rounds (default: until deadline "
        "or SIGINT)",
    )
    p_shadow.add_argument(
        "--max-steps",
        type=int,
        default=None,
        metavar="N",
        help="--tail: stop once N decisions have been audited",
    )
    p_shadow.add_argument(
        "--deadline",
        type=float,
        default=None,
        metavar="SECONDS",
        help="wall-clock budget: on expiry (or SIGINT) the audit stops "
        "at the next step boundary and reports what it has (exit 3/4)",
    )
    p_shadow.add_argument(
        "--max-catchup",
        type=int,
        default=500,
        metavar="N",
        help="--tail: apply at most N observed steps per poll round; "
        "the backlog a recovered apiserver flap dumps on the tailer "
        "replays across rounds instead of stalling the loop "
        "(docs/ROBUSTNESS.md)",
    )
    p_shadow.add_argument(
        "--breaker-cooldown",
        type=float,
        default=30.0,
        metavar="SECONDS",
        help="--tail: circuit-breaker recovery cooldown — after an "
        "apiserver outage opens the breaker, a half-open probe retries "
        "this often; the tail survives the flap instead of failing "
        "forever (0 disables recovery: one-shot CLI posture)",
    )
    _add_inject_flag(p_shadow)
    _add_obs_flags(p_shadow)
    p_shadow.add_argument(
        "--format", choices=["table", "json"], default="table",
        help="report output format",
    )
    p_shadow.set_defaults(func=cmd_shadow)

    p_timeline = sub.add_parser(
        "timeline",
        help="discrete-event cluster timeline with autoscaler policy comparison",
        description="Play a trace of pod arrivals/departures, node "
        "churn, and spot reclamations through pluggable autoscaler "
        "policies (static:K / threshold / probe, optionally @nospread) "
        "over the config's cluster, with the config's newNode spec as "
        "the candidate pool. Consecutive arrivals batch into "
        "encode-once masked scan windows and every policy rides the "
        "same batched dispatch as one scenario row, so a 1000-step "
        "trace costs a handful of device dispatches (docs/TIMELINE.md). "
        "Emits per-step cost/utilization/pending curves per policy. "
        "Exit 0 on a completed run, 2 on input errors, 3/4 on "
        "deadline/interrupt partials.",
    )
    p_timeline.add_argument(
        "-f", "--simon-config", required=True, help="simon config file path"
    )
    p_timeline.add_argument(
        "--synthetic",
        type=int,
        default=0,
        metavar="N",
        help="generate a seeded synthetic trace of N Poisson pod "
        "arrivals with exponential lifetimes (and spot reclaims when "
        "--spot-frac > 0)",
    )
    p_timeline.add_argument(
        "--trace",
        default="",
        metavar="PATH",
        help="replay this timeline-trace JSONL (written by --save-trace)",
    )
    p_timeline.add_argument(
        "--from-decision-log",
        default="",
        metavar="PATH",
        help="convert a shadow decision log (simon shadow --record / "
        "--tail-record) into a timeline trace and replay REAL cluster "
        "history through the policies (decisions become arrivals, "
        "evictions departures, node churn joins/drains)",
    )
    p_timeline.add_argument(
        "--allow-fingerprint-mismatch",
        action="store_true",
        help="accept a --from-decision-log whose cluster fingerprint "
        "does not match the config's cluster",
    )
    p_timeline.add_argument(
        "--save-trace",
        default="",
        metavar="PATH",
        help="also write the (generated or converted) trace as "
        "fingerprinted timeline-trace JSONL",
    )
    p_timeline.add_argument(
        "--seed", type=int, default=1, help="synthetic-trace seed (deterministic)"
    )
    p_timeline.add_argument(
        "--arrival-rate", type=float, default=1.0, metavar="PODS/S",
        help="synthetic Poisson arrival rate",
    )
    p_timeline.add_argument(
        "--mean-lifetime", type=float, default=120.0, metavar="SECONDS",
        help="synthetic mean pod lifetime (exponential)",
    )
    p_timeline.add_argument(
        "--long-running-frac", type=float, default=0.5, metavar="FRAC",
        help="fraction of synthetic pods that never depart",
    )
    p_timeline.add_argument(
        "--spot-frac", type=float, default=0.0, metavar="FRAC",
        help="fraction of base nodes that are spot instances (0 = none)",
    )
    p_timeline.add_argument(
        "--spot-hazard", type=float, default=1.0 / 300.0, metavar="RATE",
        help="spot reclaim hazard rate per node per second",
    )
    p_timeline.add_argument(
        "--policy",
        action="append",
        metavar="SPEC",
        help="policy to run (repeatable): static:K, threshold"
        "[:lo=30,patience=2,step=0], probe; append @nospread for the "
        "PodTopologySpread-off score profile. Default: threshold",
    )
    p_timeline.add_argument(
        "--compare",
        action="append",
        metavar="SPEC,SPEC,...",
        help="comma-separated policy list (same specs as --policy)",
    )
    p_timeline.add_argument(
        "--cadence", type=float, default=60.0, metavar="SECONDS",
        help="autoscaler decision cadence (decisions run at t=0 too)",
    )
    p_timeline.add_argument(
        "--warmup", type=float, default=0.0, metavar="SECONDS",
        help="node warm-up delay: a scale-up's candidates become "
        "schedulable this long after the decision",
    )
    p_timeline.add_argument(
        "--max-nodes", type=int, default=8, metavar="K",
        help="autoscaler candidate pool size (copies of the config's "
        "newNode spec; 0 disables scaling)",
    )
    p_timeline.add_argument(
        "--window-arrivals", type=int, default=256, metavar="N",
        help="max arrivals batched into one scan window",
    )
    p_timeline.add_argument(
        "--engine",
        choices=["tpu", "oracle"],
        default="tpu",
        help="window engine: tpu = batched masked scan rows, oracle = "
        "the serial host walk (the conformance reference)",
    )
    _add_mesh_flag(p_timeline)
    _add_guard_flags(p_timeline)
    _add_obs_flags(p_timeline)
    p_timeline.add_argument(
        "--format", choices=["table", "json"], default="table",
        help="result output format",
    )
    p_timeline.add_argument(
        "--trace-phases",
        action="store_true",
        help="print per-phase wall-clock JSON to stderr (--trace is the "
        "trace-file input here, unlike the other commands)",
    )
    p_timeline.set_defaults(func=cmd_timeline)

    p_twin = sub.add_parser(
        "twin",
        help="live digital-twin daemon: mirror a cluster, answer "
        "what-if/drain/N+K/forecast against live state",
        description="Continuously mirror a cluster on the cluster-delta "
        "substrate (a live apiserver tail or a recorded decision-log "
        "feed), audit every real scheduler decision against the warm "
        "mirror (agreement-rate and mirror-lag stream to /metrics as "
        "alertable gauges), and serve on-demand queries over HTTP: "
        "POST /v1/whatif (would these apps fit right now), /v1/drain "
        "(can I cordon these nodes/this rack), /v1/nplusk (does the "
        "live placement survive K node failures), /v1/forecast "
        "(timeline windows stepped forward from the current mirrored "
        "state). docs/TWIN.md.",
    )
    p_twin.add_argument(
        "-f", "--simon-config", required=True, help="simon config file path"
    )
    p_twin.add_argument(
        "--tail",
        action="store_true",
        help="poll the config's live cluster (kubeConfig required)",
    )
    p_twin.add_argument(
        "--feed",
        default="",
        metavar="LOG",
        help="tail a recorded decision log instead of a live cluster "
        "(the self-conformance and CI-smoke source; simon tailing its "
        "own recorded feed must agree with itself 100%%)",
    )
    p_twin.add_argument(
        "--feed-batch",
        type=int,
        default=64,
        metavar="N",
        help="feed steps replayed per poll round",
    )
    p_twin.add_argument(
        "--allow-fingerprint-mismatch",
        action="store_true",
        help="replay a --feed log recorded against different inputs "
        "(divergences become meaningful; default refuses loudly)",
    )
    p_twin.add_argument(
        "--engine",
        choices=["tpu", "oracle"],
        default="tpu",
        help="mirror probe/query engine: tpu = warm masked scans, "
        "oracle = the serial host walk",
    )
    p_twin.add_argument("--host", default="127.0.0.1", help="bind address")
    p_twin.add_argument(
        "--port", type=int, default=8081, help="bind port (0 = ephemeral)"
    )
    p_twin.add_argument(
        "--poll-interval",
        type=float,
        default=2.0,
        metavar="SECONDS",
        help="tail poll cadence",
    )
    p_twin.add_argument(
        "--max-polls",
        type=int,
        default=None,
        metavar="N",
        help="stop tailing after N polls (the mirror stays queryable "
        "at its final state; default: tail until signaled)",
    )
    p_twin.add_argument(
        "--max-catchup",
        type=int,
        default=256,
        metavar="N",
        help="max backlog steps applied per poll round (a recovered "
        "flap's giant diff converges across rounds instead of blocking "
        "queries)",
    )
    p_twin.add_argument(
        "--tick-budget",
        type=float,
        default=None,
        metavar="SECONDS",
        help="admission sheds a query 429 (with Retry-After) when the "
        "p95 query time times the queue ahead exceeds this",
    )
    p_twin.add_argument(
        "--max-request-pods",
        type=int,
        default=None,
        metavar="N",
        help="admission bound on estimated pods per what-if request",
    )
    p_twin.add_argument(
        "--drain-timeout",
        type=float,
        default=30.0,
        metavar="SECONDS",
        help="max wait for the tail thread and in-flight queries at "
        "shutdown",
    )
    p_twin.add_argument(
        "--breaker-cooldown",
        type=float,
        default=30.0,
        metavar="SECONDS",
        help="circuit-breaker half-open recovery cooldown for the "
        "apiserver endpoints (SIMON_BREAKER_COOLDOWN wins when set; "
        "0 disables recovery)",
    )
    p_twin.add_argument(
        "--snapshot",
        default="",
        metavar="PATH",
        help="append every applied mirror step to this crash-safe "
        "JSONL snapshot journal (resumed across restarts; the twin "
        "analogue of `simon serve --snapshot`)",
    )
    p_twin.add_argument(
        "--replay-snapshot",
        action="store_true",
        help="before tailing, restore the newest verified checkpoint "
        "and replay the --snapshot journal's step suffix into the "
        "mirror (bounded twin failover; docs/TWIN.md)",
    )
    p_twin.add_argument(
        "--checkpoint-interval",
        type=int,
        default=None,
        metavar="STEPS",
        help="write a verified checkpoint of the mirrored cluster "
        "every N applied steps (requires --snapshot); restore then "
        "replays at most N journal steps and the absorbed prefix is "
        "compacted only after the digest verifies "
        "(docs/ROBUSTNESS.md; default: off)",
    )
    p_twin.add_argument(
        "--keep-checkpoints",
        type=int,
        default=2,
        metavar="N",
        help="checkpoint generations retained; a corrupt newest "
        "generation falls back loudly to the previous one (default 2)",
    )
    _add_store_flag(p_twin)
    _add_obs_flags(p_twin)
    _add_telemetry_flags(p_twin)
    p_twin.set_defaults(func=cmd_twin)

    p_top = sub.add_parser(
        "top",
        help="live terminal dashboard against a running serve/twin daemon",
        description="Poll a RUNNING daemon's /v1/obs/snapshot and "
        "/v1/obs/series endpoints and render a live dashboard: health "
        "and degradation reasons, SLO burn rates and alert states, and "
        "sparklined history of the key operational signals (QPS, queue "
        "depth, latency percentiles, agreement rate, device memory). "
        "The daemon side is the resident telemetry store "
        "(docs/OBSERVABILITY.md); `simon top` is a pure reader — it "
        "never perturbs the daemon beyond two GETs per refresh.",
    )
    p_top.add_argument(
        "--url", default="",
        help="daemon base URL (wins over --host/--port)",
    )
    p_top.add_argument("--host", default="127.0.0.1", help="daemon host")
    p_top.add_argument("--port", type=int, default=8080, help="daemon port")
    p_top.add_argument(
        "--interval", type=float, default=2.0, metavar="SECONDS",
        help="refresh interval",
    )
    p_top.add_argument(
        "--window", type=float, default=300.0, metavar="SECONDS",
        help="history window rendered in the sparklines",
    )
    p_top.add_argument(
        "--series", action="append", metavar="NAME",
        help="render this series instead of the curated defaults "
        "(repeatable; names as listed by GET /v1/obs/series)",
    )
    p_top.add_argument(
        "--fleet", action="store_true",
        help="render the fleet-router frame against a `simon fleet` "
        "endpoint: per-slot panes (up/degraded/down, request rate, "
        "forward p95) plus the fleet-wide counters and SLO burn "
        "table; slots whose series are missing or TTL-stale render "
        "as gaps, never errors",
    )
    p_top.add_argument(
        "--once", action="store_true",
        help="render one frame and exit (no screen clearing; CI smoke)",
    )
    p_top.add_argument(
        "--timeout", type=float, default=5.0, metavar="SECONDS",
        help="per-request HTTP timeout",
    )
    p_top.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="json dumps the raw snapshot+series instead of rendering",
    )
    p_top.set_defaults(func=cmd_top)

    p_doctor = sub.add_parser(
        "doctor",
        help="diff two bench records and gate on perf regressions",
        description="Diff a candidate bench record against a baseline "
        "(headline value, device dispatches, XLA recompiles, peak HBM "
        "from the memory ledger, per-site latency p95s) and exit 1 on "
        "any regression past thresholds. Accepts raw bench JSON lines, "
        "JSONL runs, or the checked-in BENCH_r*.json wrappers. Counts "
        "use ABSOLUTE slack (default 0 — dispatches are semantic on a "
        "fixed scenario); times/rates/bytes use FRACTIONAL slack "
        "(default 0.5 — wall-clock on shared runners is noisy). "
        "Dimensions absent from either record are skipped, never "
        "invented. `bench.py --against` is the same diff run in-process "
        "against a fresh measurement.",
    )
    p_doctor.add_argument(
        "baseline", help="recorded bench file to diff against"
    )
    p_doctor.add_argument(
        "candidate", help="fresh bench record (file) to judge"
    )
    p_doctor.add_argument(
        "--time-tolerance", type=float, default=0.5, metavar="FRAC",
        help="fractional slack on the headline value (default 0.5; "
        "direction from the unit — seconds regress up, rates down)",
    )
    p_doctor.add_argument(
        "--dispatch-tolerance", type=int, default=0, metavar="N",
        help="absolute slack on device dispatches (default 0)",
    )
    p_doctor.add_argument(
        "--recompile-tolerance", type=int, default=0, metavar="N",
        help="absolute slack on XLA recompiles (default 0)",
    )
    p_doctor.add_argument(
        "--hbm-tolerance", type=float, default=0.5, metavar="FRAC",
        help="fractional slack on the ledger peak-HBM watermark",
    )
    p_doctor.add_argument(
        "--p95-tolerance", type=float, default=0.5, metavar="FRAC",
        help="fractional slack on per-site latency p95s",
    )
    p_doctor.add_argument(
        "--suffix-tolerance", type=float, default=0.5, metavar="FRAC",
        help="fractional slack on the incremental suffix fraction "
        "(regresses up: a growing fraction re-scans reusable rows)",
    )
    p_doctor.add_argument(
        "--store-tolerance", type=float, default=0.5, metavar="FRAC",
        help="fractional slack on the artifact-store hit rate "
        "(regresses down: cold starts paying avoidable compiles)",
    )
    p_doctor.add_argument(
        "--fleet-tolerance", type=float, default=0.5, metavar="FRAC",
        help="fractional slack on the fleet dimensions: qps_scaling "
        "(regresses down: lost horizontal scaling) and "
        "failover_seconds (regresses up: slower recovery after a "
        "replica kill)",
    )
    p_doctor.add_argument(
        "--ckpt-tolerance", type=float, default=0.5, metavar="FRAC",
        help="fractional slack on the aged-failover checkpoint "
        "restore seconds (regresses up: recovery time growing with "
        "absorbed-delta age means the bounded-recovery contract broke)",
    )
    p_doctor.add_argument(
        "--store-reject-tolerance", type=int, default=0, metavar="N",
        help="absolute slack on artifact-store rejects (default 0: a "
        "reject is a corrupt/stale entry, worth a look even though "
        "the recovery is clean)",
    )
    p_doctor.add_argument(
        "--format", choices=["text", "json"], default="text",
        help="report format (default text)",
    )
    p_doctor.add_argument(
        "--out", default="", metavar="PATH",
        help="also write the JSON report to PATH (CI artifact)",
    )
    p_doctor.set_defaults(func=cmd_doctor)

    p_version = sub.add_parser("version", help="print version")
    p_version.set_defaults(func=cmd_version)

    p_doc = sub.add_parser("gen-doc", help="generate markdown CLI docs")
    p_doc.add_argument("--output", default="docs/commandline")
    p_doc.set_defaults(func=cmd_gen_doc)
    return parser


def main(argv=None) -> int:
    _setup_logging()
    parser = build_parser()
    args = parser.parse_args(argv)
    if not getattr(args, "func", None):
        parser.print_help()
        return 0
    try:
        _arm_injection(args)
    except ValueError as e:  # InputError: a typo'd --inject is exit 2
        print(f"error: {e}", file=sys.stderr)
        return 2
    return args.func(args)


if __name__ == "__main__":
    sys.exit(main())
