"""Batched capacity-planning sweep over a TPU device mesh.

The reference's capacity loop is interactive: guess a node count, re-run
the whole simulation, ask the user (pkg/apply/apply.go:186-239). Here
every candidate count is one scenario of a single batched computation:

- the cluster is padded with `max_count` copies of the candidate node
  spec (named `simon-%02d` with the `simon/new-node` label, mirroring
  newFakeNodes, apply.go:288-306)
- scenario s enables the first s new nodes via a node-validity mask and
  drops daemonset pods that belong to disabled nodes via a pod-activity
  mask (the reference regenerates them per run)
- `vmap(run_scan_masked)` evaluates all scenarios at once; over a
  `jax.sharding.Mesh` the scenario axis is sharded across devices via
  `jit` with NamedSharding in_shardings (probe_many below) — scenarios
  are independent, so XLA's only communication is the result gather
  (this is the "distributed backend": XLA collectives over ICI, not a
  port of anything — the reference is single-process)

Returns per-scenario unscheduled counts and cluster utilization, from
which the planner picks the minimal feasible count
(satisfyResourceSetting caps, apply.go:611-697).
"""

from __future__ import annotations

import copy
from dataclasses import dataclass
from typing import List, Optional

import numpy as np

from ..models import workloads as wl
from ..models.decode import ResourceTypes
from ..models.validation import InputError
from ..scheduler.core import AppResource, _sort_app_pods
from ..scheduler.oracle import Oracle

from ..runtime.guard import run_chunked, run_laddered

# pod not present in this scenario. Duplicates the ops/scan.py and
# ops/pallas_scan.py sentinel because importing either here would pull
# jax in at module-import time (cli._force_platform must run first);
# CapacitySweep.__init__ asserts the three stay equal.
INACTIVE = -2


class PrioritySignalError(InputError):
    """Raised when a batched sweep is asked to plan a priority-bearing
    workload: the scan cannot model PrioritySort/preemption, and a
    silent non-preemptive plan would diverge from simulate() on the
    same input. Callers (apply/applier.py) catch this and fall back to
    the serial escalation loop, whose simulate() handles priority."""


# The PR-1 sweep-local OOM machinery (_is_oom / halving-retry /
# serial-fallback executor and its _OOM_INJECT test hook) moved to
# runtime/guard.py (run_chunked) so the sweep, chaos, and defrag paths
# share one audited degradation ladder.


@dataclass
class SweepResult:
    counts: List[int]
    unscheduled: np.ndarray  # [Sc] number of unschedulable (active) pods
    cpu_util: np.ndarray  # [Sc] percent
    mem_util: np.ndarray  # [Sc] percent
    placements: np.ndarray  # [Sc, P] node index / -1 / -2(inactive)
    pods: List[dict]
    node_names: List[str]
    vg_util: Optional[np.ndarray] = None  # [Sc] percent (0 when no VGs)


@dataclass
class ProbeResult:
    """One capacity scenario, evaluated by a single masked scan."""

    count: int
    unscheduled: int
    cpu_util: float
    mem_util: float
    vg_util: float
    placements: np.ndarray  # [P] node index / -1 / -2(inactive)


def _probe_to_record(res: ProbeResult) -> dict:
    """JSON-serializable journal record of one probe (runtime/journal)."""
    return {
        "count": int(res.count),
        "unscheduled": int(res.unscheduled),
        "cpuUtil": float(res.cpu_util),
        "memUtil": float(res.mem_util),
        "vgUtil": float(res.vg_util),
        "placements": [int(x) for x in np.asarray(res.placements)],
    }


def _probe_from_record(rec: dict) -> ProbeResult:
    return ProbeResult(
        count=int(rec["count"]),
        unscheduled=int(rec["unscheduled"]),
        cpu_util=float(rec["cpuUtil"]),
        mem_util=float(rec["memUtil"]),
        vg_util=float(rec["vgUtil"]),
        placements=np.asarray(rec["placements"], dtype=np.int64),
    )


def _new_nodes(spec: dict, count: int) -> List[dict]:
    out = []
    for i in range(count):
        node = wl.make_valid_node(copy.deepcopy(spec), f"{wl.NEW_NODE_NAME_PREFIX}-{i:02d}")
        node["metadata"].setdefault("labels", {})[wl.LABEL_NEW_NODE] = ""
        out.append(node)
    return out


def _daemonset_target(pod: dict) -> Optional[str]:
    """The node a daemonset pod is pinned to via its matchFields term."""
    aff = ((pod.get("spec") or {}).get("affinity") or {}).get("nodeAffinity") or {}
    required = aff.get("requiredDuringSchedulingIgnoredDuringExecution") or {}
    for term in required.get("nodeSelectorTerms") or []:
        for f in term.get("matchFields") or []:
            if f.get("key") == "metadata.name" and f.get("operator") == "In":
                values = f.get("values") or []
                if values:
                    return values[0]
    return None


class CapacitySweep:
    """Encode-once / probe-many capacity search.

    The cluster is padded with `max_count` candidate nodes exactly once;
    every probe is a single masked scan with a different node-validity
    mask — same shapes, so XLA compiles one executable for every count
    (the reference re-runs the whole simulation per guess,
    pkg/apply/apply.go:186-239).
    """

    def __init__(
        self,
        cluster: ResourceTypes,
        apps: List[AppResource],
        new_node_spec: Optional[dict],
        max_count: int,
        use_greed: bool = False,
        score_weights=None,
        share_pods_from: "Optional[CapacitySweep]" = None,
    ):
        from ..ops.encode import (
            encode_batch,
            encode_cluster,
            encode_dynamic,
            features_of_batch,
            to_scan_static,
            to_scan_state,
        )
        from ..utils.trace import phase

        self.max_count = max_count if new_node_spec is not None else 0
        padded = cluster.copy()
        padded.nodes = list(padded.nodes) + _new_nodes(new_node_spec, self.max_count)

        # Build oracle at full padding; generate the full pod sequence
        # the serial path would see (cluster pods, then apps in order).
        # A multi-spec what-if (probe_plan_multi) reuses a sibling
        # sweep's expanded pod list when expansion is provably
        # spec-INDEPENDENT: the only node-dependent expansions are
        # daemonsets (one pod per node) and greed_sort ordering. The
        # shared dicts follow the same repeated-replay contract as one
        # sweep replayed at several counts (had_node_name below).
        if (
            share_pods_from is None
            or use_greed
            or padded.daemon_sets
            or any(app.resource.daemon_sets for app in apps)
            or share_pods_from.max_count != self.max_count
            or share_pods_from.n_base != len(cluster.nodes)
        ):
            share_pods_from = None
        # replays MUTATE pod dicts (bind writes nodeName/phase), so a
        # multi-spec caller must give each sweep its own copies before
        # replaying (applier.probe_plan_multi checks this flag)
        self.pods_shared = share_pods_from is not None
        with phase("sweep/expand"):
            self.oracle = Oracle(padded.nodes)
            if share_pods_from is not None:
                # expansion (and its priority/plugin checks) shared
                pods = share_pods_from.pods
            else:
                pods: List[dict] = []
                pods.extend(wl.pods_excluding_daemon_sets(padded))
                for ds in padded.daemon_sets:
                    pods.extend(wl.pods_from_daemon_set(ds, padded.nodes))
                for app in apps:
                    app_pods = wl.generate_valid_pods_from_app(
                        app.name, app.resource, padded.nodes
                    )
                    if use_greed:
                        # same ordering the authoritative serial run
                        # will use (scheduler/core.py schedule_app):
                        # greed_sort ignores simon new nodes, so
                        # max-count padding and the per-count serial
                        # cluster sort pods identically
                        from ..scheduler.queues import greed_sort

                        app_pods = greed_sort(padded.nodes, app_pods)
                    pods.extend(_sort_app_pods(app_pods))
                from ..scheduler.preemption import (
                    build_priority_resolver,
                    pod_uses_priority,
                )

                resolver = build_priority_resolver(cluster.priority_classes)
                if any(pod_uses_priority(p, resolver) for p in pods):
                    raise PrioritySignalError(
                        "workload carries priority/priorityClassName; the "
                        "batched scan has no priority/preemption semantics — "
                        "use the serial engine (scheduler/core.py falls back "
                        "automatically)"
                    )
                if self.oracle.registry.needs_serial:
                    raise PrioritySignalError(
                        "a registered plugin defines permit() or a stateful "
                        "hook (reserve/prebind); the batched scan cannot "
                        "honor per-pod host callbacks — use the serial "
                        "engine (scheduler/core.py falls back automatically)"
                    )
        self.pods = pods
        self.n = len(padded.nodes)
        self.n_base = self.n - self.max_count

        with phase("sweep/encode"):
            self.cluster_enc = encode_cluster(self.oracle)
            self.batch = encode_batch(self.oracle, self.cluster_enc, pods)
            self.dyn = encode_dynamic(self.oracle, self.cluster_enc)
            self.static = to_scan_static(self.cluster_enc, self.batch)
            self.init = to_scan_state(self.dyn, self.batch)
            # derive features host-side: inside a jit/vmap trace
            # features_of would fall back to the ungated ALL_FEATURES scan
            self.features = features_of_batch(
                self.cluster_enc, self.batch, weights=score_weights
            )

        # which pods arrived with spec.nodeName, recorded BEFORE any
        # replay binds pods (replay_scenario writes nodeName into these
        # shared pod dicts; a later replay must not mistake a previous
        # replay's binding for an original pin)
        self.had_node_name = np.array(
            [bool((p.get("spec") or {}).get("nodeName")) for p in pods], dtype=bool
        )
        # daemonset pods of disabled candidate nodes are inactive in
        # that scenario (the reference regenerates them per run)
        self._ds_target = np.full(len(pods), -1, dtype=np.int64)
        name_to_idx = self.oracle.node_index
        for p_i, pod in enumerate(pods):
            target = _daemonset_target(pod)
            if target is not None and target in name_to_idx:
                self._ds_target[p_i] = name_to_idx[target]
        self._probe_jit = None
        self._many_jit = None
        # process-wide mesh (parallel/mesh.py configure/current_mesh,
        # the --mesh flag): the layout planner decides PER REQUEST
        # whether to shard the scenario axis (probe_many /
        # probe_scenarios) or the node axis (single probes on big
        # clusters) across it; None = the single-device ladder
        from . import mesh as mesh_mod

        self.mesh = mesh_mod.current_mesh()
        self._node_plan = None  # padded node-sharded state, built lazily
        self._mesh_retired = False  # a mesh rung fault retires the mesh
        # optional resumable journal (runtime/journal.py): probe()
        # serves journaled counts without touching the device and
        # appends every fresh result (attach_journal)
        self.journal = None
        # fused single-kernel fast path (ops/pallas_scan.py); None when
        # the batch uses machinery outside its scope or the backend is
        # not a real TPU (the interpreter would crawl at bench scale)
        from ..ops import pallas_scan, scan as scan_ops

        assert INACTIVE == scan_ops.INACTIVE == pallas_scan.INACTIVE

        self._pallas_plan = (
            pallas_scan.build_plan(
                self.cluster_enc, self.batch, self.dyn, self.features,
                weights=self.features.weights,
            )
            if pallas_scan.should_use()
            else None
        )
        from ..utils.trace import GLOBAL

        GLOBAL.note(
            "sweep-kernel",
            "pallas"
            if self._pallas_plan is not None
            else f"xla-scan ({pallas_scan.fallback_reason()})",
        )

    # -- masks -------------------------------------------------------------

    def node_valid(self, count: int) -> np.ndarray:
        valid = np.ones(self.n, dtype=bool)
        valid[self.n_base + count :] = False
        return valid

    def pod_active(self, valid: np.ndarray) -> np.ndarray:
        active = np.ones(len(self.pods), dtype=bool)
        tgt = self._ds_target
        has_tgt = tgt >= 0
        active[has_tgt] = valid[tgt[has_tgt]]
        return active

    # -- the compiled scenario ---------------------------------------------

    def _scenario(self, valid, active):
        import jax.numpy as jnp

        return self._scenario_impl(
            valid, active, jnp.asarray(self.batch.pinned_node), self.features
        )

    def _scenario_impl(self, valid, active, pinned, features):
        import jax.numpy as jnp

        from ..ops import scan as scan_ops

        placements, final = scan_ops.run_scan_masked(
            self.static,
            self.init,
            jnp.asarray(self.batch.class_of_pod),
            pinned,
            valid,
            active,
            features=features,
        )
        unsched = jnp.sum(placements == -1)
        cpu_util, mem_util, vg_util = self._utilization(valid, final)
        return placements, unsched, cpu_util, mem_util, vg_util

    def _utilization(self, valid, final):
        return _utilization_impl(self.static, valid, final)

    def attach_journal(self, journal):
        """Serve journaled probes without device work; append fresh
        ones (runtime/journal.py, `--journal` / `--resume`)."""
        self.journal = journal

    def probe(self, count: int) -> ProbeResult:
        """Evaluate one candidate count (one masked scan), through the
        engine ladder (runtime/guard.py): the fused Pallas kernel when
        a plan exists, the jitted XLA scan, and — after a classified
        device fault at each rung — the serial host oracle. A Pallas
        rung failure retires the plan so later probes skip it. Counts
        already in the attached journal never touch the device."""
        if self.journal is not None:
            cached = self.journal.get_probe(count)
            if cached is not None:
                return _probe_from_record(cached)
        res = self._probe_device(count)
        if self.journal is not None:
            self.journal.record_probe(_probe_to_record(res))
        return res

    def _probe_device(self, count: int) -> ProbeResult:
        from ..obs.costs import COSTS
        from ..obs.ledger import LEDGER
        from . import mesh as mesh_mod

        valid = self.node_valid(count)
        steps = []
        if self._pallas_plan is not None:
            steps.append(("pallas", lambda: self._probe_pallas(count, valid)))
        # node-axis mesh rung: ONE scenario over a cluster the planner
        # says is too big (or predicted not to fit) on one device —
        # each device scores its node shard, the winner reduces
        # globally (parallel/mesh.py). A classified fault retires the
        # rung for this sweep and the ladder continues unsharded.
        if self._pallas_plan is None and not self._mesh_retired:
            # site "sweep_probe": the single-device probe jit whose
            # compiled records say whether one device can hold it
            layout = mesh_mod.plan_layout(
                "sweep_probe", mesh=self.mesh, n_scenarios=1,
                n_nodes=self.n,
                sample=bool(getattr(self.features, "sample", False)),
            )
            if layout.axis == "node":
                steps.append(
                    ("mesh-scan", lambda: self._probe_mesh(count, valid))
                )
        steps.append(("xla-scan", lambda: self._probe_xla(count, valid)))
        steps.append(("serial-oracle", lambda: self._probe_serial(count, valid)))

        def on_downgrade(rung, _e):
            if rung == "pallas":
                self._pallas_plan = None  # retire the dead rung
            if rung == "mesh-scan":
                self._mesh_retired = True
                self._node_plan = None

        # predictive rung gate: once a rung's shape has compiled, the
        # memory ledger can veto re-dispatching it into a device that
        # no longer has room — the doomed dispatch is skipped instead
        # of caught (no-op until the backend/env reports a budget)
        predictor = LEDGER.rung_predictor(
            {"xla-scan": lambda: COSTS.estimate_bytes("sweep_probe")}
        )
        return run_laddered(
            steps, label="sweep-probe", on_downgrade=on_downgrade,
            predictor=predictor,
        )

    def _probe_mesh(self, count: int, valid) -> ProbeResult:
        """One capacity probe through the node-axis-sharded scan: the
        padded shard state is built once per sweep (NodeShardPlan), so
        repeated probes pay only the masks' transfer."""
        from ..utils.trace import phase
        from . import mesh as mesh_mod

        if self._node_plan is None:
            self._node_plan = mesh_mod.NodeShardPlan(
                self.mesh, self.static, self.init,
                self.batch.class_of_pod, self.batch.pinned_node,
                self.features,
            )
        with phase("sweep/probe"):
            pl, unsched, cpu, mem, vg = self._node_plan.run(
                valid, self.pod_active(valid)
            )
        return ProbeResult(
            count=count, unscheduled=unsched, cpu_util=cpu,
            mem_util=mem, vg_util=vg, placements=pl,
        )

    def _probe_pallas(self, count: int, valid) -> ProbeResult:
        from ..ops import pallas_scan
        from ..utils.trace import phase

        with phase("sweep/probe"):
            placements, final = pallas_scan.run_scan_pallas(
                self._pallas_plan,
                self.batch.class_of_pod,
                self.pod_active(valid),
                valid,
                pinned=self.batch.pinned_node,
            )
        return self._pallas_result(count, valid, placements, final)

    def _probe_xla(self, count: int, valid) -> ProbeResult:
        import jax
        import jax.numpy as jnp

        from ..utils.trace import phase

        if self._probe_jit is None:
            from ..obs import profile

            self._probe_jit = profile.instrument_jit(
                jax.jit(self._scenario), "sweep_probe"
            )
        with phase("sweep/probe"):
            placements, unsched, cpu, mem, vg = self._probe_jit(
                jnp.asarray(valid), jnp.asarray(self.pod_active(valid))
            )
            placements = np.asarray(placements)
        return ProbeResult(
            count=count,
            unscheduled=int(unsched),
            cpu_util=float(cpu),
            mem_util=float(mem),
            vg_util=float(vg),
            placements=placements,
        )

    def _probe_serial(self, count: int, valid) -> ProbeResult:
        """Last ladder rung: the deterministic host oracle, no device."""
        active = self.pod_active(valid)
        placements, _reasons = self.serial_scenario(valid, active)
        pl, unsched, cpu, mem, vg = self._host_scenario_stats(valid, placements)
        return ProbeResult(
            count=count,
            unscheduled=int(unsched),
            cpu_util=float(cpu),
            mem_util=float(mem),
            vg_util=float(vg),
            placements=pl,
        )

    def _pallas_result(self, count, valid, placements, final) -> ProbeResult:
        # same utilization arithmetic as _scenario, on the host
        v = valid[: self.n]
        alloc_c = np.asarray(self.cluster_enc.alloc_mcpu)
        alloc_m = np.asarray(self.cluster_enc.alloc_mem)
        denom_c = max(int(alloc_c[v].sum()), 1)
        denom_m = max(int(alloc_m[v].sum()), 1)
        cpu_util = 100.0 * float(final["used_mcpu"][v].sum()) / denom_c
        mem_util = 100.0 * float(final["used_mem"][v].sum()) / denom_m
        vg_cap = np.asarray(self.cluster_enc.vg_cap)
        # final VG usage exported by the kernel (storage batches ride
        # the Pallas path since r5); storage-free batches never grow
        # it, so the init state is exact for them
        vg_used = np.asarray(final.get("vg_used", self.dyn.vg_used))
        denom_vg = max(int(vg_cap[v].sum()), 1)
        vg_util = 100.0 * float(vg_used[v].sum()) / denom_vg
        return ProbeResult(
            count=count,
            unscheduled=int((placements == -1).sum()),
            cpu_util=cpu_util,
            mem_util=mem_util,
            vg_util=vg_util,
            placements=placements,
        )

    def probe_pair(self, c1: int, c2: int):
        """Two candidate counts with ONE device sync: on the Pallas
        path both scans dispatch deferred and fetch stacked (the defrag
        batching pattern) — the relay's per-sync latency is paid once.
        Falls back to two sequential probes on the XLA path."""
        if self._pallas_plan is None or (
            self.journal is not None
            and (
                self.journal.get_probe(c1) is not None
                or self.journal.get_probe(c2) is not None
            )
        ):
            # journaled counts must not ride the paired dispatch: probe()
            # serves them from the journal, so pairing would re-run them
            return self.probe(c1), self.probe(c2)
        from ..ops import pallas_scan
        from ..utils.trace import phase

        valids = [self.node_valid(c) for c in (c1, c2)]
        with phase("sweep/probe"):
            decoded = pallas_scan.run_scan_pallas_batch(
                self._pallas_plan,
                self.batch.class_of_pod,
                [
                    (self.pod_active(v), v, self.batch.pinned_node)
                    for v in valids
                ],
            )
        out = tuple(
            self._pallas_result(c, valid, placements, final)
            for c, valid, (placements, final) in zip((c1, c2), valids, decoded)
        )
        if self.journal is not None:
            for r in out:
                self.journal.record_probe(_probe_to_record(r))
        return out

    def probe_many(self, counts: List[int], mesh=None, budget=None) -> SweepResult:
        """Evaluate many counts batched (vmap; scenario-sharded over a
        device mesh when one is given). Chunked with OOM halving-retry
        (runtime/guard.py run_chunked): a scenario batch that exhausts
        device memory is split and retried, bottoming out in the
        deterministic serial oracle — every degradation trace-noted,
        never silent. `budget` halts between chunks (ExecutionHalted
        with the completed prefix attached)."""
        import jax
        import jax.numpy as jnp

        sc = len(counts)
        node_valid = np.stack([self.node_valid(c) for c in counts])
        pod_active = np.stack([self.pod_active(v) for v in node_valid])
        # ONE jitted vmap per sweep instance (JAX002: a fresh
        # jax.jit(...) per evaluate() chunk re-traced and re-compiled
        # every chunk). The mesh path reuses the same wrapper:
        # device_put commits the scenario axis to the NamedSharding and
        # jit compiles per observed input sharding ("computation
        # follows sharding"), so sharded and unsharded batches each
        # warm their own cache entry once.
        if self._many_jit is None:
            from ..obs import profile

            self._many_jit = profile.instrument_jit(
                jax.jit(jax.vmap(self._scenario)), "sweep_many",
                lead_argnum=0,
            )

        # layout planner: an explicit mesh argument wins (the historic
        # sweep_node_counts contract); otherwise the process-wide mesh
        # shards the scenario axis when the planner picks it
        from . import mesh as mesh_mod

        if mesh is None:
            layout = mesh_mod.plan_layout(
                "sweep_many", mesh=self.mesh, n_scenarios=sc,
                n_nodes=self.n,
                sample=bool(getattr(self.features, "sample", False)),
            )
            if layout.axis == "scenario":
                mesh = self.mesh
        n_dev = int(mesh.devices.size) if mesh is not None else 1

        def evaluate(lo, hi):
            nonlocal mesh
            if mesh is not None:
                try:
                    (valid_s, active_s), _rows = mesh_mod.shard_scenario_rows(
                        mesh, [node_valid[lo:hi], pod_active[lo:hi]]
                    )
                    out = self._many_jit(valid_s, active_s)
                    arrays = [np.asarray(o)[: hi - lo] for o in out]
                    return list(zip(*arrays))
                except (RuntimeError, MemoryError, OSError) as e:
                    from ..runtime.guard import try_downgrade

                    if not try_downgrade(
                        e, label="sweep", frm="mesh-scenario", to="xla-scan"
                    ):
                        raise
                    mesh = None
            out = self._many_jit(
                jnp.asarray(node_valid[lo:hi]), jnp.asarray(pod_active[lo:hi])
            )
            return list(zip(*(np.asarray(o) for o in out)))

        def serial_fallback(i):
            placements, _ = self.serial_scenario(node_valid[i], pod_active[i])
            return self._host_scenario_stats(node_valid[i], placements)

        from ..obs.costs import COSTS

        # estimator + shard count re-read per chunk (mid-run mesh
        # downgrade flips later chunks to full-size prediction)
        est_plain = COSTS.chunk_estimator("sweep_many")
        est_shard = COSTS.chunk_estimator("sweep_many", shards=n_dev)

        def estimate(lo, hi):
            return (est_shard if mesh is not None else est_plain)(lo, hi)

        rows = run_chunked(
            evaluate, sc, label="sweep", serial_fallback=serial_fallback,
            budget=budget, estimate=estimate,
            shards=lambda: n_dev if mesh is not None else 1,
        )
        placements, unsched, cpu_util, mem_util, vg_util = (
            np.stack([np.asarray(r[k]) for r in rows]) for k in range(5)
        )

        return SweepResult(
            counts=list(counts),
            unscheduled=unsched,
            cpu_util=cpu_util,
            mem_util=mem_util,
            placements=placements,
            pods=self.pods,
            node_names=[ns.name for ns in self.oracle.nodes],
            vg_util=vg_util,
        )

    # -- serial (host-oracle) scenario evaluation ---------------------------

    def serial_scenario(self, valid, active, pinned=None, pins_first=False):
        """Deterministic host-side evaluation of ONE masked scenario
        through the serial oracle (scheduler/oracle.py) — the sweep's
        last resort when even a single-scenario device batch exhausts
        memory, and the resilience engine's independent confirmation
        path (an N+K verdict is only trusted after one sampled outage
        re-simulates serially to the same answer).

        `pinned[p]` >= 0 force-binds the pod to that sweep node index
        (committed placements / original spec.nodeName); -1 schedules
        through the full filter+score cycle. Defaults to the batch's
        original pins. `pins_first` commits every pinned pod before any
        free pod schedules — the chaos model's two-pass order
        (_scenario_pinned_impl); the default interleaves in pod order like
        the single-pass capacity scan. Returns (placements[P] in SWEEP
        node indices with the scan's -1/-2 conventions,
        {pod_index: reason} for unscheduled pods)."""
        from ..scheduler.oracle import Oracle

        if pinned is None:
            pinned = np.asarray(self.batch.pinned_node)
        valid = np.asarray(valid)
        active = np.asarray(active)
        kept = [i for i in range(self.n) if valid[i]]
        oracle = Oracle(
            [self.oracle.nodes[i].node for i in kept],
            score_weights=self.features.weights,
        )
        local_of = {sweep_i: local_i for local_i, sweep_i in enumerate(kept)}
        sweep_index = self.oracle.node_index
        placements = np.full(len(self.pods), -1, dtype=np.int64)
        reasons: dict = {}

        def handle(p_i, pod, pins_only):
            if not active[p_i]:
                placements[p_i] = INACTIVE
                return
            pin = int(pinned[p_i])
            if pins_only is not None and pins_only != (pin >= 0):
                return
            # repeated-replay contract (replay_scenario): a previous
            # replay may have bound this shared dict — only original
            # spec.nodeName pins survive into this scenario
            if not self.had_node_name[p_i]:
                (pod.get("spec") or {}).pop("nodeName", None)
                (pod.get("status") or {}).pop("phase", None)
            if pin >= 0:
                if not valid[pin]:
                    # pinned to a masked-out node: does not exist in
                    # this scenario (scan INACTIVE convention)
                    placements[p_i] = INACTIVE
                    return
                if self.had_node_name[p_i]:
                    # original spec.nodeName: admit exactly like the
                    # replay (GPU-index annotations honored)
                    oracle.place_existing_pod(pod)
                else:
                    oracle._reserve_and_bind(pod, oracle.nodes[local_of[pin]])
                placements[p_i] = pin
                return
            name, reason = oracle.schedule_pod(pod)
            if name is None:
                placements[p_i] = -1
                reasons[p_i] = reason
            else:
                placements[p_i] = sweep_index[name]

        if pins_first:
            for p_i, pod in enumerate(self.pods):
                handle(p_i, pod, pins_only=True)
            for p_i, pod in enumerate(self.pods):
                if active[p_i] and int(pinned[p_i]) < 0:
                    handle(p_i, pod, pins_only=False)
        else:
            for p_i, pod in enumerate(self.pods):
                handle(p_i, pod, pins_only=None)
        return placements, reasons

    def _host_scenario_stats(self, valid, placements):
        """The (placements, unscheduled, cpu/mem/vg utilization) tuple
        of _scenario, recomputed on the host from serial placements —
        same arithmetic, aggregate form (committed requests add onto
        the encoded base usage; placements only land on valid nodes)."""
        b, d, c_enc = self.batch, self.dyn, self.cluster_enc
        v = np.asarray(valid)
        placed = np.asarray(placements) >= 0
        cls = np.asarray(b.class_of_pod)[placed]
        used_c = int(d.used_mcpu[v].sum()) + int(b.req_mcpu[cls].sum())
        used_m = int(d.used_mem[v].sum()) + int(b.req_mem[cls].sum())
        used_v = int(d.vg_used[v].sum()) + int(b.lvm_sizes[cls].sum())
        denom_c = max(int(c_enc.alloc_mcpu[v].sum()), 1)
        denom_m = max(int(c_enc.alloc_mem[v].sum()), 1)
        denom_v = max(int(c_enc.vg_cap[v].sum()), 1)
        return (
            np.asarray(placements),
            np.int64((np.asarray(placements) == -1).sum()),
            np.float64(100.0 * used_c / denom_c),
            np.float64(100.0 * used_m / denom_m),
            np.float64(100.0 * used_v / denom_v),
        )

    def probe_scenarios(self, node_valid, pod_active, pinned, budget=None,
                        site: str = "chaos"):
        """Batched masked scans with PER-SCENARIO pin vectors — the
        fault-injection substrate (resilience/chaos.py) and the
        timeline stepper's window entry point (timeline/stepper.py:
        each policy's window is one row). Each row of `node_valid`
        [Sc, N] / `pod_active` [Sc, P] / `pinned` [Sc, P] is one
        scenario; rides the same chunked executor as probe_many (OOM
        halving-retry, serial-oracle floor). Returns (placements
        [Sc, P], unscheduled [Sc], cpu_util [Sc], mem_util [Sc],
        vg_util [Sc]) as numpy arrays. `site` names the
        instrumented-jit counter family (obs) so each caller's
        dispatches stay attributable.

        Runs on the XLA masked scan (the Pallas plan is compiled for
        the batch's original pin feature set); chaos batches are
        scenario-bound, not pod-throughput-bound, so this is the
        latency-appropriate path. With a process-wide mesh the layout
        planner shards the scenario axis across it (rows are
        independent; the only communication is the result gather) via
        a per-site ``mesh_<site>`` jit family, so sharded dispatch and
        injection seams (``jit.mesh_*``) stay separately attributable;
        a classified device fault on the sharded path degrades to the
        unsharded ladder, trace-noted."""
        import jax.numpy as jnp

        from . import mesh as mesh_mod

        node_valid = np.asarray(node_valid)
        pod_active = np.asarray(pod_active)
        pinned = np.asarray(pinned)
        sc = node_valid.shape[0]
        site_jit = _scenario_rows_jit(site)
        cls = jnp.asarray(self.batch.class_of_pod)
        layout = mesh_mod.plan_layout(
            f"{site}_sweep", mesh=self.mesh, n_scenarios=sc, n_nodes=self.n,
            sample=bool(getattr(self.features, "sample", False)),
        )
        mesh = self.mesh if layout.axis == "scenario" else None
        n_dev = int(mesh.devices.size) if mesh is not None else 1
        mesh_jit = _scenario_rows_jit(f"mesh_{site}") if mesh is not None else None

        def evaluate(lo, hi):
            nonlocal mesh
            if mesh is not None:
                try:
                    (valid_s, active_s, pin_s), _rows = (
                        mesh_mod.shard_scenario_rows(
                            mesh,
                            [node_valid[lo:hi], pod_active[lo:hi], pinned[lo:hi]],
                        )
                    )
                    out = mesh_jit(
                        self.static, self.init, cls,
                        valid_s, active_s, pin_s, self.features,
                    )
                    return list(zip(*(np.asarray(o)[: hi - lo] for o in out)))
                except (RuntimeError, MemoryError, OSError) as e:
                    from ..runtime.guard import try_downgrade

                    if not try_downgrade(
                        e, label=site, frm="mesh-scenario", to="xla-scan"
                    ):
                        raise
                    mesh = None
            out = site_jit(
                self.static,
                self.init,
                cls,
                jnp.asarray(node_valid[lo:hi]),
                jnp.asarray(pod_active[lo:hi]),
                jnp.asarray(pinned[lo:hi]),
                self.features,
            )
            return list(zip(*(np.asarray(o) for o in out)))

        def serial_fallback(i):
            placements, _ = self.serial_scenario(
                node_valid[i], pod_active[i], pinned[i], pins_first=True
            )
            return self._host_scenario_stats(node_valid[i], placements)

        from ..obs.costs import COSTS

        # estimator + shard count re-read per chunk: a mid-run mesh
        # downgrade inside evaluate() must flip later chunks back to
        # full-size single-device prediction arithmetic
        est_plain = COSTS.chunk_estimator(f"{site}_sweep")
        est_shard = COSTS.chunk_estimator(f"{site}_sweep", shards=n_dev)

        def estimate(lo, hi):
            return (est_shard if mesh is not None else est_plain)(lo, hi)

        rows = run_chunked(
            evaluate, sc, label=site, serial_fallback=serial_fallback,
            budget=budget, estimate=estimate,
            shards=lambda: n_dev if mesh is not None else 1,
        )
        placements = np.stack([np.asarray(r[0]) for r in rows])
        unsched = np.array([int(r[1]) for r in rows], dtype=np.int64)
        cpu = np.array([float(r[2]) for r in rows])
        mem = np.array([float(r[3]) for r in rows])
        vg = np.array([float(r[4]) for r in rows])
        return placements, unsched, cpu, mem, vg

    # -- resource lower bound ----------------------------------------------

    def lower_bound(self, max_cpu: int = 100, max_mem: int = 100, max_vg: int = 100) -> int:
        """Smallest count not ruled out by aggregate resource totals and
        utilization caps. Any count below it either leaves pods
        unschedulable (sum of requests exceeds sum of allocatable) or
        violates a cap, so the scheduling search can start here. Purely
        arithmetic — no scan."""
        b, c_enc, d = self.batch, self.cluster_enc, self.dyn
        cls = b.class_of_pod
        req = {
            "mcpu": b.req_mcpu[cls].astype(np.int64),
            "mem": b.req_mem[cls].astype(np.int64),
            "eph": b.req_eph[cls].astype(np.int64),
            "pods": np.ones(len(self.pods), dtype=np.int64),
            "vg": b.lvm_sizes[cls].sum(axis=1).astype(np.int64),
        }
        alloc = {
            "mcpu": c_enc.alloc_mcpu,
            "mem": c_enc.alloc_mem,
            "eph": c_enc.alloc_eph,
            "pods": c_enc.alloc_pods,
            "vg": c_enc.vg_cap.sum(axis=1),
        }
        base_used = {
            "mcpu": int(d.used_mcpu.sum()),
            "mem": int(d.used_mem.sum()),
            "eph": int(d.used_eph.sum()),
            "pods": int(d.pod_cnt.sum()),
            "vg": int(d.vg_used.sum()),
        }
        for count in range(0, self.max_count + 1):
            valid = self.node_valid(count)
            active = self.pod_active(valid)
            ok = True
            for r in ("mcpu", "mem", "eph", "pods"):
                if base_used[r] + int(req[r][active].sum()) > int(alloc[r][valid].sum()):
                    ok = False
                    break
            if ok:
                for r, cap in (("mcpu", max_cpu), ("mem", max_mem), ("vg", max_vg)):
                    total_alloc = int(alloc[r][valid].sum())
                    if total_alloc == 0:
                        continue
                    used = base_used[r] + int(req[r][active].sum())
                    if int(used / total_alloc * 100) > cap:
                        ok = False
                        break
            if ok:
                return count
        return self.max_count

    # -- minimal-count search ----------------------------------------------

    def estimate_extra(self, res: ProbeResult) -> int:
        """How many more candidate nodes the unscheduled pods of this
        probe need by aggregate request (a Newton-style step for the
        escalation: usually lands within a node or two of the true
        minimum even when taints/selectors make the global lower bound
        loose)."""
        mask = res.placements == -1
        if not mask.any() or self.max_count == 0:
            return 1
        cls = self.batch.class_of_pod[np.asarray(mask)]
        b = self.batch
        new_i = self.n_base  # all candidate nodes share the spec
        extra = 1
        for req_v, alloc_v in (
            (b.req_mcpu[cls], self.cluster_enc.alloc_mcpu[new_i]),
            (b.req_mem[cls], self.cluster_enc.alloc_mem[new_i]),
            (b.req_eph[cls], self.cluster_enc.alloc_eph[new_i]),
            (np.ones(len(cls), dtype=np.int64), self.cluster_enc.alloc_pods[new_i]),
        ):
            need = int(req_v.sum())
            alloc = int(alloc_v)
            if alloc > 0 and need > 0:
                extra = max(extra, -(-need // alloc))
        return extra

    def _search_gen(self, feasible, start: int = 0, widen: bool = False):
        """The min-count search as a COROUTINE: yields lists of counts
        to probe, receives {count: ProbeResult}, and returns the best
        result (or None) via StopIteration. Extracting the control flow
        from the probe transport lets find_min_count fulfil requests
        one spec at a time while find_min_count_multi batches the
        requests of MANY specs into one device sync per round.

        Search shape (unchanged from r3/r4): probe `start`; on failure
        escalate by the unscheduled-request estimate (with a doubling
        backstop) — asking for (hi-1, hi) together on the Pallas path
        since the estimate usually lands exactly — then bisect the
        bracket, confirming hi-1 first. Monotonicity (more nodes never
        schedule fewer pods) is asserted by tests/test_capacity.py."""
        probes: dict = {}

        probes.update((yield [start]))
        res = probes[start]
        if feasible(res):
            return res
        # grow bracket: (lo known-infeasible, hi candidate]
        lo, escalations = start, 0
        while True:
            step = max(self.estimate_extra(probes[lo]), 1 << escalations)
            hi = min(lo + step, self.max_count)
            if (
                hi - lo > 1
                and hi not in probes
                and hi - 1 not in probes
                and self._pallas_plan is not None
            ):
                # hi-1 is usually the bisection's very next question:
                # ask for both in one round
                probes.update((yield [hi - 1, hi]))
            elif hi not in probes:
                probes.update((yield [hi]))
            res = probes[hi]
            if feasible(res):
                break
            lo = hi
            if hi == self.max_count:
                return None  # infeasible even at max
            escalations += 1
        # bisect (lo infeasible, hi feasible]. In the MULTI driver
        # (widen=True) a small bracket probes every interior count in
        # one round instead of log2 sequential rounds — extra scans
        # are cheap at what-if scale and each saved round saves a
        # relay round-trip; the single-spec path keeps pure bisection
        # (a 100k-pod capacity probe costs ~1s of scan, so extra
        # probes would dominate the saved latency)
        best = res
        lo_b, hi_b = lo, best.count
        if widen and 2 < hi_b - lo_b <= 16 and self._pallas_plan is not None:
            need = [c for c in range(lo_b + 1, hi_b) if c not in probes]
            if need:
                probes.update((yield need))
            for c in range(lo_b + 1, hi_b):
                if feasible(probes[c]):
                    return probes[c]
            return best
        if hi_b - lo_b > 1:
            c = hi_b - 1
            if c not in probes:
                probes.update((yield [c]))
            res = probes[c]
            if feasible(res):
                best, hi_b = res, c
            else:
                lo_b = c
        while hi_b - lo_b > 1:
            mid = (lo_b + hi_b) // 2
            if mid not in probes:
                probes.update((yield [mid]))
            res = probes[mid]
            if feasible(res):
                best, hi_b = res, mid
            else:
                lo_b = mid
        return best

    def _fulfill(self, req: List[int], on_probe=None) -> dict:
        """Probe the requested counts — paired into one device sync on
        the Pallas path when the search asks for two."""
        if len(req) == 2 and self._pallas_plan is not None:
            r1, r2 = self.probe_pair(req[0], req[1])
            out = {r1.count: r1, r2.count: r2}
        else:
            out = {c: self.probe(c) for c in req}
        if on_probe is not None:
            for r in out.values():
                on_probe(r)
        return out

    def find_min_count(
        self,
        feasible,
        start: int = 0,
        on_probe=None,
        budget=None,
    ) -> Optional[ProbeResult]:
        """Smallest count whose probe satisfies `feasible(ProbeResult)`
        (one spec; see _search_gen for the search shape). `budget` is
        checked between probe rounds (the search's safe boundary); on
        halt the raised ExecutionHalted carries a machine-readable
        partial payload: every completed probe and the best feasible
        count seen so far."""
        from ..runtime.errors import ExecutionHalted

        gen = self._search_gen(feasible, start)
        fulfilled: dict = {}
        try:
            req = next(gen)
            while True:
                if budget is not None:
                    try:
                        budget.check("capacity-probe boundary")
                    except ExecutionHalted as e:
                        e.partial = _search_partial(fulfilled, feasible)
                        raise
                got = self._fulfill(req, on_probe)
                fulfilled.update(got)
                req = gen.send(got)
        except StopIteration as stop:
            return stop.value


def _utilization_impl(static, valid, final):
    import jax.numpy as jnp

    denom_cpu = jnp.sum(jnp.where(valid, static.alloc_mcpu, 0))
    denom_mem = jnp.sum(jnp.where(valid, static.alloc_mem, 0))
    cpu_util = (
        100.0 * jnp.sum(jnp.where(valid, final.used_mcpu, 0)) / jnp.maximum(denom_cpu, 1)
    )
    mem_util = (
        100.0 * jnp.sum(jnp.where(valid, final.used_mem, 0)) / jnp.maximum(denom_mem, 1)
    )
    denom_vg = jnp.sum(jnp.where(valid[:, None], static.vg_cap, 0))
    vg_util = (
        100.0 * jnp.sum(jnp.where(valid[:, None], final.vg_used, 0)) / jnp.maximum(denom_vg, 1)
    )
    return cpu_util, mem_util, vg_util


def _scenario_pinned_impl(static, init, cls, valid, active, pinned, features):
    """TWO chained masked scans with a PER-SCENARIO pin vector — the
    resilience engine's substrate (outage scenario = node mask +
    surviving pods pinned at their committed nodes, displaced pods free
    to reschedule) and the timeline's window step. The passes model
    reality: surviving pods never unbind, so ALL pins commit before any
    displaced pod reschedules — a single interleaved scan would let an
    early displaced pod take capacity a later survivor's unconditional
    pin then overcommits. Pins are force-enabled in the features: the
    original batch may have carried none."""
    import jax.numpy as jnp

    from ..ops import scan as scan_ops

    features = features._replace(pins=True)
    p1, state1 = scan_ops.run_scan_masked(
        static, init, cls, pinned, valid,
        active & (pinned >= 0), features=features,
    )
    p2, final = scan_ops.run_scan_masked(
        static, state1, cls, pinned, valid,
        active & (pinned < 0), features=features,
    )
    placements = jnp.where(pinned >= 0, p1, p2)
    unsched = jnp.sum(placements == -1)
    cpu_util, mem_util, vg_util = _utilization_impl(static, valid, final)
    return placements, unsched, cpu_util, mem_util, vg_util


def _scenario_rows_impl(static, init, cls, valids, actives, pinneds, features):
    import jax

    def one(valid, active, pinned):
        return _scenario_pinned_impl(
            static, init, cls, valid, active, pinned, features
        )

    return jax.vmap(one)(valids, actives, pinneds)


# per-site PROCESS-WIDE jits over the pinned scenario rows (chaos,
# timeline): static/init/masks are traced pytree arguments — not
# closures — so same-shaped batches from DIFFERENT sweep instances
# (each ChaosEngine run, each timeline stepper) hit one compiled
# executable instead of recompiling per instance; per-site wrappers
# keep dispatch/recompile attribution separate (obs/profile.py) —
# "how many window dispatches did this timeline cost" must not hide
# inside the chaos counters.
_SCENARIO_ROWS_JITS: dict = {}


def _scenario_rows_jit(site: str):
    jit = _SCENARIO_ROWS_JITS.get(site)
    if jit is None:
        import jax

        from ..obs import profile

        jit = _SCENARIO_ROWS_JITS[site] = profile.instrument_jit(
            jax.jit(_scenario_rows_impl, static_argnums=(6,)),
            f"{site}_sweep",
            static_argnums=(6,),
            lead_argnum=3,  # valids: the batched scenario-rows axis
        )
    return jit


def _search_partial(fulfilled: dict, feasible) -> dict:
    """Machine-readable progress of an interrupted min-count search:
    completed probes + the best (smallest) feasible count so far."""
    rows = []
    best = None
    for count in sorted(fulfilled):
        res = fulfilled[count]
        ok = bool(feasible(res))
        rows.append(
            {
                "count": int(count),
                "unscheduled": int(res.unscheduled),
                "feasible": ok,
            }
        )
        if ok and (best is None or count < best):
            best = int(count)
    return {
        "phase": "capacity-search",
        "completedProbes": rows,
        "bestCount": best,
    }


def find_min_count_multi(jobs, on_probe=None, budget=None) -> List[Optional[ProbeResult]]:
    """Drive MANY specs' min-count searches in lockstep: `jobs` is a
    list of (CapacitySweep, feasible, start). Each round collects every
    live spec's requested probe counts, dispatches ALL of them deferred
    on the Pallas path, and fetches the stacked outputs in ONE device
    sync — so a what-if sweep over K newnode specs pays the relay's
    per-sync latency once per ROUND (~3-4 rounds total) instead of once
    per probe (~23 for the 8-spec bench; the r4 RTT bound,
    docs/PERFORMANCE.md). Sweeps on the XLA fallback path fulfil their
    requests individually inside the round.

    Replaces the per-guess re-simulation loop of the reference's
    interactive Applier (pkg/apply/apply.go:186-239) across candidate
    node SPECS, not just counts."""
    import jax.numpy as jnp

    from ..ops import pallas_scan
    from ..utils.trace import GLOBAL, phase

    # ship every spec's plan in ONE grouped transfer before round 1
    # (otherwise the first round pays one serialized relay message per
    # plan buffer)
    pallas_scan.preload_plan_group(
        [s._pallas_plan for s, _, _ in jobs if s._pallas_plan is not None]
    )
    gens = []
    pending: List[Optional[List[int]]] = []
    results: List[Optional[ProbeResult]] = []
    for sweep, feasible, start in jobs:
        g = sweep._search_gen(feasible, start, widen=True)
        gens.append(g)
        results.append(None)
        pending.append(next(g))
    live = list(range(len(jobs)))
    rounds = dispatches = syncs = 0
    round_log = []
    while live:
        import time as _time

        if budget is not None:
            budget.check("what-if probe round")
        _t0 = _time.time()
        _n0 = dispatches
        rounds += 1
        answers: List[dict] = [dict() for _ in jobs]
        deferred = []  # (job index, count, valid, device out)
        with phase("sweep/probe-multi"):
            for i in live:
                sweep = jobs[i][0]
                for c in pending[i]:
                    dispatches += 1
                    if sweep._pallas_plan is not None:
                        valid = sweep.node_valid(c)
                        try:
                            out_d = pallas_scan.run_scan_pallas(
                                sweep._pallas_plan,
                                sweep.batch.class_of_pod,
                                sweep.pod_active(valid),
                                valid,
                                pinned=sweep.batch.pinned_node,
                                defer=True,
                            )
                        except (RuntimeError, MemoryError, OSError) as e:
                            from ..runtime.guard import try_downgrade

                            if not try_downgrade(
                                e, label="whatif", frm="pallas", to="xla-scan"
                            ):
                                raise
                            # retire the dead Pallas rung for this
                            # spec; probe() finishes the downgrade
                            sweep._pallas_plan = None
                            answers[i][c] = sweep.probe(c)
                            syncs += 1
                            continue
                        deferred.append((i, c, valid, out_d))
                    else:
                        answers[i][c] = sweep.probe(c)
                        syncs += 1
            # ONE host-blocking point per round and shape: the round's
            # outputs stack on-device and fetch as a single array (on
            # the relay every blocking fetch costs ~0.1-0.15s
            # REGARDLESS of size, and per-array async host copies do
            # NOT pipeline — jax.device_get of 44 arrays measured 6s).
            # The stack is padded to a power-of-two row count so the
            # concatenate compiles for O(log max) distinct shapes ever,
            # all hits in the persistent compilation cache after the
            # first encounter.
            by_shape: dict = {}
            for item in deferred:
                by_shape.setdefault(item[3].shape, []).append(item)
            for items in by_shape.values():
                k = len(items)
                bucket = 1 << (k - 1).bit_length()
                rows_d = [it[3] for it in items]
                rows_d += [rows_d[0]] * (bucket - k)
                # the ONE deliberate device->host sync per shape
                # bucket (counted right below): stacking k probe rows
                # and pulling them together is the batching that keeps
                # a K-spec round at one relay round-trip
                stacked = np.asarray(jnp.stack(rows_d))  # simonlint: disable=JAX003
                syncs += 1
                for row, (i, c, valid, _) in zip(stacked, items):
                    sweep = jobs[i][0]
                    placements, final = pallas_scan.decode_scan_output(
                        sweep._pallas_plan,
                        row,
                        int(np.asarray(sweep.batch.class_of_pod).shape[0]),
                    )
                    answers[i][c] = sweep._pallas_result(
                        c, valid, placements, final
                    )
        nxt = []
        for i in live:
            if on_probe is not None:
                for r in answers[i].values():
                    on_probe(r)
            try:
                pending[i] = gens[i].send(answers[i])
                nxt.append(i)
            except StopIteration as stop:
                results[i] = stop.value
                pending[i] = None
        live = nxt
        round_log.append(
            f"{dispatches - _n0}p/{_time.time() - _t0:.2f}s"
        )
    GLOBAL.note("whatif-rounds", rounds)
    GLOBAL.note("whatif-dispatches", dispatches)
    GLOBAL.note("whatif-syncs", syncs)
    GLOBAL.note("whatif-round-log", ",".join(round_log))
    return results


def sweep_node_counts(
    cluster: ResourceTypes,
    apps: List[AppResource],
    new_node_spec: Optional[dict],
    counts: List[int],
    mesh=None,
    use_greed: bool = False,
    score_weights=None,
) -> SweepResult:
    """Evaluate `counts` candidate new-node counts in one batched run."""
    max_count = max(counts) if new_node_spec is not None else 0
    sweep = CapacitySweep(
        cluster,
        apps,
        new_node_spec,
        max_count,
        use_greed=use_greed,
        score_weights=score_weights,
    )
    return sweep.probe_many(counts, mesh=mesh)
