"""Batched capacity-planning sweep over a TPU device mesh.

The reference's capacity loop is interactive: guess a node count, re-run
the whole simulation, ask the user (pkg/apply/apply.go:186-239). Here
every candidate count is one scenario of a single batched computation:

- the cluster is padded with `max_count` copies of the candidate node
  spec (named `simon-%02d` with the `simon/new-node` label, mirroring
  newFakeNodes, apply.go:288-306)
- scenario s enables the first s new nodes via a node-validity mask and
  drops daemonset pods that belong to disabled nodes via a pod-activity
  mask (the reference regenerates them per run)
- `vmap(run_scan_masked)` evaluates all scenarios at once; over a
  `jax.sharding.Mesh` the scenario axis is sharded across devices with
  `shard_map` — scenarios are independent, so the only communication is
  the result gather (this is the "distributed backend": XLA collectives
  over ICI, not a port of anything — the reference is single-process)

Returns per-scenario unscheduled counts and cluster utilization, from
which the planner picks the minimal feasible count
(satisfyResourceSetting caps, apply.go:611-697).
"""

from __future__ import annotations

import copy
from dataclasses import dataclass
from typing import List, Optional

import numpy as np

from ..models import workloads as wl
from ..models.decode import ResourceTypes
from ..scheduler.core import AppResource, _sort_app_pods
from ..scheduler.oracle import Oracle


@dataclass
class SweepResult:
    counts: List[int]
    unscheduled: np.ndarray  # [Sc] number of unschedulable (active) pods
    cpu_util: np.ndarray  # [Sc] percent
    mem_util: np.ndarray  # [Sc] percent
    placements: np.ndarray  # [Sc, P] node index / -1 / -2(inactive)
    pods: List[dict]
    node_names: List[str]


def _new_nodes(spec: dict, count: int) -> List[dict]:
    out = []
    for i in range(count):
        node = wl.make_valid_node(copy.deepcopy(spec), f"{wl.NEW_NODE_NAME_PREFIX}-{i:02d}")
        node["metadata"].setdefault("labels", {})[wl.LABEL_NEW_NODE] = ""
        out.append(node)
    return out


def _daemonset_target(pod: dict) -> Optional[str]:
    """The node a daemonset pod is pinned to via its matchFields term."""
    aff = ((pod.get("spec") or {}).get("affinity") or {}).get("nodeAffinity") or {}
    required = aff.get("requiredDuringSchedulingIgnoredDuringExecution") or {}
    for term in required.get("nodeSelectorTerms") or []:
        for f in term.get("matchFields") or []:
            if f.get("key") == "metadata.name" and f.get("operator") == "In":
                values = f.get("values") or []
                if values:
                    return values[0]
    return None


def sweep_node_counts(
    cluster: ResourceTypes,
    apps: List[AppResource],
    new_node_spec: Optional[dict],
    counts: List[int],
    mesh=None,
    use_greed: bool = False,
) -> SweepResult:
    """Evaluate `counts` candidate new-node counts in one batched run."""
    import jax
    import jax.numpy as jnp

    from ..ops import scan as scan_ops
    from ..ops.encode import (
        encode_batch,
        encode_cluster,
        encode_dynamic,
        to_scan_static,
        to_scan_state,
    )

    max_count = max(counts) if new_node_spec is not None else 0
    padded = cluster.copy()
    padded.nodes = list(padded.nodes) + _new_nodes(new_node_spec, max_count)

    # Build oracle at full padding; generate the full pod sequence the
    # serial path would see (cluster pods first, then apps in order).
    oracle = Oracle(padded.nodes)
    pods: List[dict] = []
    pods.extend(wl.pods_excluding_daemon_sets(padded))
    for ds in padded.daemon_sets:
        pods.extend(wl.pods_from_daemon_set(ds, padded.nodes))
    for app in apps:
        app_pods = wl.generate_valid_pods_from_app(app.name, app.resource, padded.nodes)
        if use_greed:
            # same ordering the authoritative serial run will use
            # (scheduler/core.py schedule_app): greed_sort ignores
            # simon new nodes, so the max-count padding here and the
            # per-count serial cluster sort pods identically
            from ..scheduler.queues import greed_sort

            app_pods = greed_sort(padded.nodes, app_pods)
        pods.extend(_sort_app_pods(app_pods))

    n_base = len(padded.nodes) - max_count
    n = len(padded.nodes)

    # per-scenario masks
    sc = len(counts)
    node_valid = np.ones((sc, n), dtype=bool)
    for s, c in enumerate(counts):
        node_valid[s, n_base + c :] = False
    pod_active = np.ones((sc, len(pods)), dtype=bool)
    name_to_idx = oracle.node_index
    for p_i, pod in enumerate(pods):
        target = _daemonset_target(pod)
        if target is not None and target in name_to_idx:
            t = name_to_idx[target]
            pod_active[:, p_i] = node_valid[:, t]

    cluster_enc = encode_cluster(oracle)
    batch = encode_batch(oracle, cluster_enc, pods)
    dyn = encode_dynamic(oracle, cluster_enc)
    static = to_scan_static(cluster_enc, batch)
    init = to_scan_state(dyn, batch)
    class_arr = jnp.asarray(batch.class_of_pod)
    pinned_arr = jnp.asarray(batch.pinned_node)

    def one_scenario(valid, active):
        placements, final = scan_ops.run_scan_masked(
            static, init, class_arr, pinned_arr, valid, active
        )
        unsched = jnp.sum(placements == -1)
        denom_cpu = jnp.sum(jnp.where(valid, static.alloc_mcpu, 0))
        denom_mem = jnp.sum(jnp.where(valid, static.alloc_mem, 0))
        cpu_util = 100.0 * jnp.sum(jnp.where(valid, final.used_mcpu, 0)) / jnp.maximum(denom_cpu, 1)
        mem_util = 100.0 * jnp.sum(jnp.where(valid, final.used_mem, 0)) / jnp.maximum(denom_mem, 1)
        return placements, unsched, cpu_util, mem_util

    sweep_fn = jax.vmap(one_scenario)

    valid_j = jnp.asarray(node_valid)
    active_j = jnp.asarray(pod_active)

    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        axis = mesh.axis_names[0]
        n_dev = mesh.devices.size
        pad = (-sc) % n_dev
        if pad:
            valid_j = jnp.concatenate([valid_j, jnp.repeat(valid_j[-1:], pad, 0)])
            active_j = jnp.concatenate([active_j, jnp.repeat(active_j[-1:], pad, 0)])
        sharding = NamedSharding(mesh, P(axis))
        valid_j = jax.device_put(valid_j, sharding)
        active_j = jax.device_put(active_j, sharding)
        out = jax.jit(sweep_fn, in_shardings=(sharding, sharding))(valid_j, active_j)
        placements, unsched, cpu_util, mem_util = (np.asarray(o)[:sc] for o in out)
    else:
        out = jax.jit(sweep_fn)(valid_j, active_j)
        placements, unsched, cpu_util, mem_util = (np.asarray(o) for o in out)

    return SweepResult(
        counts=list(counts),
        unscheduled=unsched,
        cpu_util=cpu_util,
        mem_util=mem_util,
        placements=placements,
        pods=pods,
        node_names=[ns.name for ns in oracle.nodes],
    )
