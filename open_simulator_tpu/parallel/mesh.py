"""Mesh-sharded scanning: N devices buy ~N x scale (ROADMAP item 1).

Two shardable axes, one planner:

- **scenario axis** — rows of a batched dispatch (capacity counts,
  chaos outage scenarios, timeline policy windows, coalesced serve
  requests) are independent computations; committing the leading axis
  to a ``jax.sharding.Mesh`` with a ``NamedSharding`` partition spec
  splits them across devices with the result gather as the only
  communication ("computation follows sharding"; the SNIPPETS pjit
  pattern). Embarrassingly parallel: throughput scales ~N x.
- **node axis** — ONE scan over a cluster too big for one device's
  memory: every node-axis array of ``ScanStatic``/``ScanState`` is
  split across the mesh with ``shard_map``, each device scores its
  node shard locally, and per-step cross-device reductions (the
  per-shard top-1 score combine, normalization max/min, spread-count
  min, committed-node value broadcasts) pick the winning node
  GLOBALLY. The step implementation is ``ops/scan.py``'s own —
  ``_run_scan_compiled_impl`` parameterized by a reduction context —
  so the sharded scan cannot drift semantically from the single-device
  one; placements are elementwise identical (tests/test_mesh.py).
  Capacity scales ~N x nodes per mesh.

The **layout planner** (``plan_layout``) picks the axis per request
from the AOT cost registry's per-shape byte estimates (obs/costs.py)
and the device-memory ledger's fit predictions (obs/ledger.py
``predict_fit``): many scenarios -> scenario axis; one scenario over a
cluster predicted not to fit (or past the single-device node
threshold) -> node axis; no mesh / sample-mode batches -> the existing
single-device ladder, unchanged.

Mesh selection is process-wide (``configure``/``current_mesh``), wired
to ``--mesh auto|off|N`` on apply/chaos/timeline and the SIMON_MESH
env var, so every CapacitySweep / TpuEngine / stepper picks it up
without constructor plumbing. A sharded dispatch that hits a device
fault degrades down the existing guard ladder (runtime/guard.py) to
the unsharded path — trace-noted, never silent — and the
``jit.mesh_*`` instrumented sites are chaos-injection seams like every
other dispatch (runtime/inject.py).
"""

from __future__ import annotations

import logging
import math
import os
import threading
from dataclasses import dataclass
from typing import List, Optional

import numpy as np

from ..models.validation import InputError

log = logging.getLogger(__name__)

MESH_AXIS = "devices"

# single-device node count past which the planner prefers the
# node-sharded scan even when memory is not (yet) predicted tight: the
# r5 VMEM-cliff boundary where the single-chip resident path starts
# streaming (docs/PERFORMANCE.md)
DEFAULT_NODE_THRESHOLD = 25_000


def node_threshold() -> int:
    env = os.environ.get("SIMON_MESH_NODE_THRESHOLD")
    try:
        return int(env) if env else DEFAULT_NODE_THRESHOLD
    except ValueError:
        return DEFAULT_NODE_THRESHOLD


# ---------------------------------------------------------------- config

_LOCK = threading.Lock()
_STATE = {"spec": os.environ.get("SIMON_MESH", "off"), "mesh": None, "resolved": False}


def parse_mesh_spec(spec: Optional[str]) -> Optional[int]:
    """``auto`` -> -1, ``off``/empty/None -> None, ``N`` -> N (>= 1).
    Raises InputError on anything else (CLI exit 2)."""
    if spec is None:
        return None
    s = str(spec).strip().lower()
    if s in ("", "off", "0", "none"):
        return None
    if s == "auto":
        return -1
    try:
        n = int(s)
    except ValueError:
        raise InputError(
            f"--mesh {spec!r}: expected auto, off, or a device count"
        ) from None
    if n < 1:
        raise InputError(f"--mesh {spec!r}: device count must be >= 1")
    return n


def configure(spec: Optional[str]) -> None:
    """Set the process-wide mesh selection (CLI ``--mesh`` / SIMON_MESH).
    Validates the spec eagerly (InputError on junk) but resolves
    devices lazily — configure() must be callable before the platform
    is forced (cli._force_platform)."""
    parse_mesh_spec(spec)  # validate now, resolve at first current_mesh()
    with _LOCK:
        _STATE["spec"] = spec if spec is not None else "off"
        _STATE["mesh"] = None
        _STATE["resolved"] = False


def mesh_from_spec(spec: Optional[str]):
    """Build the ``jax.sharding.Mesh`` a spec names, or None (no mesh:
    single-device ladder). ``auto`` = every local device (None when the
    process only has one); ``N`` = the first N local devices."""
    want = parse_mesh_spec(spec)
    if want is None:
        return None
    import jax
    from jax.sharding import Mesh

    devices = jax.local_devices()
    if want == -1:
        if len(devices) < 2:
            return None
        return Mesh(np.array(devices), (MESH_AXIS,))
    if want > len(devices):
        raise InputError(
            f"--mesh {want}: only {len(devices)} local device(s) available"
        )
    if want == 1:
        return None
    return Mesh(np.array(devices[:want]), (MESH_AXIS,))


def current_mesh():
    """The configured process-wide mesh (None = single-device ladder).
    Resolved once per configure() call."""
    with _LOCK:
        if _STATE["resolved"]:
            return _STATE["mesh"]
    mesh = mesh_from_spec(_STATE["spec"])
    with _LOCK:
        _STATE["mesh"] = mesh
        _STATE["resolved"] = True
        if mesh is not None:
            from ..utils.trace import COUNTERS

            COUNTERS.gauge("mesh_devices", float(mesh.devices.size))
    return mesh


def effective_parallelism(mesh) -> int:
    """How much wall-clock parallelism the mesh can physically deliver:
    the device count, except on the forced host-platform CPU mesh
    (XLA_FLAGS=--xla_force_host_platform_device_count=N) where virtual
    devices beyond the core count share cores — the bench efficiency
    gate divides by this, not the nominal N, so CI boxes with 2 cores
    and 8 virtual devices measure against an honest denominator."""
    if mesh is None:
        return 1
    n_dev = int(mesh.devices.size)
    try:
        platform = mesh.devices.flat[0].platform
    except Exception:  # noqa: BLE001 - exotic device object: assume real accelerators
        return n_dev
    if platform == "cpu":
        return max(1, min(n_dev, os.cpu_count() or 1))
    return n_dev


# ---------------------------------------------------------------- planner


@dataclass(frozen=True)
class LayoutDecision:
    """One request's sharding verdict. ``axis`` is "scenario", "node",
    or "none" (single-device ladder); ``shards`` is the device count
    the dispatch will use (1 for "none")."""

    axis: str
    shards: int
    reason: str


def plan_layout(
    site: str,
    *,
    mesh,
    n_scenarios: int,
    n_nodes: int,
    sample: bool = False,
) -> LayoutDecision:
    """Pick the shard layout for one request from the mesh shape, the
    AOT cost registry's byte estimate for this site, and the memory
    ledger's fit prediction. Every decision is counted
    (``mesh_layout_<axis>_total``) and trace-noted so bench/CI fixtures
    can pin the policy:

    - no mesh (or 1 device) -> none: the existing single-device ladder.
    - sample-mode batch -> none: the Go-RNG stream is one serial
      sequence; scenario rows would race it and the node-axis prefix
      arithmetic is a full-axis serial scan.
    - >= 2 scenarios -> scenario axis over the whole mesh: rows are
      independent, so more devices never hurt and the per-device slice
      shrinks by the shard count (the shard-aware chunk estimator
      keeps run_chunked from splitting on full-replica arithmetic).
    - 1 scenario -> node axis when the ledger predicts the
      single-device dispatch will NOT fit, or the cluster is past the
      single-device node threshold (SIMON_MESH_NODE_THRESHOLD,
      default 25k — the r5 VMEM cliff); else none (the warm
      single-device path is faster for small clusters).
    """
    from ..utils.trace import COUNTERS, GLOBAL

    def decide(axis: str, shards: int, reason: str) -> LayoutDecision:
        COUNTERS.inc(f"mesh_layout_{axis}_total")
        GLOBAL.append_note(
            "mesh-layout", f"{site}: {axis} x{shards} ({reason})"
        )
        return LayoutDecision(axis=axis, shards=shards, reason=reason)

    if mesh is None:
        return decide("none", 1, "no mesh configured")
    n_dev = int(mesh.devices.size)
    if n_dev <= 1:
        return decide("none", 1, "mesh has a single device")
    if sample:
        return decide("none", 1, "sample-mode serial RNG stream")
    if n_scenarios >= 2:
        return decide(
            "scenario", n_dev,
            f"{n_scenarios} independent scenario rows over {n_dev} devices",
        )
    if n_nodes < n_dev:
        return decide("none", 1, f"{n_nodes} nodes < {n_dev} devices")
    from ..obs.costs import COSTS
    from ..obs.ledger import LEDGER

    # planning probe, not a dispatch: would_fit skips the
    # predicted-vs-actual counters so they stay about dispatches that
    # actually ran. `site` must name the SINGLE-DEVICE jit whose
    # records describe the dispatch being avoided (engine: "scan",
    # sweep probes: "sweep_probe") — the mesh site has no records
    # until a sharded dispatch already compiled.
    est = COSTS.estimate_bytes(site)
    fits = LEDGER.would_fit(int(est)) if est is not None else None
    if fits is False:
        return decide(
            "node", n_dev,
            f"ledger predicts {est} bytes will not fit on one device",
        )
    if n_nodes >= node_threshold():
        return decide(
            "node", n_dev,
            f"{n_nodes} nodes past the single-device threshold "
            f"({node_threshold()})",
        )
    return decide("none", 1, "single-device warm path fits")


# ------------------------------------------------- scenario-axis sharding


def shard_scenario_rows(mesh, arrays: List[np.ndarray]):
    """Commit the leading (scenario) axis of every array to the mesh:
    pads the axis to a multiple of the device count by repeating the
    last row (scenarios are independent — padded rows are dead weight,
    sliced off by the caller) and ``device_put``s with a
    ``NamedSharding`` over axis 0, so the jitted dispatch compiles
    SPMD-partitioned per observed input sharding. Returns (device
    arrays, original row count)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    n_dev = int(mesh.devices.size)
    rows = int(arrays[0].shape[0])
    pad = (-rows) % n_dev
    # the mesh's own leading axis name: historic callers
    # (sweep_node_counts, the multichip dryrun) build meshes named
    # "scenario", the configured process mesh uses MESH_AXIS
    sharding = NamedSharding(mesh, P(mesh.axis_names[0]))
    out = []
    for a in arrays:
        a = np.asarray(a)
        if pad:
            a = np.concatenate([a, np.repeat(a[-1:], pad, axis=0)])
        out.append(jax.device_put(a, sharding))
    return out, rows


# ----------------------------------------------------- node-axis sharding

# node-axis position per ScanStatic field; unlisted fields carry only
# class/term/port axes and replicate. Keyed by NAME so a new ScanStatic
# field fails loudly in _check_axis_tables (tests) instead of silently
# replicating a node-sized array onto every device.
_STATIC_NODE_AXIS = {
    "alloc_mcpu": 0, "alloc_mem": 0, "alloc_eph": 0, "alloc_pods": 0,
    "scalar_alloc": 1,
    "gpu_per_dev": 0, "gpu_total": 0, "gpu_count": 0, "dev_valid": 0,
    "vg_cap": 0, "vg_valid": 0, "has_storage": 0,
    "ssd_cap": 0, "ssd_valid": 0, "hdd_cap": 0, "hdd_valid": 0,
    "static_feasible": 1, "simon_raw": 1, "nodeaff_raw": 1,
    "taint_intol": 1, "avoid_score": 1, "image_score": 1,
    "topo_val": 1, "h_cand_nodes": 1, "s_q": 1, "cls_s_haskeys": 1,
    "g_topo_val": 1, "s_topo_val": 1, "s_val_onehot": 2,
    "custom_raw": 2,
}

# node-axis position per ScanState field; group_total is a per-row
# TOTAL (every shard derives the same increment after the committed-
# node broadcast) and rng_hist/rng_overflow are sample-mode-only, so
# they replicate.
_STATE_NODE_AXIS = {
    "used_mcpu": 0, "used_mem": 0, "used_eph": 0, "used_scalar": 1,
    "nz_mcpu": 0, "nz_mem": 0, "pod_cnt": 0, "ports_used": 0,
    "gpu_used": 0, "vg_used": 0, "ssd_used": 0, "hdd_used": 0,
    "tgt": 1, "own_anti_req": 1, "own_aff_pref_w": 1,
    "own_anti_pref_w": 1, "group_counts": 1, "soft_counts": 1,
}

# fields whose node axis pads with -1 ("missing topology key") instead
# of 0 — a padded node must never look like it shares topology value 0
_PAD_NEG1 = {"topo_val", "g_topo_val", "s_topo_val"}


def _pad_along(arr: np.ndarray, axis: int, pad: int, name: str) -> np.ndarray:
    if pad == 0:
        return np.asarray(arr)
    arr = np.asarray(arr)
    widths = [(0, 0)] * arr.ndim
    widths[axis] = (0, pad)
    fill = -1 if name in _PAD_NEG1 else (False if arr.dtype == bool else 0)
    return np.pad(arr, widths, constant_values=fill)


def padded_node_count(n: int, shards: int) -> int:
    return int(math.ceil(n / shards) * shards)


def pad_static(static, shards: int):
    """Pad every node-axis field of a ScanStatic to a multiple of the
    shard count. Padded nodes are inert: allocatables 0, validity masks
    False, topology values -1 — and the caller's node_valid mask is
    padded False, so no filter can ever pass one."""
    n = int(np.asarray(static.alloc_mcpu).shape[0])
    pad = padded_node_count(n, shards) - n
    if pad == 0:
        return static
    kw = {}
    for name, ax in _STATIC_NODE_AXIS.items():
        kw[name] = _pad_along(getattr(static, name), ax, pad, name)
    return static._replace(**kw)


def pad_state(init, shards: int):
    n = int(np.asarray(init.used_mcpu).shape[0])
    pad = padded_node_count(n, shards) - n
    if pad == 0:
        return init
    kw = {}
    for name, ax in _STATE_NODE_AXIS.items():
        kw[name] = _pad_along(getattr(init, name), ax, pad, name)
    return init._replace(**kw)


def pad_valid(node_valid, shards: int) -> np.ndarray:
    node_valid = np.asarray(node_valid, bool)
    pad = padded_node_count(node_valid.shape[0], shards) - node_valid.shape[0]
    if pad == 0:
        return node_valid
    return np.concatenate([node_valid, np.zeros(pad, bool)])


class _ShardCtx:
    """ops/scan.py reduction context over a shard_map'ed node axis:
    combines are mesh collectives, gathers broadcast the owning shard's
    value (+1/psum trick — every gathered table holds values >= -1),
    and the select is the per-shard top-1 reduction: local first-max,
    pmax of the shard maxima, then pmin over the global indices of the
    shards holding it — exactly the unsharded first-max in node order."""

    __slots__ = ("axis",)

    def __init__(self, axis: str):
        self.axis = axis

    def _offset(self, n_local: int):
        import jax
        import jax.numpy as jnp

        return jax.lax.axis_index(self.axis).astype(jnp.int64) * n_local

    def combine_max(self, x):
        import jax

        return jax.lax.pmax(x, self.axis)

    def combine_min(self, x):
        import jax

        return jax.lax.pmin(x, self.axis)

    def combine_sum(self, x):
        import jax

        return jax.lax.psum(x, self.axis)

    def combine_any(self, x):
        import jax
        import jax.numpy as jnp

        return jax.lax.pmax(x.astype(jnp.int32), self.axis).astype(bool)

    def gather_vec(self, vec, idx):
        import jax
        import jax.numpy as jnp

        n_l = vec.shape[-1]
        lp = idx - self._offset(n_l)
        in_range = (lp >= 0) & (lp < n_l)
        contrib = jnp.where(
            in_range, vec[jnp.clip(lp, 0, n_l - 1)].astype(jnp.int64) + 1, 0
        )
        return (jax.lax.psum(contrib, self.axis) - 1).astype(vec.dtype)

    def gather_cols(self, arr, idx):
        import jax
        import jax.numpy as jnp

        n_l = arr.shape[-1]
        lp = idx - self._offset(n_l)
        in_range = (lp >= 0) & (lp < n_l)
        col = arr[..., jnp.clip(lp, 0, n_l - 1)]
        contrib = jnp.where(in_range, col.astype(jnp.int64) + 1, 0)
        return (jax.lax.psum(contrib, self.axis) - 1).astype(arr.dtype)

    def first_max_index(self, masked):
        import jax
        import jax.numpy as jnp

        n_l = masked.shape[0]
        local_best = jnp.argmax(masked).astype(jnp.int64)
        local_max = masked[local_best]
        global_max = jax.lax.pmax(local_max, self.axis)
        big = jnp.iinfo(jnp.int64).max
        cand = jnp.where(
            local_max == global_max, self._offset(n_l) + local_best, big
        )
        return jax.lax.pmin(cand, self.axis)

    def commit_onehot(self, placement, commit, n_local):
        import jax
        import jax.numpy as jnp

        lp = placement - self._offset(n_local)
        # out-of-shard (and unplaced < 0) indices one-hot to all-zeros
        return jax.nn.one_hot(lp, n_local, dtype=jnp.int64) * commit.astype(
            jnp.int64
        )


def _utilization_ctx(static, valid, final, ctx):
    """sweep._utilization_impl with cross-shard sums: int64 totals
    combine exactly, so the percentages match the unsharded path
    bit-for-bit."""
    import jax.numpy as jnp

    denom_cpu = ctx.combine_sum(jnp.sum(jnp.where(valid, static.alloc_mcpu, 0)))
    denom_mem = ctx.combine_sum(jnp.sum(jnp.where(valid, static.alloc_mem, 0)))
    used_cpu = ctx.combine_sum(jnp.sum(jnp.where(valid, final.used_mcpu, 0)))
    used_mem = ctx.combine_sum(jnp.sum(jnp.where(valid, final.used_mem, 0)))
    cpu_util = 100.0 * used_cpu / jnp.maximum(denom_cpu, 1)
    mem_util = 100.0 * used_mem / jnp.maximum(denom_mem, 1)
    denom_vg = ctx.combine_sum(
        jnp.sum(jnp.where(valid[:, None], static.vg_cap, 0))
    )
    used_vg = ctx.combine_sum(
        jnp.sum(jnp.where(valid[:, None], final.vg_used, 0))
    )
    vg_util = 100.0 * used_vg / jnp.maximum(denom_vg, 1)
    return cpu_util, mem_util, vg_util


def _static_specs(axis: str):
    from jax.sharding import PartitionSpec as P

    from ..ops.scan import ScanStatic

    kw = {}
    for name in ScanStatic._fields:
        ax = _STATIC_NODE_AXIS.get(name)
        if ax is None:
            kw[name] = P()
        else:
            kw[name] = P(*([None] * ax + [axis]))
    return ScanStatic(**kw)


def _state_specs(init, axis: str):
    from jax.sharding import PartitionSpec as P

    from ..ops.scan import ScanState

    kw = {}
    for name in ScanState._fields:
        if getattr(init, name) is None:
            kw[name] = None
            continue
        ax = _STATE_NODE_AXIS.get(name)
        if ax is None:
            kw[name] = P()
        else:
            kw[name] = P(*([None] * ax + [axis]))
    return ScanState(**kw)


# one instrumented jit per mesh (shardings differ per mesh layout);
# static/init/masks are traced arguments, so same-shaped dispatches
# from different sweeps/engines share one compiled executable per
# (features, shapes) pair — the warm-cache contract, now on the mesh
_MESH_SCAN_JITS: dict = {}
_MESH_JIT_LOCK = threading.Lock()


def _mesh_scan_jit(mesh):
    with _MESH_JIT_LOCK:
        cached = _MESH_SCAN_JITS.get(mesh)
    if cached is not None:
        return cached
    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from ..obs import profile
    from ..ops import scan as scan_ops

    axis = mesh.axis_names[0]

    def impl(features, static, init, cls, pinned, node_valid, pod_active):
        ctx = _ShardCtx(axis)

        def body(static_l, init_l, cls_l, pinned_l, valid_l, active_l):
            placements, final = scan_ops._run_scan_compiled_impl(
                features, static_l, init_l, cls_l, pinned_l, valid_l,
                active_l, ctx=ctx,
            )
            unsched = jnp.sum(placements == -1)
            cpu, mem, vg = _utilization_ctx(static_l, valid_l, final, ctx)
            # leading device axis instead of claiming replication:
            # check_rep=False cannot verify replicated out_specs, so
            # each shard contributes one (identical) row and the host
            # reads row 0
            return (
                placements[None], unsched[None], cpu[None], mem[None],
                vg[None],
            )

        sharded = shard_map(
            body,
            mesh=mesh,
            in_specs=(
                _static_specs(axis),
                _state_specs(init, axis),
                P(),
                P(),
                P(axis),
                P(),
            ),
            out_specs=(P(axis), P(axis), P(axis), P(axis), P(axis)),
            check_rep=False,
        )
        return sharded(static, init, cls, pinned, node_valid, pod_active)

    with _MESH_JIT_LOCK:
        if mesh not in _MESH_SCAN_JITS:
            # wrapper CONSTRUCTION only — no trace or dispatch happens
            # until the first call, and this single-purpose leaf lock
            # guards nothing but the cache dict
            _MESH_SCAN_JITS[mesh] = profile.instrument_jit(  # simonlint: disable=CONC002
                jax.jit(impl, static_argnums=(0,)), "mesh_scan",
                static_argnums=(0,),
            )
        return _MESH_SCAN_JITS[mesh]


def run_node_sharded(
    mesh, static, init, class_of_pod, pinned, node_valid, pod_active,
    features,
):
    """ONE masked scan with the node axis sharded across the mesh.
    Pads the node axis to a shard multiple (padded nodes are inert and
    masked invalid), dispatches through the ``mesh_scan`` instrumented
    jit, and returns host-side (placements[P], unsched, cpu_util,
    mem_util, vg_util) — elementwise identical to
    ``ops.scan.run_scan_masked`` plus the sweep's utilization
    arithmetic. Sample-mode batches are a caller bug (the planner never
    routes them here)."""
    import jax.numpy as jnp

    if bool(getattr(features, "sample", False)):
        raise InputError(
            "sample-mode batches cannot ride the node-sharded scan "
            "(serial Go-RNG stream); the layout planner excludes them"
        )
    shards = int(mesh.devices.size)
    static_p = pad_static(static, shards)
    init_p = pad_state(init, shards)
    valid_p = pad_valid(node_valid, shards)
    out = _mesh_scan_jit(mesh)(
        features,
        static_p,
        init_p,
        jnp.asarray(class_of_pod),
        jnp.asarray(pinned),
        jnp.asarray(valid_p),
        jnp.asarray(np.asarray(pod_active, bool)),
    )
    placements = np.asarray(out[0])[0]
    from ..obs import profile

    profile.record_d2h(placements.nbytes)
    return (
        placements,
        int(np.asarray(out[1])[0]),
        float(np.asarray(out[2])[0]),
        float(np.asarray(out[3])[0]),
        float(np.asarray(out[4])[0]),
    )


class NodeShardPlan:
    """Padded node-sharded dispatch state for REPEATED probes over one
    (static, init) pair — the capacity search probes many counts
    against one encoding, so the pad + transfer cost is paid once."""

    def __init__(self, mesh, static, init, class_of_pod, pinned, features):
        import jax.numpy as jnp

        if bool(getattr(features, "sample", False)):
            raise InputError("sample-mode batches cannot ride the mesh")
        self.mesh = mesh
        self.shards = int(mesh.devices.size)
        self.static = pad_static(static, self.shards)
        self.init = pad_state(init, self.shards)
        self.cls = jnp.asarray(class_of_pod)
        self.pinned = jnp.asarray(pinned)
        self.features = features

    def run(self, node_valid, pod_active):
        import jax.numpy as jnp

        out = _mesh_scan_jit(self.mesh)(
            self.features,
            self.static,
            self.init,
            self.cls,
            self.pinned,
            jnp.asarray(pad_valid(node_valid, self.shards)),
            jnp.asarray(np.asarray(pod_active, bool)),
        )
        placements = np.asarray(out[0])[0]
        from ..obs import profile

        profile.record_d2h(placements.nbytes)
        return (
            placements,
            int(np.asarray(out[1])[0]),
            float(np.asarray(out[2])[0]),
            float(np.asarray(out[3])[0]),
            float(np.asarray(out[4])[0]),
        )
