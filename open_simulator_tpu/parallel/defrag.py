"""Pod-migration / defragmentation sweep.

The reference lists pod migration as a use case (README.md:20) but ships
no command for it — its primitives are cluster snapshot import
(pkg/simulator/simulator.go:369-441) and re-simulation. Here
defragmentation is a first-class batched what-if, the mirror image of
the capacity sweep (sweep.py):

- nodes are ranked by dominant-resource utilization, least-loaded first
  (the natural drain order: cheapest nodes to empty)
- scenario s drains the first s nodes of that ranking: their
  non-daemonset pods are released for rescheduling, their daemonset
  pods cease to exist, and the nodes are masked out of the candidate
  set; every pod still on a kept node is a forced (pinned) placement
- one vmapped masked scan evaluates all drain depths at once (sharded
  over a device mesh like the capacity sweep); the largest depth with
  zero unschedulable pods wins
- the winning depth is then replayed through the serial oracle, which
  validates it placement-for-placement (including device-level GPU and
  VG state the batched search tracks only in aggregate) and yields the
  exact migration plan

Pod ordering inside a scenario: pods are queued by DESCENDING drain
rank of their current node, so for every prefix-drain scenario all
pinned pods commit before any evicted pod schedules — each scenario
sees the semantics "existing cluster first, then the migration wave",
with one shared pod order across scenarios (vmap requirement).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, List, Optional

import numpy as np

from ..models import requests as req
from ..scheduler.core import NodeStatus, SimulateResult


@dataclass
class PodMove:
    pod: dict
    from_node: str
    to_node: str


@dataclass
class DefragResult:
    ranked_nodes: List[str]  # drain order (least utilized first)
    depths: List[int]  # evaluated drain depths
    unscheduled: np.ndarray  # [Sc] unschedulable pods per depth
    chosen_depth: int  # largest feasible depth (0 = nothing drainable)
    drained_nodes: List[str] = field(default_factory=list)
    moves: List[PodMove] = field(default_factory=list)
    result: Optional[SimulateResult] = None  # cluster after the migration


def _dominant_share(node: dict, pods: List[dict]) -> float:
    alloc = req.node_allocatable(node)
    used_cpu = used_mem = 0
    for p in pods:
        r = req.pod_requests(p)
        used_cpu += r.get("cpu", 0)
        used_mem += r.get("memory", 0)
    cpu_cap = alloc.get("cpu", 0)
    mem_cap = alloc.get("memory", 0)
    return max(
        float(used_cpu / cpu_cap) if cpu_cap else 0.0,
        float(used_mem / mem_cap) if mem_cap else 0.0,
    )


def _is_daemonset_pod(pod: dict) -> bool:
    refs = (pod.get("metadata") or {}).get("ownerReferences") or []
    return any(r.get("kind") == "DaemonSet" for r in refs)


def _strip_node_name(pod: dict) -> dict:
    """Shallow per-level copy (pod/metadata/annotations/spec/status):
    deep-copying 6k pods cost ~1s per plan while the big sub-objects
    (containers, affinity) are read-only downstream — only the dicts
    the replay's bind path mutates need to be private."""
    out = dict(pod)
    meta = dict(out.get("metadata") or {})
    if "annotations" in meta and meta["annotations"] is not None:
        meta["annotations"] = dict(meta["annotations"])
    out["metadata"] = meta
    spec = dict(out.get("spec") or {})
    spec.pop("nodeName", None)
    out["spec"] = spec
    # stale placement state must not leak into re-scheduling — copy
    # whenever present (even {}: the bind path mutates status in place)
    status = out.get("status")
    if status is not None:
        status = dict(status)
        status.pop("phase", None)
        out["status"] = status
    return out


def rank_nodes_for_drain(
    statuses: List[NodeStatus], protect: Optional[Callable[[dict], bool]] = None
) -> List[int]:
    """Indices of drainable nodes, least dominant-share first (stable on
    ties by original index). `protect(node)` True exempts a node."""
    cand = []
    for i, ns in enumerate(statuses):
        if protect is not None and protect(ns.node):
            continue
        cand.append((_dominant_share(ns.node, ns.pods), i))
    cand.sort(key=lambda t: (t[0], t[1]))
    return [i for _, i in cand]


def plan_defrag(
    snapshot: SimulateResult,
    max_drain: Optional[int] = None,
    protect: Optional[Callable[[dict], bool]] = None,
    mesh=None,
) -> DefragResult:
    """Find the deepest feasible drain and its migration plan."""
    import jax
    import jax.numpy as jnp

    from ..ops import scan as scan_ops
    from ..ops.encode import (
        encode_batch,
        encode_cluster,
        encode_dynamic,
        to_scan_static,
        to_scan_state,
    )
    from ..scheduler.oracle import Oracle

    statuses = snapshot.node_status
    nodes = [ns.node for ns in statuses]
    ranked = rank_nodes_for_drain(statuses, protect)
    n = len(nodes)
    limit = len(ranked) - 1 if len(ranked) == n else len(ranked)
    if max_drain is not None:
        limit = min(limit, max_drain)
    limit = max(limit, 0)  # never drain every schedulable node
    depths = list(range(0, limit + 1))
    ranked_names = [nodes[i]["metadata"]["name"] for i in ranked]
    if limit == 0:
        return DefragResult(
            ranked_nodes=ranked_names,
            depths=depths,
            unscheduled=np.zeros(1, dtype=np.int64),
            chosen_depth=0,
            result=snapshot,
        )

    # drain rank per node index; undrainable nodes get rank "infinity"
    rank_of = np.full(n, n + 1, dtype=np.int64)
    for r, i in enumerate(ranked):
        rank_of[i] = r

    # pod queue: descending drain rank of the current node
    entries = []  # (rank, node_idx, pod, is_ds)
    for i, ns in enumerate(statuses):
        for pod in ns.pods:
            entries.append((rank_of[i], i, pod, _is_daemonset_pod(pod)))
    entries.sort(key=lambda t: -t[0])

    if not entries:
        # pod-free cluster: every drain depth is trivially feasible
        moves, result = _replay(snapshot, ranked, limit, entries)
        return DefragResult(
            ranked_nodes=ranked_names,
            depths=depths,
            unscheduled=np.zeros(len(depths), dtype=np.int64),
            chosen_depth=limit,
            drained_nodes=ranked_names[:limit],
            moves=moves,
            result=result,
        )

    oracle = Oracle(nodes)
    clean_pods = [_strip_node_name(p) for _, _, p, _ in entries]
    cluster_enc = encode_cluster(oracle)
    batch = encode_batch(oracle, cluster_enc, clean_pods)
    dyn = encode_dynamic(oracle, cluster_enc)
    static = to_scan_static(cluster_enc, batch)
    init = to_scan_state(dyn, batch)
    class_arr = jnp.asarray(batch.class_of_pod)

    p_cnt = len(entries)
    sc = len(depths)
    home = np.array([e[1] for e in entries], dtype=np.int32)
    pod_rank = np.array([e[0] for e in entries], dtype=np.int64)
    is_ds = np.array([e[3] for e in entries], dtype=bool)

    node_valid = np.ones((sc, n), dtype=bool)
    pinned = np.empty((sc, p_cnt), dtype=np.int32)
    pod_active = np.ones((sc, p_cnt), dtype=bool)
    for s_i, depth in enumerate(depths):
        drained_idx = ranked[:depth]
        node_valid[s_i, drained_idx] = False
        evicted = pod_rank < depth
        pinned[s_i] = np.where(evicted, -1, home)
        pod_active[s_i] = ~(evicted & is_ds)

    features = scan_ops.features_of(static, jnp.asarray(pinned[0]))

    # fused-kernel fast path: one kernel launch per depth beats the
    # vmapped XLA scan (whose per-step kernels are latency-bound) by
    # ~4x at bench scale; scenarios share the device-cached plan
    from ..ops import pallas_scan

    plan = (
        pallas_scan.build_plan(cluster_enc, batch, dyn, features)
        if pallas_scan.should_use()
        else None
    )
    from ..utils.trace import GLOBAL

    GLOBAL.note(
        "defrag-kernel",
        "pallas"
        if plan is not None
        else f"xla-scan ({pallas_scan.fallback_reason()})",
    )
    if plan is not None:
        try:
            # one sync for every depth's scan (run_scan_pallas_batch)
            decoded = pallas_scan.run_scan_pallas_batch(
                plan,
                batch.class_of_pod,
                [(pod_active[s_i], node_valid[s_i], pinned[s_i]) for s_i in range(sc)],
            )
            unsched = np.zeros(sc, dtype=np.int64)
            place_by_depth = {}
            for s_i, (placements, _final) in enumerate(decoded):
                place_by_depth[s_i] = placements
                unsched[s_i] = int((placements == -1).sum())
            return _pick_depth(
                snapshot, ranked, ranked_names, depths, unsched, entries,
                place_by_depth.get,
            )
        except (RuntimeError, MemoryError, OSError) as e:
            # unified ladder (runtime/guard.py): a classified device
            # fault downgrades to the XLA scan path below; anything
            # else stays loud
            from ..runtime.guard import try_downgrade

            if not try_downgrade(e, label="defrag", frm="pallas", to="xla-scan"):
                raise
            plan = None

    # the depth sweep rides ONE module-level jit (below): static/init
    # ship as traced pytree args, features as the static arg — so
    # repeated plan_defrag calls on same-shaped clusters hit the warm
    # compile cache instead of re-tracing a fresh closure every call
    # (JAX002; the same contract as engine._scenario_scan_jit)
    pin_j = jnp.asarray(pinned)
    valid_j = jnp.asarray(node_valid)
    active_j = jnp.asarray(pod_active)

    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        axis = mesh.axis_names[0]
        n_dev = mesh.devices.size
        pad = (-sc) % n_dev
        if pad:
            pin_j = jnp.concatenate([pin_j, jnp.repeat(pin_j[-1:], pad, 0)])
            valid_j = jnp.concatenate([valid_j, jnp.repeat(valid_j[-1:], pad, 0)])
            active_j = jnp.concatenate([active_j, jnp.repeat(active_j[-1:], pad, 0)])
        sharding = NamedSharding(mesh, P(axis))
        # device_put commits the scenario axis to the mesh; jit
        # compiles per observed input sharding, so the sharded batch
        # warms its own cache entry once per mesh layout
        pin_j = jax.device_put(pin_j, sharding)
        valid_j = jax.device_put(valid_j, sharding)
        active_j = jax.device_put(active_j, sharding)
        unsched = _defrag_sweep_jit()(
            static, init, class_arr, pin_j, valid_j, active_j, features
        )
        unsched = np.asarray(unsched)[:sc]
    else:
        # OOM-halving chunked executor (runtime/guard.py): a depth
        # batch that exhausts device memory splits and retries instead
        # of killing the defrag plan
        from ..runtime.guard import run_chunked

        def evaluate(lo, hi):
            out = _defrag_sweep_jit()(
                static, init, class_arr,
                pin_j[lo:hi], valid_j[lo:hi], active_j[lo:hi], features,
            )
            return [int(x) for x in np.asarray(out)]

        from ..obs.costs import COSTS

        unsched = np.asarray(
            run_chunked(
                evaluate, sc, label="defrag",
                estimate=COSTS.chunk_estimator("defrag_sweep"),
            ),
            dtype=np.int64,
        )

    def placements_for(depth):
        placements, _ = scan_ops.run_scan_masked(
            static, init, class_arr,
            jnp.asarray(pinned[depth]), jnp.asarray(node_valid[depth]),
            jnp.asarray(pod_active[depth]), features=features,
        )
        return np.asarray(placements)

    return _pick_depth(
        snapshot, ranked, ranked_names, depths, unsched, entries,
        placements_for,
    )


def _defrag_sweep_impl(static, init, cls, pins, valids, actives, features):
    import jax
    import jax.numpy as jnp

    from ..ops import scan as scan_ops

    def one(pin, valid, active):
        placements, _final = scan_ops.run_scan_masked(
            static, init, cls, pin, valid, active, features=features
        )
        # only the count leaves the device; the winning depth's exact
        # placements are re-derived on demand by placements_for
        return jnp.sum(placements == -1)

    return jax.vmap(one)(pins, valids, actives)


_DEFRAG_SWEEP_JIT = None


def _defrag_sweep_jit():
    """The jitted drain-depth vmap, compiled once per (shape,
    features) pair PROCESS-WIDE: static/init/masks are traced pytree
    arguments (not closures), so repeated defrag planning over
    same-shaped clusters hits the jit cache instead of recompiling —
    the same warm-cache contract as engine._scenario_scan_jit, and
    counted by the same dispatch/recompile instrumentation."""
    global _DEFRAG_SWEEP_JIT
    if _DEFRAG_SWEEP_JIT is None:
        import jax

        from ..obs import profile

        _DEFRAG_SWEEP_JIT = profile.instrument_jit(
            jax.jit(_defrag_sweep_impl, static_argnums=(6,)),
            "defrag_sweep",
            static_argnums=(6,),
            lead_argnum=3,  # pins: the batched drain-depth axis
        )
    return _DEFRAG_SWEEP_JIT


def _pick_depth(snapshot, ranked, ranked_names, depths, unsched, entries,
                placements_for=None):
    """Deepest feasible drain per the batched search, then host-state
    validation (mirrors the applier's sweep-hint + authoritative-run
    split): the batched scan's own placements replay as filter-checked
    forced commits; a full serial re-schedule only runs if that
    disagrees, and on failure the next shallower depth is tried."""
    for depth in sorted((d for d in depths if unsched[d] == 0), reverse=True):
        validated = None
        if placements_for is not None and depth > 0:
            validated = _replay_forced(
                snapshot, ranked, depth, entries, placements_for(depth)
            )
        if validated is None:
            validated = _replay(snapshot, ranked, depth, entries)
        if validated is not None:
            moves, result = validated
            return DefragResult(
                ranked_nodes=ranked_names,
                depths=depths,
                unscheduled=unsched,
                chosen_depth=depth,
                drained_nodes=ranked_names[:depth],
                moves=moves,
                result=result,
            )
    return DefragResult(
        ranked_nodes=ranked_names,
        depths=depths,
        unscheduled=unsched,
        chosen_depth=0,
        result=snapshot,
    )


def _replay_setup(snapshot, ranked, depth, entries):
    """Shared prologue of both replay flavors: a preemption-free oracle
    over the kept nodes with every kept pod re-committed, a map from
    snapshot node index to its kept NodeState, and the evicted pods as
    (entry_idx, node_idx, pod)."""
    from ..scheduler.oracle import Oracle

    statuses = snapshot.node_status
    drained = set(ranked[:depth])
    kept = [(i, ns) for i, ns in enumerate(statuses) if i not in drained]
    # a defrag replay must never evict running pods to make a drained
    # pod fit — moves have to land in genuinely free capacity
    oracle = Oracle([ns.node for _, ns in kept], enable_preemption=False)
    kept_state = {i: oracle.nodes[k] for k, (i, _) in enumerate(kept)}

    evicted = []
    for e_i, (_rank, node_idx, pod, is_ds) in enumerate(entries):
        if node_idx in drained:
            if not is_ds:
                evicted.append((e_i, node_idx, pod))
            continue
        oracle.place_existing_pod(pod)
    return oracle, kept_state, evicted


def _replay_result(oracle):
    return SimulateResult(
        unscheduled_pods=[],
        node_status=[
            NodeStatus(node=ns.node, pods=list(ns.pods)) for ns in oracle.nodes
        ],
    )


def _replay_forced(snapshot, ranked, depth, entries, placements):
    """Validated replay driven by the batched scan's placements for
    this depth: kept pods re-commit as-is; each evicted pod's scan
    target is checked against live host state with the full framework
    filter set plus the permit plugins, then force-committed — O(1)
    nodes per move instead of the serial path's full prioritize cycle.
    Returns None (caller falls back to the serial _replay) on any
    disagreement."""
    statuses = snapshot.node_status
    oracle, kept_state, evicted = _replay_setup(snapshot, ranked, depth, entries)

    moves: List[PodMove] = []
    for e_i, node_idx, pod in evicted:
        target = int(placements[e_i])
        ns = kept_state.get(target)
        if ns is None:  # unplaced, or a target the drain masked out
            return None
        clean = _strip_node_name(pod)
        if not oracle.passes_filters_on_node(clean, ns):
            return None
        # the serial path enforces Reserve/Permit/PreBind via
        # _select_and_bind — a forced commit must not skip a plugin's
        # veto or cache mutation. Any veto aborts to the serial replay
        # (no unreserve bookkeeping needed here: the caller discards
        # this oracle and the serial path rebuilds plugin state from a
        # fresh run).
        for plugin in oracle.registry.plugins:
            if not plugin.reserve(clean, ns.node):
                return None
        for plugin in oracle.registry.plugins:
            if not plugin.permit(clean, ns.node):
                return None
        for plugin in oracle.registry.plugins:
            if not plugin.prebind(clean, ns.node):
                return None
        oracle._reserve_and_bind(clean, ns)
        for plugin in oracle.registry.plugins:
            plugin.postbind(clean, ns.node)
        moves.append(
            PodMove(
                pod=clean,
                from_node=statuses[node_idx].node["metadata"]["name"],
                to_node=ns.name,
            )
        )
    return moves, _replay_result(oracle)


def _replay(snapshot, ranked, depth, entries):
    """Serial-oracle validation of one drain depth (full scheduling
    cycle per evicted pod). Returns (moves, SimulateResult) or None if
    any evicted pod fails."""
    statuses = snapshot.node_status
    oracle, _kept_state, evicted = _replay_setup(snapshot, ranked, depth, entries)

    moves: List[PodMove] = []
    for _e_i, node_idx, pod in evicted:
        clean = _strip_node_name(pod)
        target, _reason = oracle.schedule_pod(clean)
        if target is None:
            return None
        moves.append(
            PodMove(
                pod=clean,
                from_node=statuses[node_idx].node["metadata"]["name"],
                to_node=target,
            )
        )

    # a validated plan schedules every evicted pod by construction
    return moves, _replay_result(oracle)
