"""Label selectors, node affinity, taints and tolerations.

Host-side implementations of the matching semantics the reference gets
from k8s.io/apimachinery and k8s.io/component-helpers:

- label selector match (matchLabels + matchExpressions In/NotIn/Exists/
  DoesNotExist), used by pod-affinity terms and topology-spread
  constraints (vendor/.../interpodaffinity/filtering.go,
  podtopologyspread/filtering.go)
- node selector / node affinity terms incl. Gt/Lt and matchFields
  (vendor/.../framework/plugins/helper/node_affinity.go)
- toleration / taint matching (vendor/k8s.io/api/core/v1/toleration.go,
  used by the TaintToleration plugin and daemon.Predicates)

These run on the host both in the serial oracle and in the tensor
encoder (which precomputes match matrices for the JAX scan).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


# ---------------------------------------------------------------- selectors


def match_labels_selector(selector: Optional[dict], labels: dict) -> bool:
    """LabelSelector (matchLabels + matchExpressions) vs a label map.

    A nil selector matches nothing; an empty selector matches everything
    (k8s LabelSelectorAsSelector semantics).
    """
    if selector is None:
        return False
    ml = selector.get("matchLabels") or {}
    for k, v in ml.items():
        if labels.get(k) != v:
            return False
    for expr in selector.get("matchExpressions") or []:
        if not _match_expression(expr, labels):
            return False
    return True


def _match_expression(expr: dict, labels: dict) -> bool:
    key = expr.get("key", "")
    op = expr.get("operator", "")
    values = expr.get("values") or []
    present = key in labels
    val = labels.get(key)
    if op == "In":
        return present and val in values
    if op == "NotIn":
        return not present or val not in values
    if op == "Exists":
        return present
    if op == "DoesNotExist":
        return not present
    return False


# ------------------------------------------------------------ node affinity


def _match_node_expression(expr: dict, labels: dict) -> bool:
    key = expr.get("key", "")
    op = expr.get("operator", "")
    values = expr.get("values") or []
    present = key in labels
    val = labels.get(key)
    if op == "In":
        return present and val in values
    if op == "NotIn":
        return not present or val not in values
    if op == "Exists":
        return present
    if op == "DoesNotExist":
        return not present
    if op in ("Gt", "Lt"):
        if not present or len(values) != 1:
            return False
        try:
            lhs = int(val)
            rhs = int(values[0])
        except (TypeError, ValueError):
            return False
        return lhs > rhs if op == "Gt" else lhs < rhs
    return False


def match_node_selector_term(term: dict, node_labels: dict, node_fields: dict) -> bool:
    """One NodeSelectorTerm: ANDs matchExpressions (labels) + matchFields.

    A term with no (valid) requirements matches nothing, per k8s
    nodeaffinity.NewNodeSelector.
    """
    exprs = term.get("matchExpressions") or []
    fields = term.get("matchFields") or []
    if not exprs and not fields:
        return False
    for e in exprs:
        if not _match_node_expression(e, node_labels):
            return False
    for e in fields:
        if not _match_node_expression(e, node_fields):
            return False
    return True


def match_node_selector(node_selector: dict, node_labels: dict, node_fields: dict) -> bool:
    """NodeSelector: OR over terms. Empty term list matches nothing."""
    terms = node_selector.get("nodeSelectorTerms") or []
    return any(match_node_selector_term(t, node_labels, node_fields) for t in terms)


def pod_matches_node_selector_and_affinity(pod_spec: dict, node: "dict") -> bool:
    """PodMatchesNodeSelectorAndAffinityTerms (vendor/.../plugins/helper).

    nodeSelector (exact label map) AND requiredDuringScheduling node
    affinity. Used by the NodeAffinity filter, daemonset eligibility and
    topology-spread candidate-node filtering.
    """
    labels = (node.get("metadata") or {}).get("labels") or {}
    fields = {"metadata.name": (node.get("metadata") or {}).get("name", "")}
    ns = pod_spec.get("nodeSelector") or {}
    for k, v in ns.items():
        if labels.get(k) != v:
            return False
    affinity = pod_spec.get("affinity") or {}
    node_aff = affinity.get("nodeAffinity") or {}
    required = node_aff.get("requiredDuringSchedulingIgnoredDuringExecution")
    if required is not None:
        if not match_node_selector(required, labels, fields):
            return False
    return True


def preferred_node_affinity_score(pod_spec: dict, node: dict) -> int:
    """Sum of weights of matching preferred scheduling terms.

    NodeAffinity.Score (vendor/.../nodeaffinity/node_affinity.go:77-107).
    An empty preferred term matches all objects per the API comment, but
    NewPreferredSchedulingTerms skips terms with no requirements, so an
    empty term contributes nothing.
    """
    labels = (node.get("metadata") or {}).get("labels") or {}
    fields = {"metadata.name": (node.get("metadata") or {}).get("name", "")}
    affinity = pod_spec.get("affinity") or {}
    node_aff = affinity.get("nodeAffinity") or {}
    total = 0
    for wterm in node_aff.get("preferredDuringSchedulingIgnoredDuringExecution") or []:
        pref = wterm.get("preference") or {}
        if match_node_selector_term(pref, labels, fields):
            total += int(wterm.get("weight", 0))
    return total


# --------------------------------------------------------- taints/tolerations


def toleration_tolerates_taint(tol: dict, taint: dict) -> bool:
    """v1.Toleration.ToleratesTaint."""
    t_effect = tol.get("effect", "")
    if t_effect and t_effect != taint.get("effect", ""):
        return False
    t_key = tol.get("key", "")
    if t_key and t_key != taint.get("key", ""):
        return False
    op = tol.get("operator") or "Equal"
    if op == "Exists":
        return True
    if op == "Equal":
        return tol.get("value", "") == taint.get("value", "")
    return False


def tolerations_tolerate_taint(tolerations: list, taint: dict) -> bool:
    return any(toleration_tolerates_taint(t, taint) for t in tolerations or [])


def find_untolerated_taint(taints: list, tolerations: list, effects=("NoSchedule", "NoExecute")):
    """FindMatchingUntoleratedTaint filtered to scheduling effects.

    Returns the first taint (in node order) with an effect in `effects`
    that no toleration tolerates, or None.
    """
    for taint in taints or []:
        if taint.get("effect") not in effects:
            continue
        if not tolerations_tolerate_taint(tolerations, taint):
            return taint
    return None


def count_intolerable_prefer_no_schedule(taints: list, tolerations: list) -> int:
    """TaintToleration score input (taint_toleration.go:123-135).

    Only tolerations with empty effect or PreferNoSchedule are considered
    (getAllTolerationPreferNoSchedule).
    """
    prefer_tols = [
        t for t in tolerations or [] if not t.get("effect") or t.get("effect") == "PreferNoSchedule"
    ]
    n = 0
    for taint in taints or []:
        if taint.get("effect") != "PreferNoSchedule":
            continue
        if not tolerations_tolerate_taint(prefer_tols, taint):
            n += 1
    return n


# ------------------------------------------------------------- affinity terms


@dataclass
class AffinityTerm:
    """A required/preferred pod (anti)affinity term, pre-resolved.

    Mirrors framework.AffinityTerm (vendor/.../framework/types.go): the
    term's namespaces default to the owning pod's namespace when the term
    lists none.
    """

    selector: Optional[dict]
    topology_key: str
    namespaces: frozenset
    weight: int = 0  # only for preferred terms

    def matches_pod(self, pod: dict) -> bool:
        meta = pod.get("metadata") or {}
        ns = meta.get("namespace") or "default"
        if ns not in self.namespaces:
            return False
        return match_labels_selector(self.selector, meta.get("labels") or {})


def _get_terms(pod: dict, kind: str, mode: str) -> list:
    spec = pod.get("spec") or {}
    affinity = spec.get("affinity") or {}
    section = affinity.get(kind) or {}
    return section.get(mode) or []


def resolve_affinity_terms(pod: dict, kind: str, mode: str) -> list:
    """Extract AffinityTerms from a pod.

    kind: 'podAffinity' | 'podAntiAffinity'
    mode: 'requiredDuringSchedulingIgnoredDuringExecution' |
          'preferredDuringSchedulingIgnoredDuringExecution'
    """
    meta = pod.get("metadata") or {}
    own_ns = meta.get("namespace") or "default"
    out = []
    for raw in _get_terms(pod, kind, mode):
        weight = 0
        term = raw
        if mode.startswith("preferred"):
            weight = int(raw.get("weight", 0))
            term = raw.get("podAffinityTerm") or {}
        namespaces = term.get("namespaces") or []
        ns_set = frozenset(namespaces) if namespaces else frozenset([own_ns])
        out.append(
            AffinityTerm(
                selector=term.get("labelSelector"),
                topology_key=term.get("topologyKey", ""),
                namespaces=ns_set,
                weight=weight,
            )
        )
    return out
