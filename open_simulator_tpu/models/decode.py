"""YAML ingestion: directory walking and the 13-kind object demux.

Mirrors pkg/utils/utils.go:44-131 (ParseFilePath / ReadYamlFile /
GetYamlContentFromDirectory) and pkg/simulator/utils.go:139-183
(GetObjectFromYamlContent). Objects are kept as plain dicts (the parsed
YAML); typed behavior lives in accessor modules.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import List

import yaml

_YAML_EXT = (".yaml", ".yml")


@dataclass
class ResourceTypes:
    """The 13 kinds the reference tracks (pkg/simulator/core.go:29-43)."""

    pods: List[dict] = field(default_factory=list)
    deployments: List[dict] = field(default_factory=list)
    replica_sets: List[dict] = field(default_factory=list)
    replication_controllers: List[dict] = field(default_factory=list)
    stateful_sets: List[dict] = field(default_factory=list)
    daemon_sets: List[dict] = field(default_factory=list)
    jobs: List[dict] = field(default_factory=list)
    cron_jobs: List[dict] = field(default_factory=list)
    nodes: List[dict] = field(default_factory=list)
    services: List[dict] = field(default_factory=list)
    persistent_volume_claims: List[dict] = field(default_factory=list)
    storage_classes: List[dict] = field(default_factory=list)
    pod_disruption_budgets: List[dict] = field(default_factory=list)
    # Extension beyond the reference demux (pkg/simulator/utils.go:139-183
    # has no PriorityClass case): kept so priorityClassName on workloads
    # can resolve to a numeric priority the way the real apiserver's
    # admission plugin would (scheduler/preemption.py).
    priority_classes: List[dict] = field(default_factory=list)

    def extend(self, other: "ResourceTypes"):
        for f in self.__dataclass_fields__:
            getattr(self, f).extend(getattr(other, f))

    def copy(self) -> "ResourceTypes":
        out = ResourceTypes()
        for f in self.__dataclass_fields__:
            setattr(out, f, list(getattr(self, f)))
        return out


_KIND_FIELD = {
    "Pod": "pods",
    "Deployment": "deployments",
    "ReplicaSet": "replica_sets",
    "ReplicationController": "replication_controllers",
    "StatefulSet": "stateful_sets",
    "DaemonSet": "daemon_sets",
    "Job": "jobs",
    "CronJob": "cron_jobs",
    "Node": "nodes",
    "Service": "services",
    "PersistentVolumeClaim": "persistent_volume_claims",
    "StorageClass": "storage_classes",
    "PodDisruptionBudget": "pod_disruption_budgets",
    "PriorityClass": "priority_classes",
}


def list_files(path: str) -> List[str]:
    """ParseFilePath: a dir yields its (recursive) files, a file itself."""
    if os.path.isdir(path):
        out = []
        for root, _, files in os.walk(path):
            for f in sorted(files):
                out.append(os.path.join(root, f))
        return sorted(out)
    return [path]


def read_yaml_documents(path: str) -> List[dict]:
    if not path.endswith(_YAML_EXT):
        return []
    with open(path) as f:
        docs = list(yaml.safe_load_all(f))
    return [d for d in docs if isinstance(d, dict)]


def yaml_content_from_directory(dir_path: str) -> List[str]:
    """Raw YAML strings from every .yaml/.yml under dir (recursively)."""
    out = []
    for p in list_files(dir_path):
        if p.endswith(_YAML_EXT):
            with open(p) as f:
                out.append(f.read())
    return out


def decode_yaml_content(yaml_strings: List[str]) -> ResourceTypes:
    """GetObjectFromYamlContent: demux documents by kind; unknown kinds
    are silently skipped (pkg/simulator/utils.go:175-177)."""
    res = ResourceTypes()
    for s in yaml_strings:
        for doc in yaml.safe_load_all(s):
            if not isinstance(doc, dict):
                continue
            kind = doc.get("kind")
            f = _KIND_FIELD.get(kind)
            if f is None:
                continue
            getattr(res, f).append(doc)
    return res


def load_directory(dir_path: str) -> ResourceTypes:
    return decode_yaml_content(yaml_content_from_directory(dir_path))
