"""k8s API validation subset.

The reference's MakeValidPod/MakeValidNodeByNode run the *real*
kubernetes validation library over every generated object
(pkg/utils/utils.go:519-532 ValidatePod -> validation.ValidatePodCreate;
utils.go:657-671 ValidateNode -> validation.ValidateNode). This module
ports the subset of those invariants the simulator depends on — object
names, label syntax, resource-quantity well-formedness, selector
operator arity, toleration/taint consistency, enum fields — with the
upstream message strings (public apimachinery/validation constants), so
malformed input is rejected loudly with the same words a real apiserver
would use.

Errors aggregate in field-path order and are wrapped as
`invalid pod: ...` / `invalid node: ...` exactly like utils.go:530/668.
"""

from __future__ import annotations

import re
from typing import List, Optional

from ..utils.quantity import parse_quantity

# -- apimachinery/pkg/util/validation string formats -----------------------

_DNS1123_LABEL_RE = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")
DNS1123_LABEL_MSG = (
    "a lowercase RFC 1123 label must consist of lower case alphanumeric "
    "characters or '-', and must start and end with an alphanumeric "
    "character (e.g. 'my-name',  or '123-abc', regex used for validation "
    "is '[a-z0-9]([-a-z0-9]*[a-z0-9])?')"
)
DNS1123_LABEL_MAX = 63

_DNS1123_SUBDOMAIN_RE = re.compile(
    r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?(\.[a-z0-9]([-a-z0-9]*[a-z0-9])?)*$"
)
DNS1123_SUBDOMAIN_MSG = (
    "a lowercase RFC 1123 subdomain must consist of lower case alphanumeric "
    "characters, '-' or '.', and must start and end with an alphanumeric "
    "character (e.g. 'example.com', regex used for validation is "
    r"'[a-z0-9]([-a-z0-9]*[a-z0-9])?(\.[a-z0-9]([-a-z0-9]*[a-z0-9])?)*')"
)
DNS1123_SUBDOMAIN_MAX = 253

_QUALIFIED_NAME_RE = re.compile(r"^([A-Za-z0-9][-A-Za-z0-9_.]*)?[A-Za-z0-9]$")
QUALIFIED_NAME_MSG = (
    "name part must consist of alphanumeric characters, '-', '_' or '.', "
    "and must start and end with an alphanumeric character (e.g. 'MyName',  "
    "or 'my.name',  or '123-abc', regex used for validation is "
    "'([A-Za-z0-9][-A-Za-z0-9_.]*)?[A-Za-z0-9]')"
)
QUALIFIED_NAME_MAX = 63

_LABEL_VALUE_RE = re.compile(r"^(([A-Za-z0-9][-A-Za-z0-9_.]*)?[A-Za-z0-9])?$")
LABEL_VALUE_MSG = (
    "a valid label must be an empty string or consist of alphanumeric "
    "characters, '-', '_' or '.', and must start and end with an "
    "alphanumeric character (e.g. 'MyValue',  or 'my_value',  or '12345', "
    "regex used for validation is "
    "'(([A-Za-z0-9][-A-Za-z0-9_.]*)?[A-Za-z0-9])?')"
)


class InputError(ValueError):
    """Malformed user input (vs an internal error): the CLI catches
    this for a clean `error: ...` + exit 1, while real bugs stay loud."""


def _max_len_error(length: int) -> str:
    return f"must be no more than {length} bytes"


def _to_int(value) -> Optional[int]:
    """Integer coercion that returns None for non-integer input
    instead of raising, so malformed numerics aggregate as field
    errors. ALL floats are rejected (even integral 80.0): the real
    apiserver's strict JSON decode refuses any float into an int
    field, so truncating or accepting here would pass manifests a
    real cluster rejects."""
    if isinstance(value, bool):
        return None
    if isinstance(value, int):
        return value
    if isinstance(value, str):
        try:
            return int(value, 10)
        except ValueError:
            return None
    return None


def _is_dns1123_label(value: str) -> List[str]:
    errs = []
    if len(value) > DNS1123_LABEL_MAX:
        errs.append(_max_len_error(DNS1123_LABEL_MAX))
    if not _DNS1123_LABEL_RE.match(value):
        errs.append(DNS1123_LABEL_MSG)
    return errs


def _is_dns1123_subdomain(value: str) -> List[str]:
    errs = []
    if len(value) > DNS1123_SUBDOMAIN_MAX:
        errs.append(_max_len_error(DNS1123_SUBDOMAIN_MAX))
    if not _DNS1123_SUBDOMAIN_RE.match(value):
        errs.append(DNS1123_SUBDOMAIN_MSG)
    return errs


def _is_qualified_name(value: str) -> List[str]:
    """apimachinery IsQualifiedName: [prefix/]name with a DNS-1123
    subdomain prefix and a 63-char name part."""
    errs = []
    parts = value.split("/")
    if len(parts) == 1:
        name = parts[0]
    elif len(parts) == 2:
        prefix, name = parts
        if not prefix:
            errs.append("prefix part must be non-empty")
        else:
            errs.extend(
                "prefix part " + m for m in _is_dns1123_subdomain(prefix)
            )
    else:
        errs.append(
            "a qualified name "
            + QUALIFIED_NAME_MSG
            + " with an optional DNS subdomain prefix and '/' (e.g. "
            "'example.com/MyName')"
        )
        return errs
    if not name:
        errs.append("name part must be non-empty")
    elif len(name) > QUALIFIED_NAME_MAX:
        errs.append("name part " + _max_len_error(QUALIFIED_NAME_MAX))
    if name and not _QUALIFIED_NAME_RE.match(name):
        errs.append(QUALIFIED_NAME_MSG)
    return errs


def _is_label_value(value: str) -> List[str]:
    errs = []
    if len(value) > QUALIFIED_NAME_MAX:
        errs.append(_max_len_error(QUALIFIED_NAME_MAX))
    if not _LABEL_VALUE_RE.match(value):
        errs.append(LABEL_VALUE_MSG)
    return errs


# -- field.Error rendering (k8s.io/apimachinery field pkg) -----------------


class _ErrorList(list):
    def invalid(self, path: str, value, detail: str):
        self.append(f'{path}: Invalid value: "{value}": {detail}')

    def required(self, path: str, detail: str = ""):
        self.append(f"{path}: Required value" + (f": {detail}" if detail else ""))

    def unsupported(self, path: str, value, supported: List[str]):
        sup = ", ".join(f'"{s}"' for s in supported)
        self.append(
            f'{path}: Unsupported value: "{value}": supported values: {sup}'
        )

    def duplicate(self, path: str, value):
        self.append(f'{path}: Duplicate value: "{value}"')


def _validate_object_meta(meta: dict, path: str, errs: _ErrorList):
    name = meta.get("name") or ""
    generate_name = meta.get("generateName") or ""
    if not name and not generate_name:
        errs.required(f"{path}.name", "name or generateName is required")
    elif name:
        for m in _is_dns1123_subdomain(name):
            errs.invalid(f"{path}.name", name, m)
    if generate_name:
        # ValidateObjectMeta runs the name fn over generateName with
        # prefix=true: maskTrailingDash replaces a trailing "-" (and
        # the char before it) with "a", since a random suffix will be
        # appended — "web--" validates as "weba".
        candidate = generate_name
        if len(candidate) > 1 and candidate.endswith("-"):
            candidate = candidate[:-2] + "a"
        for m in _is_dns1123_subdomain(candidate):
            errs.invalid(f"{path}.generateName", generate_name, m)
    ns = meta.get("namespace")
    if ns:
        for m in _is_dns1123_label(ns):
            errs.invalid(f"{path}.namespace", ns, m)
    for key, value in (meta.get("labels") or {}).items():
        for m in _is_qualified_name(str(key)):
            errs.invalid(f"{path}.labels", key, m)
        for m in _is_label_value(str(value)):
            errs.invalid(f"{path}.labels", value, m)
    for key in meta.get("annotations") or {}:
        for m in _is_qualified_name(str(key)):
            errs.invalid(f"{path}.annotations", key, m)


def _validate_quantity(raw, path: str, errs: _ErrorList) -> Optional[int]:
    try:
        value = parse_quantity(raw)
    except (ValueError, TypeError):
        errs.invalid(
            path,
            raw,
            "quantities must match the regular expression "
            "'^([+-]?[0-9.]+)([eEinumkKMGTP]*[-+]?[0-9]*)$'",
        )
        return None
    if value < 0:
        errs.invalid(path, raw, "must be greater than or equal to 0")
        return None
    return value


def _validate_resources(resources: dict, path: str, errs: _ErrorList):
    requests = (resources or {}).get("requests") or {}
    limits = (resources or {}).get("limits") or {}
    parsed_limits = {}
    for rname, raw in limits.items():
        parsed_limits[rname] = _validate_quantity(raw, f"{path}.limits", errs)
    for rname, raw in requests.items():
        req = _validate_quantity(raw, f"{path}.requests", errs)
        lim = parsed_limits.get(rname)
        if req is not None and lim is not None and req > lim:
            errs.invalid(
                f"{path}.requests",
                raw,
                f"must be less than or equal to {rname} limit",
            )


_SELECTOR_OPERATORS = ["In", "NotIn", "Exists", "DoesNotExist", "Gt", "Lt"]


def _validate_node_selector_term(term: dict, path: str, errs: _ErrorList):
    for i, expr in enumerate(term.get("matchExpressions") or []):
        epath = f"{path}.matchExpressions[{i}]"
        key = expr.get("key") or ""
        for m in _is_qualified_name(key):
            errs.invalid(f"{epath}.key", key, m)
        op = expr.get("operator") or ""
        values = expr.get("values") or []
        if op in ("In", "NotIn"):
            if not values:
                errs.required(
                    f"{epath}.values",
                    "must be specified when `operator` is 'In' or 'NotIn'",
                )
        elif op in ("Exists", "DoesNotExist"):
            if values:
                errs.append(
                    f"{epath}.values: Forbidden: may not be specified when "
                    "`operator` is 'Exists' or 'DoesNotExist'"
                )
        elif op in ("Gt", "Lt"):
            if len(values) != 1:
                errs.required(
                    f"{epath}.values",
                    "must be specified single value when `operator` is 'Lt' or 'Gt'",
                )
        else:
            errs.invalid(f"{epath}.operator", op, "not a valid selector operator")


_TAINT_EFFECTS = ["NoSchedule", "PreferNoSchedule", "NoExecute"]


def _validate_tolerations(tolerations: list, path: str, errs: _ErrorList):
    for i, tol in enumerate(tolerations or []):
        tpath = f"{path}[{i}]"
        key = tol.get("key") or ""
        op = tol.get("operator") or ""
        if key:
            for m in _is_qualified_name(key):
                errs.invalid(f"{tpath}.key", key, m)
        elif op and op != "Exists":
            errs.invalid(
                f"{tpath}.operator",
                op,
                "operator must be Exists when `key` is empty, which means "
                '"match all values and all keys"',
            )
        if op == "Exists" and tol.get("value"):
            errs.invalid(
                f"{tpath}.operator",
                tol["value"],
                "value must be empty when `operator` is 'Exists'",
            )
        if op not in ("", "Equal", "Exists"):
            errs.unsupported(f"{tpath}.operator", op, ["Equal", "Exists"])
        effect = tol.get("effect") or ""
        if tol.get("tolerationSeconds") is not None and effect != "NoExecute":
            errs.invalid(
                f"{tpath}.effect",
                effect,
                "effect must be 'NoExecute' when `tolerationSeconds` is set",
            )
        if effect and effect not in _TAINT_EFFECTS:
            errs.unsupported(f"{tpath}.effect", effect, _TAINT_EFFECTS)


def _validate_containers(containers: list, path: str, errs: _ErrorList):
    seen_names = set()
    for i, c in enumerate(containers or []):
        cpath = f"{path}[{i}]"
        name = c.get("name") or ""
        if not name:
            errs.required(f"{cpath}.name")
        else:
            for m in _is_dns1123_label(name):
                errs.invalid(f"{cpath}.name", name, m)
            if name in seen_names:
                errs.duplicate(f"{cpath}.name", name)
            seen_names.add(name)
        if not c.get("image"):
            errs.required(f"{cpath}.image")
        _validate_resources(c.get("resources") or {}, f"{cpath}.resources", errs)
        for j, port in enumerate(c.get("ports") or []):
            ppath = f"{cpath}.ports[{j}]"
            cp = port.get("containerPort")
            if cp is None:
                errs.required(f"{ppath}.containerPort")
            elif _to_int(cp) is None or not (0 < _to_int(cp) < 65536):
                errs.invalid(
                    f"{ppath}.containerPort",
                    cp,
                    "must be between 1 and 65535, inclusive",
                )
            hp = port.get("hostPort")
            if hp is not None and (
                _to_int(hp) is None or not (0 < _to_int(hp) < 65536)
            ):
                errs.invalid(
                    f"{ppath}.hostPort", hp, "must be between 1 and 65535, inclusive"
                )
            proto = port.get("protocol", "TCP")
            if proto not in ("TCP", "UDP", "SCTP"):
                errs.unsupported(f"{ppath}.protocol", proto, ["TCP", "UDP", "SCTP"])


def pod_validation_errors(pod: dict) -> List[str]:
    """The ValidatePodCreate subset, as field.Error strings."""
    errs = _ErrorList()
    meta = pod.get("metadata") or {}
    _validate_object_meta(meta, "metadata", errs)
    spec = pod.get("spec") or {}
    containers = spec.get("containers") or []
    if not containers:
        errs.required("spec.containers")
    _validate_containers(containers, "spec.containers", errs)
    _validate_containers(
        spec.get("initContainers") or [], "spec.initContainers", errs
    )
    for key, value in (spec.get("nodeSelector") or {}).items():
        for m in _is_qualified_name(str(key)):
            errs.invalid("spec.nodeSelector", key, m)
        for m in _is_label_value(str(value)):
            errs.invalid("spec.nodeSelector", value, m)
    node_affinity = (spec.get("affinity") or {}).get("nodeAffinity") or {}
    required = node_affinity.get("requiredDuringSchedulingIgnoredDuringExecution")
    if required:
        base = (
            "spec.affinity.nodeAffinity."
            "requiredDuringSchedulingIgnoredDuringExecution"
        )
        terms = required.get("nodeSelectorTerms")
        if not terms:
            errs.required(
                f"{base}.nodeSelectorTerms", "must have at least one node selector term"
            )
        for i, term in enumerate(terms or []):
            _validate_node_selector_term(
                term or {}, f"{base}.nodeSelectorTerms[{i}]", errs
            )
    for i, pref in enumerate(
        node_affinity.get("preferredDuringSchedulingIgnoredDuringExecution") or []
    ):
        base = (
            "spec.affinity.nodeAffinity."
            f"preferredDuringSchedulingIgnoredDuringExecution[{i}]"
        )
        weight = _to_int(pref.get("weight"))
        if weight is None or not (1 <= weight <= 100):
            errs.invalid(
                f"{base}.weight", pref.get("weight"), "must be in the range 1-100"
            )
        _validate_node_selector_term(
            pref.get("preference") or {}, f"{base}.preference", errs
        )
    _validate_tolerations(spec.get("tolerations"), "spec.tolerations", errs)
    rp = spec.get("restartPolicy")
    if rp and rp not in ("Always", "OnFailure", "Never"):
        errs.unsupported("spec.restartPolicy", rp, ["Always", "OnFailure", "Never"])
    dp = spec.get("dnsPolicy")
    if dp and dp not in ("ClusterFirstWithHostNet", "ClusterFirst", "Default", "None"):
        errs.unsupported(
            "spec.dnsPolicy",
            dp,
            ["ClusterFirstWithHostNet", "ClusterFirst", "Default", "None"],
        )
    ads = spec.get("activeDeadlineSeconds")
    if ads is not None and (_to_int(ads) is None or _to_int(ads) < 1):
        errs.invalid(
            "spec.activeDeadlineSeconds", ads, "must be between 1 and 2147483647, inclusive"
        )
    return list(errs)


def node_validation_errors(node: dict) -> List[str]:
    """The ValidateNode subset, as field.Error strings."""
    errs = _ErrorList()
    meta = node.get("metadata") or {}
    _validate_object_meta(meta, "metadata", errs)
    seen = set()
    for i, taint in enumerate(((node.get("spec") or {}).get("taints")) or []):
        tpath = f"spec.taints[{i}]"
        key = taint.get("key") or ""
        if not key:
            errs.required(f"{tpath}.key")
        else:
            for m in _is_qualified_name(key):
                errs.invalid(f"{tpath}.key", key, m)
        value = taint.get("value") or ""
        for m in _is_label_value(value):
            errs.invalid(f"{tpath}.value", value, m)
        effect = taint.get("effect") or ""
        if not effect:
            errs.required(f"{tpath}.effect")
        elif effect not in _TAINT_EFFECTS:
            errs.unsupported(f"{tpath}.effect", effect, _TAINT_EFFECTS)
        if (key, effect) in seen:
            errs.append(
                f"{tpath}: Duplicate value: taints must be unique by key "
                "and effect pair"
            )
        seen.add((key, effect))
    status = node.get("status") or {}
    for section in ("capacity", "allocatable"):
        for rname, raw in (status.get(section) or {}).items():
            _validate_quantity(raw, f"status.{section}", errs)
    return list(errs)


def validate_pod(pod: dict):
    """ValidatePod (utils.go:519-532): raise with the aggregated
    field errors joined like the reference."""
    errs = pod_validation_errors(pod)
    if errs:
        raise InputError("invalid pod: " + "\n".join(errs))


def validate_pod_name(pod: dict):
    """Name-only fast path for replica clones of an already-validated
    workload template (the only per-clone field is the generated name)."""
    errs = _ErrorList()
    meta = pod.get("metadata") or {}
    name = meta.get("name") or ""
    if not name:
        errs.required("metadata.name", "name or generateName is required")
    else:
        for m in _is_dns1123_subdomain(name):
            errs.invalid("metadata.name", name, m)
    if errs:
        raise InputError("invalid pod: " + "\n".join(errs))


def validate_node(node: dict):
    """ValidateNode (utils.go:657-671)."""
    errs = node_validation_errors(node)
    if errs:
        raise InputError("invalid node: " + "\n".join(errs))
