"""Local-storage and GPU-share codecs.

Mirrors pkg/utils/utils.go:541-654 (NodeStorage / VolumeRequest /
GetPodLocalPVCs) and the open-gpu-share annotation helpers
(vendor/github.com/alibaba/open-gpu-share/pkg/utils/pod.go, node.go).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import List, Optional

from ..utils.quantity import q_value
from .workloads import (
    ANNO_NODE_LOCAL_STORAGE,
    ANNO_POD_LOCAL_STORAGE,
    SC_LVM,
)

GPU_MEM_ANNO = "alibabacloud.com/gpu-mem"
GPU_COUNT_ANNO = "alibabacloud.com/gpu-count"
GPU_INDEX_ANNO = "alibabacloud.com/gpu-index"
GPU_MODEL_LABEL = "alibabacloud.com/gpu-card-model"


def _to_int(v, default=0) -> int:
    if v is None:
        return default
    if isinstance(v, bool):
        return int(v)
    if isinstance(v, (int, float)):
        return int(v)
    try:
        return q_value(v)
    except (ValueError, ZeroDivisionError):
        return default


def _to_bool(v) -> bool:
    if isinstance(v, bool):
        return v
    return str(v).lower() == "true"


@dataclass
class VG:
    name: str
    capacity: int
    requested: int = 0


@dataclass
class Device:
    name: str
    capacity: int
    media_type: str = "hdd"  # 'ssd' | 'hdd'
    is_allocated: bool = False


@dataclass
class NodeStorage:
    vgs: List[VG] = field(default_factory=list)
    devices: List[Device] = field(default_factory=list)

    def to_json(self) -> str:
        return json.dumps(
            {
                "vgs": [
                    {"name": vg.name, "capacity": str(vg.capacity), "requested": str(vg.requested)}
                    for vg in self.vgs
                ],
                "devices": [
                    {
                        "name": d.name,
                        "device": d.name,
                        "capacity": str(d.capacity),
                        "mediaType": d.media_type,
                        "isAllocated": "true" if d.is_allocated else "false",
                    }
                    for d in self.devices
                ],
            }
        )


def parse_node_storage(node: dict) -> Optional[NodeStorage]:
    """GetNodeStorage: decode the simon/node-local-storage annotation."""
    anno = (node.get("metadata") or {}).get("annotations") or {}
    raw = anno.get(ANNO_NODE_LOCAL_STORAGE)
    if raw is None:
        return None
    data = json.loads(raw) if isinstance(raw, str) else raw
    vgs = [
        VG(
            name=vg.get("name", ""),
            capacity=_to_int(vg.get("capacity")),
            requested=_to_int(vg.get("requested")),
        )
        for vg in data.get("vgs") or []
    ]
    devices = [
        Device(
            name=d.get("device") or d.get("name") or "",
            capacity=_to_int(d.get("capacity")),
            media_type=str(d.get("mediaType", "hdd")).lower(),
            is_allocated=_to_bool(d.get("isAllocated", False)),
        )
        for d in data.get("devices") or []
    ]
    return NodeStorage(vgs=vgs, devices=devices)


def set_node_storage(node: dict, storage: NodeStorage):
    meta = node.setdefault("metadata", {})
    meta.setdefault("annotations", {})[ANNO_NODE_LOCAL_STORAGE] = storage.to_json()


@dataclass
class LocalVolume:
    size: int
    kind: str  # 'LVM' | 'SSD' | 'HDD'
    sc_name: str

    @property
    def is_lvm(self) -> bool:
        return self.sc_name in SC_LVM or self.kind == "LVM"


def parse_pod_local_volumes(pod: dict):
    """GetPodLocalPVCs: split the simon/pod-local-storage volumes into
    (lvm, device) requests."""
    anno = (pod.get("metadata") or {}).get("annotations") or {}
    raw = anno.get(ANNO_POD_LOCAL_STORAGE)
    if raw is None:
        return [], []
    data = json.loads(raw) if isinstance(raw, str) else raw
    lvm, device = [], []
    for v in data.get("volumes") or []:
        kind = v.get("kind", "")
        if kind not in ("LVM", "SSD", "HDD"):
            continue
        vol = LocalVolume(size=_to_int(v.get("size")), kind=kind, sc_name=v.get("scName", ""))
        if vol.is_lvm:
            lvm.append(vol)
        else:
            device.append(vol)
    return lvm, device


# --------------------------------------------------------------- GPU share


def pod_gpu_request(pod: dict):
    """(per-GPU memory, gpu count) from pod annotations
    (GetGpuMemoryAndCountFromPodAnnotation)."""
    anno = (pod.get("metadata") or {}).get("annotations") or {}
    mem = _to_int(anno.get(GPU_MEM_ANNO))
    count = _to_int(anno.get(GPU_COUNT_ANNO))
    return mem, count


def pod_gpu_memory(pod: dict) -> int:
    anno = (pod.get("metadata") or {}).get("annotations") or {}
    return _to_int(anno.get(GPU_MEM_ANNO))


def node_total_gpu_memory(node: dict) -> int:
    """GetTotalGpuMemory: node capacity alibabacloud.com/gpu-mem."""
    cap = (node.get("status") or {}).get("capacity") or {}
    return _to_int(cap.get(GPU_MEM_ANNO))


def node_gpu_count(node: dict) -> int:
    cap = (node.get("status") or {}).get("capacity") or {}
    return _to_int(cap.get(GPU_COUNT_ANNO))


def node_gpu_per_device_memory(node: dict) -> int:
    count = node_gpu_count(node)
    if count <= 0:
        return 0
    return node_total_gpu_memory(node) // count
