"""Workload -> pod controller emulation.

Re-implements pkg/utils/utils.go:133-500 (MakeValidPodsBy* /
MakeValidPod / AddWorkloadInfoToPod / SetObjectMetaFromObject) and the
daemonset eligibility path (utils.go:357-398 + the vendored
daemon.Predicates, daemon_controller.go:1251-1258).

Faithful quirks preserved on purpose (they are observable semantics):
- Generated pods take their labels/annotations from the OWNER object,
  not from spec.template.metadata (SetObjectMetaFromObject,
  utils.go:336-347). This is how e.g. GPU annotations on a ReplicaSet
  reach its pods, and what affinity self-matching sees.
- Deployment pods go through an intermediate ReplicaSet whose
  labels/annotations come from the Deployment.
- StatefulSet pod names are `<name>-<ordinal>`; all other generated pods
  are `<owner>-<hash>` (hash width 5 for pods, 10 for workloads).
- PVC volumes are rewritten to hostPath /tmp; env/mounts/probes dropped
  (MakeValidPod, utils.go:410-492).
- StatefulSet volumeClaimTemplates become the `simon/pod-local-storage`
  annotation (utils.go:273-316).
"""

from __future__ import annotations

import copy
import hashlib
import itertools
import json
from typing import Optional

from . import labels as lbl
from .validation import validate_pod_name
from ..utils.quantity import q_value

# pkg/type/const.go
ANNO_WORKLOAD_KIND = "simon/workload-kind"
ANNO_WORKLOAD_NAME = "simon/workload-name"
ANNO_WORKLOAD_NAMESPACE = "simon/workload-namespace"
ANNO_NODE_LOCAL_STORAGE = "simon/node-local-storage"
ANNO_POD_LOCAL_STORAGE = "simon/pod-local-storage"
ANNO_NODE_GPU_SHARE = "simon/node-gpu-share"
LABEL_NEW_NODE = "simon/new-node"
LABEL_APP_NAME = "simon/app-name"
NEW_NODE_NAME_PREFIX = "simon"
DEFAULT_SCHEDULER_NAME = "default-scheduler"
MAX_NUM_NEW_NODE = 100
WORKLOAD_HASH_DIGITS = 10
POD_HASH_DIGITS = 5

# open-local storage class names (pkg/utils/const.go)
SC_LVM = ("open-local-lvm", "yoda-lvm")
SC_SSD = (
    "open-local-device-ssd",
    "open-local-mountpoint-ssd",
    "yoda-mountpoint-ssd",
    "yoda-device-ssd",
)
SC_HDD = (
    "open-local-device-hdd",
    "open-local-mountpoint-hdd",
    "yoda-mountpoint-hdd",
    "yoda-device-hdd",
)

_name_counter = itertools.count()


def reset_name_counter():
    """Deterministic generated-name suffixes for reproducible tests."""
    global _name_counter
    _name_counter = itertools.count()


def name_counter_state() -> int:
    """The counter's next value, without advancing it (observing
    requires a draw, so the counter is re-seated at the drawn value).
    The serve coalescer snapshots the post-cluster-expansion state once
    and replays it before expanding EVERY request's apps, so a
    coalesced request's generated pod names are identical to the names
    a standalone `simulate()` of that request would mint."""
    global _name_counter
    n = next(_name_counter)
    _name_counter = itertools.count(n)
    return n


def set_name_counter(n: int):
    """Re-seat the generated-name counter at `n` (see
    name_counter_state)."""
    global _name_counter
    _name_counter = itertools.count(n)


def _hash_suffix(digits: int) -> str:
    n = next(_name_counter)
    return hashlib.sha256(str(n).encode()).hexdigest()[:digits]


def _meta_from_owner(owner: dict, kind: str, gen_pod: bool) -> dict:
    """SetObjectMetaFromObject: name = owner-<hash>, labels/annotations
    copied from the owner, ownerReference recorded."""
    ometa = owner.get("metadata") or {}
    name = ometa.get("name", "")
    return {
        "name": f"{name}-{_hash_suffix(POD_HASH_DIGITS if gen_pod else WORKLOAD_HASH_DIGITS)}",
        "namespace": ometa.get("namespace"),
        "generateName": name,
        "annotations": dict(ometa.get("annotations") or {}),
        "labels": dict(ometa.get("labels") or {}),
        "ownerReferences": [
            {
                "kind": kind,
                "name": name,
                "controller": True,
            }
        ],
    }


def _meta_for_replica(base_anno: dict, namespace, gen_name: str, shared_refs) -> dict:
    """Per-replica metadata with the template-invariant parts hoisted
    (annotations still copied per pod — the GPU binder writes a
    per-pod device index into them; labels are assigned by the caller
    from the template's shared dict)."""
    return {
        "name": f"{gen_name}-{_hash_suffix(POD_HASH_DIGITS)}",
        "namespace": namespace,
        "generateName": gen_name,
        "annotations": dict(base_anno),
        "ownerReferences": shared_refs,
    }


def make_valid_pod(pod: dict, _name_only_validation: bool = False) -> dict:
    """MakeValidPod: defaulting + sanitization (utils.go:410-492).

    `_name_only_validation` is the replica fast path: pods expanded
    from one workload template are identical except for the generated
    name, so the caller validates the first clone fully and the rest
    name-only (the reference re-validates every clone; at 100k pods
    that is ~2 s of host time for zero information)."""
    pod = copy.deepcopy(pod)
    meta = pod.setdefault("metadata", {})
    meta.setdefault("labels", {})
    if not meta.get("namespace"):
        meta["namespace"] = "default"
    meta.setdefault("annotations", {})
    spec = pod.setdefault("spec", {})
    if not spec.get("dnsPolicy"):
        spec["dnsPolicy"] = "ClusterFirst"
    if not spec.get("restartPolicy"):
        spec["restartPolicy"] = "Always"
    if not spec.get("schedulerName"):
        spec["schedulerName"] = DEFAULT_SCHEDULER_NAME
    spec.pop("imagePullSecrets", None)
    for key in ("initContainers", "containers"):
        for c in spec.get(key) or []:
            c.pop("volumeMounts", None)
            c.pop("env", None)
            c.pop("livenessProbe", None)
            c.pop("readinessProbe", None)
            c.pop("startupProbe", None)
            sc = c.get("securityContext")
            if sc is not None and "privileged" in sc:
                sc["privileged"] = False
    for v in spec.get("volumes") or []:
        if "persistentVolumeClaim" in v:
            v.pop("persistentVolumeClaim")
            v["hostPath"] = {"path": "/tmp"}
    _validate_pod(pod, _name_only_validation)
    return pod


def _validate_pod(pod: dict, name_only: bool = False):
    """ValidatePod parity (utils.go:519-532): the k8s validation subset
    in models/validation.py, with upstream field-error messages."""
    from .validation import validate_pod, validate_pod_name

    if name_only:
        validate_pod_name(pod)
    else:
        validate_pod(pod)


def add_workload_info(pod: dict, kind: str, name: str, namespace: str) -> dict:
    anno = pod["metadata"].setdefault("annotations", {})
    anno[ANNO_WORKLOAD_KIND] = kind
    anno[ANNO_WORKLOAD_NAME] = name
    anno[ANNO_WORKLOAD_NAMESPACE] = namespace
    return pod


def _expand_template(owner: dict, kind: str, count: int) -> list:
    from .validation import validate_pod_name

    ometa = owner.get("metadata") or {}
    owner_name = ometa.get("name", "")
    owner_ns = ometa.get("namespace", "")
    pods = []
    shared_spec = None
    for i in range(count):
        if shared_spec is None:
            pod = make_valid_pod(
                {
                    "metadata": _meta_from_owner(owner, kind, gen_pod=True),
                    "spec": copy.deepcopy(
                        ((owner.get("spec") or {}).get("template") or {}).get("spec") or {}
                    ),
                }
            )
            shared_spec = pod["spec"]
            first_meta = pod["metadata"]
            # replicas share ONE labels dict and ONE ownerReferences
            # list (content is identical per template; the only
            # post-expansion label write — the app-name label,
            # generate_valid_pods_from_app — stamps the same value for
            # every replica, and nothing mutates ownerReferences).
            # Annotations stay per-pod: the GPU binder writes a per-pod
            # device index there. Sharing lets the encode class-key
            # memo hit by identity (ops/encode.py) instead of
            # re-freezing 100k label dicts.
            shared_labels = first_meta.setdefault("labels", {})
            shared_refs = first_meta.get("ownerReferences")
            namespace = first_meta.get("namespace")
            add_workload_info(pod, kind, owner_name, owner_ns)
            base_anno_full = dict(pod["metadata"]["annotations"])
        else:
            # clone fast path: all replicas share the sanitized
            # template spec — nested structures are read-only after
            # expansion, and direct key writes (the binder's nodeName)
            # land on this clone's own top-level dict. The template was
            # fully validated on the first clone; only the generated
            # name varies. At 100k pods the deepcopy+revalidate path
            # this replaces was ~16 s of host time.
            meta = _meta_for_replica(
                base_anno_full, namespace, owner_name, shared_refs
            )
            meta["labels"] = shared_labels
            pod = {"metadata": meta, "spec": dict(shared_spec)}
            _validate_pod_name_cached(pod)
        pods.append(pod)
    return pods


def pods_from_replica_set(rs: dict) -> list:
    replicas = (rs.get("spec") or {}).get("replicas")
    return _expand_template(rs, "ReplicaSet", 1 if replicas is None else int(replicas))


def pods_from_deployment(deploy: dict) -> list:
    spec = deploy.get("spec") or {}
    # intermediate ReplicaSet named <deploy>-<hash10>, owned by the
    # Deployment (generateReplicaSetFromDeployment, utils.go:185-195);
    # pods then carry an ownerReference to the RS
    rs = {
        "kind": "ReplicaSet",
        "metadata": _meta_from_owner(deploy, "Deployment", gen_pod=False),
        "spec": {
            "selector": spec.get("selector"),
            "replicas": spec.get("replicas"),
            "template": spec.get("template"),
        },
    }
    return pods_from_replica_set(rs)


def pods_from_replication_controller(rc: dict) -> list:
    replicas = (rc.get("spec") or {}).get("replicas")
    return _expand_template(rc, "ReplicationController", 1 if replicas is None else int(replicas))


def pods_from_job(job: dict) -> list:
    completions = (job.get("spec") or {}).get("completions")
    return _expand_template(job, "Job", 1 if completions is None else int(completions))


def pods_from_cron_job(cronjob: dict) -> list:
    spec = cronjob.get("spec") or {}
    job_template = spec.get("jobTemplate") or {}
    meta = _meta_from_owner(cronjob, "CronJob", gen_pod=False)
    anno = dict((job_template.get("metadata") or {}).get("annotations") or {})
    anno["cronjob.kubernetes.io/instantiate"] = "manual"
    meta["annotations"] = anno
    job = {
        "kind": "Job",
        "metadata": meta,
        "spec": (job_template.get("spec") or {}),
    }
    return pods_from_job(job)


def pods_from_stateful_set(sts: dict) -> list:
    spec = sts.get("spec") or {}
    replicas = spec.get("replicas")
    count = 1 if replicas is None else int(replicas)
    name = (sts.get("metadata") or {}).get("name", "")
    pods = _expand_template(sts, "StatefulSet", count)
    for ordinal, pod in enumerate(pods):
        pod["metadata"]["name"] = f"{name}-{ordinal}"
    _set_storage_annotation(pods, spec.get("volumeClaimTemplates") or [])
    return pods


def _set_storage_annotation(pods: list, volume_claim_templates: list):
    """volumeClaimTemplates -> simon/pod-local-storage annotation
    (utils.go:273-316). Size is serialized as a string per the Go
    `json:"size,string"` tag."""
    volumes = []
    for pvc in volume_claim_templates:
        sc = (pvc.get("spec") or {}).get("storageClassName")
        if sc is None:
            continue
        requested = q_value(
            (((pvc.get("spec") or {}).get("resources") or {}).get("requests") or {}).get("storage")
        )
        if sc in SC_LVM:
            kind = "LVM"
        elif sc in SC_SSD:
            kind = "SSD"
        elif sc in SC_HDD:
            kind = "HDD"
        else:
            continue
        volumes.append({"size": str(requested), "kind": kind, "scName": sc})
    if not volumes:
        volumes = []
    payload = json.dumps({"volumes": volumes})
    for pod in pods:
        pod["metadata"].setdefault("annotations", {})[ANNO_POD_LOCAL_STORAGE] = payload


# raw-pod -> intern-key memo: planners and benches expand the SAME
# decoded pod dicts once per simulate() call, and the sort-keyed
# json.dumps content key below is ~60% of warm re-expansion wall-clock
# at 20k bare pods. Keyed on the raw pod's identity — the entry holds
# a strong ref to the pod, so a key hit proves identity (the
# utils/memo.py contract; decoded inputs are read-only after load).
# The sentinel marks non-JSON-serializable pods that must take the
# full per-pod path every time.
_POD_KEY_CACHE: dict = {}
_POD_KEY_CACHE_MAX = 1 << 17
_UNSERIALIZABLE = object()


def _register_pod_key_cache():
    from ..utils.memo import register_cache

    register_cache(_POD_KEY_CACHE.clear)


_register_pod_key_cache()


def _pod_intern_key(pod: dict):
    hit = _POD_KEY_CACHE.get(id(pod))
    if hit is not None:
        return hit[1]
    meta = pod.get("metadata") or {}
    try:
        # everything except metadata.name participates in the key,
        # so a clone can only differ from its first by name —
        # generateName, apiVersion/kind, status etc. are all
        # shared content
        key = json.dumps(
            {
                "metadata": {k: v for k, v in meta.items() if k != "name"},
                "rest": {k: v for k, v in pod.items() if k != "metadata"},
            },
            sort_keys=True,
        )
    except (TypeError, ValueError):
        key = _UNSERIALIZABLE
    if len(_POD_KEY_CACHE) >= _POD_KEY_CACHE_MAX:
        _POD_KEY_CACHE.clear()
    _POD_KEY_CACHE[id(pod)] = (pod, key)
    return key


# validated pod NAMES (value-keyed — strings are immutable): re-runs
# over the same decoded inputs re-validate the same 20k-100k generated
# names against the same DNS-1123 regex for zero information. Only
# successes are cached; failures raise before insertion.
_VALID_NAMES: set = set()
_VALID_NAMES_MAX = 1 << 17


def _validate_pod_name_cached(pod: dict) -> None:
    name = (pod.get("metadata") or {}).get("name") or ""
    if name in _VALID_NAMES:
        return
    validate_pod_name(pod)
    if len(_VALID_NAMES) >= _VALID_NAMES_MAX:
        _VALID_NAMES.clear()
    _VALID_NAMES.add(name)


class ExpandIndex:
    """Group index emitted alongside workload expansion: pods of one
    group are clones of one content-identical template — same spec,
    labels, annotations content, nodeName; everything but
    metadata.name — so queue-sort keys, effective priorities, encode
    class keys, and pin targets resolve ONCE per group and broadcast
    by numpy indexing instead of per-pod Python passes
    (scheduler/core.py schedule_app, ops/encode.py encode_batch).

    `group_of[i]` is the group of the i-th expanded pod, `firsts[g]`
    a representative pod of group g (one of the expanded pods)."""

    __slots__ = ("group_of", "firsts")

    def __init__(self):
        self.group_of: list = []
        self.firsts: list = []

    def new_group(self, first: dict) -> int:
        self.firsts.append(first)
        return len(self.firsts) - 1

    def mark(self, gid: int) -> None:
        self.group_of.append(gid)

    def mark_group(self, first: dict, count: int) -> None:
        gid = self.new_group(first)
        self.group_of.extend([gid] * count)


def pod_from_pod(pod: dict, _interned: Optional[dict] = None, index=None) -> dict:
    """MakeValidPod for a bare Pod resource. With `_interned` (a
    per-batch dict the caller threads through), raw pods whose content
    — minus name/generateName — is identical sanitize ONCE and clone
    like workload-template replicas: shared sanitized spec and labels
    (content-equal by key construction; the only post-expansion label
    write stamps the same app-name for every pod), per-pod annotations
    (the GPU binder writes a per-pod device index) and status (the
    binder writes phase). A 20k-pod app built from a handful of pod
    shapes costs a handful of deepcopy+validation passes instead of
    20k, and the shared spec objects let the encode class-key memo hit
    by identity (ops/encode.py). Non-JSON-serializable input falls
    back to the full per-pod path. `index` (an ExpandIndex) records
    the pod's content group."""
    if _interned is None:
        pod = make_valid_pod(pod)
        if index is not None:
            index.mark_group(pod, 1)
        return pod
    meta = pod.get("metadata") or {}
    key = _pod_intern_key(pod)
    if key is _UNSERIALIZABLE:
        pod = make_valid_pod(pod)
        if index is not None:
            index.mark_group(pod, 1)
        return pod
    entry = _interned.get(key)
    if entry is None:
        first = make_valid_pod(pod)
        gid = index.new_group(first) if index is not None else -1
        fmeta = first["metadata"]
        # clone template, precomputed once per group: the non-varying
        # top-level items and the shared sub-dict refs
        base = {
            k: v for k, v in first.items() if k not in ("metadata", "spec", "status")
        }
        _interned[key] = (
            first, gid, base, fmeta,
            fmeta.get("annotations") or {}, first["spec"],
            first.get("status"),
        )
        if index is not None:
            index.mark(gid)
        return first
    first, gid, base, fmeta, fanno, fspec, fstatus = entry
    clone_meta = dict(fmeta)
    clone_meta["name"] = meta.get("name", "")
    clone_meta["annotations"] = dict(fanno)
    clone = dict(base)
    clone["metadata"] = clone_meta
    clone["spec"] = dict(fspec)
    if fstatus is not None:
        clone["status"] = copy.deepcopy(fstatus)
    if clone_meta.get("name") or not clone_meta.get("generateName"):
        # name present: format-validate it; name AND generateName both
        # absent: raise the same required error the full path would.
        # generateName-only clones skip: their generateName is part of
        # the intern key, so the first's full validation covered it
        _validate_pod_name_cached(clone)
    if index is not None:
        index.mark(gid)
    return clone


# ------------------------------------------------------------------ daemonset


def _pin_pod_to_node(pod_spec: dict, node_name: str):
    """SetDaemonSetPodNodeNameByNodeAffinity (utils.go:812-857): inject a
    required matchFields metadata.name term; existing terms get their
    matchFields replaced (matchExpressions kept)."""
    req = {"key": "metadata.name", "operator": "In", "values": [node_name]}
    affinity = pod_spec.setdefault("affinity", {})
    node_aff = affinity.setdefault("nodeAffinity", {})
    required = node_aff.get("requiredDuringSchedulingIgnoredDuringExecution")
    if not required or not required.get("nodeSelectorTerms"):
        node_aff["requiredDuringSchedulingIgnoredDuringExecution"] = {
            "nodeSelectorTerms": [{"matchFields": [req]}]
        }
        return
    for term in required["nodeSelectorTerms"]:
        term["matchFields"] = [req]


def node_should_run_pod(node: dict, pod: dict) -> bool:
    """daemon.Predicates subset used by NodeShouldRunPod
    (utils.go:356-367): nodeName + node affinity + NoSchedule/NoExecute
    taints."""
    if node is None:
        return False
    spec = pod.get("spec") or {}
    node_name = (node.get("metadata") or {}).get("name", "")
    if spec.get("nodeName") and spec["nodeName"] != node_name:
        return False
    if not lbl.pod_matches_node_selector_and_affinity(spec, node):
        return False
    taints = (node.get("spec") or {}).get("taints") or []
    if lbl.find_untolerated_taint(taints, spec.get("tolerations")) is not None:
        return False
    return True


def pods_from_daemon_set(ds: dict, nodes: list) -> list:
    """One pinned pod per eligible node (utils.go:369-398)."""
    meta = ds.get("metadata") or {}
    pods = []
    for n_i, node in enumerate(nodes):
        node_name = (node.get("metadata") or {}).get("name", "")
        pod = {
            "metadata": _meta_from_owner(ds, "DaemonSet", gen_pod=True),
            "spec": copy.deepcopy(((ds.get("spec") or {}).get("template") or {}).get("spec") or {}),
        }
        _pin_pod_to_node(pod["spec"], node_name)
        # name-only is sound here even though clones differ by their
        # matchFields pin: the pin is machine-generated (not user
        # input), and the user template was fully validated on clone 0
        pod = make_valid_pod(pod, _name_only_validation=n_i > 0)
        add_workload_info(pod, "DaemonSet", meta.get("name", ""), meta.get("namespace", ""))
        if node_should_run_pod(node, pod):
            pods.append(pod)
    return pods


# ------------------------------------------------------------------- facade


def pods_excluding_daemon_sets(resources, index: Optional[ExpandIndex] = None) -> list:
    """GetValidPodExcludeDaemonSet (pkg/simulator/utils.go:76-136).
    With `index`, records each pod's content group (ExpandIndex): every
    `_expand_template` call yields one group (replicas are clones of
    one validated template), bare pods group by intern key."""
    pods = []
    interned: dict = {}
    for p in resources.pods:
        pods.append(pod_from_pod(p, _interned=interned, index=index))

    def extend(ps):
        pods.extend(ps)
        if index is not None and ps:
            index.mark_group(ps[0], len(ps))

    for d in resources.deployments:
        extend(pods_from_deployment(d))
    for rs in resources.replica_sets:
        extend(pods_from_replica_set(rs))
    for rc in resources.replication_controllers:
        extend(pods_from_replication_controller(rc))
    for sts in resources.stateful_sets:
        extend(pods_from_stateful_set(sts))
    for job in resources.jobs:
        extend(pods_from_job(job))
    for cj in resources.cron_jobs:
        extend(pods_from_cron_job(cj))
    return pods


def generate_valid_pods_from_app(
    app_name: str, resources, nodes: list, index: Optional[ExpandIndex] = None
) -> list:
    """GenerateValidPodsFromAppResources (pkg/simulator/utils.go:36-73):
    regular workloads + per-node daemonset pods, all labelled with the
    app name. With `index` (ExpandIndex) the app-name label stamps once
    per GROUP — clones share their labels dict with the group's first
    by construction, so the write is identical, minus one pass over
    100k pods."""
    pods = pods_excluding_daemon_sets(resources, index=index)
    for ds in resources.daemon_sets:
        ds_pods = pods_from_daemon_set(ds, nodes)
        pods.extend(ds_pods)
        if index is not None:
            for pod in ds_pods:
                # daemonset pods pin per node via matchFields — every
                # pod is its own content group
                index.mark_group(pod, 1)
    if index is not None:
        for first in index.firsts:
            first["metadata"].setdefault("labels", {})[LABEL_APP_NAME] = app_name
    else:
        for pod in pods:
            pod["metadata"].setdefault("labels", {})[LABEL_APP_NAME] = app_name
    return pods


def make_valid_node(node: dict, node_name: str) -> dict:
    """MakeValidNodeByNode (utils.go:502-516), incl. its ValidateNode
    call (utils.go:657-671)."""
    from .validation import validate_node

    node = copy.deepcopy(node)
    meta = node.setdefault("metadata", {})
    meta["name"] = node_name
    meta.setdefault("labels", {})["kubernetes.io/hostname"] = node_name
    meta.setdefault("annotations", {})
    validate_node(node)
    return node
