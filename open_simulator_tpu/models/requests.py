"""Pod resource-request computation.

Reproduces:
- computePodResourceRequest: max(sum(containers), each init container)
  + overhead (vendor/.../noderesources/fit.go:148-165)
- resourcehelper.PodRequestsAndLimits (used by the Simon plugin score,
  pkg/simulator/plugin/simon.go:45)
- the non-zero default requests used by scoring
  (vendor/.../scheduler/util/non_zero.go: 100m CPU / 200MB memory)
"""

from __future__ import annotations

from fractions import Fraction

from ..utils.memo import IdentityMemo
from ..utils.quantity import parse_quantity

CPU = "cpu"
MEMORY = "memory"
EPHEMERAL = "ephemeral-storage"
PODS = "pods"

DEFAULT_MILLI_CPU = 100  # 0.1 core
DEFAULT_MEMORY = 200 * 1024 * 1024  # 200MB

_NATIVE = {CPU, MEMORY, EPHEMERAL, PODS, "hugepages-1Gi", "hugepages-2Mi"}


def is_extended_resource(name: str) -> bool:
    """v1helper.IsExtendedResourceName approximation: non-native, has a
    domain prefix that is not kubernetes.io, and is not hugepages."""
    if name in _NATIVE or name.startswith("hugepages-"):
        return False
    if name.startswith("requests."):
        return False
    return True


def is_scalar_resource(name: str) -> bool:
    """Resources tracked in NodeInfo ScalarResources: extended resources,
    hugepages, and attachable volumes."""
    return is_extended_resource(name) or name.startswith("hugepages-") or name.startswith(
        "attachable-volumes-"
    )


def _add(acc: dict, rl: dict):
    for name, q in (rl or {}).items():
        acc[name] = acc.get(name, Fraction(0)) + parse_quantity(q)


def _set_max(acc: dict, rl: dict):
    for name, q in (rl or {}).items():
        v = parse_quantity(q)
        if v > acc.get(name, Fraction(0)):
            acc[name] = v


def pod_requests(pod: dict) -> dict:
    """max(sum over containers, any init container) + overhead.

    Returns {resource_name: Fraction base units}.
    """
    spec = pod.get("spec") or {}
    acc: dict = {}
    for c in spec.get("containers") or []:
        _add(acc, (c.get("resources") or {}).get("requests"))
    for c in spec.get("initContainers") or []:
        _set_max(acc, (c.get("resources") or {}).get("requests"))
    _add(acc, spec.get("overhead"))
    return acc


def pod_limits(pod: dict) -> dict:
    spec = pod.get("spec") or {}
    acc: dict = {}
    for c in spec.get("containers") or []:
        _add(acc, (c.get("resources") or {}).get("limits"))
    for c in spec.get("initContainers") or []:
        _set_max(acc, (c.get("resources") or {}).get("limits"))
    _add(acc, spec.get("overhead"))
    return acc


def pod_request_milli_cpu(pod: dict) -> int:
    v = pod_requests(pod).get(CPU, Fraction(0)) * 1000
    return -((-v.numerator) // v.denominator)


def pod_request_int(pod: dict, resource: str) -> int:
    v = pod_requests(pod).get(resource, Fraction(0))
    return -((-v.numerator) // v.denominator)


def pod_nonzero_request(pod: dict, resource: str) -> int:
    """calculatePodResourceRequest with GetNonzeroRequestForResource:
    per-container defaulting of unset cpu/memory requests, then
    max(sum(containers), each init container) + overhead.
    (vendor/.../noderesources/resource_allocation.go:117-141)
    """
    spec = pod.get("spec") or {}

    def nonzero(requests: dict) -> int:
        requests = requests or {}
        if resource == CPU:
            if CPU not in requests:
                return DEFAULT_MILLI_CPU
            v = parse_quantity(requests[CPU]) * 1000
            return -((-v.numerator) // v.denominator)
        if resource == MEMORY:
            if MEMORY not in requests:
                return DEFAULT_MEMORY
            v = parse_quantity(requests[MEMORY])
            return -((-v.numerator) // v.denominator)
        v = parse_quantity(requests.get(resource))
        return -((-v.numerator) // v.denominator)

    total = 0
    for c in spec.get("containers") or []:
        total += nonzero((c.get("resources") or {}).get("requests"))
    for c in spec.get("initContainers") or []:
        v = nonzero((c.get("resources") or {}).get("requests"))
        if v > total:
            total = v
    overhead = spec.get("overhead") or {}
    if resource in overhead:
        # reference quirk preserved: calculatePodResourceRequest adds
        # overhead via Quantity.Value() even for CPU, mixing whole cores
        # into a millicore total (resource_allocation.go:134-137)
        q = parse_quantity(overhead[resource])
        total += -((-q.numerator) // q.denominator)
    return total


class RequestSummary:
    """Precomputed per-pod request numbers for the hot accounting paths
    (oracle commit/remove, report aggregation). `mcpu/mem/eph` use the
    scheduler's ceil semantics (NodeInfo accounting); `floor_mcpu/
    floor_mem` use the floor semantics of PodRequestsAndLimits-based
    report code."""

    __slots__ = (
        "mcpu", "mem", "eph", "scalars", "nz_mcpu", "nz_mem",
        "floor_mcpu", "floor_mem",
    )

    def __init__(self, pod: dict):
        reqs = pod_requests(pod)
        cpu = reqs.get(CPU, Fraction(0))
        mem = reqs.get(MEMORY, Fraction(0))
        eph = reqs.get(EPHEMERAL, Fraction(0))
        mcpu1000 = cpu * 1000
        self.mcpu = -((-mcpu1000.numerator) // mcpu1000.denominator)
        self.mem = -((-mem.numerator) // mem.denominator)
        self.eph = -((-eph.numerator) // eph.denominator)
        self.floor_mcpu = mcpu1000.numerator // mcpu1000.denominator
        self.floor_mem = mem.numerator // mem.denominator
        scalars = []
        for name, v in reqs.items():
            if name in (CPU, MEMORY, EPHEMERAL):
                continue
            if is_scalar_resource(name):
                scalars.append((name, -((-v.numerator) // v.denominator)))
        self.scalars = tuple(scalars)
        self.nz_mcpu = pod_nonzero_request(pod, CPU)
        self.nz_mem = pod_nonzero_request(pod, MEMORY)


# replica clones of one workload template share their containers /
# initContainers / overhead objects (workloads.py _expand_template), so
# one summary serves the whole workload (see utils/memo.py contract)
_SUMMARY_MEMO = IdentityMemo()


def pod_request_summary(pod: dict) -> RequestSummary:
    spec = pod.get("spec") or {}
    sources = (spec.get("containers"), spec.get("initContainers"), spec.get("overhead"))
    return _SUMMARY_MEMO.get(sources, lambda: RequestSummary(pod))


# report tables and replay re-read allocatables once per pod row, which
# is 100k+ quantity parses at bench scale; allocatable dicts are not
# mutated after load (the GPU plugin adjusts NodeState.alloc, not the
# raw node object). Sized above the node axis: one entry per NODE lives
# here (unlike the per-template memos), and a cap below the node count
# would wholesale-clear mid-run, re-parsing every allocatable each pass.
_ALLOC_MEMO = IdentityMemo(max_entries=1 << 17)


def node_allocatable(node: dict) -> dict:
    """Node allocatable as {resource: Fraction base units}."""
    status = node.get("status") or {}
    alloc = status.get("allocatable")
    if alloc is None:
        alloc = status.get("capacity")
    if not alloc:
        # don't memoize a throwaway `{}` key — its fresh id would miss
        # every time and churn the cache
        return {}
    return _ALLOC_MEMO.get(
        (alloc,), lambda: {name: parse_quantity(q) for name, q in alloc.items()}
    )


def node_alloc_milli_cpu(node: dict) -> int:
    v = node_allocatable(node).get(CPU, Fraction(0)) * 1000
    return v.numerator // v.denominator


def node_alloc_int(node: dict, resource: str) -> int:
    v = node_allocatable(node).get(resource, Fraction(0))
    return v.numerator // v.denominator
