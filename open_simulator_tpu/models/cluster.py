"""Cluster construction from config directories.

Mirrors CreateClusterResourceFromClusterConfig
(pkg/simulator/simulator.go:444-459) and
MatchAndSetLocalStorageAnnotationOnNode (pkg/simulator/utils.go:293-309):
every YAML under the directory is demuxed by kind, and any `<node>.json`
file whose basename matches a node name becomes that node's
`simon/node-local-storage` annotation.
"""

from __future__ import annotations

import json
import os

from .decode import ResourceTypes, list_files, load_directory
from .workloads import ANNO_NODE_LOCAL_STORAGE


def match_and_set_local_storage(nodes: list, dir_path: str):
    storage = {}
    for p in list_files(dir_path):
        if not p.endswith(".json"):
            continue
        name = os.path.splitext(os.path.basename(p))[0]
        with open(p) as f:
            try:
                storage[name] = json.dumps(json.load(f))
            except json.JSONDecodeError:
                continue
    for node in nodes:
        meta = node.setdefault("metadata", {})
        name = meta.get("name", "")
        if name in storage:
            meta.setdefault("annotations", {})[ANNO_NODE_LOCAL_STORAGE] = storage[name]


def cluster_from_config_dir(path: str) -> ResourceTypes:
    resources = load_directory(path)
    match_and_set_local_storage(resources.nodes, path)
    return resources
