"""Offline Helm-chart rendering.

Mirrors pkg/chart/chart.go:18-118 (ProcessChart): load Chart.yaml +
values.yaml, process chart dependencies (subcharts under charts/ with
condition gating and value scoping), render templates with fabricated
release values (Release.Name = app name, Namespace default, Revision 1,
Service Helm), skip NOTES.txt, and emit manifests in Helm's
InstallOrder (chart.go:54-118).

The helm Go engine is not available here, so this module implements a
real subset of Go text/template + sprig as an AST interpreter:

  actions     {{ expr }} with {{- ... -}} whitespace trim, {{/* */}}
  data        .path lookups, $ (root dot), $var, literals, (pipelines)
  blocks      if / else if / else, range (lists + sorted maps, with
              $i, $v := decls), with, define
  variables   {{ $x := expr }} and {{ $x = expr }}, block-scoped
  templates   define/include/template across all chart files (incl.
              _helpers.tpl and subcharts — one shared namespace, as in
              helm), tpl for string re-rendering
  functions   the sprig/builtin subset real charts use (quote, default,
              toYaml, nindent, printf, eq/and/or, dict/list, trunc,
              b64enc, required, ...)

Unknown/missing paths render empty (non-strict mode, matching the
engine's default used by the reference).
"""

from __future__ import annotations

import atexit
import base64
import hashlib
import json
import os
import re
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import yaml

from .validation import InputError

# helm releaseutil.InstallOrder (chart.go:84-118 sorts with this)
INSTALL_ORDER = [
    "Namespace",
    "NetworkPolicy",
    "ResourceQuota",
    "LimitRange",
    "PodSecurityPolicy",
    "PodDisruptionBudget",
    "ServiceAccount",
    "Secret",
    "SecretList",
    "ConfigMap",
    "StorageClass",
    "PersistentVolume",
    "PersistentVolumeClaim",
    "CustomResourceDefinition",
    "ClusterRole",
    "ClusterRoleList",
    "ClusterRoleBinding",
    "ClusterRoleBindingList",
    "Role",
    "RoleList",
    "RoleBinding",
    "RoleBindingList",
    "Service",
    "DaemonSet",
    "Pod",
    "ReplicationController",
    "ReplicaSet",
    "Deployment",
    "HorizontalPodAutoscaler",
    "StatefulSet",
    "Job",
    "CronJob",
    "Ingress",
    "APIService",
]
_ORDER_INDEX = {k: i for i, k in enumerate(INSTALL_ORDER)}

_TOKEN = re.compile(r"\{\{(-?)\s*(.*?)\s*(-?)\}\}", re.S)


class ChartError(InputError):
    """A template/chart evaluation error is an input error: the chart
    the user pointed simon at does not render. Rooting it in
    InputError (a ValueError) routes it to exit code 2 with a clean
    `error:` line instead of a traceback."""


class _Missing:
    """Sentinel for unresolved paths (renders empty, falsy)."""

    def __str__(self):
        return ""

    def __bool__(self):
        return False

    def __eq__(self, other):
        return isinstance(other, _Missing)

    def __hash__(self):
        return 0


MISSING = _Missing()


def _truthy(v) -> bool:
    if v is MISSING or v is None:
        return False
    if isinstance(v, bool):
        return v
    if isinstance(v, (int, float)):
        return v != 0
    if isinstance(v, (str, list, dict, tuple)):
        return len(v) > 0
    return True


def _gostr(v) -> str:
    """Render a value the way Go's %v does for the cases charts hit."""
    if v is MISSING or v is None:
        return ""
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float) and v == int(v):
        # Go prints 2.0 as 2 for untyped constants in practice charts use
        return str(int(v))
    if isinstance(v, (dict, list)):
        return yaml.safe_dump(v, default_flow_style=True).strip()
    return str(v)


# ---------------------------------------------------------------------------
# Lexing: template text -> [("text", s) | ("act", s)] with trims applied
# ---------------------------------------------------------------------------


def _lex(text: str) -> List[Tuple[str, str]]:
    parts: List[Tuple[str, str]] = []
    pos = 0
    trim_next = False
    for m in _TOKEN.finditer(text):
        lit = text[pos : m.start()]
        if trim_next:
            lit = lit.lstrip()
        if m.group(1) == "-":
            lit = lit.rstrip()
        if lit:
            parts.append(("text", lit))
        action = m.group(2).strip()
        trim_next = m.group(3) == "-"
        if action.startswith("/*"):
            pos = m.end()
            continue  # comment
        parts.append(("act", action))
        pos = m.end()
    lit = text[pos:]
    if trim_next:
        lit = lit.lstrip()
    if lit:
        parts.append(("text", lit))
    return parts


# ---------------------------------------------------------------------------
# Expression tokenizer: string -> atom list; parens become sublists
# ---------------------------------------------------------------------------


def _tokenize_expr(s: str):
    atoms: List = []
    stack: List[List] = [atoms]
    i, n = 0, len(s)
    while i < n:
        ch = s[i]
        if ch.isspace():
            i += 1
        elif ch == "(":
            sub: List = []
            stack[-1].append(sub)
            stack.append(sub)
            i += 1
        elif ch == ")":
            if len(stack) > 1:
                stack.pop()
            i += 1
        elif ch == "|":
            stack[-1].append("|")
            i += 1
        elif ch in "\"'`":
            j = i + 1
            buf = []
            while j < n and s[j] != ch:
                if ch == '"' and s[j] == "\\" and j + 1 < n:
                    esc = s[j + 1]
                    buf.append({"n": "\n", "t": "\t", '"': '"', "\\": "\\"}.get(esc, esc))
                    j += 2
                else:
                    buf.append(s[j])
                    j += 1
            stack[-1].append(("str", "".join(buf)))
            i = j + 1
        else:
            j = i
            while j < n and not s[j].isspace() and s[j] not in "()|\"'`":
                j += 1
            stack[-1].append(s[i:j])
            i = j
    return atoms


def _split_pipeline(atoms: List) -> List[List]:
    cmds: List[List] = [[]]
    for a in atoms:
        if a == "|":
            cmds.append([])
        else:
            cmds[-1].append(a)
    return [c for c in cmds if c]


# ---------------------------------------------------------------------------
# Parsing: lexed parts -> AST
# Nodes: ("text", s) ("out", expr) ("var", name, expr, decl)
#        ("if", [(expr, body), ...], else_body)
#        ("range", [varnames], expr, body, else_body)
#        ("with", varname|None, expr, body, else_body)
#        ("define", name, body) ("template", name_expr, ctx_expr)
# ---------------------------------------------------------------------------

_VAR_ACT = re.compile(r"^(\$[A-Za-z_][\w]*)\s*(:?=)\s*(.*)$", re.S)
_RANGE_DECL = re.compile(r"^((?:\$[\w]+\s*,\s*)?\$[\w]+)\s*:=\s*(.*)$", re.S)


def _parse(parts: List[Tuple[str, str]], i: int, in_block: bool):
    """Returns (nodes, next_i, terminator_action_or_None)."""
    nodes: List = []
    while i < len(parts):
        kind, payload = parts[i]
        if kind == "text":
            nodes.append(("text", payload))
            i += 1
            continue
        act = payload
        if act == "end" or act == "else" or act.startswith("else if ") or act.startswith("else if\t"):
            if in_block:
                return nodes, i, act
            i += 1  # stray terminator outside a block: ignore
            continue
        if act.startswith("if ") or act.startswith("if\t"):
            branches = []
            cond = act[3:].strip()
            body, i, term = _parse(parts, i + 1, True)
            branches.append((cond, body))
            else_body: List = []
            while term is not None and term.startswith("else if"):
                cond = term[len("else if") :].strip()
                body, i, term = _parse(parts, i + 1, True)
                branches.append((cond, body))
            if term == "else":
                else_body, i, term = _parse(parts, i + 1, True)
            nodes.append(("if", branches, else_body))
            i += 1
            continue
        if act.startswith("range ") or act == "range":
            rest = act[5:].strip()
            m = _RANGE_DECL.match(rest)
            if m:
                varnames = [v.strip() for v in m.group(1).split(",")]
                expr = m.group(2)
            else:
                varnames, expr = [], rest
            body, i, term = _parse(parts, i + 1, True)
            else_body = []
            if term == "else":
                else_body, i, term = _parse(parts, i + 1, True)
            nodes.append(("range", varnames, expr, body, else_body))
            i += 1
            continue
        if act.startswith("with ") or act.startswith("with\t"):
            rest = act[5:].strip()
            varname = None
            m = _VAR_ACT.match(rest)
            if m and m.group(2) == ":=":
                varname, rest = m.group(1), m.group(3)
            body, i, term = _parse(parts, i + 1, True)
            else_body = []
            if term == "else":
                else_body, i, term = _parse(parts, i + 1, True)
            nodes.append(("with", varname, rest, body, else_body))
            i += 1
            continue
        if act.startswith("define ") or act.startswith("block "):
            is_block = act.startswith("block ")
            rest = act.split(None, 1)[1].strip()
            atoms = _tokenize_expr(rest)
            name = atoms[0][1] if atoms and isinstance(atoms[0], tuple) else str(atoms[0])
            body, i, _term = _parse(parts, i + 1, True)
            nodes.append(("define", name, body))
            if is_block:
                # block = define + template in place
                ctx = rest[len(name) + 2 :].strip() or "."
                nodes.append(("template", ('"%s"' % name), ctx))
            i += 1
            continue
        if act.startswith("template "):
            atoms = _tokenize_expr(act[9:].strip())
            name_atom = atoms[0] if atoms else ("str", "")
            ctx = atoms[1:] or ["."]
            nodes.append(("template", name_atom, ctx))
            i += 1
            continue
        m = _VAR_ACT.match(act)
        if m:
            nodes.append(("var", m.group(1), m.group(3), m.group(2) == ":="))
            i += 1
            continue
        nodes.append(("out", act))
        i += 1
    return nodes, i, None


def _parse_template(text: str) -> List:
    nodes, _, _ = _parse(_lex(text), 0, False)
    return nodes


# ---------------------------------------------------------------------------
# Evaluation
# ---------------------------------------------------------------------------


class _Env:
    __slots__ = ("root", "dot", "scopes", "templates", "depth")

    def __init__(self, root, dot, templates, scopes=None, depth=0):
        self.root = root
        self.dot = dot
        self.templates = templates
        self.scopes = scopes if scopes is not None else [{"$": dot}]
        self.depth = depth

    def child(self, dot=None):
        e = _Env(self.root, self.dot if dot is None else dot, self.templates,
                 self.scopes + [{}], self.depth)
        return e

    def get_var(self, name):
        for scope in reversed(self.scopes):
            if name in scope:
                return scope[name]
        return MISSING

    def set_var(self, name, value, decl):
        if decl:
            self.scopes[-1][name] = value
            return
        for scope in reversed(self.scopes):
            if name in scope:
                scope[name] = value
                return
        self.scopes[-1][name] = value


def _field(value, part: str):
    if value is MISSING or value is None:
        return MISSING
    if isinstance(value, dict):
        return value[part] if part in value else MISSING
    att = getattr(value, part, MISSING)
    return att


def _walk(value, path: str):
    for part in path.split("."):
        if part:
            value = _field(value, part)
    return value


def _eval_atom(atom, env: _Env):
    if isinstance(atom, list):
        return _eval_pipeline(atom, env)
    if isinstance(atom, tuple):  # ("str", s)
        return atom[1]
    s = atom
    if s == ".":
        return env.dot
    if s.startswith("."):
        return _walk(env.dot, s[1:])
    if s == "$":
        return env.get_var("$")
    if s.startswith("$"):
        head, dot, rest = s.partition(".")
        v = env.get_var(head)
        return _walk(v, rest) if dot else v
    if s == "true":
        return True
    if s == "false":
        return False
    if s in ("nil", "null"):
        return None
    try:
        return int(s)
    except ValueError:
        pass
    try:
        return float(s)
    except ValueError:
        pass
    return MISSING  # bare ident with no args and not a function


def _eval_command(atoms: List, env: _Env, piped=None):
    if not atoms:
        return MISSING
    head = atoms[0]
    extra = [] if piped is None else [piped]
    if isinstance(head, str) and not head.startswith((".", "$")) and (
        head in FUNCS or len(atoms) > 1 or piped is not None
    ):
        if head in FUNCS:
            args = [_eval_atom(a, env) for a in atoms[1:]] + extra
            return FUNCS[head](args, env)
        # not a known function: fall through to value semantics
    value = _eval_atom(head, env)
    if callable(value):
        args = [_eval_atom(a, env) for a in atoms[1:]] + extra
        try:
            return value(*args)
        except (TypeError, ValueError, KeyError, IndexError, AttributeError) as e:
            # a failed method/value call renders as "<no value>" like a
            # Go template error-less miss, but the swallowed reason is
            # kept for `--trace` output — a chart that silently renders
            # wrong must be diagnosable without a debugger
            from ..utils.trace import GLOBAL

            GLOBAL.append_note(
                "chart-template-call",
                f"{head!r}: {type(e).__name__}: {e}",
            )
            return MISSING
    return value


def _eval_pipeline(atoms: List, env: _Env):
    cmds = _split_pipeline(atoms)
    if not cmds:
        return MISSING
    val = _eval_command(cmds[0], env)
    for cmd in cmds[1:]:
        val = _eval_command(cmd, env, piped=val)
    return val


def _eval_expr(expr: str, env: _Env):
    return _eval_pipeline(_tokenize_expr(expr), env)


def _exec(nodes: List, env: _Env, out: List[str]):
    for node in nodes:
        tag = node[0]
        if tag == "text":
            out.append(node[1])
        elif tag == "out":
            out.append(_gostr(_eval_expr(node[1], env)))
        elif tag == "var":
            env.set_var(node[1], _eval_expr(node[2], env), node[3])
        elif tag == "if":
            done = False
            for cond, body in node[1]:
                if _truthy(_eval_expr(cond, env)):
                    _exec(body, env.child(), out)
                    done = True
                    break
            if not done and node[2]:
                _exec(node[2], env.child(), out)
        elif tag == "with":
            _varname, expr, body, else_body = node[1], node[2], node[3], node[4]
            v = _eval_expr(expr, env)
            if _truthy(v):
                child = env.child(dot=v)
                if _varname:
                    child.set_var(_varname, v, True)
                _exec(body, child, out)
            elif else_body:
                _exec(else_body, env.child(), out)
        elif tag == "range":
            varnames, expr, body, else_body = node[1], node[2], node[3], node[4]
            v = _eval_expr(expr, env)
            items: List[Tuple] = []
            if isinstance(v, dict):
                items = [(k, v[k]) for k in sorted(v, key=str)]
            elif isinstance(v, (list, tuple)):
                items = list(enumerate(v))
            elif isinstance(v, int) and not isinstance(v, bool):
                items = [(i, i) for i in range(v)]
            if items:
                for key, elem in items:
                    child = env.child(dot=elem)
                    if len(varnames) == 1:
                        child.set_var(varnames[0], elem, True)
                    elif len(varnames) == 2:
                        child.set_var(varnames[0], key, True)
                        child.set_var(varnames[1], elem, True)
                    _exec(body, child, out)
            elif else_body:
                _exec(else_body, env.child(), out)
        elif tag == "define":
            env.templates[node[1]] = node[2]
        elif tag == "template":
            name_atom, ctx_atoms = node[1], node[2]
            name = (
                name_atom[1]
                if isinstance(name_atom, tuple)
                else _gostr(_eval_atom(name_atom, env))
            )
            dot = _eval_pipeline(list(ctx_atoms), env) if isinstance(ctx_atoms, list) else _eval_expr(ctx_atoms, env)
            out.append(_include(name, dot, env))
    return out


def _include(name: str, dot, env: _Env) -> str:
    body = env.templates.get(name)
    if body is None:
        return ""
    if env.depth > 250:
        raise ChartError(f"template recursion too deep rendering {name!r}")
    child = _Env(env.root, dot, env.templates, [{"$": dot}], env.depth + 1)
    return "".join(_exec(body, child, []))


# ---------------------------------------------------------------------------
# Function library (text/template builtins + the sprig subset charts use)
# ---------------------------------------------------------------------------


def _arg(args, i, default=MISSING):
    return args[i] if len(args) > i else default


def _to_int(v):
    if isinstance(v, bool):
        return int(v)
    try:
        return int(float(str(v)))
    except (TypeError, ValueError):
        return 0


def _go_printf(fmt, args):
    out = []
    ai = 0
    i = 0
    while i < len(fmt):
        ch = fmt[i]
        if ch != "%":
            out.append(ch)
            i += 1
            continue
        j = i + 1
        while j < len(fmt) and fmt[j] in "-+ #0123456789.":
            j += 1
        if j >= len(fmt):
            out.append(ch)
            break
        verb = fmt[j]
        spec = fmt[i:j]
        a = args[ai] if ai < len(args) else MISSING
        if verb == "%":
            out.append("%")
            i = j + 1
            continue
        ai += 1
        if verb in "dxXob":
            out.append((spec + verb) % _to_int(a))
        elif verb in "feEgG":
            try:
                out.append((spec + verb) % float(a))
            except (TypeError, ValueError):
                out.append(_gostr(a))
        elif verb == "q":
            out.append('"%s"' % _gostr(a))
        elif verb == "t":
            out.append("true" if _truthy(a) else "false")
        else:  # s, v
            out.append((spec + "s") % _gostr(a))
        i = j + 1
    return "".join(out)


def _indent(n, s):
    pad = " " * _to_int(n)
    return "\n".join(pad + line if line else line for line in _gostr(s).split("\n"))


def _fn_dict(args, env):
    d = {}
    for k, v in zip(args[::2], args[1::2]):
        d[_gostr(k)] = v
    return d


def _fn_merge(args, env):
    # merge dst src...: dst wins (sprig merge semantics)
    out: dict = {}
    for src in reversed([a for a in args if isinstance(a, dict)]):
        _deep_merge_into(out, src)
    return out


def _deep_merge_into(dst: dict, src: dict):
    for k, v in src.items():
        if isinstance(v, dict) and isinstance(dst.get(k), dict):
            _deep_merge_into(dst[k], v)
        else:
            dst[k] = v


def _fn_required(args, env):
    msg, v = _arg(args, 0, ""), _arg(args, 1)
    if v is MISSING or v is None:
        raise ChartError(_gostr(msg) or "required value missing")
    return v


def _fn_tpl(args, env):
    text, dot = _gostr(_arg(args, 0, "")), _arg(args, 1, env.dot)
    nodes = _parse_template(text)
    child = _Env(env.root, dot, env.templates, [{"$": dot}], env.depth + 1)
    return "".join(_exec(nodes, child, []))


def _cmp(args, op):
    if len(args) < 2:
        return False
    a, b = args[0], args[1]
    try:
        return op(a, b)
    except TypeError:
        return op(_gostr(a), _gostr(b))


def _eq(args, env):
    if len(args) < 2:
        return False
    first = args[0]
    return any(_loose_eq(first, other) for other in args[1:])


def _loose_eq(a, b):
    if type(a) is type(b):
        return a == b
    if isinstance(a, bool) or isinstance(b, bool):
        return a is b
    if isinstance(a, (int, float)) and isinstance(b, (int, float)):
        return a == b
    return _gostr(a) == _gostr(b)


FUNCS = {
    "quote": lambda a, e: " ".join('"%s"' % _gostr(x) for x in a),
    "squote": lambda a, e: " ".join("'%s'" % _gostr(x) for x in a),
    "default": lambda a, e: (a[1] if len(a) > 1 and _truthy(a[1]) else _arg(a, 0)),
    "coalesce": lambda a, e: next((x for x in a if _truthy(x)), MISSING),
    "ternary": lambda a, e: (_arg(a, 0) if _truthy(_arg(a, 2)) else _arg(a, 1)),
    "empty": lambda a, e: not _truthy(_arg(a, 0)),
    "int": lambda a, e: _to_int(_arg(a, 0)),
    "int64": lambda a, e: _to_int(_arg(a, 0)),
    "float64": lambda a, e: float(_gostr(_arg(a, 0)) or 0),
    "toString": lambda a, e: _gostr(_arg(a, 0)),
    "toYaml": lambda a, e: (
        ""
        if _arg(a, 0) in (MISSING, None)
        else yaml.safe_dump(_arg(a, 0), default_flow_style=False).rstrip()
    ),
    "fromYaml": lambda a, e: yaml.safe_load(_gostr(_arg(a, 0, ""))) or {},
    "toJson": lambda a, e: json.dumps(
        None if _arg(a, 0) is MISSING else _arg(a, 0), separators=(",", ":")
    ),
    "fromJson": lambda a, e: json.loads(_gostr(_arg(a, 0, "null")) or "null") or {},
    "indent": lambda a, e: _indent(_arg(a, 0, 0), _arg(a, 1, "")),
    "nindent": lambda a, e: "\n" + _indent(_arg(a, 0, 0), _arg(a, 1, "")),
    "trim": lambda a, e: _gostr(_arg(a, 0, "")).strip(),
    "trimSuffix": lambda a, e: (
        _gostr(_arg(a, 1, ""))[: -len(_gostr(_arg(a, 0)))]
        if _gostr(_arg(a, 1, "")).endswith(_gostr(_arg(a, 0, "")))
        and _gostr(_arg(a, 0))
        else _gostr(_arg(a, 1, ""))
    ),
    "trimPrefix": lambda a, e: (
        _gostr(_arg(a, 1, ""))[len(_gostr(_arg(a, 0))) :]
        if _gostr(_arg(a, 1, "")).startswith(_gostr(_arg(a, 0, "")))
        else _gostr(_arg(a, 1, ""))
    ),
    "trunc": lambda a, e: (
        _gostr(_arg(a, 1, ""))[: _to_int(_arg(a, 0, 0))]
        if _to_int(_arg(a, 0, 0)) >= 0
        else _gostr(_arg(a, 1, ""))[_to_int(_arg(a, 0, 0)) :]
    ),
    "replace": lambda a, e: _gostr(_arg(a, 2, "")).replace(
        _gostr(_arg(a, 0, "")), _gostr(_arg(a, 1, ""))
    ),
    "lower": lambda a, e: _gostr(_arg(a, 0, "")).lower(),
    "upper": lambda a, e: _gostr(_arg(a, 0, "")).upper(),
    "title": lambda a, e: _gostr(_arg(a, 0, "")).title(),
    "abbrev": lambda a, e: _gostr(_arg(a, 1, ""))[: _to_int(_arg(a, 0, 0))],
    "contains": lambda a, e: _gostr(_arg(a, 0, "")) in _gostr(_arg(a, 1, "")),
    "hasPrefix": lambda a, e: _gostr(_arg(a, 1, "")).startswith(_gostr(_arg(a, 0, ""))),
    "hasSuffix": lambda a, e: _gostr(_arg(a, 1, "")).endswith(_gostr(_arg(a, 0, ""))),
    "repeat": lambda a, e: _gostr(_arg(a, 1, "")) * _to_int(_arg(a, 0, 0)),
    "join": lambda a, e: _gostr(_arg(a, 0, "")).join(
        _gostr(x) for x in (_arg(a, 1) if isinstance(_arg(a, 1), (list, tuple)) else [])
    ),
    "split": lambda a, e: {
        f"_{i}": part
        for i, part in enumerate(_gostr(_arg(a, 1, "")).split(_gostr(_arg(a, 0, " "))))
    },
    "splitList": lambda a, e: _gostr(_arg(a, 1, "")).split(_gostr(_arg(a, 0, " "))),
    "printf": lambda a, e: _go_printf(_gostr(_arg(a, 0, "")), a[1:]),
    "print": lambda a, e: " ".join(_gostr(x) for x in a),
    "println": lambda a, e: " ".join(_gostr(x) for x in a) + "\n",
    "eq": _eq,
    "ne": lambda a, e: not _eq(a, e),
    "lt": lambda a, e: _cmp(a, lambda x, y: x < y),
    "le": lambda a, e: _cmp(a, lambda x, y: x <= y),
    "gt": lambda a, e: _cmp(a, lambda x, y: x > y),
    "ge": lambda a, e: _cmp(a, lambda x, y: x >= y),
    "and": lambda a, e: next((x for x in a if not _truthy(x)), a[-1] if a else MISSING),
    "or": lambda a, e: next((x for x in a if _truthy(x)), a[-1] if a else MISSING),
    "not": lambda a, e: not _truthy(_arg(a, 0)),
    "add": lambda a, e: sum(_to_int(x) for x in a),
    "add1": lambda a, e: _to_int(_arg(a, 0)) + 1,
    "sub": lambda a, e: _to_int(_arg(a, 0)) - sum(_to_int(x) for x in a[1:]),
    "mul": lambda a, e: _prod(a),
    "div": lambda a, e: (
        _to_int(_arg(a, 0)) // _to_int(_arg(a, 1)) if _to_int(_arg(a, 1)) else 0
    ),
    "mod": lambda a, e: (
        _to_int(_arg(a, 0)) % _to_int(_arg(a, 1)) if _to_int(_arg(a, 1)) else 0
    ),
    "max": lambda a, e: max((_to_int(x) for x in a), default=0),
    "min": lambda a, e: min((_to_int(x) for x in a), default=0),
    "len": lambda a, e: len(_arg(a, 0, "")) if _arg(a, 0) is not MISSING else 0,
    "first": lambda a, e: (_arg(a, 0)[0] if _truthy(_arg(a, 0)) else MISSING),
    "last": lambda a, e: (_arg(a, 0)[-1] if _truthy(_arg(a, 0)) else MISSING),
    "rest": lambda a, e: list(_arg(a, 0, []))[1:],
    "initial": lambda a, e: list(_arg(a, 0, []))[:-1],
    "uniq": lambda a, e: list(dict.fromkeys(_arg(a, 0, []))),
    "sortAlpha": lambda a, e: sorted(_gostr(x) for x in _arg(a, 0, [])),
    "reverse": lambda a, e: list(reversed(_arg(a, 0, []))),
    "has": lambda a, e: _arg(a, 0) in (_arg(a, 1) or []),
    "until": lambda a, e: list(range(_to_int(_arg(a, 0, 0)))),
    "untilStep": lambda a, e: list(
        range(_to_int(_arg(a, 0, 0)), _to_int(_arg(a, 1, 0)), _to_int(_arg(a, 2, 1)) or 1)
    ),
    "seq": lambda a, e: " ".join(
        str(i) for i in range(_to_int(_arg(a, 0, 1)), _to_int(_arg(a, -1, 0)) + 1)
    ),
    "list": lambda a, e: list(a),
    "tuple": lambda a, e: list(a),
    "dict": _fn_dict,
    "get": lambda a, e: (
        _arg(a, 0).get(_gostr(_arg(a, 1)), "") if isinstance(_arg(a, 0), dict) else ""
    ),
    "set": lambda a, e: _dict_set(a),
    "unset": lambda a, e: _dict_unset(a),
    "hasKey": lambda a, e: isinstance(_arg(a, 0), dict) and _gostr(_arg(a, 1)) in a[0],
    "keys": lambda a, e: [k for d in a if isinstance(d, dict) for k in d],
    "values": lambda a, e: [v for d in a if isinstance(d, dict) for v in d.values()],
    "pick": lambda a, e: {
        k: v
        for k, v in (_arg(a, 0) or {}).items()
        if k in {_gostr(x) for x in a[1:]}
    },
    "omit": lambda a, e: {
        k: v
        for k, v in (_arg(a, 0) or {}).items()
        if k not in {_gostr(x) for x in a[1:]}
    },
    "merge": _fn_merge,
    "mergeOverwrite": lambda a, e: _fn_merge(list(reversed(a)), e),
    "deepCopy": lambda a, e: json.loads(json.dumps(_arg(a, 0))),
    "kindIs": lambda a, e: _kind_of(_arg(a, 1)) == _gostr(_arg(a, 0)),
    "kindOf": lambda a, e: _kind_of(_arg(a, 0)),
    "typeOf": lambda a, e: _kind_of(_arg(a, 0)),
    "b64enc": lambda a, e: base64.b64encode(_gostr(_arg(a, 0, "")).encode()).decode(),
    "b64dec": lambda a, e: base64.b64decode(_gostr(_arg(a, 0, "")).encode()).decode(
        errors="replace"
    ),
    "sha256sum": lambda a, e: hashlib.sha256(_gostr(_arg(a, 0, "")).encode()).hexdigest(),
    "adler32sum": lambda a, e: str(_adler32(_gostr(_arg(a, 0, "")))),
    "regexMatch": lambda a, e: bool(re.search(_gostr(_arg(a, 0, "")), _gostr(_arg(a, 1, "")))),
    # Go replacement syntax ${1} -> Python \1
    "regexReplaceAll": lambda a, e: re.sub(
        _gostr(_arg(a, 0, "")),
        re.sub(r"\$\{?(\d+)\}?", r"\\\1", _gostr(_arg(a, 2, ""))),
        _gostr(_arg(a, 1, "")),
    ),
    "index": lambda a, e: _fn_index(a),
    "required": _fn_required,
    "fail": lambda a, e: (_ for _ in ()).throw(ChartError(_gostr(_arg(a, 0, "fail")))),
    "include": lambda a, e: _include(_gostr(_arg(a, 0, "")), _arg(a, 1), e),
    "tpl": _fn_tpl,
    "lookup": lambda a, e: {},  # no live cluster in the simulator
    "semverCompare": lambda a, e: True,  # offline render: accept all
    "randAlphaNum": lambda a, e: "x" * _to_int(_arg(a, 0, 8)),  # deterministic
    "uuidv4": lambda a, e: "00000000-0000-4000-8000-000000000000",
    "now": lambda a, e: "2020-01-01T00:00:00Z",
    "date": lambda a, e: "2020-01-01",
    "dateInZone": lambda a, e: "2020-01-01",
    "htpasswd": lambda a, e: "",
    "genCA": lambda a, e: {"Cert": "", "Key": ""},
    "genSignedCert": lambda a, e: {"Cert": "", "Key": ""},
    "genSelfSignedCert": lambda a, e: {"Cert": "", "Key": ""},
}

def _fn_index(args):
    """text/template `index`: walk maps by key and slices by position."""
    cur = _arg(args, 0)
    for key in args[1:]:
        if isinstance(cur, dict):
            cur = cur.get(_gostr(key), MISSING) if _gostr(key) in cur else cur.get(key, MISSING)
        elif isinstance(cur, (list, tuple)):
            i = _to_int(key)
            cur = cur[i] if 0 <= i < len(cur) else MISSING
        else:
            return MISSING
        if cur is MISSING:
            return MISSING
    return cur


def _prod(args):
    out = 1
    for x in args:
        out *= _to_int(x)
    return out


def _dict_set(args):
    d = _arg(args, 0)
    if isinstance(d, dict):
        d[_gostr(_arg(args, 1))] = _arg(args, 2)
    return d


def _dict_unset(args):
    d = _arg(args, 0)
    if isinstance(d, dict):
        d.pop(_gostr(_arg(args, 1)), None)
    return d


def _kind_of(v) -> str:
    if isinstance(v, bool):
        return "bool"
    if isinstance(v, int):
        return "int"
    if isinstance(v, float):
        return "float64"
    if isinstance(v, str):
        return "string"
    if isinstance(v, (list, tuple)):
        return "slice"
    if isinstance(v, dict):
        return "map"
    if v is None or v is MISSING:
        return "invalid"
    return type(v).__name__


def _adler32(s: str) -> int:
    import zlib

    return zlib.adler32(s.encode())


class _APIVersions:
    """Minimal .Capabilities.APIVersions with a Has method."""

    _KNOWN = {"v1", "apps/v1", "batch/v1", "batch/v1beta1", "networking.k8s.io/v1",
              "rbac.authorization.k8s.io/v1", "storage.k8s.io/v1",
              "policy/v1beta1", "apiextensions.k8s.io/v1"}

    def Has(self, version):
        return _gostr(version) in self._KNOWN


def default_capabilities() -> dict:
    # the vendored scheduler engine is k8s v1.20.5 (SURVEY.md §0)
    return {
        "KubeVersion": {
            "Major": "1",
            "Minor": "20",
            "Version": "v1.20.5",
            "GitVersion": "v1.20.5",
        },
        "APIVersions": _APIVersions(),
    }


# ---------------------------------------------------------------------------
# Public rendering API
# ---------------------------------------------------------------------------


def render_template(text: str, context: dict, templates: Optional[dict] = None) -> str:
    """Render the supported Go-template subset with `context` as both the
    root and the initial dot (the helm convention)."""
    nodes = _parse_template(text)
    env = _Env(context, context, templates if templates is not None else {})
    return "".join(_exec(nodes, env, []))


def _deep_merge(base: dict, override: dict) -> dict:
    out = dict(base)
    for k, v in (override or {}).items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


class _Subchart:
    __slots__ = ("name", "path", "meta", "values")

    def __init__(self, name, path, meta, values):
        self.name = name
        self.path = path
        self.meta = meta
        self.values = values


def _load_chart_meta(path: str) -> Tuple[dict, dict]:
    chart_file = os.path.join(path, "Chart.yaml")
    if not os.path.isfile(chart_file):
        raise ChartError(f"{path}: not a helm chart (no Chart.yaml)")
    with open(chart_file) as f:
        meta = yaml.safe_load(f) or {}
    values = {}
    values_file = os.path.join(path, "values.yaml")
    if os.path.isfile(values_file):
        with open(values_file) as f:
            values = yaml.safe_load(f) or {}
    return meta, values


def _dependencies(path: str, meta: dict) -> List[dict]:
    deps = list(meta.get("dependencies") or [])
    req_file = os.path.join(path, "requirements.yaml")
    if os.path.isfile(req_file):
        with open(req_file) as f:
            req = yaml.safe_load(f) or {}
        deps.extend(req.get("dependencies") or [])
    return deps


def _dependency_enabled(dep: dict, parent_values: dict) -> bool:
    """Helm condition gating (ProcessDependencyConditions): the first
    resolvable condition path decides; absent conditions mean enabled."""
    cond = dep.get("condition")
    if not cond:
        return True
    for path in str(cond).split(","):
        cur = parent_values
        found = True
        for part in path.strip().split("."):
            if isinstance(cur, dict) and part in cur:
                cur = cur[part]
            else:
                found = False
                break
        if found:
            return bool(cur)
    return True


# unpacked .tgz dependencies, keyed by (path, mtime) so repeated
# renders of the same chart reuse one scratch extraction; LRU-bounded,
# evicted/exit-time scratch dirs removed (value = (chart_root, tmpdir))
_ARCHIVE_CACHE: "OrderedDict[tuple, tuple]" = OrderedDict()  # key -> (root, tmp)
_ARCHIVE_CACHE_CAP = 32
# LRU eviction must NOT rmtree immediately — an in-flight render may
# still hold _Subchart.path pointers into the evicted extraction.
# Evicted dirs park here and are reclaimed at the next process_chart
# entry (no render in flight then) or at process exit.
_ARCHIVE_EVICTED: List[str] = []
_ARCHIVE_LIVE: List[str] = []  # dirs still referenced by the cache


def _purge_evicted_archives() -> None:
    import shutil

    while _ARCHIVE_EVICTED:
        shutil.rmtree(_ARCHIVE_EVICTED.pop(), ignore_errors=True)


def _cleanup_archive_scratch() -> None:
    import shutil

    _purge_evicted_archives()
    while _ARCHIVE_LIVE:
        shutil.rmtree(_ARCHIVE_LIVE.pop(), ignore_errors=True)
    _ARCHIVE_CACHE.clear()


atexit.register(_cleanup_archive_scratch)


def _unpack_chart_archive(archive_path: str) -> Optional[str]:
    """Helm packaged dependency (helm loader.Load accepts both a chart
    directory and a .tgz archive): extract to a scratch dir and return
    the chart root — the top-level directory holding Chart.yaml, which
    `helm package` names after the chart. Archive members with unsafe
    paths are refused by tarfile's data filter (manual member screening
    on Pythons predating the `filter` kwarg)."""
    key = (archive_path, os.path.getmtime(archive_path))
    if key in _ARCHIVE_CACHE:
        _ARCHIVE_CACHE.move_to_end(key)
        return _ARCHIVE_CACHE[key][0]
    import tarfile
    import tempfile

    root = None
    tmp = None
    try:
        tmp = tempfile.mkdtemp(prefix="simon-chart-")
        _ARCHIVE_LIVE.append(tmp)
        with tarfile.open(archive_path, "r:gz") as tf:
            try:
                tf.extractall(tmp, filter="data")
            except TypeError:  # Python < 3.10.12/3.11.4: no filter kwarg
                safe = [
                    m
                    for m in tf.getmembers()
                    if (m.isreg() or m.isdir())
                    and not m.name.startswith("/")
                    and ".." not in m.name.split("/")
                ]
                tf.extractall(tmp, members=safe)
        for entry in sorted(os.listdir(tmp)):
            cand = os.path.join(tmp, entry)
            if os.path.isdir(cand) and os.path.isfile(
                os.path.join(cand, "Chart.yaml")
            ):
                root = cand
                break
    except (tarfile.TarError, OSError):
        root = None
    _ARCHIVE_CACHE[key] = (root, tmp)
    if len(_ARCHIVE_CACHE) > _ARCHIVE_CACHE_CAP:
        _evicted_root, evicted_tmp = _ARCHIVE_CACHE.popitem(last=False)[1]
        if evicted_tmp:
            _ARCHIVE_LIVE.remove(evicted_tmp)
            _ARCHIVE_EVICTED.append(evicted_tmp)
    return root


def _collect_charts(
    name: str, path: str, values: dict, globals_: dict, _loaded=None
) -> List[_Subchart]:
    """Flatten parent + enabled subcharts with helm value scoping:
    subchart values = deep_merge(subchart defaults, parent.values[name]),
    with `global` propagated down. charts/ entries may be unpacked
    directories or `helm package` .tgz archives. `_loaded` carries an
    already-parsed (meta, values) pair so callers that peeked at
    Chart.yaml for the dedup key don't parse it twice."""
    meta, own_values = _loaded if _loaded is not None else _load_chart_meta(path)
    merged = _deep_merge(own_values, values)
    g = _deep_merge(globals_, merged.get("global") or {})
    if g:
        merged["global"] = g
    charts = [_Subchart(name, path, meta, merged)]
    # charts/ entries are unpacked under the dependency's chart *name*;
    # an alias renames the subchart at load time (helm chartutil), so
    # condition gating and value scoping key on the alias when present
    deps_by_name = {d.get("name"): d for d in _dependencies(path, meta)}
    charts_dir = os.path.join(path, "charts")
    if os.path.isdir(charts_dir):
        seen_entries = set()
        for entry in sorted(os.listdir(charts_dir)):
            sub_path = os.path.join(charts_dir, entry)
            if os.path.isfile(sub_path) and entry.endswith((".tgz", ".tar.gz")):
                # packaged dependency: the dependency key is the chart's
                # metadata name (helm matches deps by name; the archive
                # filename only carries name-version by convention, so
                # dedup must come from the extracted Chart.yaml below,
                # never from the filename — an archive hand-renamed to
                # '<seen-chart>-X.Y.Z.tgz' may contain a different
                # chart). Extraction of a duplicate is cheap: the
                # archive cache keys on (path, mtime).
                sub_path = _unpack_chart_archive(sub_path)
                if sub_path is None:
                    continue
                sub_loaded = _load_chart_meta(sub_path)
                entry = sub_loaded[0].get("name") or entry
            elif not os.path.isdir(sub_path) or not os.path.isfile(
                os.path.join(sub_path, "Chart.yaml")
            ):
                continue
            else:
                # dedup + dependency lookup key on the chart's metadata
                # name for directories too — a vendored dir may carry a
                # versioned name that differs from the chart name
                sub_loaded = _load_chart_meta(sub_path)
                entry = sub_loaded[0].get("name") or entry
            # a dependency vendored both unpacked and as a .tgz (helm
            # pull --untar next to helm dependency update leftovers)
            # loads once — the sorted walk puts the directory first
            if entry in seen_entries:
                continue
            seen_entries.add(entry)
            dep = deps_by_name.get(entry, {})
            if dep and not _dependency_enabled(dep, merged):
                continue
            sub_name = dep.get("alias") or entry
            sub_values = merged.get(sub_name) or {}
            charts.extend(
                _collect_charts(sub_name, sub_path, sub_values, g, _loaded=sub_loaded)
            )
    return charts


def process_chart(name: str, path: str, extra_values: Optional[dict] = None) -> List[str]:
    """ProcessChart (pkg/chart/chart.go:18-41): render a chart directory
    (with its subcharts) into YAML manifest strings in install order."""
    # no render in flight here: safe to reclaim LRU-evicted extractions
    _purge_evicted_archives()
    charts = _collect_charts(name, path, extra_values or {}, {})

    release = {
        "Name": name,
        "Namespace": "default",
        "IsUpgrade": False,
        "IsInstall": True,
        "Revision": 1,
        "Service": "Helm",
    }
    capabilities = default_capabilities()

    # Pass 1: one shared named-template namespace across parent+subcharts
    # (helm semantics: all defines are global). Defines are registered
    # under each chart's own context so closures over .Chart resolve at
    # include time via the caller's env — matching helm, where defines
    # capture nothing.
    templates: Dict[str, List] = {}
    chart_files: List[Tuple[_Subchart, str, str, List]] = []
    for chart in charts:
        tdir = os.path.join(chart.path, "templates")
        if not os.path.isdir(tdir):
            continue
        for root, _, files in os.walk(tdir):
            if os.path.basename(root) == "tests":
                continue  # helm test hooks are not installed
            for fname in sorted(files):
                if fname.endswith("NOTES.txt"):
                    continue
                if not fname.endswith((".yaml", ".yml", ".tpl")):
                    continue
                fpath = os.path.join(root, fname)
                with open(fpath) as f:
                    text = f.read()
                nodes = _parse_template(text)
                _register_defines(nodes, templates)
                rel = os.path.relpath(fpath, chart.path)
                chart_files.append((chart, fname, rel, nodes))

    manifests: List[Tuple[str, str]] = []
    for chart, fname, rel, nodes in chart_files:
        if fname.startswith("_"):
            continue  # partials only contribute defines
        chart_meta = dict(chart.meta)
        chart_meta.setdefault("Name", chart_meta.get("name", chart.name))
        context = {
            "Values": chart.values,
            "Release": release,
            "Chart": chart_meta,
            "Capabilities": capabilities,
            "Template": {
                "Name": f"{chart.name}/{rel}",
                "BasePath": f"{chart.name}/templates",
            },
        }
        env = _Env(context, context, templates)
        rendered = "".join(_exec(nodes, env, []))
        if not rendered.strip():
            continue
        for doc_text in re.split(r"^---\s*$", rendered, flags=re.M):
            if not doc_text.strip():
                continue
            try:
                doc = yaml.safe_load(doc_text)
            except yaml.YAMLError:
                continue
            if not isinstance(doc, dict) or "kind" not in doc:
                continue
            manifests.append((doc.get("kind", ""), doc_text))
    manifests.sort(key=lambda kv: _ORDER_INDEX.get(kv[0], len(INSTALL_ORDER)))
    return [m for _, m in manifests]


def _register_defines(nodes: List, templates: Dict[str, List]):
    for node in nodes:
        tag = node[0]
        if tag == "define":
            templates[node[1]] = node[2]
            _register_defines(node[2], templates)
        elif tag == "if":
            for _, body in node[1]:
                _register_defines(body, templates)
            _register_defines(node[2], templates)
        elif tag in ("range", "with"):
            _register_defines(node[3], templates)
            _register_defines(node[4], templates)
