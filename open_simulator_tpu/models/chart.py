"""Offline Helm-chart rendering.

Mirrors pkg/chart/chart.go:18-118 (ProcessChart): load Chart.yaml +
values.yaml, render templates with fabricated release values
(Release.Name = app name, Namespace default, Revision 1, Service Helm),
skip NOTES.txt, and emit manifests in Helm's InstallOrder.

The helm Go engine is not available here, so this module implements the
Go-template subset that k8s charts of this shape actually use:

  {{ .Values.a.b }} / {{ $.Values.a.b }}   dotted lookups
  {{ .Release.Name }}                       release object
  {{ int EXPR }} {{ quote EXPR }} {{ default D EXPR }} {{ toYaml EXPR }}
  {{- if EXPR }} ... {{- else }} ... {{- end }}   with Go truthiness
  {{- range ... }} is NOT supported (none of the target charts use it)

Unknown/missing paths render empty (non-strict mode).
"""

from __future__ import annotations

import os
import re
from typing import List, Optional

import yaml

# helm releaseutil.InstallOrder
INSTALL_ORDER = [
    "Namespace",
    "NetworkPolicy",
    "ResourceQuota",
    "LimitRange",
    "PodSecurityPolicy",
    "PodDisruptionBudget",
    "ServiceAccount",
    "Secret",
    "SecretList",
    "ConfigMap",
    "StorageClass",
    "PersistentVolume",
    "PersistentVolumeClaim",
    "CustomResourceDefinition",
    "ClusterRole",
    "ClusterRoleList",
    "ClusterRoleBinding",
    "ClusterRoleBindingList",
    "Role",
    "RoleList",
    "RoleBinding",
    "RoleBindingList",
    "Service",
    "DaemonSet",
    "Pod",
    "ReplicationController",
    "ReplicaSet",
    "Deployment",
    "HorizontalPodAutoscaler",
    "StatefulSet",
    "Job",
    "CronJob",
    "Ingress",
    "APIService",
]
_ORDER_INDEX = {k: i for i, k in enumerate(INSTALL_ORDER)}

_TOKEN = re.compile(r"\{\{(-?)\s*(.*?)\s*(-?)\}\}")


class _Missing:
    """Sentinel for unresolved paths (renders empty, falsy)."""

    def __str__(self):
        return ""

    def __bool__(self):
        return False


MISSING = _Missing()


def _lookup(context: dict, path: str):
    cur = context
    for part in path.split("."):
        if not part:
            continue
        if isinstance(cur, dict) and part in cur:
            cur = cur[part]
        else:
            return MISSING
    return cur


def _truthy(v) -> bool:
    if v is MISSING or v is None:
        return False
    if isinstance(v, bool):
        return v
    if isinstance(v, (int, float)):
        return v != 0
    if isinstance(v, (str, list, dict)):
        return len(v) > 0
    return True


def _eval_expr(expr: str, context: dict):
    expr = expr.strip()
    if not expr:
        return MISSING
    # pipelines: a | b | c
    if "|" in expr:
        parts = [p.strip() for p in expr.split("|")]
        val = _eval_expr(parts[0], context)
        for fn in parts[1:]:
            val = _apply_func(fn.split() + [val], context, piped=True)
        return val
    tokens = _split_tokens(expr)
    if len(tokens) == 1:
        tok = tokens[0]
        if tok.startswith(('"', "'")):
            return tok[1:-1]
        if tok.startswith("$."):
            return _lookup(context, tok[2:])
        if tok.startswith("."):
            return _lookup(context, tok[1:])
        if tok in ("true", "false"):
            return tok == "true"
        try:
            return int(tok)
        except ValueError:
            try:
                return float(tok)
            except ValueError:
                return MISSING
    return _apply_func(tokens, context)


def _split_tokens(expr: str) -> List[str]:
    out, cur, quote = [], "", None
    for ch in expr:
        if quote:
            cur += ch
            if ch == quote:
                quote = None
            continue
        if ch in "\"'":
            quote = ch
            cur += ch
        elif ch.isspace():
            if cur:
                out.append(cur)
                cur = ""
        else:
            cur += ch
    if cur:
        out.append(cur)
    return out


def _apply_func(tokens, context, piped=False):
    name = tokens[0]
    args = [
        t if not isinstance(t, str) else _eval_expr(t, context) for t in tokens[1:]
    ]
    if name == "int":
        v = args[0] if args else MISSING
        try:
            return int(float(str(v))) if not isinstance(v, bool) and v is not MISSING else 0
        except (TypeError, ValueError):
            return 0
    if name == "quote":
        v = args[0] if args else ""
        return f'"{v}"'
    if name == "default":
        # default DEFAULT VALUE
        if len(args) >= 2:
            return args[1] if _truthy(args[1]) else args[0]
        return args[0] if args else MISSING
    if name == "toYaml":
        v = args[0] if args else None
        if v is MISSING or v is None:
            return ""
        return yaml.safe_dump(v, default_flow_style=False).rstrip()
    if name in ("eq", "ne"):
        if len(args) >= 2:
            same = str(args[0]) == str(args[1])
            return same if name == "eq" else not same
        return False
    if name == "not":
        return not _truthy(args[0] if args else MISSING)
    # unknown function: pass through last arg
    return args[-1] if args else MISSING


def render_template(text: str, context: dict) -> str:
    """Render the supported Go-template subset."""
    # tokenize into literals and actions with trim markers applied
    parts = []  # (kind, payload)
    pos = 0
    for m in _TOKEN.finditer(text):
        lit = text[pos : m.start()]
        if m.group(1) == "-":
            lit = lit.rstrip()
        parts.append(("lit", lit))
        parts.append(("act", (m.group(2), m.group(3) == "-")))
        pos = m.end()
    parts.append(("lit", text[pos:]))

    # post-process right-trim: a trailing '-' on an action trims leading
    # whitespace of the following literal
    out: List[str] = []
    stack: List[bool] = []  # emit states for if/else nesting
    trim_next = False

    def emitting():
        return all(stack)

    for kind, payload in parts:
        if kind == "lit":
            lit = payload
            if trim_next:
                lit = lit.lstrip()
                trim_next = False
            if emitting():
                out.append(lit)
            continue
        action, rtrim = payload
        trim_next = rtrim
        if action.startswith("if "):
            cond = _truthy(_eval_expr(action[3:], context)) if emitting() else False
            stack.append(cond)
        elif action == "else":
            if stack:
                stack[-1] = not stack[-1]
        elif action.startswith("else if "):
            if stack:
                stack[-1] = (not stack[-1]) and _truthy(_eval_expr(action[8:], context))
        elif action == "end":
            if stack:
                stack.pop()
        elif action.startswith("/*"):
            continue  # comment
        else:
            if emitting():
                v = _eval_expr(action, context)
                out.append("" if v is MISSING or v is None else str(v))
    return "".join(out)


def _deep_merge(base: dict, override: dict) -> dict:
    out = dict(base)
    for k, v in (override or {}).items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


def process_chart(name: str, path: str, extra_values: Optional[dict] = None) -> List[str]:
    """ProcessChart (pkg/chart/chart.go:18-41): render a chart directory
    into a list of YAML manifest strings in install order."""
    chart_file = os.path.join(path, "Chart.yaml")
    if not os.path.isfile(chart_file):
        raise ValueError(f"{path}: not a helm chart (no Chart.yaml)")
    values = {}
    values_file = os.path.join(path, "values.yaml")
    if os.path.isfile(values_file):
        with open(values_file) as f:
            values = yaml.safe_load(f) or {}
    if extra_values:
        values = _deep_merge(values, extra_values)
    context = {
        "Values": values,
        "Release": {
            "Name": name,
            "Namespace": "default",
            "IsUpgrade": False,
            "IsInstall": True,
            "Revision": 1,
            "Service": "Helm",
        },
        "Chart": yaml.safe_load(open(chart_file)) or {},
    }
    manifests = []  # (kind, rendered)
    tdir = os.path.join(path, "templates")
    for root, _, files in os.walk(tdir):
        for fname in sorted(files):
            if fname.endswith("NOTES.txt") or fname.startswith("_"):
                continue
            if not fname.endswith((".yaml", ".yml", ".tpl")):
                continue
            with open(os.path.join(root, fname)) as f:
                rendered = render_template(f.read(), context)
            if not rendered.strip():
                continue
            for doc_text in re.split(r"^---\s*$", rendered, flags=re.M):
                if not doc_text.strip():
                    continue
                try:
                    doc = yaml.safe_load(doc_text)
                except yaml.YAMLError:
                    continue
                if not isinstance(doc, dict) or "kind" not in doc:
                    continue
                manifests.append((doc.get("kind", ""), doc_text))
    manifests.sort(key=lambda kv: _ORDER_INDEX.get(kv[0], len(INSTALL_ORDER)))
    return [m for _, m in manifests]
