"""N-replica serve/twin fleet: consistent-hash routing, replica
supervision, and journal-replay failover.

ROADMAP item 2's scale-OUT layer. One process is the hard ceiling no
matter how fast the warm path gets; every scale-out primitive already
exists in the repo — the content-addressed AOT store makes a new
replica zero-compile, crash-safe session snapshots plus the
cluster-delta journal make warm state replayable, and request IDs +
SLO burn rates make a fleet observable. This package composes them so
a replica can die without a user noticing:

- ``hashing``  — slot-affine consistent-hash ring (tenant-affine
  routing; a replacement replica inherits its slot, so failover moves
  ZERO keys).
- ``replica``  — supervised serve subprocesses: spawn, /healthz
  probing, restart-with-backoff (the PR-2 retry discipline), slot
  lock files that refuse split-brain double-spawns.
- ``replay``   — bootstrap a replacement session from the dead
  replica's session-snapshot + cluster-delta journal, torn tail
  tolerated, interior damage refused loudly.
- ``router``   — the thin HTTP router daemon behind ``simon fleet``:
  failover reroutes carry their ORIGINAL request IDs (429/503 +
  Retry-After when saturated, never silent drops), fleet-aggregated
  /metrics with cardinality-bounded per-replica labels, fleet
  /healthz + telemetry for ``simon top``.

Injection seams ``fleet.route``, ``fleet.probe``, ``fleet.replay``,
``fleet.spawn`` join the runtime/inject.py grammar so the chaos
matrix (tests/test_chaos_matrix.py FLEET_CELLS) can drive kill-9
mid-burst, torn-journal handoff, split-brain double-spawn, and
probe-flap scenarios to documented degradations.
"""

from .hashing import HashRing  # noqa: F401
from .replay import read_session_events, replay_into_session  # noqa: F401
from .replica import DoubleSpawnError, ReplicaProcess, SlotLock  # noqa: F401
from .router import FleetRouter, render_fleet_metrics  # noqa: F401
