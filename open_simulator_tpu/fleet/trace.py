"""Cross-process trace stitching: one span tree per fleet request.

A request forwarded by the fleet router produces spans in TWO
processes with TWO independent id spaces and clock epochs: the router
records ``fleet/request`` -> ``fleet/forward`` (plus ``fleet/reroute``
siblings for failed attempts and a ``fleet/shed`` leaf on
exhaustion), and the replica that answered records its own
``serve/request`` subtree (queue_wait / evaluate — serve/coalescer.py)
carrying the router's forward-span id as a ``remote_parent``
ATTRIBUTE (propagated in ``X-Simon-Trace-Context``; span ids are
process-local so a remote id can never be a structural parent).

This module is the collector that makes those halves ONE tree:

- ``fetch_replica_spans`` drains a replica's span ring through its
  existing ``POST /debug/dump`` surface (no new replica endpoint, no
  extra work on the request hot path);
- ``stitch_request_trace`` is the pure core: select both sides'
  spans for one request id, remap every span into one fresh id
  space, attach each replica ``serve/request`` root under the router
  ``fleet/forward`` span whose id it names (and whose slot matches
  the dump it came from — the slot check keeps a shared-recorder
  test double from stitching the same subtree twice), and re-base
  replica timestamps into the router's clock domain;
- ``trace_endpoint`` serves ``GET /v1/fleet/trace?requestId=...`` on
  the router: a Chrome-trace-exportable document (``traceEvents``
  with ``args.span_id``/``args.parent_id``) that
  ``tools/validate_trace.py`` validates unchanged.

Reroutes and failovers are visible BY CONSTRUCTION: every attempt —
the failed forward, the reroute marker, the answering forward — is a
sibling under the same ``fleet/request`` root.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Dict, List, Optional

from ..obs.spans import RECORDER

#: spans fetched per replica dump — mirrors telemetry.DUMP_MAX_SPANS;
#: the stitcher reads the dump's inline event list, never the full ring
FETCH_TIMEOUT_S = 10.0


def _rid_of(event: dict) -> Optional[str]:
    attrs = event.get("attrs")
    return attrs.get("request_id") if isinstance(attrs, dict) else None


def fetch_replica_spans(
    url: str, timeout_s: float = FETCH_TIMEOUT_S
) -> List[dict]:
    """One replica's recorded span events (``as_dict`` shape) via its
    ``POST /debug/dump`` endpoint. Raises OSError/URLError on an
    unreachable replica — the caller decides whether a missing dump
    degrades or fails the stitch."""
    req = urllib.request.Request(
        url + "/debug/dump", data=b"", method="POST"
    )
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        doc = json.loads(resp.read().decode("utf-8"))
    spans = doc.get("spans") if isinstance(doc, dict) else None
    events = spans.get("events") if isinstance(spans, dict) else None
    return [e for e in (events or []) if isinstance(e, dict)]


def stitch_request_trace(
    rid: str,
    router_events: List[dict],
    replica_events_by_slot: Dict[str, List[dict]],
) -> List[dict]:
    """One request's stitched span forest as a list of plain dicts
    ``{id, parent, name, t0, t1, tid, pid, attrs}`` in ONE id space
    and the ROUTER'S clock domain. Pure: feed it recorded events from
    any source (live dumps, test recorders, archived dumps)."""
    fresh = 0
    out: List[dict] = []

    def emit(event, parent, t_offset, pid):
        nonlocal fresh
        fresh += 1
        attrs = dict(event.get("attrs") or {})
        out.append(
            {
                "id": fresh,
                "parent": parent,
                "name": event.get("name", "?"),
                "t0": float(event.get("t0", 0.0)) + t_offset,
                "t1": float(event.get("t1", 0.0)) + t_offset,
                "tid": event.get("tid", 0),
                "pid": pid,
                "attrs": attrs,
            }
        )
        return fresh

    # -- router side: the fleet/* spans recorded for this request
    r_events = [
        e
        for e in router_events
        if _rid_of(e) == rid and str(e.get("name", "")).startswith("fleet/")
    ]
    r_ids = {e.get("id") for e in r_events}
    children: Dict[Optional[int], List[dict]] = {}
    for e in r_events:
        parent = e.get("parent")
        children.setdefault(parent if parent in r_ids else None, []).append(e)
    # old forward-span id -> (new id, slot, new-domain t0): what a
    # replica root's remote_parent attr resolves against
    forwards: Dict[int, tuple] = {}

    def walk(event, parent_new):
        nid = emit(event, parent_new, 0.0, pid=0)
        if event.get("name") == "fleet/forward":
            attrs = event.get("attrs") or {}
            forwards[event.get("id")] = (
                nid,
                str(attrs.get("slot", "")),
                float(event.get("t0", 0.0)),
            )
        for child in sorted(
            children.get(event.get("id"), []),
            key=lambda c: float(c.get("t0", 0.0)),
        ):
            walk(child, nid)

    roots = sorted(
        children.get(None, []), key=lambda e: float(e.get("t0", 0.0))
    )
    for root in roots:
        walk(root, None)

    # -- replica side: serve/request roots naming one of our forwards
    for slot in sorted(replica_events_by_slot):
        events = [
            e
            for e in replica_events_by_slot[slot]
            if _rid_of(e) == rid
            and str(e.get("name", "")).startswith("serve/")
        ]
        ids = {e.get("id") for e in events}
        kids: Dict[int, List[dict]] = {}
        for e in events:
            parent = e.get("parent")
            if parent in ids:
                kids.setdefault(parent, []).append(e)
        for root in events:
            if root.get("name") != "serve/request":
                continue
            if (root.get("parent") in ids):
                continue  # nested under another serve span: not a root
            remote = (root.get("attrs") or {}).get("remote_parent")
            match = forwards.get(remote)
            if match is None or match[1] != slot:
                # not stitched by THIS router's forwards (a direct
                # request, or — shared-recorder double — a dump that
                # also contains the other slot's spans)
                continue
            fwd_new, _, fwd_t0 = match
            # re-base into the router's clock domain: the replica
            # subtree starts where its forward span started (span
            # NESTING is structural via parent ids; the time shift
            # only makes the Chrome rendering sensible)
            offset = fwd_t0 - float(root.get("t0", 0.0))
            pid = 1 + sorted(replica_events_by_slot).index(slot)

            def walk_replica(event, parent_new):
                nid = emit(event, parent_new, offset, pid)
                for child in sorted(
                    kids.get(event.get("id"), []),
                    key=lambda c: float(c.get("t0", 0.0)),
                ):
                    walk_replica(child, nid)

            walk_replica(root, fwd_new)
    return out


def chrome_trace_doc(stitched: List[dict], rid: str) -> dict:
    """A Chrome trace-event document of one stitched request tree —
    the exact shape ``tools/validate_trace.py`` checks (``X`` events,
    microsecond ts, span/parent ids in ``args``)."""
    events = []
    for s in stitched:
        args = {"span_id": s["id"], "parent_id": s["parent"]}
        args.update(
            {k: v for k, v in (s.get("attrs") or {}).items() if v is not None}
        )
        events.append(
            {
                "name": s["name"],
                "ph": "X",
                "ts": round(s["t0"] * 1e6, 3),
                "dur": round(max(s["t1"] - s["t0"], 0.0) * 1e6, 3),
                "pid": s.get("pid", 0),
                "tid": s.get("tid", 0),
                "args": args,
            }
        )
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "simonFleetTrace": {"requestId": rid, "spans": len(events)},
    }


def collect_request_trace(
    router, rid: str, timeout_s: float = FETCH_TIMEOUT_S
) -> dict:
    """Stitch one request's trace from the LIVE fleet: the router's
    own recorder plus a span drain of every reachable replica. An
    unreachable replica degrades to a router-only tree (its absence
    is visible as a forward span with no serve subtree), it never
    fails the collection."""
    router_events = [s.as_dict() for s in RECORDER.snapshot()]
    replica_events: Dict[str, List[dict]] = {}
    for slot in sorted(router.replicas):
        replica = router.replicas[slot]
        if not replica.url or router._health.get(slot) == "down":
            continue
        try:
            replica_events[slot] = fetch_replica_spans(
                replica.url, timeout_s=timeout_s
            )
        except (OSError, urllib.error.URLError, ValueError):
            continue
    stitched = stitch_request_trace(rid, router_events, replica_events)
    return chrome_trace_doc(stitched, rid)


def trace_endpoint(router, path: str) -> tuple:
    """GET /v1/fleet/trace handler body: ``requestId`` query param
    selects the request; answers the stitched Chrome trace document,
    404 when no span on either side carries that id. Returns
    ``(status, payload dict)``."""
    from urllib.parse import parse_qs, urlparse

    q = parse_qs(urlparse(path).query)
    rids = q.get("requestId") or []
    if not rids:
        return 400, {"error": "missing requestId query parameter"}
    from ..obs.telemetry import sanitize_request_id

    rid = sanitize_request_id(rids[-1])
    if not rid:
        return 400, {"error": "empty requestId"}
    doc = collect_request_trace(router, rid)
    if not doc["traceEvents"]:
        return 404, {
            "error": f"no spans recorded for request id {rid!r} "
            "(expired from the ring, or never routed here)"
        }
    return 200, doc
