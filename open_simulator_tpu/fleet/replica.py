"""Supervised serve replica subprocesses.

One ``ReplicaProcess`` owns one fleet slot (``r0``, ``r1``, ...): the
slot's lock file, its session snapshot journal, and at most one live
``simon serve`` child at a time. The supervision contract:

- **Spawn** launches the child with ``--port 0`` and parses the
  machine-readable ``simon serve listening on http://HOST:PORT``
  stdout line for the base URL; stdout/stderr stream to per-slot log
  files in the fleet directory. Spawn failures retry with the PR-2
  capped-exponential backoff (``runtime.retry.backoff_delay``) —
  every attempt passes the ``fleet.spawn`` injection seam first.
- **Slot locks refuse split-brain**: ``fleet-dir/<slot>.lock`` holds
  the supervisor pid. A second spawn against a slot whose lock holder
  is still alive raises ``DoubleSpawnError`` (an input error — two
  replicas appending the same snapshot journal would corrupt it, so
  the refusal is loud and immediate, never retried). A stale lock
  (holder dead) is reclaimed silently: that is exactly the failover
  path.
- **Probe** is one GET /healthz through the ``fleet.probe`` seam with
  a hard timeout. A degraded replica's ``Retry-After`` hint is
  surfaced so the router backs off probing instead of hot-looping.
- **Kill/terminate** are idempotent; ``alive()`` is the supervisor's
  death detector.

The slot's snapshot journal path is stable across restarts, so a
replacement child resumes the dead child's journal and — with
``--replay-snapshot`` — replays its delta stream (fleet/replay.py)
before answering its first request.
"""

from __future__ import annotations

import json
import logging
import os
import re
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import List, Optional

from ..models.validation import InputError
from ..runtime import inject as _inject
from ..runtime.errors import BackendUnavailable
from ..runtime.retry import backoff_delay
from ..utils.trace import COUNTERS

log = logging.getLogger("simon.fleet")

#: the machine-parsable readiness line printed by cmd_serve
_LISTENING_RE = re.compile(r"listening on (http://\S+)")

#: consecutive failed probes before the supervisor declares a replica
#: dead (one flaky probe must not trigger a full restart)
PROBE_FAILURE_THRESHOLD = 3

DEFAULT_SPAWN_ATTEMPTS = 4
DEFAULT_READY_TIMEOUT_S = 180.0


class DoubleSpawnError(InputError):
    """A second replica was spawned against a slot whose lock holder
    is still alive — split-brain on the slot's snapshot journal.
    Refused loudly (exit 2 posture), never retried."""


class SlotLock:
    """Pid lock file guarding one fleet slot. Created exclusively;
    a stale lock (holder pid dead) is reclaimed, a live one refuses."""

    def __init__(self, path: str):
        self.path = path
        self.held = False

    @staticmethod
    def _pid_alive(pid: int) -> bool:
        if pid <= 0:
            return False
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            return False
        except PermissionError:
            return True
        except OSError:
            return False
        return True

    def acquire(self, owner_pid: Optional[int] = None):
        pid = os.getpid() if owner_pid is None else owner_pid
        for _ in range(2):  # second pass after reclaiming a stale lock
            try:
                fd = os.open(self.path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except FileExistsError:
                holder = self._read_holder()
                if holder is not None and self._pid_alive(holder):
                    if holder == pid:
                        return  # re-acquire by the same supervisor
                    raise DoubleSpawnError(
                        f"slot lock {self.path} is held by live pid "
                        f"{holder}; refusing double-spawn (two replicas "
                        "on one slot would corrupt its snapshot journal)"
                    )
                # stale: holder died without releasing — the failover
                # path. Reclaim and retry the exclusive create.
                try:
                    os.unlink(self.path)
                except OSError:
                    log.debug("stale lock %s vanished under reclaim", self.path)
                continue
            with os.fdopen(fd, "w") as f:
                f.write(json.dumps({"pid": pid}))
            self.held = True
            return
        raise DoubleSpawnError(
            f"slot lock {self.path} could not be acquired (lost the "
            "reclaim race to another supervisor)"
        )

    def _read_holder(self) -> Optional[int]:
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                return int((json.load(f) or {}).get("pid", 0)) or None
        except (OSError, ValueError):
            return None

    def release(self):
        if not self.held:
            return
        self.held = False
        try:
            os.unlink(self.path)
        except OSError:
            log.debug("slot lock %s already removed", self.path)


class ReplicaProcess:
    """One supervised serve child bound to one fleet slot."""

    def __init__(
        self,
        slot: str,
        argv: List[str],
        fleet_dir: str,
        probe_timeout_s: float = 5.0,
        ready_timeout_s: float = DEFAULT_READY_TIMEOUT_S,
    ):
        self.slot = slot
        self.argv = list(argv)
        self.fleet_dir = fleet_dir
        self.probe_timeout_s = probe_timeout_s
        self.ready_timeout_s = ready_timeout_s
        self.lock = SlotLock(os.path.join(fleet_dir, f"{slot}.lock"))
        self.snapshot_path = os.path.join(fleet_dir, f"{slot}.snapshot.jsonl")
        self.url: Optional[str] = None
        self.proc: Optional[subprocess.Popen] = None
        self.restarts = 0
        self.probe_failures = 0  # consecutive; reset on success
        self.retry_after_s = 0  # degraded replica's backoff hint
        self._ready = threading.Event()
        self._reader: Optional[threading.Thread] = None

    # -- identity ------------------------------------------------------------

    @property
    def pid(self) -> Optional[int]:
        return self.proc.pid if self.proc is not None else None

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    # -- spawn ---------------------------------------------------------------

    def spawn(
        self, attempts: int = DEFAULT_SPAWN_ATTEMPTS, sleep=time.sleep
    ) -> str:
        """Launch the child and block until its listening line appears
        (returns the base URL). Spawn faults (the ``fleet.spawn``
        seam, exec failures, a child that dies before listening) retry
        with capped-exponential backoff; ``DoubleSpawnError`` refuses
        immediately. Raises the last failure when attempts run out."""
        self.lock.acquire()
        last: Optional[BaseException] = None
        for attempt in range(1, attempts + 1):
            try:
                _inject.fire("fleet.spawn", slot=self.slot, attempt=attempt)
                # the slot lock MUST be held across the launch — that
                # is the split-brain guarantee, not an accidental hold
                return self._spawn_once()  # simonlint: disable=CONC002
            except DoubleSpawnError:
                raise
            except Exception as e:  # noqa: BLE001 - retried, re-raised on exhaustion
                last = e
                self._reap()
                COUNTERS.inc("fleet_spawn_retry_total")
                if attempt < attempts:
                    sleep(backoff_delay(f"fleet.spawn.{self.slot}", attempt))
        assert last is not None
        raise last

    def _spawn_once(self) -> str:
        self.url = None
        self._ready.clear()
        stderr_log = open(  # noqa: SIM115 - lifetime is the child's
            os.path.join(self.fleet_dir, f"{self.slot}.stderr.log"),
            "ab",
        )
        # the child imports open_simulator_tpu by module path; when the
        # package runs from a source checkout (not installed), its root
        # must be on the child's PYTHONPATH. The child inherits the
        # supervisor's cwd so relative paths inside the config (e.g.
        # the example CR's customConfig dir) keep resolving.
        pkg_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        env = dict(os.environ)
        env["PYTHONPATH"] = (
            pkg_root + os.pathsep + env["PYTHONPATH"]
            if env.get("PYTHONPATH")
            else pkg_root
        )
        try:
            self.proc = subprocess.Popen(
                self.argv,
                stdout=subprocess.PIPE,
                stderr=stderr_log,
                env=env,
            )
        finally:
            stderr_log.close()  # child holds its own descriptor
        COUNTERS.inc("fleet_spawn_total")
        self._reader = threading.Thread(
            target=self._pump_stdout, args=(self.proc,), daemon=True
        )
        self._reader.start()
        deadline = time.monotonic() + self.ready_timeout_s
        while time.monotonic() < deadline:
            if self._ready.wait(timeout=0.1):
                assert self.url is not None
                self.probe_failures = 0
                return self.url
            if self.proc.poll() is not None:
                raise BackendUnavailable(
                    f"replica {self.slot} exited rc={self.proc.returncode} "
                    "before listening (see its stderr log in the fleet dir)"
                )
        self.kill()
        raise BackendUnavailable(
            f"replica {self.slot} did not print its listening line within "
            f"{self.ready_timeout_s:.0f}s"
        )

    def _pump_stdout(self, proc: subprocess.Popen):
        log_path = os.path.join(self.fleet_dir, f"{self.slot}.stdout.log")
        with open(log_path, "ab") as log:
            for raw in iter(proc.stdout.readline, b""):
                log.write(raw)
                log.flush()
                if not self._ready.is_set():
                    m = _LISTENING_RE.search(raw.decode("utf-8", "replace"))
                    if m:
                        self.url = m.group(1).rstrip("/")
                        self._ready.set()

    def _reap(self):
        if self.proc is not None and self.proc.poll() is None:
            self.kill()
        self.proc = None
        self.url = None

    # -- probe ---------------------------------------------------------------

    def probe(self) -> dict:
        """One GET /healthz. Returns the health document augmented
        with ``probeOk``; a connection failure returns
        ``{"probeOk": False, ...}`` and bumps the consecutive-failure
        count. A degraded replica's Retry-After header is kept as the
        probing backoff hint. (The ``fleet.probe`` injection seam
        fires in the router's supervision pass, which wraps this.)"""
        if not self.url:
            self.probe_failures += 1
            return {"probeOk": False, "error": "no url (not spawned)"}
        try:
            with urllib.request.urlopen(
                self.url + "/healthz", timeout=self.probe_timeout_s
            ) as resp:
                doc = json.loads(resp.read().decode("utf-8"))
                retry_after = resp.headers.get("Retry-After")
        except (OSError, urllib.error.URLError, ValueError) as e:
            self.probe_failures += 1
            COUNTERS.inc("fleet_probe_failures_total")
            return {"probeOk": False, "error": str(e)}
        self.probe_failures = 0
        self.retry_after_s = int(retry_after) if retry_after else 0
        doc["probeOk"] = True
        return doc

    # -- teardown ------------------------------------------------------------

    def terminate(self):
        if self.proc is not None and self.proc.poll() is None:
            try:
                self.proc.send_signal(signal.SIGTERM)
            except OSError:
                log.debug("replica %s exited before SIGTERM landed", self.slot)

    def kill(self):
        if self.proc is not None and self.proc.poll() is None:
            try:
                self.proc.kill()
            except OSError:
                log.debug("replica %s exited before SIGKILL landed", self.slot)
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                log.warning("replica %s unreaped after SIGKILL", self.slot)

    def wait(self, timeout_s: float) -> Optional[int]:
        if self.proc is None:
            return None
        try:
            return self.proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            return None

    def release(self):
        self.lock.release()


def serve_argv(
    config_path: str,
    *,
    aot_store: str,
    snapshot_path: str,
    checkpoint_interval: Optional[int] = None,
    keep_checkpoints: Optional[int] = None,
    extra: List[str] = (),
) -> List[str]:
    """The canonical replica command line: ephemeral port, shared AOT
    store, the slot's snapshot journal, and journal replay on boot —
    the zero-compile warm-bootstrap contract in one argv. With
    ``checkpoint_interval`` the replica also writes verified state
    checkpoints, so its replacement's replay is bounded by the
    interval instead of the slot's lifetime (runtime/checkpoint.py)."""
    argv = [
        sys.executable,
        "-m",
        "open_simulator_tpu.cli",
        "serve",
        "-f",
        config_path,
        "--port",
        "0",
        "--aot-store",
        aot_store,
        "--snapshot",
        snapshot_path,
        "--replay-snapshot",
    ]
    if checkpoint_interval:
        argv += ["--checkpoint-interval", str(int(checkpoint_interval))]
    if keep_checkpoints:
        argv += ["--keep-checkpoints", str(int(keep_checkpoints))]
    argv += list(extra)
    return argv
