"""Journal-replay bootstrap for a replacement replica.

A serve replica journals every applied cluster delta to its session
snapshot (serve/sessions.py ``record_delta``). When the replica dies,
its warm in-memory state — the roster mutations absorbed since boot —
is exactly the delta stream in that journal. A replacement bootstraps
by building a fresh Session from the same config, then replaying the
dead replica's journal through ``Session.apply_delta`` before it
answers its first request:

- compiled executables come from the shared content-addressed AOT
  store (zero new XLA compiles — the store was populated by the
  replica being replaced, and store hits do not count as recompiles);
- roster state comes from this replay (dict-identical committed scan
  digest and the same ``delta_seq`` as the dead replica — pinned by
  tests/test_fleet.py).

Reading follows the runtime/journal.py recovery discipline: header
fingerprint validated FIRST, complete records replayed, a torn final
line (the replica died mid-append) dropped and counted, interior
damage refused loudly (``JournalMismatch`` — serving un-replayed
state would answer requests wrongly, which is worse than refusing to
boot). The read is strictly read-only: the serve daemon itself
resumes the same file for append afterwards (and truncates the torn
tail durably); replay must not race that by holding the file open.

Injection seam ``fleet.replay`` fires once per replay so the chaos
matrix can drive bootstrap faults to their documented degradation.
"""

from __future__ import annotations

import json
from typing import List, Tuple

from ..runtime import inject as _inject
from ..runtime.journal import JOURNAL_VERSION, JournalMismatch
from ..utils.trace import COUNTERS


def read_session_events(path: str, fingerprint: str) -> Tuple[List[dict], int]:
    """Read a session snapshot journal read-only. Returns
    ``(records, dropped)``: every complete non-header record in append
    order, and the count of torn trailing lines discarded. Raises
    ``JournalMismatch`` on header/fingerprint mismatch or interior
    damage — the same refusals as ``Journal.resume``."""
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError as e:
        raise JournalMismatch(f"cannot replay from {path}: {e}") from e
    lines = raw.split(b"\n")
    if not lines or not lines[0].strip():
        raise JournalMismatch(f"{path}: empty journal, nothing to replay")
    try:
        header = json.loads(lines[0])
    except ValueError as e:
        raise JournalMismatch(f"{path}: unreadable journal header: {e}") from e
    if not isinstance(header, dict) or header.get("kind") != "header":
        raise JournalMismatch(f"{path}: first record is not a journal header")
    if header.get("version") != JOURNAL_VERSION:
        raise JournalMismatch(
            f"{path}: journal version {header.get('version')!r} != "
            f"{JOURNAL_VERSION}"
        )
    if header.get("fingerprint") != fingerprint:
        raise JournalMismatch(
            f"{path}: journal fingerprint {header.get('fingerprint')!r} does "
            f"not match the expected snapshot format ({fingerprint!r}); "
            "refusing to replay a journal from a different subsystem"
        )
    body, tail = lines[1:-1], lines[-1]
    records: List[dict] = []
    for i, line in enumerate(body):
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
        except ValueError as e:
            raise JournalMismatch(
                f"{path}: corrupt journal record on line {i + 2}: {e}"
            ) from e
        if not isinstance(rec, dict):
            raise JournalMismatch(
                f"{path}: corrupt journal record on line {i + 2}: "
                "record is not an object"
            )
        records.append(rec)
    dropped = 0
    if tail.strip():
        # no trailing newline: the replica died mid-append. Keep the
        # record only if it parses whole; else it is the torn tail —
        # expected damage, dropped and counted, never fatal.
        try:
            rec = json.loads(tail)
        except ValueError:
            rec = None
        if isinstance(rec, dict):
            records.append(rec)
        else:
            dropped = 1
    return records, dropped


def replay_into_session(session, path: str) -> dict:
    """Replay the delta stream journaled at ``path`` into ``session``
    (deltas recorded against other cluster fingerprints are skipped —
    a multi-session snapshot replays only the primary's stream).
    Returns a summary dict: ``deltas`` seen for this fingerprint,
    ``applied``/``skipped``/``reloads`` from ``apply_delta``,
    ``dropped`` torn-tail lines, and the journaled ``requestIds`` (the
    X-Simon-Request-Id correlation carried across the failover)."""
    from ..serve.sessions import SNAPSHOT_VERSION
    from ..runtime.journal import config_fingerprint
    from ..twin.deltas import ClusterDelta

    _inject.fire("fleet.replay", path=path)
    fp = config_fingerprint(
        {"format": "serve-session-snapshot", "version": SNAPSHOT_VERSION}
    )
    records, dropped = read_session_events(path, fp)
    summary = {
        "deltas": 0,
        "applied": 0,
        "skipped": 0,
        "reloads": 0,
        "dropped": dropped,
        "requestIds": [],
    }
    for rec in records:
        if rec.get("kind") != "session" or rec.get("event") != "delta":
            continue
        if rec.get("fingerprint") != session.fingerprint:
            continue
        summary["deltas"] += 1
        rid = rec.get("requestId")
        if rid:
            summary["requestIds"].append(rid)
        out = session.apply_delta(ClusterDelta.from_record(rec["delta"]))
        if out == "skipped":
            summary["skipped"] += 1
        else:
            summary["applied"] += 1
            if out == "reloaded":
                summary["reloads"] += 1
    COUNTERS.inc("fleet_replayed_deltas_total", summary["deltas"])
    if dropped:
        COUNTERS.inc("fleet_replay_torn_tail_total", dropped)
    return summary
