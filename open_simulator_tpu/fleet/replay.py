"""Snapshot-then-suffix bootstrap for a replacement replica.

A serve replica journals every applied cluster delta to its session
snapshot (serve/sessions.py ``record_delta``) and — with
``--checkpoint-interval`` — periodically writes a verified checkpoint
of the committed session (runtime/checkpoint.py). When the replica
dies, a replacement bootstraps in two stages:

1. **Restore** (``restore_into_session``): walk the retained
   checkpoint generations newest → oldest; the first one whose header
   validates AND whose payload re-materializes to the recorded state
   digest is adopted wholesale (``Session.restore_state``). A refused
   generation — torn, corrupt, stale toolchain, digest mismatch — is
   counted (``ckpt_restore_fallback_total``) and logged, and the walk
   falls back to the previous generation: a longer replay, never a
   silent wrong state. No usable generation means full-journal replay
   (the pre-checkpoint posture).
2. **Suffix replay**: the journal's delta records with ``seq`` past
   the restored checkpoint replay through ``Session.apply_delta``;
   the absorbed prefix is skipped by sequence (correct even when the
   compactor never got to truncate it). Replay cost is therefore
   O(--checkpoint-interval), not O(daemon lifetime).

Without checkpoints the original contract is unchanged — a fresh
Session from the same config, then the full delta stream:

- compiled executables come from the shared content-addressed AOT
  store (zero new XLA compiles — the store was populated by the
  replica being replaced, and store hits do not count as recompiles);
- roster state comes from this replay (dict-identical committed scan
  digest and the same ``delta_seq`` as the dead replica — pinned by
  tests/test_fleet.py).

Reading follows the runtime/journal.py recovery discipline: header
fingerprint validated FIRST, complete records replayed, a torn final
line (the replica died mid-append) dropped and counted, interior
damage refused loudly (``JournalMismatch`` — serving un-replayed
state would answer requests wrongly, which is worse than refusing to
boot). The read is strictly read-only: the serve daemon itself
resumes the same file for append afterwards (and truncates the torn
tail durably); replay must not race that by holding the file open.

Injection seam ``fleet.replay`` fires once per replay so the chaos
matrix can drive bootstrap faults to their documented degradation.
"""

from __future__ import annotations

import json
import logging
import time
from typing import List, Optional, Tuple

from ..runtime import inject as _inject
from ..runtime.journal import JOURNAL_VERSION, JournalMismatch
from ..utils.trace import COUNTERS

log = logging.getLogger("simon.fleet")


def read_session_events(path: str, fingerprint: str) -> Tuple[List[dict], int]:
    """Read a session snapshot journal read-only. Returns
    ``(records, dropped)``: every complete non-header record in append
    order, and the count of torn trailing lines discarded. Raises
    ``JournalMismatch`` on header/fingerprint mismatch or interior
    damage — the same refusals as ``Journal.resume``."""
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError as e:
        raise JournalMismatch(f"cannot replay from {path}: {e}") from e
    lines = raw.split(b"\n")
    if not lines or not lines[0].strip():
        raise JournalMismatch(f"{path}: empty journal, nothing to replay")
    try:
        header = json.loads(lines[0])
    except ValueError as e:
        raise JournalMismatch(f"{path}: unreadable journal header: {e}") from e
    if not isinstance(header, dict) or header.get("kind") != "header":
        raise JournalMismatch(f"{path}: first record is not a journal header")
    if header.get("version") != JOURNAL_VERSION:
        raise JournalMismatch(
            f"{path}: journal version {header.get('version')!r} != "
            f"{JOURNAL_VERSION}"
        )
    if header.get("fingerprint") != fingerprint:
        raise JournalMismatch(
            f"{path}: journal fingerprint {header.get('fingerprint')!r} does "
            f"not match the expected snapshot format ({fingerprint!r}); "
            "refusing to replay a journal from a different subsystem"
        )
    body, tail = lines[1:-1], lines[-1]
    records: List[dict] = []
    for i, line in enumerate(body):
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
        except ValueError as e:
            raise JournalMismatch(
                f"{path}: corrupt journal record on line {i + 2}: {e}"
            ) from e
        if not isinstance(rec, dict):
            raise JournalMismatch(
                f"{path}: corrupt journal record on line {i + 2}: "
                "record is not an object"
            )
        records.append(rec)
    dropped = 0
    if tail.strip():
        # no trailing newline: the replica died mid-append. Keep the
        # record only if it parses whole; else it is the torn tail —
        # expected damage, dropped and counted, never fatal.
        try:
            rec = json.loads(tail)
        except ValueError:
            rec = None
        if isinstance(rec, dict):
            records.append(rec)
        else:
            dropped = 1
    return records, dropped


def restore_into_session(session, snapshot_path: str) -> Optional[dict]:
    """Adopt the newest TRUSTABLE checkpoint generation for
    ``snapshot_path`` into ``session``. Returns
    ``{"deltaSeq", "stateDigest", "path"}`` on success, None when no
    generation exists or every one was refused (the caller replays the
    full journal). The trust ladder per generation, newest first:
    header validation (kind/version/toolchain/fingerprint/sha256,
    ``load_checkpoint``), then the payload re-materialized to a fresh
    roster expansion whose digest must equal the header's
    ``stateDigest`` — all BEFORE the session is touched, under one
    delta-lock hold, so a refused generation leaves the session
    exactly as it was."""
    from ..runtime.checkpoint import (
        CheckpointMismatch,
        checkpoint_dir,
        list_checkpoints,
        load_checkpoint,
    )
    from ..serve.session import (
        cluster_from_payload,
        materialized_state_digest,
    )

    generations = list_checkpoints(checkpoint_dir(snapshot_path))
    for seq, path in generations:
        try:
            header, payload = load_checkpoint(
                path, expect_fingerprint=session.fingerprint
            )
            # _delta_lock is an RLock (session.py): restore_state
            # re-acquiring it under this hold is reentrant, not a
            # deadlock — the outer hold makes verify+swap one atomic cut
            with session._delta_lock:  # simonlint: disable=CONC002
                cluster = cluster_from_payload(payload)
                fresh = materialized_state_digest(cluster)
                if fresh != header["stateDigest"]:
                    raise CheckpointMismatch(
                        f"{path}: payload re-materializes to digest "
                        f"{fresh!r}, header claims "
                        f"{header['stateDigest']!r}; refusing this "
                        "generation"
                    )
                session.restore_state(cluster, header["deltaSeq"])
        except CheckpointMismatch as e:
            COUNTERS.inc("ckpt_restore_fallback_total")
            log.warning(
                "checkpoint generation refused, falling back to the "
                "previous one (longer replay, never silent wrong state): %s",
                e,
            )
            continue
        COUNTERS.inc("ckpt_restore_total")
        return {
            "deltaSeq": int(header["deltaSeq"]),
            "stateDigest": header["stateDigest"],
            "path": path,
        }
    if generations:
        log.warning(
            "all %d checkpoint generation(s) under %s refused; "
            "recovering by full journal replay",
            len(generations),
            checkpoint_dir(snapshot_path),
        )
    return None


def replay_into_session(session, path: str, use_checkpoints: bool = True) -> dict:
    """Bootstrap ``session`` from the snapshot at ``path``: checkpoint
    restore first (``use_checkpoints``), then replay the journal's
    delta suffix (deltas recorded against other cluster fingerprints
    are skipped — a multi-session snapshot replays only the primary's
    stream). Returns a summary dict: ``deltas`` REPLAYED for this
    fingerprint, ``applied``/``skipped``/``reloads`` from
    ``apply_delta``, ``skippedPrefix`` records absorbed by the restored
    checkpoint, ``checkpoint`` (the restore summary or None),
    ``dropped`` torn-tail lines, and the journaled ``requestIds`` (the
    X-Simon-Request-Id correlation carried across the failover)."""
    from ..serve.sessions import SNAPSHOT_VERSION
    from ..runtime.journal import config_fingerprint
    from ..twin.deltas import ClusterDelta

    t0 = time.perf_counter()
    _inject.fire("fleet.replay", path=path)
    restored = restore_into_session(session, path) if use_checkpoints else None
    base_seq = restored["deltaSeq"] if restored else 0
    fp = config_fingerprint(
        {"format": "serve-session-snapshot", "version": SNAPSHOT_VERSION}
    )
    records, dropped = read_session_events(path, fp)
    summary = {
        "deltas": 0,
        "applied": 0,
        "skipped": 0,
        "reloads": 0,
        "skippedPrefix": 0,
        "checkpoint": restored,
        "dropped": dropped,
        "requestIds": [],
    }
    for rec in records:
        if rec.get("kind") != "session" or rec.get("event") != "delta":
            continue
        if rec.get("fingerprint") != session.fingerprint:
            continue
        seq = rec.get("seq")
        if base_seq:
            if isinstance(seq, int):
                if seq <= base_seq:
                    summary["skippedPrefix"] += 1
                    continue
            else:
                # a pre-checkpoint-era record with no sequence: it was
                # in the journal when the checkpoint captured the
                # session, so it is absorbed — blind-applying it on
                # top of the restore would double-apply. Skipped LOUDLY.
                summary["skippedPrefix"] += 1
                COUNTERS.inc("fleet_replay_unsequenced_skipped_total")
                log.warning(
                    "unsequenced delta record in %s skipped after a "
                    "checkpoint restore at seq %d (absorbed by the "
                    "snapshot; re-applying would double-count)",
                    path,
                    base_seq,
                )
                continue
        summary["deltas"] += 1
        rid = rec.get("requestId")
        if rid:
            summary["requestIds"].append(rid)
        out = session.apply_delta(ClusterDelta.from_record(rec["delta"]))
        if out == "skipped":
            summary["skipped"] += 1
        else:
            summary["applied"] += 1
            if out == "reloaded":
                summary["reloads"] += 1
    COUNTERS.inc("fleet_replayed_deltas_total", summary["deltas"])
    COUNTERS.inc("fleet_replay_deltas_total", summary["deltas"])
    if summary["skippedPrefix"]:
        COUNTERS.inc(
            "ckpt_restore_deltas_skipped_total", summary["skippedPrefix"]
        )
    if dropped:
        COUNTERS.inc("fleet_replay_torn_tail_total", dropped)
    if restored:
        COUNTERS.gauge(
            "ckpt_restore_seconds", round(time.perf_counter() - t0, 6)
        )
    return summary
