"""The fleet router daemon behind ``simon fleet``.

A thin HTTP reverse proxy in front of N serve replicas. Design
posture: the router holds NO session state — replicas own sessions,
journals, and compiled executables; the router owns only the ring,
the health table, and the supervision loop — so the router itself is
trivially restartable and never on the zero-compile critical path.

- **Tenant-affine routing**: the routing key is the request's
  ``X-Simon-Cluster`` header (a cluster fingerprint) when present,
  else its tenant (``X-Simon-Tenant`` header or JSON ``tenant`` key),
  consistent-hashed over the slot ring (fleet/hashing.py). One
  tenant's warm session, committed scan, and delta journal live on
  ONE replica and stay there.
- **Failover, never silent drops**: the request body is buffered
  before forwarding, so a replica that dies mid-request is retried
  against the next slot in ``route_order`` with the ORIGINAL
  X-Simon-Request-Id. Replica answers — including 429/503 with their
  Retry-After — pass through verbatim plus an ``X-Simon-Fleet-
  Replica`` header naming the slot that answered. When no replica can
  answer, the router sheds with 503 + Retry-After and the request id
  in the body (the PR-11 shed contract), never a dropped connection.
- **Supervision**: a background loop probes each replica's /healthz
  through the ``fleet.probe`` seam, honors a degraded replica's
  Retry-After hint (backs off probing instead of hot-looping), and
  declares a replica dead after PROBE_FAILURE_THRESHOLD consecutive
  failures OR process exit — then respawns it into the same slot with
  capped-exponential backoff. The replacement resumes the slot's
  snapshot journal and replays its delta stream (fleet/replay.py), so
  it rejoins dict-identical and zero-compile.
- **Aggregated observability**: /metrics emits the router's own
  ``simon_fleet_*`` counters plus a cardinality-bounded allowlist of
  per-replica families scraped from each live replica and re-labeled
  ``{replica="<slot>"}`` (bounded: |allowlist| x N series, no tenant
  or request labels cross the aggregation). /healthz aggregates fleet
  readiness with the per-replica table; /v1/obs/snapshot feeds
  ``simon top``.
"""

from __future__ import annotations

import json
import logging
import signal
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from ..models.validation import InputError
from ..obs import telemetry
from ..obs.histo import HISTOS
from ..obs.spans import RECORDER
from ..runtime import inject as _inject
from ..runtime.errors import EXIT_OK, EXIT_PARTIAL_DEADLINE, GuardError
from ..utils.trace import COUNTERS
from .hashing import HashRing
from .replica import PROBE_FAILURE_THRESHOLD

log = logging.getLogger("simon.fleet")

#: per-replica metric families re-exported by the fleet /metrics
#: aggregation. An ALLOWLIST, not a passthrough: fleet cardinality is
#: bounded at |this list| x N replicas regardless of what a replica
#: exposes (per-tenant and per-site families deliberately excluded).
REPLICA_METRIC_ALLOWLIST = (
    "simon_serve_requests_total",
    "simon_serve_shed_total",
    "simon_serve_queue_depth",
    "simon_serve_batches_total",
    "simon_jax_recompiles_total",
    "simon_jax_dispatches_total",
    "simon_aot_store_hit_total",
    "simon_aot_store_save_total",
)

#: how long scraped replica metrics stay fresh before /metrics
#: re-scrapes (bounds scrape amplification: one fleet scrape costs at
#: most N replica scrapes per TTL window)
SCRAPE_TTL_S = 2.0

#: hop-by-hop headers never forwarded in either direction
_HOP_HEADERS = {
    "connection",
    "keep-alive",
    "transfer-encoding",
    "host",
    "content-length",
}


def _shed_body(reason: str, message: str, request_id: str) -> bytes:
    """The router's 503 shed body — same shape as the coalescer's
    partial_body so clients parse one schema fleet-wide."""
    return json.dumps(
        {
            "success": False,
            "partial": True,
            "reason": reason,
            "error": message,
            "requestId": request_id,
        },
        sort_keys=True,
    ).encode()


class FleetRouter:
    """Owns the ring, the replica table, the probe/respawn loop, and
    the proxy HTTP server."""

    def __init__(
        self,
        replicas: List,
        host: str = "127.0.0.1",
        port: int = 0,
        probe_interval_s: float = 2.0,
        drain_timeout_s: float = 30.0,
        forward_timeout_s: float = 120.0,
        slo_engine=None,
        obs_cadence_s: float = 1.0,
        supervise: bool = True,
        spawn_attempts: int = 4,
        audit=None,
    ):
        if not replicas:
            raise InputError("a fleet needs at least one replica")
        # failover audit timeline (fleet/audit.py) — optional: probe
        # flaps, death declarations, respawns, and the first 200 after
        # a failover are appended as fsync'd JSONL events
        self.audit = audit
        self.replicas = {r.slot: r for r in replicas}
        if len(self.replicas) != len(replicas):
            raise InputError("replica slots must be unique")
        self.ring = HashRing(sorted(self.replicas))
        self.probe_interval_s = probe_interval_s
        self.drain_timeout_s = drain_timeout_s
        self.forward_timeout_s = forward_timeout_s
        self.slo_engine = slo_engine
        self.supervise = supervise
        self.spawn_attempts = spawn_attempts
        self.telemetry = telemetry.TelemetryRuntime(
            cadence_s=obs_cadence_s, slo_engine=slo_engine
        )
        # health table: slot -> "up" | "degraded" | "down"; routing
        # consults it, the probe loop maintains it. A slot marked
        # down by a failed FORWARD is rerouted immediately — the
        # probe loop confirms and respawns asynchronously.
        self._health: Dict[str, str] = {s: "up" for s in self.replicas}
        self._health_lock = threading.Lock()
        self._next_probe: Dict[str, float] = {s: 0.0 for s in self.replicas}
        self._scrape_cache: Dict[str, tuple] = {}  # slot -> (t, text)
        self._shutdown = threading.Event()
        self._probe_thread: Optional[threading.Thread] = None
        router = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                log.debug("%s %s", self.address_string(), fmt % args)

            def _send(self, status, body, content_type="application/json", headers=()):
                self.send_response(status)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    status, reasons, table = router.readiness()
                    hdrs = ()
                    if reasons:
                        hdrs = (("Retry-After", str(router.retry_after_s())),)
                    self._send(
                        200,
                        json.dumps(
                            {
                                "ok": True,
                                "status": status,
                                "degraded": bool(reasons),
                                "reasons": reasons,
                                "replicas": table,
                                "sloAlerting": (
                                    router.slo_engine.alerting()
                                    if router.slo_engine is not None
                                    else []
                                ),
                                "draining": router._shutdown.is_set(),
                            },
                            sort_keys=True,
                        ).encode(),
                        headers=hdrs,
                    )
                elif self.path == "/metrics":
                    self._send(
                        200,
                        render_fleet_metrics(router),
                        content_type="text/plain; version=0.0.4",
                    )
                elif self.path.startswith("/v1/obs/series"):
                    status, doc = telemetry.series_endpoint(self.path)
                    self._send(status, json.dumps(doc, sort_keys=True).encode())
                elif self.path.startswith("/v1/fleet/trace"):
                    from .trace import trace_endpoint

                    status, doc = trace_endpoint(router, self.path)
                    self._send(status, json.dumps(doc, sort_keys=True).encode())
                elif self.path == "/v1/obs/snapshot":
                    self._send(
                        200,
                        json.dumps(
                            telemetry.snapshot_doc(
                                router.slo_engine,
                                runtime=router.telemetry,
                                extra={
                                    "daemon": "fleet",
                                    "health": router.readiness()[0],
                                    "replicas": {
                                        s: router._health.get(s, "down")
                                        for s in router.replicas
                                    },
                                },
                            ),
                            sort_keys=True,
                        ).encode(),
                    )
                else:
                    self._proxy("GET")

            def do_POST(self):
                if self.path == "/debug/dump":
                    length = int(self.headers.get("Content-Length") or 0)
                    status, doc = telemetry.handle_debug_dump(
                        self.rfile.read(length),
                        slo_engine=router.slo_engine,
                        runtime=router.telemetry,
                        label="fleet",
                    )
                    self._send(status, json.dumps(doc, sort_keys=True).encode())
                    return
                self._proxy("POST")

            def _proxy(self, method: str):
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                rid = telemetry.ensure_request_id(
                    self.headers.get(telemetry.REQUEST_ID_HEADER)
                )
                status, resp_body, headers = router.route_and_forward(
                    method, self.path, body, dict(self.headers.items()), rid
                )
                self._send(status, resp_body, headers=headers)

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.httpd.daemon_threads = True
        self.host, self.port = self.httpd.server_address[:2]
        self._server_thread = threading.Thread(
            target=self.httpd.serve_forever,
            name="simon-fleet-http",
            daemon=True,
        )

    # -- routing -------------------------------------------------------------

    @staticmethod
    def routing_key(headers: Dict[str, str], body: bytes) -> str:
        """Cluster fingerprint when the client names one, else the
        tenant — the affinity key that keeps one tenant's warm state
        on one replica."""
        lower = {k.lower(): v for k, v in headers.items()}
        if lower.get("x-simon-cluster"):
            return lower["x-simon-cluster"]
        if lower.get("x-simon-tenant"):
            return lower["x-simon-tenant"]
        if body:
            try:
                doc = json.loads(body.decode("utf-8"))
                if isinstance(doc, dict) and doc.get("tenant"):
                    return str(doc["tenant"])
            except ValueError:
                # unparseable body: not an error — route by default key
                log.debug("routing body is not JSON; using default tenant")
        return "default"

    def route_and_forward(
        self,
        method: str,
        path: str,
        body: bytes,
        headers: Dict[str, str],
        rid: str,
    ):
        """Try every live slot in ring order for this key; a dead or
        unreachable replica is marked down and the NEXT slot gets the
        same body with the same request id. Returns
        ``(status, body, header_tuples)``. Exhaustion sheds 503 +
        Retry-After — the caller always gets an answer.

        The whole attempt sequence is one ``fleet/request`` span tree
        under the request's id: each live forward is a
        ``fleet/forward`` child (its span id crosses the wire in
        ``X-Simon-Trace-Context`` so the replica's ``serve/request``
        subtree stitches under it — fleet/trace.py), each failed
        attempt a ``fleet/reroute`` sibling, an exhaustion shed a
        ``fleet/shed`` leaf."""
        COUNTERS.inc("fleet_requests_total")
        key = self.routing_key(headers, body)
        order = self.ring.route_order(key)
        rid_header = (telemetry.REQUEST_ID_HEADER, rid)
        # a chained router hop arrives with its own trace context:
        # remember it as the root's remote parent and keep counting hops
        in_parent, in_hop = telemetry.parse_trace_context(
            headers.get(telemetry.TRACE_CONTEXT_HEADER)
        )
        root_attrs = {"method": method, "key": key}
        if in_parent is not None:
            root_attrs["remote_parent"] = in_parent
            root_attrs["fleet_hop"] = in_hop
        attempted = 0
        with telemetry.request_scope(rid), RECORDER.span(
            "fleet/request", **root_attrs
        ) as root:
            for slot in order:
                replica = self.replicas.get(slot)
                if replica is None or not replica.url:
                    continue
                if self._health.get(slot) == "down":
                    continue
                if slot != order[0] or attempted:
                    # not the key's owner (owner down/skipped) or a retry
                    # after a failed forward — either way a reroute
                    COUNTERS.inc("fleet_reroutes_total")
                attempted += 1
                t_attempt = time.perf_counter()
                try:
                    _inject.fire("fleet.route", slot=slot, key=key)
                    return self._forward(
                        replica, method, path, body, headers, rid,
                        hop=in_hop + 1, attempt=attempted,
                    )
                except (OSError, urllib.error.URLError, GuardError) as e:
                    # connection-level failure (or a classified fault fired
                    # at the fleet.route seam): the replica never produced
                    # an HTTP answer, so retrying elsewhere cannot double-
                    # apply anything. Mark it down; the probe loop will
                    # confirm death and respawn into the slot.
                    log.warning(
                        "replica %s unreachable (%s); rerouting %s",
                        slot, e, rid,
                    )
                    RECORDER.record_span(
                        "fleet/reroute",
                        t_attempt,
                        time.perf_counter(),
                        parent_id=root,
                        slot=slot,
                        attempt=attempted,
                        error=type(e).__name__,
                    )
                    self._mark(slot, "down")
                    COUNTERS.inc("fleet_forward_failures_total")
                    continue
            COUNTERS.inc("fleet_shed_total")
            t_shed = time.perf_counter()
            RECORDER.record_span(
                "fleet/shed", t_shed, t_shed,
                parent_id=root, attempts=attempted,
            )
            return (
                503,
                _shed_body(
                    "fleet",
                    "no live replica could answer (fleet saturated or "
                    "restarting); retry after the hinted delay",
                    rid,
                ),
                (rid_header, ("Retry-After", str(self.retry_after_s()))),
            )

    def _forward(
        self, replica, method, path, body, headers, rid, hop=1, attempt=1
    ):
        """One proxied hop. HTTP error statuses are ANSWERS (a 429's
        Retry-After must reach the client untouched), so urllib's
        HTTPError is converted, never retried. The forward span's id
        crosses the wire as trace context; the reply always carries
        the request id back even when the replica's answer (a proxied
        GET, an old replica) didn't echo it."""
        fwd = {
            k: v
            for k, v in headers.items()
            if k.lower() not in _HOP_HEADERS
        }
        fwd[telemetry.REQUEST_ID_HEADER] = rid
        t0 = time.perf_counter()
        with RECORDER.span(
            "fleet/forward", slot=replica.slot, attempt=attempt
        ) as fwd_sid:
            if fwd_sid is not None:
                fwd[telemetry.TRACE_CONTEXT_HEADER] = (
                    telemetry.format_trace_context(fwd_sid, hop=hop)
                )
            else:
                fwd.pop(telemetry.TRACE_CONTEXT_HEADER, None)
            req = urllib.request.Request(
                replica.url + path,
                data=body if method == "POST" else None,
                headers=fwd,
                method=method,
            )
            try:
                resp = urllib.request.urlopen(
                    req, timeout=self.forward_timeout_s
                )
            except urllib.error.HTTPError as e:
                resp = e  # an answered error status, not a transport fault
            with resp:
                out_body = resp.read()
                out_headers = [
                    (k, v)
                    for k, v in resp.headers.items()
                    if k.lower() not in _HOP_HEADERS
                    and k.lower() != "content-type"
                ]
            out_headers.append(("X-Simon-Fleet-Replica", replica.slot))
            if not any(
                k.lower() == telemetry.REQUEST_ID_HEADER.lower()
                for k, _ in out_headers
            ):
                out_headers.append((telemetry.REQUEST_ID_HEADER, rid))
            COUNTERS.inc(f"fleet_replica_requests:{replica.slot}")
            HISTOS.observe(
                f"fleet/forward/{replica.slot}", time.perf_counter() - t0
            )
            self._update_imbalance_gauge()
            self._note_answer(replica.slot, resp.status)
            return resp.status, out_body, tuple(out_headers)

    def _update_imbalance_gauge(self) -> None:
        """``fleet_slot_imbalance`` gauge: max over slots of
        (slot's cumulative answered requests / fleet mean) − 1 — 0.0
        is a perfectly balanced ring, 1.0 means the hottest slot
        carries double the mean. Sampled into the series store each
        telemetry cadence, judged by the ``fleet_imbalance`` SLO
        kind."""
        counts = [
            COUNTERS.get(f"fleet_replica_requests:{slot}")
            for slot in self.replicas
        ]
        total = sum(counts)
        if total <= 0 or not counts:
            return
        mean = total / len(counts)
        COUNTERS.gauge("fleet_slot_imbalance", max(counts) / mean - 1.0)

    def _note_answer(self, slot: str, status: int) -> None:
        """Audit hook: the first 2xx answered through a slot with a
        pending failover closes that slot's audit timeline."""
        audit = getattr(self, "audit", None)
        if audit is not None and 200 <= int(status) < 300:
            audit.note_first_200(slot)

    # -- health / supervision ------------------------------------------------

    def _mark(self, slot: str, state: str):
        with self._health_lock:
            prev = self._health.get(slot)
            self._health[slot] = state
        if state == "down" and prev != "down":
            COUNTERS.inc("fleet_replica_down_total")
            self._next_probe[slot] = 0.0  # probe loop reacts now

    def retry_after_s(self) -> int:
        """The shed/degraded backoff hint: the largest hint any
        replica advertised, floored at the probe interval (a respawn
        cannot complete faster than the loop that notices the death)."""
        hints = [
            getattr(r, "retry_after_s", 0) or 0 for r in self.replicas.values()
        ]
        return max(1, int(round(self.probe_interval_s)), *hints)

    def readiness(self):
        """-> (status, reasons, per-replica table). Degraded while any
        slot is down/degraded or fleet SLOs alert; the table is what
        CI and ``simon top`` read to find each replica's url/pid."""
        reasons = []
        table = []
        for slot in sorted(self.replicas):
            r = self.replicas[slot]
            state = self._health.get(slot, "down")
            table.append(
                {
                    "id": slot,
                    "url": r.url,
                    "status": state,
                    "pid": getattr(r, "pid", None),
                    "restarts": getattr(r, "restarts", 0),
                    "probeFailures": getattr(r, "probe_failures", 0),
                }
            )
            if state != "up":
                reasons.append(f"replica {slot} is {state}")
        if self.slo_engine is not None:
            reasons.extend(self.slo_engine.reasons())
        return ("degraded" if reasons else "ok"), reasons, table

    def probe_once(self, now: Optional[float] = None) -> None:
        """One supervision pass: probe due replicas, honor degraded
        Retry-After hints, respawn dead process-backed replicas with
        backoff. Called by the probe loop; callable directly in tests
        (deterministic, no sleeps of its own)."""
        now = time.monotonic() if now is None else now
        for slot in sorted(self.replicas):
            replica = self.replicas[slot]
            if now < self._next_probe.get(slot, 0.0):
                continue
            dead = hasattr(replica, "alive") and not replica.alive()
            dead_reason = "process exited" if dead else ""
            if not dead:
                try:
                    _inject.fire("fleet.probe", slot=slot)
                    doc = replica.probe()
                except GuardError as e:  # the fleet.probe seam's faults
                    doc = {"probeOk": False, "error": str(e)}
                    replica.probe_failures += 1
                    COUNTERS.inc("fleet_probe_failures_total")
                if doc.get("probeOk"):
                    state = "degraded" if doc.get("degraded") else "up"
                    self._mark(slot, state)
                    if self.audit is not None:
                        self.audit.note_probe_ok(slot)
                    hint = getattr(replica, "retry_after_s", 0)
                    wait = max(self.probe_interval_s, float(hint or 0))
                    self._next_probe[slot] = now + wait
                    continue
                if self.audit is not None:
                    self.audit.note_probe_flap(
                        slot, failures=replica.probe_failures
                    )
                dead = (
                    replica.probe_failures >= PROBE_FAILURE_THRESHOLD
                    or (hasattr(replica, "alive") and not replica.alive())
                )
                dead_reason = (
                    f"{replica.probe_failures} consecutive probe failures"
                )
                if not dead:
                    # flaky probe: keep routing to it, probe again soon
                    self._next_probe[slot] = now + self.probe_interval_s
                    continue
            self._mark(slot, "down")
            if self.audit is not None:
                self.audit.note_declared_dead(slot, reason=dead_reason)
            if not (self.supervise and hasattr(replica, "spawn")):
                self._next_probe[slot] = now + self.probe_interval_s
                continue
            self._failover(replica)
            self._next_probe[slot] = time.monotonic() + self.probe_interval_s

    def _failover(self, replica) -> None:
        """Respawn a dead replica into its slot. The slot keeps its
        ring position (zero key movement) and its snapshot journal
        (the replacement replays the dead replica's delta stream)."""
        slot = replica.slot
        COUNTERS.inc("fleet_failovers_total")
        log.warning("replica %s is down; respawning into its slot", slot)
        replica.kill()  # reap a half-dead process before reclaiming
        replica.release()
        if self.audit is not None:
            self.audit.note_lock_reclaim(slot)
        replica.restarts += 1
        replica.probe_failures = 0
        try:
            replica.spawn(attempts=self.spawn_attempts)
        except Exception as e:  # noqa: BLE001 - the loop retries next pass
            log.error("respawn of %s failed: %s", slot, e)
            COUNTERS.inc("fleet_respawn_failures_total")
            if self.audit is not None:
                self.audit.note_respawn(slot, ok=False, error=str(e))
            return
        self._mark(slot, "up")
        COUNTERS.inc("fleet_respawns_total")
        if self.audit is not None:
            self.audit.note_respawn(
                slot, ok=True, pid=getattr(replica, "pid", None)
            )
            self.audit.note_replay_progress(
                slot, delta_seq=self._fetch_delta_seq(replica)
            )

    def _fetch_delta_seq(self, replica) -> Optional[int]:
        """The replacement's replayed delta sequence from its
        state-digest endpoint — audit evidence that journal replay
        finished, best-effort (None when unreachable)."""
        if not replica.url:
            return None
        try:
            with urllib.request.urlopen(
                replica.url + "/v1/state-digest", timeout=5.0
            ) as resp:
                doc = json.loads(resp.read().decode("utf-8"))
            return int(doc.get("deltaSeq"))
        except (OSError, urllib.error.URLError, ValueError, TypeError):
            return None

    def _probe_loop(self):
        while not self._shutdown.is_set():
            try:
                self.probe_once()
            except Exception:  # noqa: BLE001 - supervision must not die
                log.exception("fleet probe pass failed")
            self._shutdown.wait(min(0.2, self.probe_interval_s))

    # -- metrics scrape ------------------------------------------------------

    def scrape_replica(self, replica) -> str:
        """A replica's /metrics text, cached for SCRAPE_TTL_S."""
        now = time.monotonic()
        cached = self._scrape_cache.get(replica.slot)
        if cached is not None and now - cached[0] < SCRAPE_TTL_S:
            return cached[1]
        if not replica.url or self._health.get(replica.slot) == "down":
            return ""
        try:
            with urllib.request.urlopen(
                replica.url + "/metrics", timeout=self.forward_timeout_s
            ) as resp:
                text = resp.read().decode("utf-8", "replace")
        except (OSError, urllib.error.URLError):
            return ""
        self._scrape_cache[replica.slot] = (now, text)
        return text

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        self.telemetry.start()
        self._server_thread.start()
        if self.probe_interval_s > 0:
            self._probe_thread = threading.Thread(
                target=self._probe_loop, name="simon-fleet-probe", daemon=True
            )
            self._probe_thread.start()
        log.info("simon fleet listening on %s:%d", self.host, self.port)

    def begin_shutdown(self):
        self._shutdown.set()

    def shutdown(self) -> int:
        """Drain the fleet: stop probing (no respawns during drain),
        SIGTERM every process-backed replica, wait for their drains,
        release slot locks. Exit 0 when every replica drained in time,
        3 when one had to be killed."""
        self.begin_shutdown()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=5.0)
        clean = True
        deadline = time.monotonic() + self.drain_timeout_s
        for r in self.replicas.values():
            if hasattr(r, "terminate"):
                r.terminate()
        for r in self.replicas.values():
            if not hasattr(r, "wait"):
                continue
            rc = r.wait(max(0.1, deadline - time.monotonic()))
            if rc is None:
                log.warning(
                    "replica %s did not drain in time; killing", r.slot
                )
                r.kill()
                clean = False
            elif rc != 0:
                log.warning("replica %s drained with rc=%d", r.slot, rc)
                clean = False
            if hasattr(r, "release"):
                r.release()
        self.telemetry.stop()
        if self.audit is not None:
            self.audit.close()
        self.httpd.shutdown()
        self.httpd.server_close()
        return EXIT_OK if clean else EXIT_PARTIAL_DEADLINE

    def run_until_signaled(self) -> int:
        def handler(signum, frame):
            log.info("received signal %d: draining fleet", signum)
            self._wake.set()

        self._wake = threading.Event()
        prev_term = signal.signal(signal.SIGTERM, handler)
        prev_int = signal.signal(signal.SIGINT, handler)
        try:
            self._wake.wait()
            return self.shutdown()
        finally:
            signal.signal(signal.SIGTERM, prev_term)
            signal.signal(signal.SIGINT, prev_int)


# -- exposition --------------------------------------------------------------


def render_fleet_metrics(router: FleetRouter) -> bytes:
    """Prometheus exposition of the router's own counters plus the
    cardinality-bounded per-replica re-export (one sample per
    allowlisted family per live replica, labeled ``{replica="rN"}``)."""
    from ..serve.server import _escape_label

    snap = COUNTERS.snapshot()
    counts = snap["counts"]
    lines: List[str] = []

    def metric(name, kind, help_text, value):
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {kind}")
        lines.append(f"{name} {value}")

    metric(
        "simon_fleet_requests_total", "counter",
        "Requests accepted by the fleet router (any outcome).",
        counts.get("fleet_requests_total", 0),
    )
    metric(
        "simon_fleet_reroutes_total", "counter",
        "Requests retried against another replica after a forward failure.",
        counts.get("fleet_reroutes_total", 0),
    )
    metric(
        "simon_fleet_shed_total", "counter",
        "Requests shed 503 because no live replica could answer.",
        counts.get("fleet_shed_total", 0),
    )
    metric(
        "simon_fleet_forward_failures_total", "counter",
        "Connection-level forward failures (replica marked down).",
        counts.get("fleet_forward_failures_total", 0),
    )
    metric(
        "simon_fleet_failovers_total", "counter",
        "Replica deaths detected by the supervision loop.",
        counts.get("fleet_failovers_total", 0),
    )
    metric(
        "simon_fleet_respawns_total", "counter",
        "Replacement replicas successfully spawned into a slot.",
        counts.get("fleet_respawns_total", 0),
    )
    metric(
        "simon_fleet_respawn_failures_total", "counter",
        "Failover respawns that exhausted their spawn attempts.",
        counts.get("fleet_respawn_failures_total", 0),
    )
    metric(
        "simon_fleet_spawn_total", "counter",
        "Replica child processes launched (initial + respawns).",
        counts.get("fleet_spawn_total", 0),
    )
    metric(
        "simon_fleet_spawn_retry_total", "counter",
        "Spawn attempts that failed and were retried with backoff.",
        counts.get("fleet_spawn_retry_total", 0),
    )
    metric(
        "simon_fleet_probe_failures_total", "counter",
        "Health probes that failed (connection error or injected fault).",
        counts.get("fleet_probe_failures_total", 0),
    )
    metric(
        "simon_fleet_replayed_deltas_total", "counter",
        "Cluster deltas replayed into bootstrapping sessions.",
        counts.get("fleet_replayed_deltas_total", 0),
    )
    metric(
        "simon_fleet_replay_torn_tail_total", "counter",
        "Torn journal tails dropped during bootstrap replay.",
        counts.get("fleet_replay_torn_tail_total", 0),
    )
    up = sum(1 for s in router.replicas if router._health.get(s) == "up")
    metric(
        "simon_fleet_replicas", "gauge",
        "Configured replica slots.", len(router.replicas),
    )
    metric(
        "simon_fleet_replicas_up", "gauge",
        "Replica slots currently routable.", up,
    )

    # -- per-replica series (bounded: a few fixed families x N slots)
    lines.append(
        "# HELP simon_fleet_replica_up Replica routability (1 up, 0 not)."
    )
    lines.append("# TYPE simon_fleet_replica_up gauge")
    for slot in sorted(router.replicas):
        v = 1 if router._health.get(slot) == "up" else 0
        lines.append(
            f'simon_fleet_replica_up{{replica="{_escape_label(slot)}"}} {v}'
        )
    lines.append(
        "# HELP simon_fleet_replica_requests_total Requests answered per "
        "replica (router-side count)."
    )
    lines.append("# TYPE simon_fleet_replica_requests_total counter")
    for slot in sorted(router.replicas):
        n = counts.get(f"fleet_replica_requests:{slot}", 0)
        lines.append(
            "simon_fleet_replica_requests_total"
            f'{{replica="{_escape_label(slot)}"}} {n}'
        )

    scraped: Dict[str, List[str]] = {name: [] for name in REPLICA_METRIC_ALLOWLIST}
    for slot in sorted(router.replicas):
        text = router.scrape_replica(router.replicas[slot])
        if not text:
            continue
        for line in text.splitlines():
            if line.startswith("#") or not line.strip():
                continue
            name, _, value = line.partition(" ")
            if name in scraped:
                scraped[name].append(
                    f'simon_fleet_{name[len("simon_"):]}'
                    f'{{replica="{_escape_label(slot)}"}} {value}'
                )
    for name in REPLICA_METRIC_ALLOWLIST:
        if not scraped[name]:
            continue
        short = name[len("simon_"):]
        lines.append(
            f"# HELP simon_fleet_{short} Per-replica re-export of {name}."
        )
        lines.append(f"# TYPE simon_fleet_{short} untyped")
        lines.extend(scraped[name])
    # staleness of the TTL-cached aggregation itself: age of the OLDEST
    # cached replica scrape (0 with an empty cache). Also pushed as a
    # gauge so the series store / SLO engine can watch it.
    now = time.monotonic()
    ages = [now - t for (t, _) in router._scrape_cache.values()]
    cache_age = round(max(ages), 3) if ages else 0.0
    COUNTERS.gauge("fleet_metrics_cache_age_seconds", cache_age)
    metric(
        "simon_fleet_metrics_cache_age_seconds", "gauge",
        "Age of the oldest cached replica /metrics scrape (TTL "
        f"{SCRAPE_TTL_S}s).",
        cache_age,
    )
    metric(
        "simon_fleet_slot_imbalance", "gauge",
        "Hottest slot's answered-request share over the fleet mean, "
        "minus one (0 = balanced).",
        round(snap["gauges"].get("fleet_slot_imbalance", 0.0), 6),
    )
    metric(
        "simon_fleet_failovers_audited_total", "counter",
        "Failover episodes closed by the audit timeline.",
        counts.get("fleet_failovers_audited_total", 0),
    )
    metric(
        "simon_fleet_failover_ms_total", "counter",
        "Cumulative audited failover duration (integer milliseconds).",
        counts.get("fleet_failover_ms_total", 0),
    )
    metric(
        "simon_fleet_failover_seconds", "gauge",
        "Total duration of the most recently audited failover episode.",
        round(snap["gauges"].get("fleet_failover_seconds", 0.0), 6),
    )
    # per-phase breakdown of the last audited episode (bounded: the
    # fixed 5-phase partition, absent until a failover has been audited)
    from .audit import PHASE_DURATIONS

    phase_lines = []
    for phase in PHASE_DURATIONS:
        v = snap["gauges"].get(f"fleet_failover_phase_seconds:{phase}")
        if v is not None:
            phase_lines.append(
                "simon_fleet_failover_phase_seconds"
                f'{{phase="{_escape_label(phase)}"}} {round(v, 6)}'
            )
    if phase_lines:
        lines.append(
            "# HELP simon_fleet_failover_phase_seconds Last audited "
            "episode's per-phase durations (they partition the total)."
        )
        lines.append("# TYPE simon_fleet_failover_phase_seconds gauge")
        lines.extend(phase_lines)
    if router.slo_engine is not None:
        lines.extend(router.slo_engine.prometheus_lines())
    return ("\n".join(lines) + "\n").encode()
