"""Failover audit timeline: a durable, phase-by-phase recovery log.

``fleet.failover_seconds`` (bench.py, doctor-gated since r16) is one
opaque number — kill-9 to the next 200 through the router. When it
regresses, the first question is WHICH phase got slow: did the probe
loop take longer to notice, did the slot lock linger, did the respawn
crawl, or did journal replay balloon? This module answers that with a
structured audit log the router appends as supervision happens:

    probe_flap -> declared_dead -> lock_reclaim -> respawn
        -> replay_progress -> first_200

- **Durable by construction**: JSONL, one fsync'd line per event,
  with a validated header line — the same torn-tail-tolerant journal
  discipline as fleet/replay.py (a crash mid-append loses at most the
  line being written, never corrupts the readable prefix).
- **Episodes**: one failover episode per (slot, episode#) opens at the
  first probe flap (or straight at death for a process exit), closes
  at the first 2xx answered through the respawned slot. A flap that
  recovers without a death closes as ``recovered`` — flap noise is
  visible but never counted as a failover.
- **Per-phase series**: closing an episode computes the phase
  durations that PARTITION the episode (they sum to the total by
  construction), publishes them as gauges/counters the router's
  telemetry runtime samples into the series store, and feeds the
  doctor's ``fleet.failover_phases.*`` breakdown via bench.py.

``validate_audit_log`` (and ``tools/validate_audit.py``) is the CI
gate: header intact, phases known and time-ordered, every complete
episode's durations summing to its total.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

from ..models.validation import InputError
from ..utils.trace import COUNTERS

#: event phases in causal order; durations partition the episode
PHASES = (
    "probe_flap",
    "declared_dead",
    "lock_reclaim",
    "respawn",
    "replay_progress",
    "first_200",
)
#: the per-phase DURATION names (summary "phases" dict keys): each
#: measures the gap from the previous checkpoint to the named one
PHASE_DURATIONS = (
    "detect",      # first flap (or death) -> declared dead
    "reclaim",     # declared dead -> slot lock reclaimed
    "respawn",     # lock reclaimed -> replacement listening
    "replay",      # listening -> journal replay confirmed (delta seq)
    "first_200",   # replay confirmed -> first 2xx through the slot
)
_DURATION_OF = dict(zip(PHASES[1:], PHASE_DURATIONS))

AUDIT_KIND = "simon-fleet-audit"
AUDIT_VERSION = 1
#: events other than the six phases that may appear in a valid log
_META_PHASES = ("recovered", "failover_complete")


class FailoverAudit:
    """Append-only fsync'd JSONL failover audit log plus the live
    episode state machine. Thread-safe: the probe loop appends phases
    while forward threads call ``note_first_200`` on every answer
    (cheap no-op unless the slot has a pending failover)."""

    def __init__(self, path: str, clock=time.monotonic, wall=time.time):
        self.path = path
        self._clock = clock
        self._wall = wall
        self._lock = threading.Lock()
        # slot -> open episode {"episode", "marks": {phase: mono}, "dead": bool}
        self._open: Dict[str, dict] = {}
        self._episode_counter: Dict[str, int] = {}
        #: completed episode summaries, oldest first (bench reads the
        #: newest for the doctor's phase breakdown)
        self.completed: List[dict] = []
        fresh = not os.path.exists(path)
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")  # noqa: SIM115 - long-lived journal handle, closed in close()
        if fresh or os.path.getsize(path) == 0:
            self._append(
                {
                    "kind": AUDIT_KIND,
                    "version": AUDIT_VERSION,
                    "createdAt": self._wall(),
                }
            )

    # -- the fsync'd append --------------------------------------------------

    # audited: called WITH self._lock held by every note_* path — the
    # event order on disk must match the state machine's order
    def _append(self, doc: dict) -> None:  # simonlint: disable=CONC001
        self._f.write(json.dumps(doc, sort_keys=True) + "\n")
        self._f.flush()
        os.fsync(self._f.fileno())

    # audited: _clock/_wall are set once in __init__ and never
    # reassigned — reading them without the lock is race-free
    def _event(self, slot: str, phase: str, **extra) -> dict:  # simonlint: disable=CONC001
        doc = {
            "slot": slot,
            "phase": phase,
            "t": round(self._wall(), 6),
            "mono": round(self._clock(), 6),
        }
        doc.update({k: v for k, v in extra.items() if v is not None})
        return doc

    # -- episode state machine ------------------------------------------------

    # audited: called WITH self._lock held by _mark — split out only
    # to keep the state machine readable
    def _open_episode(self, slot: str) -> dict:  # simonlint: disable=CONC001
        ep = self._episode_counter.get(slot, 0) + 1
        self._episode_counter[slot] = ep
        state = {"episode": ep, "marks": {}, "dead": False}
        self._open[slot] = state
        return state

    # audited CONC002: the fsync'd append happens under the lock ON
    # PURPOSE — the on-disk event order IS the state machine's order;
    # audit events are rare (supervision cadence, not the hot path)
    def _mark(self, slot: str, phase: str, **extra) -> None:  # simonlint: disable=CONC002
        with self._lock:
            state = self._open.get(slot)
            if state is None:
                state = self._open_episode(slot)
            doc = self._event(slot, phase, episode=state["episode"], **extra)
            # first occurrence wins: repeated flaps (or respawn
            # retries) extend the log, not the checkpoint
            state["marks"].setdefault(phase, doc["mono"])
            if phase == "declared_dead":
                state["dead"] = True
            self._append(doc)

    def note_probe_flap(self, slot: str, failures: int = 0) -> None:
        self._mark(slot, "probe_flap", failures=failures)

    # audited CONC002: see _mark — ordered fsync under the lock is the
    # journal discipline, and probe events are supervision-cadence rare
    def note_probe_ok(self, slot: str) -> None:  # simonlint: disable=CONC002
        """A healthy probe closes a flap-only episode as recovered —
        no failover happened, the flaps stay on the record."""
        with self._lock:
            state = self._open.get(slot)
            if state is None or state["dead"]:
                return
            self._append(
                self._event(slot, "recovered", episode=state["episode"])
            )
            del self._open[slot]

    def note_declared_dead(self, slot: str, reason: str = "") -> None:
        self._mark(slot, "declared_dead", reason=reason or None)

    def note_lock_reclaim(self, slot: str) -> None:
        self._mark(slot, "lock_reclaim")

    # audited CONC002: see _mark — ordered fsync under the lock
    def note_respawn(  # simonlint: disable=CONC002
        self,
        slot: str,
        ok: bool = True,
        pid: Optional[int] = None,
        error: str = "",
    ) -> None:
        if not ok:
            # a failed spawn attempt is an event, not a checkpoint:
            # the phase clock keeps running until a spawn SUCCEEDS
            with self._lock:
                state = self._open.get(slot)
                if state is None:
                    return
                self._append(
                    self._event(
                        slot,
                        "respawn_failed",
                        episode=state["episode"],
                        error=error or None,
                    )
                )
            return
        self._mark(slot, "respawn", pid=pid)

    def note_replay_progress(
        self, slot: str, delta_seq: Optional[int] = None
    ) -> None:
        self._mark(slot, "replay_progress", deltaSeq=delta_seq)

    # audited: lock-free read of a dict the GIL keeps coherent — a
    # stale answer only delays the episode close by one forward
    def pending(self, slot: str) -> bool:  # simonlint: disable=CONC001
        """Whether the slot has a declared-dead episode awaiting its
        first 200 (the router's forward path checks this cheaply)."""
        state = self._open.get(slot)
        return bool(state and state["dead"])

    # audited CONC002: see _mark — ordered fsync under the lock; the
    # fast path (no pending episode) returns before any I/O
    def note_first_200(self, slot: str) -> Optional[dict]:  # simonlint: disable=CONC002
        """Close the slot's pending failover episode at its first
        2xx: emit the ``failover_complete`` summary (phase durations
        partitioning first-event -> first-200) and publish the
        duration gauges/counters. Returns the summary, or None when
        no failover was pending."""
        with self._lock:
            state = self._open.get(slot)
            if state is None or not state["dead"]:
                return None
            now = self._clock()
            marks = dict(state["marks"])
            marks["first_200"] = now
            start = min(marks.values())
            total = max(now - start, 0.0)
            phases: Dict[str, float] = {}
            prev = start
            for phase in PHASES[1:]:
                dur_name = _DURATION_OF[phase]
                at = marks.get(phase)
                if at is None:
                    phases[dur_name] = 0.0
                    continue
                phases[dur_name] = round(max(at - prev, 0.0), 6)
                prev = at
            summary = self._event(
                slot,
                "failover_complete",
                episode=state["episode"],
                totalSeconds=round(total, 6),
                phases=phases,
            )
            self._append(summary)
            self.completed.append(summary)
            del self._open[slot]
        COUNTERS.gauge("fleet_failover_seconds", round(total, 6))
        COUNTERS.inc(
            "fleet_failover_ms_total", max(int(round(total * 1000)), 1)
        )
        COUNTERS.inc("fleet_failovers_audited_total")
        for name, dur in phases.items():
            COUNTERS.gauge(f"fleet_failover_phase_seconds:{name}", dur)
        return summary

    def close(self) -> None:
        with self._lock:
            try:
                self._f.close()
            except OSError:  # noqa: S110 - closing a dying journal is best-effort
                pass


# -- validation ---------------------------------------------------------------


def read_audit_log(path: str) -> tuple:
    """``(events, torn_tail)``: every parseable event line after the
    validated header. The LAST line may be torn (crash mid-append) and
    is dropped + counted; interior damage raises InputError — same
    posture as fleet/replay.py."""
    with open(path, encoding="utf-8") as f:
        lines = f.read().splitlines()
    if not lines:
        raise InputError(f"{path}: empty audit log (missing header)")
    try:
        header = json.loads(lines[0])
    except ValueError:
        raise InputError(f"{path}: audit header line is not JSON") from None
    if (
        not isinstance(header, dict)
        or header.get("kind") != AUDIT_KIND
        or header.get("version") != AUDIT_VERSION
    ):
        raise InputError(
            f"{path}: not a {AUDIT_KIND} v{AUDIT_VERSION} log "
            f"(header {str(header)[:120]!r})"
        )
    events: List[dict] = []
    torn = 0
    for i, line in enumerate(lines[1:], start=2):
        if not line.strip():
            continue
        try:
            doc = json.loads(line)
        except ValueError:
            if i == len(lines):
                torn = 1  # torn tail: drop, count, keep the prefix
                break
            raise InputError(
                f"{path}:{i}: interior audit line is not JSON"
            ) from None
        if not isinstance(doc, dict):
            raise InputError(f"{path}:{i}: audit event is not an object")
        events.append(doc)
    return events, torn


def validate_audit_log(
    path: str, sum_tolerance_s: float = 0.05
) -> dict:
    """Structural + arithmetic validation of one audit log. Checks:
    known phases only, per-episode monotone timestamps in causal
    order, and — for every ``failover_complete`` — all five phase
    durations present, non-negative, and summing to ``totalSeconds``
    within ``sum_tolerance_s``. Returns a summary dict; raises
    InputError on any violation."""
    events, torn = read_audit_log(path)
    known = set(PHASES) | set(_META_PHASES) | {"respawn_failed"}
    episodes: Dict[tuple, List[dict]] = {}
    for i, e in enumerate(events):
        phase = e.get("phase")
        if phase not in known:
            raise InputError(f"{path}: unknown phase {phase!r} (event {i})")
        slot = e.get("slot")
        if not isinstance(slot, str) or not slot:
            raise InputError(f"{path}: event {i} has no slot")
        if not isinstance(e.get("mono"), (int, float)):
            raise InputError(f"{path}: event {i} has no mono timestamp")
        episodes.setdefault((slot, e.get("episode")), []).append(e)
    complete = 0
    for (slot, ep), evs in sorted(episodes.items(), key=lambda kv: str(kv[0])):
        marks = {}
        for e in evs:
            marks.setdefault(e["phase"], float(e["mono"]))
        order = [marks[p] for p in PHASES if p in marks]
        if order != sorted(order):
            raise InputError(
                f"{path}: episode {slot}/{ep}: phases out of causal order"
            )
        summaries = [e for e in evs if e["phase"] == "failover_complete"]
        if len(summaries) > 1:
            raise InputError(
                f"{path}: episode {slot}/{ep}: {len(summaries)} summaries"
            )
        if not summaries:
            continue
        s = summaries[0]
        phases = s.get("phases")
        total = s.get("totalSeconds")
        if not isinstance(phases, dict) or not isinstance(
            total, (int, float)
        ):
            raise InputError(
                f"{path}: episode {slot}/{ep}: summary missing "
                "phases/totalSeconds"
            )
        for name in PHASE_DURATIONS:
            v = phases.get(name)
            if not isinstance(v, (int, float)) or v < 0:
                raise InputError(
                    f"{path}: episode {slot}/{ep}: phase duration "
                    f"{name!r} missing or negative: {v!r}"
                )
        sum_phases = sum(float(phases[n]) for n in PHASE_DURATIONS)
        if abs(sum_phases - float(total)) > max(
            sum_tolerance_s, 0.01 * float(total)
        ):
            raise InputError(
                f"{path}: episode {slot}/{ep}: phase durations sum "
                f"{sum_phases:.6f}s != totalSeconds {float(total):.6f}s"
            )
        complete += 1
    return {
        "path": path,
        "events": len(events),
        "episodes": len(episodes),
        "complete": complete,
        "tornTail": torn,
        "slots": sorted({slot for (slot, _ep) in episodes}),
    }
