"""Slot-affine consistent-hash ring.

The router hashes a routing key (the request's tenant — warm
sessions, committed scans, and delta journals are tenant-affine, so
every request for one tenant should land on one replica and stay
there) onto a ring of virtual nodes. Properties the fleet leans on:

- **Deterministic**: ring points are sha256 of ``"slot#i"`` — no
  process-local randomness, so every router instance (and every test)
  agrees on the mapping.
- **Minimal movement**: adding or removing one slot moves only the
  keys that hash into that slot's arcs (~1/N of the keyspace), never
  reshuffles the rest. tests/test_fleet.py pins this.
- **Slot identity, not process identity**: members are slot names
  (``r0``, ``r1``, ...). A replacement replica inherits the dead
  replica's slot, so a failover moves ZERO keys — the replacement
  serves exactly the tenants the dead replica owned, which is what
  makes journal-replay bootstrap (fleet/replay.py) sufficient to
  restore its warm state.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Dict, List

from ..models.validation import InputError

#: virtual nodes per slot — enough to keep per-slot load within a few
#: percent of uniform at small N without bloating ring rebuilds
DEFAULT_VNODES = 64


def _point(label: str) -> int:
    return int.from_bytes(
        hashlib.sha256(label.encode("utf-8")).digest()[:8], "big"
    )


class HashRing:
    """Consistent-hash ring over named slots."""

    def __init__(self, slots: List[str] = (), vnodes: int = DEFAULT_VNODES):
        if vnodes < 1:
            raise InputError(f"vnodes must be >= 1, got {vnodes}")
        self.vnodes = vnodes
        self._points: List[int] = []  # sorted ring positions
        self._owner: Dict[int, str] = {}  # position -> slot
        self._slots: List[str] = []
        for s in slots:
            self.add(s)

    # -- membership ----------------------------------------------------------

    def add(self, slot: str):
        if slot in self._slots:
            return
        self._slots.append(slot)
        for i in range(self.vnodes):
            p = _point(f"{slot}#{i}")
            # sha256 collisions across distinct labels are not a real
            # concern; first writer keeps the point for determinism
            if p in self._owner:
                continue
            bisect.insort(self._points, p)
            self._owner[p] = slot

    def remove(self, slot: str):
        if slot not in self._slots:
            return
        self._slots.remove(slot)
        for i in range(self.vnodes):
            p = _point(f"{slot}#{i}")
            if self._owner.get(p) == slot:
                del self._owner[p]
                idx = bisect.bisect_left(self._points, p)
                if idx < len(self._points) and self._points[idx] == p:
                    del self._points[idx]

    def slots(self) -> List[str]:
        return list(self._slots)

    def __len__(self) -> int:
        return len(self._slots)

    def __contains__(self, slot: str) -> bool:
        return slot in self._slots

    # -- routing -------------------------------------------------------------

    def route(self, key: str) -> str:
        """The slot owning ``key`` (first ring point at or after the
        key's hash, wrapping)."""
        if not self._points:
            raise InputError("cannot route on an empty hash ring")
        p = _point(key)
        idx = bisect.bisect_right(self._points, p)
        if idx == len(self._points):
            idx = 0
        return self._owner[self._points[idx]]

    def route_order(self, key: str) -> List[str]:
        """Every slot in failover-preference order for ``key``: the
        owner first, then the distinct slots met walking the ring.
        The router tries these in order when the owner is down, so a
        tenant's failover target is stable too (requests rerouted
        mid-burst all land on the SAME surviving replica)."""
        if not self._points:
            return []
        p = _point(key)
        start = bisect.bisect_right(self._points, p)
        order: List[str] = []
        n = len(self._points)
        for off in range(n):
            slot = self._owner[self._points[(start + off) % n]]
            if slot not in order:
                order.append(slot)
                if len(order) == len(self._slots):
                    break
        return order
