"""Timeline reports: per-step cost/utilization/pending curves per
policy, plus the head-to-head comparison rendering (text + JSON).

A sample is taken at every pod arrival and at every window boundary
(churn, autoscale decision, departure batch), so the curves have true
per-event granularity even though a whole window of arrivals rides one
device dispatch — intra-window points are reconstructed host-side from
the window's placements in arrival order (timeline/stepper.py).

"Cost" is node-seconds: the integral of up-node count over time (per
policy). It is deliberately unit-free — multiply by a per-node price to
get money; the comparison between policies is the point, not the
currency.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional


@dataclass
class StepSample:
    """One point on a policy's curves."""

    time: float
    pending: int  # pods waiting for a node
    running: int  # scheduler-placed pods currently up
    nodes_up: int  # schedulable nodes (base + joined + candidates)
    candidates_up: int  # autoscaler candidates among nodes_up
    cpu_util: float  # percent over up-node allocatable
    mem_util: float
    cost_node_s: float  # cumulative node-seconds up to `time`

    def as_dict(self) -> dict:
        return {
            "time": round(self.time, 6),
            "pending": self.pending,
            "running": self.running,
            "nodesUp": self.nodes_up,
            "candidatesUp": self.candidates_up,
            "cpuUtil": round(self.cpu_util, 3),
            "memUtil": round(self.mem_util, 3),
            "costNodeSeconds": round(self.cost_node_s, 3),
        }


@dataclass
class PolicyTimeline:
    """One policy's run over the trace."""

    policy: str
    samples: List[StepSample] = field(default_factory=list)
    decisions: List[dict] = field(default_factory=list)
    displaced_total: int = 0  # pods requeued by drain/reclaim/scale-down
    displaced_by: dict = field(default_factory=dict)  # cause -> count
    lost_total: int = 0  # daemonset / node-bound pods lost with a node
    never_scheduled: int = 0  # pods that departed while still pending

    @property
    def final(self) -> Optional[StepSample]:
        return self.samples[-1] if self.samples else None

    @property
    def peak_pending(self) -> int:
        return max((s.pending for s in self.samples), default=0)

    @property
    def peak_nodes(self) -> int:
        return max((s.nodes_up for s in self.samples), default=0)

    def mean_util(self) -> tuple:
        """Time-weighted mean cpu/mem utilization over the samples."""
        if len(self.samples) < 2:
            s = self.final
            return (s.cpu_util, s.mem_util) if s else (0.0, 0.0)
        cpu = mem = span = 0.0
        for a, b in zip(self.samples, self.samples[1:]):
            dt = b.time - a.time
            cpu += a.cpu_util * dt
            mem += a.mem_util * dt
            span += dt
        if span <= 0:
            s = self.final
            return (s.cpu_util, s.mem_util)
        return (cpu / span, mem / span)

    def pending_seconds(self) -> float:
        """Integral of the pending-pod count over time — the policy's
        aggregate queueing pain (lower is better)."""
        total = 0.0
        for a, b in zip(self.samples, self.samples[1:]):
            total += a.pending * (b.time - a.time)
        return total

    def as_dict(self) -> dict:
        cpu, mem = self.mean_util()
        final = self.final
        return {
            "policy": self.policy,
            "finalPending": final.pending if final else 0,
            "peakPending": self.peak_pending,
            "pendingSeconds": round(self.pending_seconds(), 3),
            "meanCpuUtil": round(cpu, 3),
            "meanMemUtil": round(mem, 3),
            "peakNodes": self.peak_nodes,
            "finalNodes": final.nodes_up if final else 0,
            "costNodeSeconds": round(final.cost_node_s, 3) if final else 0.0,
            "displaced": self.displaced_total,
            "displacedBy": dict(sorted(self.displaced_by.items())),
            "lost": self.lost_total,
            "neverScheduled": self.never_scheduled,
            "decisions": list(self.decisions),
            "samples": [s.as_dict() for s in self.samples],
        }


@dataclass
class TimelineComparison:
    """N policies over one shared trace."""

    trace_fingerprint: str
    events: int
    arrivals: int
    windows: int
    # batched scan rounds (windows + policy probe decisions) — device
    # dispatches on engine=tpu, serial evaluations on engine=oracle
    dispatches: int
    horizon_s: float
    engine: str
    policies: List[PolicyTimeline] = field(default_factory=list)
    partial: bool = False
    meta: dict = field(default_factory=dict)

    def policy(self, name: str) -> Optional[PolicyTimeline]:
        for p in self.policies:
            if p.policy == name:
                return p
        return None

    def as_dict(self) -> dict:
        return {
            "traceFingerprint": self.trace_fingerprint,
            "events": self.events,
            "arrivals": self.arrivals,
            "windows": self.windows,
            "dispatches": self.dispatches,
            "horizonSeconds": round(self.horizon_s, 6),
            "engine": self.engine,
            "partial": self.partial,
            "meta": dict(self.meta),
            "policies": [p.as_dict() for p in self.policies],
        }

    def render_text(self, curve_points: int = 12) -> str:
        from ..apply.report import render_table

        lines = [
            f"Timeline: {self.arrivals} arrival(s) / {self.events} event(s) "
            f"over {self.horizon_s:.1f}s, {self.windows} window(s), "
            f"{self.dispatches} batched scan round(s), engine {self.engine}"
            + (" [PARTIAL]" if self.partial else ""),
        ]
        rows = []
        for p in self.policies:
            cpu, mem = p.mean_util()
            final = p.final
            ups = sum(1 for d in p.decisions if d.get("delta", 0) > 0)
            downs = sum(1 for d in p.decisions if d.get("delta", 0) < 0)
            rows.append([
                p.policy,
                str(final.pending if final else 0),
                str(p.peak_pending),
                f"{p.pending_seconds():.0f}",
                f"{cpu:.1f}%",
                f"{mem:.1f}%",
                str(p.peak_nodes),
                f"{final.cost_node_s:.0f}" if final else "0",
                f"+{ups}/-{downs}",
                str(p.displaced_total),
            ])
        lines.append(render_table(
            ["Policy", "Pending(end)", "Pending(peak)", "Pending·s",
             "CPU", "Mem", "Nodes(peak)", "Node·s", "Scale", "Displaced"],
            rows,
        ))
        # compact shared-time curve table: one row per sampled instant,
        # one "pending/nodes/cpu%" cell per policy. Cells are aligned
        # by TIME, not sample index — profile groups run separate
        # steppers whose boundary-sample counts differ, so index k is
        # not the same instant across groups; each cell shows the
        # policy's latest sample at or before the row's time
        # (step-function semantics).
        base = next((p for p in self.policies if p.samples), None)
        if base is not None and curve_points > 0:
            stride = max(len(base.samples) // curve_points, 1)
            picks = list(range(0, len(base.samples), stride))
            if picks[-1] != len(base.samples) - 1:
                picks.append(len(base.samples) - 1)
            cursors = [0] * len(self.policies)
            rows = []
            for k in picks:
                t = base.samples[k].time
                row = [f"{t:8.1f}"]
                for p_i, p in enumerate(self.policies):
                    if not p.samples:
                        row.append("-")
                        continue
                    c = cursors[p_i]
                    while (
                        c + 1 < len(p.samples)
                        and p.samples[c + 1].time <= t
                    ):
                        c += 1
                    cursors[p_i] = c
                    s = p.samples[c]
                    row.append(f"{s.pending}p/{s.nodes_up}n/{s.cpu_util:.0f}%")
                rows.append(row)
            lines.append("per-step curves (pending pods / nodes up / cpu):")
            lines.append(render_table(
                ["t(s)"] + [p.policy for p in self.policies], rows
            ))
        return "\n".join(lines)
