"""Head-to-head policy comparison over one shared trace.

Policies sharing a score profile ride ONE stepper — every window is a
single batched dispatch with one scenario row per policy. Policies with
a different profile (``@nospread``) need their own encoding (the scan's
score weights are compile-time static), so they group into a second
stepper over the same events; windows then dispatch per group, and the
merged report sums windows/dispatches across groups.
"""

from __future__ import annotations

from typing import List, Optional

from .autoscaler import Policy
from .events import Event
from .report import TimelineComparison
from .stepper import TimelineStepper


def run_policies(
    cluster,
    events: List[Event],
    policies: List[Policy],
    new_node_spec: Optional[dict] = None,
    max_nodes: int = 8,
    cadence_s: float = 60.0,
    warmup_s: float = 0.0,
    window_arrivals: int = 256,
    engine: str = "tpu",
    budget=None,
    journal=None,
) -> TimelineComparison:
    """Run every policy over `events` and merge the per-profile runs
    into one comparison (policy order preserved). A deadline/SIGINT
    halt re-raises ExecutionHalted with the merged partial report of
    every group finished or in flight attached."""
    from ..runtime.errors import ExecutionHalted

    groups: dict = {}
    for pol in policies:
        groups.setdefault(pol.profile, []).append(pol)
    merged: Optional[TimelineComparison] = None
    done: List[TimelineComparison] = []

    def merge(parts: List[TimelineComparison]) -> TimelineComparison:
        head = parts[0]
        out = TimelineComparison(
            trace_fingerprint=head.trace_fingerprint,
            events=head.events,
            arrivals=head.arrivals,
            windows=sum(p.windows for p in parts),
            dispatches=sum(p.dispatches for p in parts),
            horizon_s=head.horizon_s,
            engine=head.engine,
            partial=any(p.partial for p in parts),
            meta=dict(head.meta),
        )
        by_name = {}
        for part in parts:
            for tl in part.policies:
                by_name[tl.policy] = tl
        out.policies = [
            by_name[pol.name] for pol in policies if pol.name in by_name
        ]
        if len(parts) > 1:
            out.meta["profileGroups"] = len(parts)
        return out

    for profile, group in groups.items():
        stepper = TimelineStepper(
            cluster,
            events,
            group,
            new_node_spec=new_node_spec,
            max_nodes=max_nodes,
            cadence_s=cadence_s,
            warmup_s=warmup_s,
            window_arrivals=window_arrivals,
            engine=engine,
            score_weights=group[0].weights,
            budget=budget,
            journal=journal,
            journal_prefix=f"{profile}:" if len(groups) > 1 else "",
        )
        try:
            done.append(stepper.run())
        except ExecutionHalted as e:
            partial = getattr(e, "partial_report", None)
            parts = done + ([partial] if partial is not None else [])
            if parts:
                merged = merge(parts)
                merged.partial = True
                e.partial = {
                    "phase": "timeline",
                    "report": merged.as_dict(),
                }
                e.partial_report = merged
            raise
    return merge(done)
