"""The windowed timeline stepper.

The naive discrete-event simulation schedules one pod per event — a
1000-step trace is 1000 ``simulate()`` calls. Here the timeline rides
the batched masked scan instead (the chaos substrate,
parallel/sweep.py probe_scenarios): every node and every pod that EVER
exists in the trace is encoded ONCE, and the cluster's state at any
instant is a (node_valid, pod_active, pinned) triple —

- nodes that are up (base nodes minus drains/reclaims, plus joins and
  enabled autoscaler candidates) form ``node_valid``;
- pods that have arrived and not departed form ``pod_active``
  (daemonset pods follow their node's validity for free, exactly like
  the capacity sweep's disabled-node convention);
- pods placed in earlier windows pin to their nodes (pins commit
  unconditionally in the scan's first pass — real pods do not move),
  pods displaced by a drain/reclaim and pods still pending are free
  and reschedule through the full filter+score cycle in arrival order.

A WINDOW is a run of consecutive arrivals between boundaries (node
churn, autoscale-decision cadence ticks, warm-up activations, the
arrival cap). One window = ONE device dispatch evaluating every
policy's row of the batched scan — so N policies over a 1000-step
trace cost a handful of dispatches total, not 1000·N simulate() calls.
Within-window curves are reconstructed host-side from the window's
placements in arrival order (report.py).

Quantization semantics (docs/TIMELINE.md): departures and churn
falling inside a window take effect at the window's CLOSE — capacity
is never freed early, so a placement never uses capacity that is not
surely free; arrivals schedule at their own event times in order.
The serial conformance path (``engine="oracle"``) evaluates the exact
same per-window (valid, active, pinned) state through the host oracle
(CapacitySweep.serial_scenario), so windowed-vs-serial equivalence is
a testable contract, not an approximation claim.

Budget deadlines are checked at every window boundary; with a journal,
completed window placements (and the probe policy's decision scans)
replay from disk and a resumed run re-executes zero device work.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..models.validation import InputError
from ..models import workloads as wl
from ..parallel.sweep import CapacitySweep, ProbeResult
from ..resilience.chaos import displaced_free_mask
from ..runtime.errors import ExecutionHalted
from .autoscaler import Policy, PolicyObservation
from .events import (
    AUTOSCALE_DECISION,
    CHURN_KINDS,
    NODE_DRAIN,
    NODE_JOIN,
    POD_ARRIVAL,
    POD_DEPARTURE,
    SPOT_RECLAIM,
    Event,
    trace_fingerprint,
)
from .report import PolicyTimeline, StepSample, TimelineComparison

_INF = float("inf")


@dataclass
class _PolicyState:
    """Per-policy mutable timeline state."""

    policy: Policy
    tl: PolicyTimeline
    placed: np.ndarray  # [P] current node index of ~had pods, -1 free
    node_up: np.ndarray  # [N] bool
    cand_up: int = 0  # enabled candidates (always a prefix)
    # committed scale-ups still warming: (t_effective, add_count)
    activations: List[Tuple[float, int]] = field(default_factory=list)
    cost: float = 0.0  # node-seconds accumulated up to window start

    def next_activation(self) -> float:
        return min((t for t, _ in self.activations), default=_INF)


class TimelineStepper:
    """Run one trace through N same-score-profile policies.

    Policies with different score profiles need their own encoding
    (the scan's score weights are compile-time static); the comparison
    harness (compare.py) groups them and merges the reports."""

    def __init__(
        self,
        cluster,
        events: List[Event],
        policies: List[Policy],
        new_node_spec: Optional[dict] = None,
        max_nodes: int = 8,
        cadence_s: float = 60.0,
        warmup_s: float = 0.0,
        window_arrivals: int = 256,
        engine: str = "tpu",
        score_weights=None,
        budget=None,
        journal=None,
        journal_prefix: str = "",
    ):
        if engine not in ("tpu", "oracle"):
            raise InputError(f"timeline engine must be tpu|oracle, not {engine!r}")
        if cadence_s <= 0:
            raise InputError(f"decision cadence must be > 0s, got {cadence_s}")
        if warmup_s < 0:
            raise InputError(f"warm-up delay must be >= 0s, got {warmup_s}")
        if window_arrivals < 1:
            raise InputError(
                f"window arrival cap must be >= 1, got {window_arrivals}"
            )
        if not policies:
            raise InputError("timeline needs at least one policy")
        self.events = list(events)
        self.engine = engine
        self.cadence_s = float(cadence_s)
        self.warmup_s = float(warmup_s)
        self.window_arrivals = int(window_arrivals)
        self.budget = budget
        self.journal = journal
        self.journal_prefix = journal_prefix
        self.trace_fp = trace_fingerprint(self.events)

        # ---- the encode-once universe: every node and pod that ever exists
        arrival_events = [ev for ev in self.events if ev.kind == POD_ARRIVAL]
        join_nodes: List[dict] = []
        base_names = {
            ((n.get("metadata") or {}).get("name")) for n in cluster.nodes
        }
        seen_joins = set(base_names)
        for ev in self.events:
            if ev.kind != NODE_JOIN:
                continue
            name = ((ev.node or {}).get("metadata") or {}).get("name")
            if not name:
                raise InputError(
                    f"NodeJoin event at t={ev.time} carries no node name"
                )
            if name in seen_joins:
                continue  # re-join of a known node: mask flip only
            seen_joins.add(name)
            join_nodes.append(wl.make_valid_node(ev.node, name))
        tl_cluster = cluster.copy()
        tl_cluster.nodes = list(cluster.nodes) + join_nodes
        tl_cluster.pods = list(cluster.pods) + [ev.pod for ev in arrival_events]
        # workload expansion names pods from a process-global counter;
        # reset so repeated in-process runs (and compare.py's per-profile
        # re-encodings) expand the identical sequence (the chaos rule)
        wl.reset_name_counter()
        self.sweep = CapacitySweep(
            tl_cluster,
            [],
            new_node_spec,
            max_nodes,
            score_weights=score_weights,
        )
        self.n = self.sweep.n
        self.p = len(self.sweep.pods)
        self.n_base = self.sweep.n_base
        self.cand_total = self.sweep.max_count
        self.n_real_base = len(cluster.nodes)  # up at t=0

        # arrival event k -> sweep pod index (positional: resources.pods
        # entries expand 1:1 in order, cluster pods first)
        self.arrival_pod_idx = [
            len(cluster.pods) + k for k in range(len(arrival_events))
        ]
        self._arrival_seq = {
            id(ev): self.arrival_pod_idx[k]
            for k, ev in enumerate(arrival_events)
        }
        # namespace/name -> sweep pod indices (departure resolution;
        # latest-arrived wins when a name recurs, e.g. evict + re-create)
        self._ref_idx: Dict[str, List[int]] = {}
        for p_i, pod in enumerate(self.sweep.pods):
            meta = pod.get("metadata") or {}
            ref = f"{meta.get('namespace') or 'default'}/{meta.get('name') or ''}"
            self._ref_idx.setdefault(ref, []).append(p_i)

        # shared presence state
        self.arrived = np.zeros(self.p, dtype=bool)
        day0 = set(range(self.p)) - set(self.arrival_pod_idx)
        self.arrived[list(day0)] = True
        self.departed = np.zeros(self.p, dtype=bool)
        self.had = np.asarray(self.sweep.had_node_name, dtype=bool)
        self.orig_pin = np.asarray(self.sweep.batch.pinned_node, dtype=np.int64)
        cls = np.asarray(self.sweep.batch.class_of_pod, dtype=np.int64)
        self._req_c = np.asarray(self.sweep.batch.req_mcpu)[cls].astype(np.int64)
        self._req_m = np.asarray(self.sweep.batch.req_mem)[cls].astype(np.int64)

        node_up0 = np.zeros(self.n, dtype=bool)
        node_up0[: self.n_real_base] = True
        self.states = [
            _PolicyState(
                policy=pol,
                tl=PolicyTimeline(policy=pol.name),
                placed=np.full(self.p, -1, dtype=np.int64),
                node_up=node_up0.copy(),
            )
            for pol in policies
        ]
        self.windows = 0
        self.dispatches = 0
        self._partial = False
        self._last_close = 0.0

    # ------------------------------------------------------------ utilities

    def _node_idx(self, name: str, ev: Event) -> int:
        idx = self.sweep.oracle.node_index.get(name)
        if idx is None:
            raise InputError(
                f"{ev.kind} event at t={ev.time} names unknown node {name!r}"
            )
        return int(idx)

    def _present(self) -> np.ndarray:
        return self.arrived & ~self.departed

    def _active(self, st: _PolicyState) -> np.ndarray:
        return self.sweep.pod_active(st.node_up) & self._present()

    def _pinned(self, st: _PolicyState) -> np.ndarray:
        return np.where(self.had, self.orig_pin, st.placed).astype(np.int64)

    def _free_mask(self, st: _PolicyState) -> np.ndarray:
        return self._active(st) & ~self.had & (st.placed < 0)

    def _usage(self, st: _PolicyState, accounted: np.ndarray) -> tuple:
        """(used_mcpu, used_mem, denom_mcpu, denom_mem) over up nodes —
        the same arithmetic as CapacitySweep._host_scenario_stats, in
        cumulative form for intra-window samples."""
        v = st.node_up
        d, c_enc = self.sweep.dyn, self.sweep.cluster_enc
        used_c = int(d.used_mcpu[v].sum()) + int(self._req_c[accounted].sum())
        used_m = int(d.used_mem[v].sum()) + int(self._req_m[accounted].sum())
        denom_c = max(int(c_enc.alloc_mcpu[v].sum()), 1)
        denom_m = max(int(c_enc.alloc_mem[v].sum()), 1)
        return used_c, used_m, denom_c, denom_m

    def _pinned_had_mask(self, st: _PolicyState) -> np.ndarray:
        """Node-bound pods occupying capacity: original spec.nodeName
        pods that are present, active, and whose node is up."""
        return (
            self.had
            & self._active(st)
            & (self.orig_pin >= 0)
            & st.node_up[np.clip(self.orig_pin, 0, None)]
        )

    def _sample(self, st: _PolicyState, t: float, t_start: float) -> StepSample:
        """Full-state sample at `t` (window-boundary form)."""
        acc = ((st.placed >= 0) & ~self.had) | self._pinned_had_mask(st)
        used_c, used_m, den_c, den_m = self._usage(st, acc)
        pending = int(self._free_mask(st).sum())
        nodes = int(st.node_up.sum())
        return StepSample(
            time=t,
            pending=pending,
            running=int(((st.placed >= 0) & ~self.had).sum()),
            nodes_up=nodes,
            candidates_up=int(st.node_up[self.n_base :].sum()),
            cpu_util=100.0 * used_c / den_c,
            mem_util=100.0 * used_m / den_m,
            cost_node_s=st.cost + nodes * (t - t_start),
        )

    # ------------------------------------------------------------ main loop

    def run(self) -> TimelineComparison:
        try:
            return self._run_inner()
        except ExecutionHalted as e:
            self._partial = True
            report = self.comparison()
            e.partial = {"phase": "timeline", "report": report.as_dict()}
            e.partial_report = report
            raise

    def _run_inner(self) -> TimelineComparison:
        from ..utils.trace import GLOBAL

        events = self.events
        horizon = events[-1].time if events else 0.0
        next_tick = 0.0  # decisions run at t=0 too (initial provisioning)
        i = 0
        while True:
            # chaos seam: deterministic faults at the window boundary
            # (runtime/inject.py; ExecutionHalted here carries the
            # partial report through run()'s handler like a deadline)
            from ..runtime import inject as _inject

            _inject.fire("timeline.tick", window=self.windows)
            if self.budget is not None:
                self.budget.check(f"timeline window {self.windows}")
            t_start = self._last_close if self.windows else 0.0
            t_act = min(st.next_activation() for st in self.states)
            t_bound = min(next_tick, t_act)
            # ---- collect the window (for-loop: bounded by the stream)
            arrivals: List[int] = []  # event indices, in order
            departures: List[int] = []
            boundary_ev: Optional[Event] = None
            t_close = None
            j = i
            for j in range(i, len(events)):
                ev = events[j]
                if ev.time >= t_bound:
                    t_close = t_bound
                    break
                if ev.kind in CHURN_KINDS:
                    boundary_ev = ev
                    t_close = ev.time
                    j += 1
                    break
                if ev.kind == POD_ARRIVAL:
                    if len(arrivals) >= self.window_arrivals:
                        t_close = ev.time  # cap boundary; ev stays queued
                        break
                    arrivals.append(j)
                elif ev.kind == POD_DEPARTURE:
                    departures.append(j)
            else:
                # normal exhaustion: every event consumed. On breaks,
                # `j` is the resume point (the churn branch advanced
                # past its consumed event; the boundary/cap breaks
                # leave event j queued for the next window).
                j = len(events)
            exhausted = False
            if t_close is None:  # stream ran out before any boundary
                if t_bound <= horizon:
                    t_close = t_bound
                else:
                    t_close = max(horizon, t_start)
                    exhausted = True
            i = j

            # ---- arrivals become present and the window dispatches
            arr_pods = [self._arrival_seq[id(events[k])] for k in arrivals]
            arr_times = [events[k].time for k in arrivals]
            self.arrived[arr_pods] = True
            rows = self._dispatch_window(arr_pods)
            self._emit_samples(rows, arr_pods, arr_times, t_start, t_close)

            # ---- close: departures, then cost roll-forward
            self._apply_departures(departures)
            changed = bool(departures)
            for st in self.states:
                st.cost += int(st.node_up.sum()) * (t_close - t_start)
            self._last_close = t_close
            self.windows += 1

            # ---- boundary effects
            if boundary_ev is not None:
                self._apply_churn(boundary_ev)
                changed = True
            for st in self.states:
                due = [a for a in st.activations if a[0] <= t_close]
                if due:
                    st.activations = [
                        a for a in st.activations if a[0] > t_close
                    ]
                    for _t, k in due:
                        self._scale_up_now(st, k)
                    changed = True
            if next_tick <= t_close:
                self._decide(next_tick)
                while next_tick <= t_close:
                    if self.budget is not None:
                        self.budget.check("timeline tick advance")
                    next_tick += self.cadence_s
                changed = True
            if changed:
                for st in self.states:
                    st.tl.samples.append(self._sample(st, t_close, t_close))
            if exhausted:
                break

        for st in self.states:
            if st.activations:
                GLOBAL.append_note(
                    "timeline-warmup",
                    f"{st.policy.name}: {len(st.activations)} scale-up(s) "
                    "still warming at the horizon (never activated)",
                )
        GLOBAL.note("timeline-windows", str(self.windows))
        GLOBAL.note("timeline-dispatches", str(self.dispatches))
        return self.comparison()

    # ------------------------------------------------------------ dispatch

    def _dispatch_window(self, arr_pods: List[int]):
        """One batched dispatch over every policy that has free pods to
        (re)schedule; returns {state index: placements row} and updates
        each dispatched policy's `placed`."""
        from ..utils.trace import phase

        work = [
            k for k, st in enumerate(self.states)
            if bool(self._free_mask(st).any())
        ]
        if not work:
            return {}
        valids = np.stack([self.states[k].node_up for k in work])
        actives = np.stack([self._active(self.states[k]) for k in work])
        pins = np.stack([self._pinned(self.states[k]) for k in work])
        key = f"{self.journal_prefix}tlw:{self.windows}"
        names = [self.states[k].policy.name for k in work]
        rows: Dict[int, np.ndarray] = {}
        journaled = None
        if self.journal is not None:
            rec = self.journal.get_scenario(key)
            if rec is not None and all(
                name in (rec.get("placements") or {}) for name in names
            ):
                journaled = rec
        if journaled is not None:
            for k, name in zip(work, names):
                rows[k] = np.asarray(
                    journaled["placements"][name], dtype=np.int64
                )
        else:
            with phase("timeline/window"):
                if self.engine == "tpu":
                    placements, _u, _c, _m, _v = self.sweep.probe_scenarios(
                        valids, actives, pins, site="timeline"
                    )
                else:
                    placements = np.stack([
                        self.sweep.serial_scenario(
                            valids[r], actives[r], pins[r], pins_first=True
                        )[0]
                        for r in range(len(work))
                    ])
            self.dispatches += 1
            # incremental accounting (ROADMAP item 3 vocabulary): each
            # window re-decides only its FREE pods — rows placed in
            # earlier windows ride along as pins, the reused prefix
            from ..utils.trace import COUNTERS

            free_rows = int(
                sum(self._free_mask(self.states[k]).sum() for k in work)
            )
            pinned_rows = int((pins >= 0).sum())
            COUNTERS.inc("incremental_suffix_pods_total", free_rows)
            COUNTERS.inc("incremental_prefix_reused_pods_total", pinned_rows)
            for r, k in enumerate(work):
                rows[k] = np.asarray(placements[r], dtype=np.int64)
            if self.journal is not None:
                self.journal.record_scenario(
                    key,
                    {
                        "placements": {
                            name: [int(x) for x in rows[k]]
                            for k, name in zip(work, names)
                        }
                    },
                )
        for k in work:
            st = self.states[k]
            free = self._free_mask(st)
            row = rows[k]
            st.placed[free] = np.where(row[free] >= 0, row[free], -1)
        return rows

    def _emit_samples(self, rows, arr_pods, arr_times, t_start, t_close):
        """Reconstruct intra-window curve points per policy: retried
        pods commit at the window start, each arrival at its own event
        time, in arrival order (= batch order = scan commit order).
        An arrival-free window that still dispatched (displaced pods
        requeueing after churn) samples once at its close so the
        curves show the recovery."""
        if not arr_pods:
            for k in rows:
                st = self.states[k]
                st.tl.samples.append(self._sample(st, t_close, t_start))
            return
        arr_mask = np.zeros(self.p, dtype=bool)
        if arr_pods:
            arr_mask[np.asarray(arr_pods, dtype=np.int64)] = True
        for k, st in enumerate(self.states):
            nodes = int(st.node_up.sum())
            cand = int(st.node_up[self.n_base :].sum())
            active = self._active(st)
            pinned_had = self._pinned_had_mask(st)
            acc = ((st.placed >= 0) & ~self.had) | pinned_had
            acc_start = acc & ~arr_mask
            used_c, used_m, den_c, den_m = self._usage(st, acc_start)
            running = int((acc_start & ~self.had).sum())
            pending = int((self._free_mask(st) & ~arr_mask).sum())
            for p_i, t in zip(arr_pods, arr_times):
                if st.placed[p_i] >= 0:
                    used_c += int(self._req_c[p_i])
                    used_m += int(self._req_m[p_i])
                    running += 1
                elif pinned_had[p_i]:
                    # a pre-bound arrival occupies capacity unscheduled
                    used_c += int(self._req_c[p_i])
                    used_m += int(self._req_m[p_i])
                elif active[p_i] and not self.had[p_i]:
                    pending += 1
                st.tl.samples.append(StepSample(
                    time=t,
                    pending=pending,
                    running=running,
                    nodes_up=nodes,
                    candidates_up=cand,
                    cpu_util=100.0 * used_c / den_c,
                    mem_util=100.0 * used_m / den_m,
                    cost_node_s=st.cost + nodes * (t - t_start),
                ))

    # ------------------------------------------------------------ boundary

    def _apply_departures(self, departures: List[int]):
        for k in departures:
            ev = self.events[k]
            candidates = [
                p_i
                for p_i in self._ref_idx.get(ev.pod_ref, ())
                if self.arrived[p_i] and not self.departed[p_i]
            ]
            if not candidates:
                raise InputError(
                    f"PodDeparture at t={ev.time} references "
                    f"{ev.pod_ref!r}, which is not present in the timeline"
                )
            p_i = candidates[-1]  # latest arrival of a recurring name
            self.departed[p_i] = True
            for st in self.states:
                if not self.had[p_i] and st.placed[p_i] < 0:
                    st.tl.never_scheduled += 1
                st.placed[p_i] = -1

    def _take_node_down(self, st: _PolicyState, idx: int, reason: str):
        if not st.node_up[idx]:
            return
        st.node_up[idx] = False
        active_after = self._active(st)
        disp = displaced_free_mask(st.placed, st.node_up, self.had, active_after)
        n_disp = int(disp.sum())
        if n_disp:
            st.placed[disp] = -1
            st.tl.displaced_total += n_disp
            st.tl.displaced_by[reason] = (
                st.tl.displaced_by.get(reason, 0) + n_disp
            )
        present = self._present()
        lost_ds = int(
            ((np.asarray(self.sweep._ds_target) == idx) & present).sum()
        )
        lost_bound = int(
            (self.had & (self.orig_pin == idx) & present).sum()
        )
        st.tl.lost_total += lost_ds + lost_bound

    def _apply_churn(self, ev: Event):
        if ev.kind == NODE_JOIN:
            name = ((ev.node or {}).get("metadata") or {}).get("name")
            idx = self._node_idx(name, ev)
            for st in self.states:
                st.node_up[idx] = True
        elif ev.kind in (NODE_DRAIN, SPOT_RECLAIM):
            idx = self._node_idx(ev.node_name, ev)
            if idx >= self.n_base:
                raise InputError(
                    f"{ev.kind} event names autoscaler candidate "
                    f"{ev.node_name!r}; the candidate pool belongs to the "
                    "policies (use AutoscaleDecision deltas)"
                )
            for st in self.states:
                self._take_node_down(st, idx, ev.kind)
        elif ev.kind == AUTOSCALE_DECISION:
            # a recorded decision in the INPUT trace applies verbatim to
            # every policy's candidate pool (replaying one run's
            # decisions against another workload)
            for st in self.states:
                self._apply_delta(st, ev.delta, ev.time, warmup=0.0,
                                  reason=ev.reason or "trace")

    # ------------------------------------------------------------ decisions

    def _scale_up_now(self, st: _PolicyState, k: int):
        lo = self.n_base + int(st.node_up[self.n_base :].sum())
        hi = min(lo + k, self.n)
        st.node_up[lo:hi] = True

    def _apply_delta(self, st: _PolicyState, delta: int, t: float,
                     warmup: float, reason: str):
        """Apply a scale delta: +k warms the next k candidates
        (activation after `warmup`), -k drains the highest-index
        enabled candidates immediately (pending warm-ups cancel
        first)."""
        if delta > 0:
            room = self.cand_total - st.cand_up
            k = min(delta, room)
            if k <= 0:
                return
            st.cand_up += k
            if warmup > 0:
                st.activations.append((t + warmup, k))
            else:
                self._scale_up_now(st, k)
            st.tl.decisions.append(
                {"time": t, "delta": k, "reason": reason,
                 "effective": t + warmup}
            )
        elif delta < 0:
            total = min(-delta, st.cand_up)
            if total <= 0:
                return
            st.cand_up -= total
            # cancel warming capacity before draining live nodes
            k = total
            while k and st.activations:
                t_eff, n_act = st.activations[-1]
                take = min(k, n_act)
                if take == n_act:
                    st.activations.pop()
                else:
                    st.activations[-1] = (t_eff, n_act - take)
                k -= take
            enabled = int(st.node_up[self.n_base :].sum())
            for d in range(k):
                self._take_node_down(
                    st, self.n_base + enabled - 1 - d, "scale-down"
                )
            st.tl.decisions.append(
                {"time": t, "delta": -total, "reason": reason, "effective": t}
            )

    def _pending_need_nodes(self, st: _PolicyState) -> int:
        """Candidate nodes the pending pods need by aggregate request —
        apply's escalation estimate (CapacitySweep.estimate_extra) on a
        synthetic probe whose failures are exactly the pending set."""
        free = self._free_mask(st)
        if not free.any() or self.cand_total == 0:
            return 0
        fake = ProbeResult(
            count=0, unscheduled=int(free.sum()), cpu_util=0.0,
            mem_util=0.0, vg_util=0.0,
            placements=np.where(free, -1, 0).astype(np.int64),
        )
        return int(self.sweep.estimate_extra(fake))

    def _probe_counts(self, st: _PolicyState, counts: List[int]):
        """The probe policy's decision scan: every candidate count as
        one batched row over the CURRENT timeline state (pins kept,
        pending pods free) — one device dispatch per decision."""
        key = f"{self.journal_prefix}tlp:{self.windows}:{st.policy.name}"
        rec = self.journal.get_scenario(key) if self.journal is not None else None
        if rec is not None and rec.get("counts") == list(counts) and "vg" in rec:
            return [
                ProbeResult(
                    count=int(c), unscheduled=int(u), cpu_util=float(cu),
                    mem_util=float(mu), vg_util=float(vu), placements=None,
                )
                for c, u, cu, mu, vu in zip(
                    rec["counts"], rec["unscheduled"], rec["cpu"],
                    rec["mem"], rec["vg"],
                )
            ]
        valids, actives, pins = [], [], []
        for c in counts:
            v = st.node_up.copy()
            v[self.n_base : self.n_base + c] = True
            v[self.n_base + c :] = False
            placed_ok = (st.placed >= 0) & v[np.clip(st.placed, 0, None)]
            pin = np.where(
                self.had, self.orig_pin, np.where(placed_ok, st.placed, -1)
            ).astype(np.int64)
            valids.append(v)
            actives.append(self.sweep.pod_active(v) & self._present())
            pins.append(pin)
        from ..utils.trace import phase

        with phase("timeline/probe"):
            if self.engine == "tpu":
                _pl, unsched, cpu, mem, vg = self.sweep.probe_scenarios(
                    np.stack(valids), np.stack(actives), np.stack(pins),
                    site="timeline",
                )
            else:
                rows = [
                    self.sweep.serial_scenario(
                        valids[r], actives[r], pins[r], pins_first=True
                    )[0]
                    for r in range(len(counts))
                ]
                stats = [
                    self.sweep._host_scenario_stats(valids[r], rows[r])
                    for r in range(len(counts))
                ]
                unsched = [s[1] for s in stats]
                cpu = [s[2] for s in stats]
                mem = [s[3] for s in stats]
                vg = [s[4] for s in stats]
        self.dispatches += 1
        out = [
            ProbeResult(
                count=int(c), unscheduled=int(u), cpu_util=float(cu),
                mem_util=float(mu), vg_util=float(vu), placements=None,
            )
            for c, u, cu, mu, vu in zip(counts, unsched, cpu, mem, vg)
        ]
        if self.journal is not None:
            self.journal.record_scenario(key, {
                "counts": [int(c) for c in counts],
                "unscheduled": [int(r.unscheduled) for r in out],
                "cpu": [float(r.cpu_util) for r in out],
                "mem": [float(r.mem_util) for r in out],
                "vg": [float(r.vg_util) for r in out],
            })
        return out

    def _decide(self, t: float):
        from ..utils.trace import phase

        with phase("timeline/decide"):
            for st in self.states:
                free = self._free_mask(st)
                acc = ((st.placed >= 0) & ~self.had) | self._pinned_had_mask(st)
                used_c, used_m, den_c, den_m = self._usage(st, acc)
                obs = PolicyObservation(
                    time=t,
                    pending=int(free.sum()),
                    pending_need_nodes=self._pending_need_nodes(st),
                    cpu_util=100.0 * used_c / den_c,
                    mem_util=100.0 * used_m / den_m,
                    nodes_up=int(st.node_up.sum()),
                    candidates_up=st.cand_up,
                    candidates_total=self.cand_total,
                )
                delta = st.policy.decide(
                    obs, probe=lambda counts, _st=st: self._probe_counts(_st, counts)
                )
                if delta:
                    self._apply_delta(
                        st, int(delta), t, self.warmup_s,
                        reason=f"policy:{st.policy.name}",
                    )

    # ------------------------------------------------------------ results

    def comparison(self) -> TimelineComparison:
        return TimelineComparison(
            trace_fingerprint=self.trace_fp,
            events=len(self.events),
            arrivals=len(self.arrival_pod_idx),
            windows=self.windows,
            dispatches=self.dispatches,
            horizon_s=self.events[-1].time if self.events else 0.0,
            engine=self.engine,
            policies=[st.tl for st in self.states],
            partial=self._partial,
            meta={
                "cadenceSeconds": self.cadence_s,
                "warmupSeconds": self.warmup_s,
                "windowArrivalCap": self.window_arrivals,
                "candidateNodes": self.cand_total,
            },
        )
