"""Pluggable autoscaler policies for the timeline's decision loop.

The stepper calls ``policy.decide(obs, probe=...)`` at every decision
cadence tick (including t=0) and applies the returned DELTA to the
candidate node pool: positive deltas enable candidates after the
configured warm-up delay, negative deltas drain the highest-index
enabled candidates immediately (their pods requeue through the full
filter+score cycle — the chaos displacement rule).

Policies:

- ``static:K``  — hold exactly K candidates up (the no-autoscaler
  baseline; K=0 is pure trace playback);
- ``threshold`` — scale up when pods are pending (by the stepper's
  aggregate-request node estimate), scale down one node after
  ``patience`` consecutive calm ticks (utilization under ``lo`` with
  nothing pending);
- ``probe``     — the capacity-probe policy: every decision evaluates
  ALL candidate counts as batched scenario rows over the live timeline
  state (one device dispatch — the sweep's probe_many pattern flattened
  into a single round) and jumps straight to the minimal count that
  schedules everything within apply's utilization caps
  (apply/applier._capacity_feasible — the same MaxCPU/MaxMemory/MaxVG
  contract ``simon apply`` plans under).

A policy spec may carry a score profile suffix: ``threshold@nospread``
runs the policy under ``ScoreWeights(spread=0)`` (PodTopologySpread
off — replicas pack onto fewer nodes instead of spreading; the closest
thing to a binpack study the reference's score-plugin set offers — it
registers no MostAllocated scorer, algorithmprovider/registry.go).
Policies with different profiles are grouped onto separate encodings by
the comparison harness (timeline/compare.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, List, Optional

from ..models.validation import InputError
from ..scheduler.schedconfig import DEFAULT_SCORE_WEIGHTS, ScoreWeights

#: named score profiles a policy spec can select with ``@profile``
SCORE_PROFILES = {
    "default": None,  # the engine default (DEFAULT_SCORE_WEIGHTS)
    "nospread": DEFAULT_SCORE_WEIGHTS._replace(spread=0),
}


@dataclass
class PolicyObservation:
    """What a policy sees at a decision tick."""

    time: float
    pending: int  # pods currently waiting for a node
    pending_need_nodes: int  # candidate nodes the pending pods need by
    # aggregate request (stepper-computed, >= 1 when pending > 0)
    cpu_util: float
    mem_util: float
    nodes_up: int
    candidates_up: int  # enabled + warming (committed scale-ups)
    candidates_total: int


class Policy:
    """Base policy. Subclasses implement ``decide``; ``probe`` is a
    stepper-provided callable (counts -> per-count feasibility rows)
    that costs one device dispatch — only the probe policy uses it."""

    name: str = "policy"
    profile: str = "default"

    @property
    def weights(self) -> Optional[ScoreWeights]:
        return SCORE_PROFILES[self.profile]

    def decide(
        self, obs: PolicyObservation, probe: Optional[Callable] = None
    ) -> int:
        raise NotImplementedError

    def describe(self) -> str:
        return self.name


class StaticPolicy(Policy):
    """Hold exactly ``count`` candidates up from t=0."""

    def __init__(self, count: int = 0):
        if count < 0:
            raise InputError(f"static policy count must be >= 0, got {count}")
        self.count = count
        self.name = f"static:{count}"

    def decide(self, obs, probe=None) -> int:
        return self.count - obs.candidates_up


class ThresholdPolicy(Policy):
    """Reactive scale-up on pending pods, patient scale-down on calm.

    Scale-up sizes itself from the stepper's aggregate-request estimate
    (``obs.pending_need_nodes``) so one decision absorbs a burst
    instead of trickling a node per tick; ``step`` > 0 caps it.
    Scale-down waits ``patience`` consecutive ticks with nothing
    pending and cpu AND mem under ``lo`` percent, then releases one
    node per tick — conservative by design (a reclaimed node's pods
    requeue, and thrashing is the classic autoscaler failure mode)."""

    def __init__(self, lo: float = 30.0, patience: int = 2, step: int = 0):
        if not 0 <= lo <= 100:
            raise InputError(f"threshold lo={lo} outside [0, 100]")
        if patience < 1:
            raise InputError(f"threshold patience must be >= 1, got {patience}")
        if step < 0:
            raise InputError(f"threshold step must be >= 0, got {step}")
        self.lo = lo
        self.patience = patience
        self.step = step
        self._calm = 0
        self.name = "threshold"

    def decide(self, obs, probe=None) -> int:
        if obs.pending > 0:
            self._calm = 0
            up = max(obs.pending_need_nodes, 1)
            if self.step:
                up = min(up, self.step)
            return min(up, obs.candidates_total - obs.candidates_up)
        if (
            obs.candidates_up > 0
            and obs.cpu_util < self.lo
            and obs.mem_util < self.lo
        ):
            self._calm += 1
            if self._calm >= self.patience:
                self._calm = 0
                return -1
        else:
            self._calm = 0
        return 0


class ProbePolicy(Policy):
    """Capacity-probe policy: pick the minimal candidate count that
    schedules everything within apply's utilization caps, re-evaluated
    from live timeline state at every tick (one batched dispatch)."""

    def __init__(self):
        self.name = "probe"

    def decide(self, obs, probe=None) -> int:
        if probe is None or obs.candidates_total == 0:
            return 0
        from ..apply.applier import _capacity_feasible

        feasible, _caps = _capacity_feasible()
        rows = probe(list(range(obs.candidates_total + 1)))
        for row in rows:  # rows arrive in ascending count order
            if feasible(row):
                return int(row.count) - obs.candidates_up
        # nothing feasible even with every candidate: take them all —
        # partial relief beats none, and the report shows the residue
        return obs.candidates_total - obs.candidates_up


def parse_policy(spec: str) -> Policy:
    """``name[:args][@profile]`` -> Policy. Examples: ``static:3``,
    ``threshold``, ``threshold:lo=20,patience=3``, ``probe@nospread``."""
    body, _, profile = spec.partition("@")
    profile = profile or "default"
    if profile not in SCORE_PROFILES:
        raise InputError(
            f"unknown score profile {profile!r} (have: "
            f"{', '.join(sorted(SCORE_PROFILES))})"
        )
    name, _, argstr = body.partition(":")
    kwargs = {}
    if name == "static":
        if not argstr:
            raise InputError("static policy needs a count: static:K")
        try:
            policy = StaticPolicy(int(argstr))
        except ValueError as e:
            raise InputError(f"static policy count {argstr!r}: {e}") from e
    elif name == "threshold":
        for part in filter(None, argstr.split(",")):
            k, sep, v = part.partition("=")
            if not sep:
                raise InputError(
                    f"threshold arg {part!r}: expected key=value"
                )
            kwargs[k] = v
        try:
            policy = ThresholdPolicy(
                lo=float(kwargs.pop("lo", 30.0)),
                patience=int(kwargs.pop("patience", 2)),
                step=int(kwargs.pop("step", 0)),
            )
        except ValueError as e:
            raise InputError(f"threshold policy args {argstr!r}: {e}") from e
        if kwargs:
            raise InputError(
                f"unknown threshold arg(s): {', '.join(sorted(kwargs))}"
            )
    elif name == "probe":
        if argstr:
            raise InputError("probe policy takes no args")
        policy = ProbePolicy()
    else:
        raise InputError(
            f"unknown policy {name!r} (have: static:K, threshold, probe)"
        )
    policy.profile = profile
    if profile != "default":
        policy.name = f"{policy.name}@{profile}"
    return policy


def parse_policies(specs: List[str]) -> List[Policy]:
    out = [parse_policy(s) for s in specs]
    names = [p.name for p in out]
    if len(set(names)) != len(names):
        raise InputError(f"duplicate policy names in {names}")
    return out
