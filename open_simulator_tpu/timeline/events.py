"""Timeline events: the typed model, the deterministic heap, the trace
file format, synthetic generators, and the shadow-log converter.

Event kinds (format version 1):

- ``PodArrival``   — a pod enters the cluster and wants scheduling (a
  pod arriving with ``spec.nodeName`` set occupies its node unscheduled,
  like the scan's original-pin convention);
- ``PodDeparture`` — a pod (named by ``namespace/name``) finishes and
  releases its capacity. The windowed stepper applies departures at the
  close of the window they fall in (docs/TIMELINE.md, "quantization");
- ``NodeJoin``     — a node (full spec carried in the event) becomes
  schedulable;
- ``NodeDrain``    — a node leaves gracefully: its scheduler-placed
  pods requeue through the full filter+score cycle;
- ``SpotReclaim``  — a spot node is reclaimed: identical displacement
  semantics to the chaos engine's outages (daemonset pods die with the
  node, original ``spec.nodeName`` pods are node-bound and lost);
- ``AutoscaleDecision`` — a recorded scale delta on the candidate node
  pool (written into reports by the policy loop; honored verbatim when
  present in an INPUT trace, so one run's decisions can be replayed
  against another workload).

Ordering is total and deterministic: ``(time, seq)`` with ``seq``
assigned in insertion order — equal-time events are FIFO, so a trace
replays byte-identically regardless of heap internals.

The trace file is JSONL riding the PR-2 journal discipline
(runtime/journal.py): a fingerprinted header line, one event per line,
flushed+fsync'd per append, torn final line tolerated on read, interior
damage and fingerprint mismatches refused loudly.
"""

from __future__ import annotations

import heapq
import json
import math
import os
from dataclasses import dataclass
from typing import Iterable, List, Optional, Tuple

from ..runtime.journal import JournalMismatch, config_fingerprint
from ..utils.gorand import GoRand

TRACE_VERSION = 1
TRACE_FORMAT = "timeline-trace"

POD_ARRIVAL = "PodArrival"
POD_DEPARTURE = "PodDeparture"
NODE_JOIN = "NodeJoin"
NODE_DRAIN = "NodeDrain"
SPOT_RECLAIM = "SpotReclaim"
AUTOSCALE_DECISION = "AutoscaleDecision"

EVENT_KINDS = (
    POD_ARRIVAL,
    POD_DEPARTURE,
    NODE_JOIN,
    NODE_DRAIN,
    SPOT_RECLAIM,
    AUTOSCALE_DECISION,
)

# kinds that change node capacity: the windowed stepper breaks a scan
# window at every one of these (stepper.py BOUNDARY_KINDS reads this)
CHURN_KINDS = (NODE_JOIN, NODE_DRAIN, SPOT_RECLAIM, AUTOSCALE_DECISION)


@dataclass
class Event:
    """One timeline event. ``time`` is seconds since trace start;
    ``seq`` totals the order (assigned by the heap / reader)."""

    time: float
    kind: str
    seq: int = 0
    pod: Optional[dict] = None  # PodArrival: the full pod object
    pod_ref: str = ""  # PodDeparture: "namespace/name"
    node: Optional[dict] = None  # NodeJoin: the full node object
    node_name: str = ""  # NodeDrain / SpotReclaim
    delta: int = 0  # AutoscaleDecision: candidate-pool delta
    reason: str = ""  # free-form provenance ("hazard", "policy:x")

    def key(self) -> Tuple[float, int]:
        return (self.time, self.seq)

    def as_record(self) -> dict:
        rec = {"kind": "event", "event": self.kind, "time": self.time,
               "seq": self.seq}
        if self.pod is not None:
            rec["pod"] = self.pod
        if self.pod_ref:
            rec["podRef"] = self.pod_ref
        if self.node is not None:
            rec["node"] = self.node
        if self.node_name:
            rec["nodeName"] = self.node_name
        if self.delta:
            rec["delta"] = self.delta
        if self.reason:
            rec["reason"] = self.reason
        return rec

    @classmethod
    def from_record(cls, rec: dict) -> "Event":
        kind = rec.get("event")
        if kind not in EVENT_KINDS:
            raise JournalMismatch(f"unknown timeline event kind {kind!r}")
        ev = cls(
            time=float(rec.get("time", 0.0)),
            kind=kind,
            seq=int(rec.get("seq", 0)),
            pod=rec.get("pod"),
            pod_ref=str(rec.get("podRef") or ""),
            node=rec.get("node"),
            node_name=str(rec.get("nodeName") or ""),
            delta=int(rec.get("delta") or 0),
            reason=str(rec.get("reason") or ""),
        )
        if kind == POD_ARRIVAL and not isinstance(ev.pod, dict):
            raise JournalMismatch("PodArrival event has no pod object")
        if kind == POD_DEPARTURE and not ev.pod_ref:
            raise JournalMismatch("PodDeparture event has no podRef")
        if kind == NODE_JOIN and not isinstance(ev.node, dict):
            raise JournalMismatch("NodeJoin event has no node object")
        if kind in (NODE_DRAIN, SPOT_RECLAIM) and not ev.node_name:
            raise JournalMismatch(f"{kind} event has no nodeName")
        return ev


class EventHeap:
    """Deterministic event priority queue ordered by ``(time, seq)``.

    ``push`` assigns the next ``seq`` when the event has none (seq 0
    and not yet claimed), so same-time events pop in insertion order —
    the autoscaler relies on this when it schedules warm-up NodeJoins
    mid-run. Pop order is a pure function of the pushed sequence:
    identical pushes produce identical traces, byte for byte."""

    def __init__(self, events: Iterable[Event] = ()):
        self._heap: List[Tuple[float, int, Event]] = []
        self._next_seq = 0
        for ev in events:
            self.push(ev)

    def push(self, ev: Event) -> Event:
        if ev.seq == 0 and self._next_seq > 0 or ev.seq < 0:
            ev.seq = self._next_seq
        self._next_seq = max(self._next_seq, ev.seq) + 1
        heapq.heappush(self._heap, (ev.time, ev.seq, ev))
        return ev

    def pop(self) -> Event:
        return heapq.heappop(self._heap)[2]

    def peek(self) -> Optional[Event]:
        return self._heap[0][2] if self._heap else None

    def __len__(self) -> int:
        return len(self._heap)

    def drain(self) -> List[Event]:
        out = []
        while self._heap:
            out.append(self.pop())
        return out


def trace_fingerprint(events: List[Event]) -> str:
    """Digest of a fully-ordered event list — the identity a report or
    journal is keyed on (two generators that emit the same events get
    the same fingerprint, whatever produced them)."""
    return config_fingerprint([ev.as_record() for ev in events])


class TraceWriter:
    """Append-only fsync'd JSONL trace writer (the journal append
    discipline: a crash keeps every event that finished writing).
    ``fsync_each=False`` batches durability to one fsync at close —
    for bulk writes of an already-complete event list, where the
    per-append discipline would pay ~1k fsyncs for nothing (the
    reader tolerates a torn tail either way)."""

    def __init__(self, path: str, fingerprint: str,
                 meta: Optional[dict] = None, fsync_each: bool = True):
        self.path = path
        self.written = 0
        self._fsync_each = fsync_each
        self._f = open(path, "w", encoding="utf-8")
        header = {
            "kind": "header",
            "version": TRACE_VERSION,
            "format": TRACE_FORMAT,
            "fingerprint": fingerprint,
        }
        if meta:
            header["meta"] = meta
        self._emit(header)

    def _emit(self, rec: dict):
        from ..runtime import inject as _inject

        line = json.dumps(rec, separators=(",", ":")) + "\n"
        # chaos crash point (runtime/inject.py): a `crash` clause
        # leaves a durable torn prefix, like a real mid-append death
        _inject.crash_write("journal.fsync.timeline", self._f, line)
        self._f.write(line)
        if self._fsync_each:
            self._f.flush()
            os.fsync(self._f.fileno())

    def append(self, ev: Event):
        self._emit(ev.as_record())
        self.written += 1

    def close(self):
        if self._f is not None:
            self._f.flush()
            os.fsync(self._f.fileno())
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def write_trace(path: str, events: List[Event], meta: Optional[dict] = None) -> str:
    """Write a complete event list; returns its fingerprint. One fsync
    at close — the list is complete (and, for synthetic specs,
    regenerable), so the per-append discipline buys nothing here."""
    fp = trace_fingerprint(events)
    with TraceWriter(path, fp, meta=meta, fsync_each=False) as w:
        for ev in events:
            w.append(ev)
    return fp


def read_trace(
    path: str, fingerprint: Optional[str] = None
) -> Tuple[List[Event], dict]:
    """Read a timeline trace: validate the header (and, when given, the
    trace fingerprint — mismatch refuses loudly), replay complete
    records, tolerate a torn final line. Returns ``(events, meta)``
    where meta carries the header plus ``{"dropped": n}``."""
    with open(path, "rb") as f:
        raw = f.read()
    lines = raw.split(b"\n")
    if not lines or not lines[0].strip():
        raise JournalMismatch(f"{path}: empty timeline trace")
    try:
        header = json.loads(lines[0])
    except ValueError as e:
        raise JournalMismatch(f"{path}: unreadable trace header: {e}") from e
    if not isinstance(header, dict) or header.get("kind") != "header":
        raise JournalMismatch(f"{path}: first record is not a header")
    if header.get("format") != TRACE_FORMAT:
        raise JournalMismatch(
            f"{path}: not a timeline trace (format {header.get('format')!r})"
        )
    if header.get("version") != TRACE_VERSION:
        raise JournalMismatch(
            f"{path}: timeline-trace version {header.get('version')!r} != "
            f"{TRACE_VERSION}"
        )
    if fingerprint is not None and header.get("fingerprint") != fingerprint:
        raise JournalMismatch(
            f"{path}: trace fingerprint {header.get('fingerprint')!r} does "
            f"not match ({fingerprint!r}); refusing to replay a trace "
            "recorded against different inputs"
        )
    body, tail = lines[1:-1], lines[-1]
    events: List[Event] = []
    dropped = 0

    def parse(line: bytes, lineno: int, torn_ok: bool) -> bool:
        try:
            rec = json.loads(line)
        except ValueError as e:
            if torn_ok:
                return False  # torn mid-append: expected damage
            raise JournalMismatch(
                f"{path}: corrupt trace record on line {lineno}: {e}"
            ) from e
        if not isinstance(rec, dict):
            if torn_ok:
                return False
            raise JournalMismatch(
                f"{path}: corrupt trace record on line {lineno}: record "
                "is not an object"
            )
        events.append(Event.from_record(rec))
        return True

    for i, line in enumerate(body):
        if line.strip():
            parse(line, i + 2, torn_ok=False)
    if tail.strip() and not parse(tail, len(lines), torn_ok=True):
        dropped = 1
    # a trace must already be totally ordered: the stepper walks it
    # sequentially and an out-of-order event would silently reorder
    # history (generated traces are ordered by construction)
    for prev, ev in zip(events, events[1:]):
        if ev.key() < prev.key():
            raise JournalMismatch(
                f"{path}: events out of order at seq {ev.seq} "
                f"(t={ev.time} after t={prev.time})"
            )
    meta = dict(header)
    meta["dropped"] = dropped
    return events, meta


# --------------------------------------------------- synthetic traces


def _float64(rng: GoRand) -> float:
    """Go ``Rand.Float64``: Int63 scaled into [0, 1) with the == 1.0
    rejection retry — keeps the synthetic stream on the same
    deterministic Go source every other seeded feature uses."""
    while True:
        f = rng.int63() / (1 << 63)
        if f != 1.0:
            return f


def _exp(rng: GoRand, rate: float) -> float:
    """Exponential(rate) draw via inversion of the Go Float64 stream."""
    return -math.log(1.0 - _float64(rng)) / rate


@dataclass
class SyntheticSpec:
    """Knobs of the seeded synthetic workload.

    ``arrivals`` Poisson pod arrivals at ``arrival_rate`` per second;
    each pod draws a size class (round-robin over ``pod_shapes``) and an
    exponential lifetime with mean ``mean_lifetime_s`` unless it lands
    in the ``long_running_frac`` (no departure). ``spot_frac`` of the
    BASE cluster's nodes (every ``1/spot_frac``-th by index) are spot
    instances, each reclaimed at an Exp(``spot_hazard``) time when that
    falls inside the horizon. All draws come from one seeded Go
    math/rand stream, so a spec + seed IS the trace."""

    arrivals: int = 200
    arrival_rate: float = 1.0  # pods per second
    mean_lifetime_s: float = 120.0
    long_running_frac: float = 0.5
    spot_frac: float = 0.0
    spot_hazard: float = 1.0 / 300.0  # reclaims per second per spot node
    seed: int = 1
    namespace: str = "timeline"
    # (cpu, memory) request shapes, cycled per arrival
    pod_shapes: Tuple[Tuple[str, str], ...] = (
        ("500m", "1Gi"),
        ("1", "2Gi"),
        ("250m", "512Mi"),
        ("2", "4Gi"),
    )

    def as_dict(self) -> dict:
        return {
            "arrivals": self.arrivals,
            "arrivalRate": self.arrival_rate,
            "meanLifetimeS": self.mean_lifetime_s,
            "longRunningFrac": self.long_running_frac,
            "spotFrac": self.spot_frac,
            "spotHazard": self.spot_hazard,
            "seed": self.seed,
        }


def _synthetic_pod(i: int, shape: Tuple[str, str], namespace: str) -> dict:
    cpu, mem = shape
    return {
        "kind": "Pod",
        "metadata": {
            "name": f"tl-pod-{i:05d}",
            "namespace": namespace,
            "labels": {"simon/timeline": "synthetic"},
        },
        "spec": {
            "containers": [
                {
                    "name": "c",
                    "image": "img-timeline",
                    "resources": {"requests": {"cpu": cpu, "memory": mem}},
                }
            ]
        },
    }


def generate_synthetic(
    spec: SyntheticSpec, node_names: Iterable[str] = ()
) -> List[Event]:
    """Deterministic synthetic trace: Poisson arrivals + exponential
    lifetimes + spot-reclaim hazard over the named base nodes. Same
    (spec, node list) -> byte-identical event list
    (tests/test_timeline.py pins this)."""
    rng = GoRand(spec.seed)
    heap = EventHeap()
    t = 0.0
    for i in range(spec.arrivals):
        t += _exp(rng, spec.arrival_rate)
        shape = spec.pod_shapes[i % len(spec.pod_shapes)]
        heap.push(Event(time=t, kind=POD_ARRIVAL,
                        pod=_synthetic_pod(i, shape, spec.namespace)))
        if _float64(rng) >= spec.long_running_frac:
            dep = t + _exp(rng, 1.0 / spec.mean_lifetime_s)
            if dep <= t:  # pragma: no cover - fp underflow guard
                dep = t + 1e-6
            heap.push(Event(
                time=dep, kind=POD_DEPARTURE,
                pod_ref=f"{spec.namespace}/tl-pod-{i:05d}",
                reason="lifetime",
            ))
    horizon = t
    if spec.spot_frac > 0:
        stride = max(int(round(1.0 / spec.spot_frac)), 1)
        for k, name in enumerate(node_names):
            if k % stride:
                continue
            reclaim = _exp(rng, spec.spot_hazard)
            if reclaim <= horizon:
                heap.push(Event(time=reclaim, kind=SPOT_RECLAIM,
                                node_name=name, reason="hazard"))
    events = heap.drain()
    # departures past the horizon stay (capacity still frees inside the
    # trace tail window); seqs are re-stamped in final order so the
    # serialized trace is its own canonical ordering
    for seq, ev in enumerate(events):
        ev.seq = seq
    return events


# --------------------------------------- shadow decision-log converter


def events_from_decision_log(steps) -> List[Event]:
    """Convert shadow decision-log steps (shadow/log.py) into a
    timeline trace — the PR-7 tail item: recorded real-cluster history
    replays through what-if policies.

    Mapping (one time unit per step, preserving order):

    - a ``decision`` step's pod becomes a PodArrival — the TIMELINE
      re-decides placement, so the real scheduler's chosen node is
      dropped (that is the point: what would THIS policy have done);
      failed decisions arrive too (the pod wants scheduling);
    - ``place_pod`` deltas (pre-bound arrivals) become PodArrivals that
      keep their ``spec.nodeName`` — original-pin semantics;
    - ``evict_pod`` deltas become PodDepartures;
    - ``add_node`` / ``remove_node`` deltas become NodeJoin/NodeDrain.
    """
    events: List[Event] = []
    t = 0.0
    for step in steps:
        t += 1.0
        for op in step.deltas:
            name = op.get("op")
            if name == "place_pod" and isinstance(op.get("pod"), dict):
                events.append(Event(time=t, kind=POD_ARRIVAL,
                                    pod=op["pod"], reason="prebound"))
            elif name == "evict_pod":
                ref = (f"{op.get('namespace') or 'default'}/"
                       f"{op.get('name') or ''}")
                events.append(Event(time=t, kind=POD_DEPARTURE,
                                    pod_ref=ref, reason="evicted"))
            elif name == "add_node" and isinstance(op.get("node"), dict):
                events.append(Event(time=t, kind=NODE_JOIN,
                                    node=op["node"], reason="churn"))
            elif name == "remove_node":
                events.append(Event(time=t, kind=NODE_DRAIN,
                                    node_name=str(op.get("name") or ""),
                                    reason="churn"))
            else:
                raise JournalMismatch(
                    f"decision-log delta op {name!r} has no timeline mapping"
                )
        if step.kind == "decision" and isinstance(step.pod, dict):
            pod = dict(step.pod)
            # the decision pod is UNSCHEDULED by the log contract; any
            # stray binding must not become an original pin here
            if isinstance(pod.get("spec"), dict) and pod["spec"].get("nodeName"):
                pod["spec"] = {
                    k: v for k, v in pod["spec"].items() if k != "nodeName"
                }
            events.append(Event(time=t, kind=POD_ARRIVAL, pod=pod,
                                reason="decision"))
    for seq, ev in enumerate(events):
        ev.seq = seq
    return events
