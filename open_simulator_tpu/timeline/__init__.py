"""Discrete-event cluster timeline (`simon timeline`).

Everything else in the framework answers static questions — does this
fit, how many nodes, does the plan survive an outage, did the real
scheduler agree. The timeline adds the time axis (ROADMAP item 3): pod
arrivals and departures, node churn and spot reclamation, and a
simulated cluster-autoscaler closing the reference's interactive
add-node planner (pkg/apply/apply.go:186-239) over time, with
head-to-head policy comparison on one shared trace.

Modules:

- ``events``  — typed events, the deterministic event heap, the
  fingerprinted JSONL trace format, seeded synthetic generators
  (Poisson arrivals, exponential lifetimes, spot-reclaim hazard), and
  the shadow decision-log converter;
- ``stepper`` — the windowed stepper: consecutive arrivals batch into
  encode-once masked scan windows riding the chaos-style per-scenario
  (node_valid, pod_active, pinned) rows, so a 1000-step trace costs a
  handful of device dispatches instead of 1000 ``simulate()`` calls;
- ``autoscaler`` — the pluggable policy loop (static / threshold /
  capacity-probe) with decision cadence and node warm-up delay;
- ``compare``  — N policies as batched scenario rows over one trace;
- ``report``   — per-step cost/utilization/pending curves, text+JSON.
"""

from .events import (  # noqa: F401
    AUTOSCALE_DECISION,
    NODE_DRAIN,
    NODE_JOIN,
    POD_ARRIVAL,
    POD_DEPARTURE,
    SPOT_RECLAIM,
    Event,
    EventHeap,
    SyntheticSpec,
    TraceWriter,
    events_from_decision_log,
    generate_synthetic,
    read_trace,
    trace_fingerprint,
)
from .autoscaler import Policy, parse_policy  # noqa: F401
from .compare import run_policies  # noqa: F401
from .report import PolicyTimeline, TimelineComparison  # noqa: F401
from .stepper import TimelineStepper  # noqa: F401
