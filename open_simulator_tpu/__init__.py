"""open-simulator-tpu: a TPU-native cluster-scheduling simulator.

A from-scratch re-design of the capabilities of `alibaba/open-simulator`
(reference at /root/reference, pure Go) for TPU hardware:

- Cluster state lives as HBM-resident tensors (node capacity matrix, pod
  request matrix, vocab-encoded labels/taints/selectors).
- The kube-scheduler filter/score plugin pipeline (reference:
  vendor/k8s.io/kubernetes/pkg/scheduler) is re-implemented as pure JAX
  functions fused over the node axis and driven by a `lax.scan`
  sequential-commit loop that reproduces the serial one-pod-at-a-time
  semantics of the reference (pkg/simulator/simulator.go:218-243) without
  its goroutine/channel handshake.
- Capacity planning (reference pkg/apply/apply.go:186-239) is a batched
  what-if sweep over candidate node counts/specs, shardable over a TPU
  device mesh.

Layout:
  models/     host-side k8s object model, YAML ingestion, workload->pod
              controller emulation, chart rendering
  ops/        JAX tensor encoding + filter/score/scan kernels
  scheduler/  oracle (serial python reference) + TPU engine + Simulate facade
  parallel/   device-mesh sharding for sweeps and huge clusters
  apply/      capacity planner + reports
"""

__version__ = "0.1.0"
