"""Persistent compile-artifact store: zero-compile cold starts.

PR 10 made every jit site an AOT-compiled named executable keyed by
shape-signature — but only in-process: a fresh ``simon serve`` re-pays
the full XLA compile bill before its first answer. This module
persists those executables across processes as a content-addressed
on-disk store:

- one file per (site, shape-signature) under ``--aot-store DIR`` (or
  ``SIMON_AOT_STORE``), named by a sha256 of the site, the rendered
  signature, and the TOOL DIGEST (jax/jaxlib versions, backend
  platform + version, device count, store schema) — an artifact
  compiled by a different toolchain can never be offered to this one;
- entries are written crash-safely (tmp + ``os.replace``, the PR-2
  journal discipline) with a JSON header carrying the payload sha256
  and the cost/memory analysis, so verification happens BEFORE any
  payload deserialization;
- stale / corrupt / digest-mismatched entries are refused LOUDLY
  (``aot_store_reject_total`` + a warning naming the file and why) and
  the site recompiles — a bad store can cost a compile, never an
  answer;
- serialization rides ``jax.experimental.serialize_executable``; on
  backends where executable export is unsupported the store degrades
  to enabling JAX's own persistent compilation cache rooted in the
  same directory (``xla-cache/``), keyed by jax's hashes instead of
  ours — cold starts still skip XLA, only the loaded-cost bookkeeping
  is lost.

The load path is a guard seam (``aot.store_load`` injection point):
classified faults degrade to a counted miss + recompile, identical
results — the chaos matrix drives this (tests/test_chaos_matrix.py).

Counters (``/metrics`` as ``simon_aot_store_*``, bench obs blocks via
``aot_store_block``): ``aot_store_hit_total``, ``aot_store_miss_total``,
``aot_store_reject_total``, ``aot_store_save_total`` (+ per-site
variants for hits).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
import struct
import tempfile
import threading
from contextlib import suppress
from typing import Optional

from ..runtime import inject as _inject
from ..runtime.errors import (
    BackendUnavailable,
    CompileFailure,
    DeviceOOM,
    ExternalIOError,
)
from ..utils.trace import COUNTERS

log = logging.getLogger(__name__)

STORE_ENV = "SIMON_AOT_STORE"
#: force the persistent-compilation-cache fallback even where
#: executable serialization works (testing / debugging knob)
MODE_ENV = "SIMON_AOT_STORE_MODE"

#: bump when the entry layout changes — old entries then digest-miss
#: (they were keyed with the old schema string) instead of misparsing
_SCHEMA = "simon-aot-1"
_MAGIC = b"SIMONAOT\n"

#: faults at the load seam that degrade to a counted recompile; an
#: unclassified error or a ConformanceError stays loud
_DEGRADABLE = (
    DeviceOOM,
    CompileFailure,
    BackendUnavailable,
    ExternalIOError,
    OSError,
)


def _tool_digest() -> str:
    """Digest of everything that makes a serialized executable
    loadable HERE: jax + jaxlib versions, backend platform and its
    runtime version, device count (a 1-device artifact must not load
    into an 8-device mesh process), and the store schema."""
    import jax

    backend = jax.devices()[0]
    client = getattr(backend, "client", None)
    parts = (
        _SCHEMA,
        getattr(jax, "__version__", "?"),
        getattr(getattr(jax, "lib", None), "__version__", "?"),
        getattr(backend, "platform", "?"),
        str(getattr(client, "platform_version", "?")),
        str(jax.device_count()),
    )
    return hashlib.sha256("|".join(parts).encode()).hexdigest()[:24]


def render_signature(site: str, key) -> Optional[str]:
    """Deterministic cross-process text of an InstrumentedJit
    shape-signature ``(treedef, ((shape, dtype, weak) | ('static',
    leaf), ...))``. Static leaves render by repr — ScanFeatures /
    ScoreWeights NamedTuples, bools, ints and strings are all
    repr-stable. A leaf whose repr leaks an object identity (``0x``
    address) cannot key a cross-process store: return None and the
    signature stays in-process only (counted miss, never a wrong
    hit)."""
    try:
        treedef, sig = key
        rendered = f"{treedef}|{sig!r}"
    except (TypeError, ValueError):
        return None
    if " at 0x" in rendered or "object at" in rendered:
        return None
    return f"{site}|{rendered}"


class ArtifactStore:
    """One directory of compiled-executable entries. Thread-safe: the
    lock covers the fallback latch; file operations are atomic
    (tmp + rename) and idempotent per digest."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._lock = threading.Lock()
        # None = undecided (probe on first save), True = executable
        # serialization unsupported here -> jax persistent cache mode
        self._fallback: Optional[bool] = None
        if os.environ.get(MODE_ENV, "") == "cache":
            self._fallback = True
            self._enable_jax_cache()
        self.tool = _tool_digest()

    # -- keying ------------------------------------------------------------

    def entry_path(self, site: str, key) -> Optional[str]:
        rendered = render_signature(site, key)
        if rendered is None:
            return None
        digest = hashlib.sha256(
            f"{self.tool}|{rendered}".encode()
        ).hexdigest()[:32]
        safe_site = "".join(c if c.isalnum() or c in "-_" else "_" for c in site)
        return os.path.join(self.root, f"{safe_site}-{digest}.aotx")

    # -- load --------------------------------------------------------------

    def load(self, site: str, key):
        """Return ``(compiled, CostRecord)`` for a verified store entry,
        or None (counted miss/reject — the caller compiles). Never
        raises for a bad entry: a corrupt store costs a compile, not an
        answer. The ``aot.store_load`` chaos seam lives here; classified
        faults degrade to a reject + recompile."""
        path = self.entry_path(site, key)
        if path is None:
            COUNTERS.inc("aot_store_miss_total")
            return None
        try:
            _inject.fire("aot.store_load", jit_site=site)
            with self._lock:
                fallback = self._fallback
            if fallback:
                # jax's own cache does the persistence; our load is
                # always a miss (the compile below hits jax's cache)
                COUNTERS.inc("aot_store_miss_total")
                return None
            if not os.path.exists(path):
                COUNTERS.inc("aot_store_miss_total")
                COUNTERS.inc(f"aot_store_miss_{site}")
                return None
            with open(path, "rb") as f:
                blob = f.read()
            header, payload = self._parse(path, blob)
            if header is None:
                COUNTERS.inc("aot_store_reject_total")
                return None
            entry = self._deserialize(site, path, header, payload)
            if entry is None:
                COUNTERS.inc("aot_store_reject_total")
                return None
            COUNTERS.inc("aot_store_hit_total")
            COUNTERS.inc(f"aot_store_hit_{site}")
            from ..utils.trace import GLOBAL

            GLOBAL.note("aot-store-hit", site)
            return entry
        except _DEGRADABLE as e:
            # the degradation contract of the chaos matrix: a store
            # fault (injected or real I/O) is a loud reject + recompile
            log.warning(
                "aot store: load of %s degraded to recompile (%s: %s)",
                site, type(e).__name__, str(e).split("\n", 1)[0][:120],
            )
            COUNTERS.inc("aot_store_reject_total")
            from ..utils.trace import GLOBAL

            GLOBAL.note("aot-store-degraded", f"{site}: {type(e).__name__}")
            return None

    def _parse(self, path: str, blob: bytes):
        """Split + verify an entry file. Returns (header, payload) or
        (None, None) with the refusal logged — every branch names the
        file and the exact mismatch."""
        if not blob.startswith(_MAGIC):
            log.warning("aot store: %s: bad magic; refusing entry", path)
            return None, None
        off = len(_MAGIC)
        if len(blob) < off + 4:
            log.warning("aot store: %s: truncated header length", path)
            return None, None
        (hlen,) = struct.unpack(">I", blob[off:off + 4])
        off += 4
        if len(blob) < off + hlen:
            log.warning("aot store: %s: truncated header (torn write?)", path)
            return None, None
        try:
            header = json.loads(blob[off:off + hlen].decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            log.warning("aot store: %s: unparseable header", path)
            return None, None
        payload = blob[off + hlen:]
        if header.get("tool") != self.tool:
            log.warning(
                "aot store: %s: toolchain digest mismatch (entry %s, "
                "process %s); refusing and recompiling",
                path, header.get("tool"), self.tool,
            )
            return None, None
        sha = hashlib.sha256(payload).hexdigest()
        if header.get("payload_sha256") != sha:
            log.warning(
                "aot store: %s: payload sha256 mismatch (corrupt entry); "
                "refusing and recompiling", path,
            )
            return None, None
        return header, payload

    def _deserialize(self, site: str, path: str, header: dict, payload: bytes):
        """Rehydrate a verified payload into ``(compiled, CostRecord)``.
        The sha256 gate ran already, so unpickling is over bytes we
        wrote ourselves."""
        from ..obs.costs import CostRecord

        try:
            from jax.experimental import serialize_executable

            ser, in_tree, out_tree = pickle.loads(payload)
            compiled = serialize_executable.deserialize_and_load(
                ser, in_tree, out_tree
            )
        except Exception as e:  # noqa: BLE001 - any rehydration fault degrades to a counted reject + recompile; the compile path surfaces real errors
            log.warning(
                "aot store: %s: deserialization failed (%s); refusing and "
                "recompiling", path, str(e).split("\n", 1)[0][:120],
            )
            return None
        cost = header.get("cost") or {}
        rec = CostRecord(
            site=site,
            flops=float(cost.get("flops", 0.0)),
            bytes_accessed=float(cost.get("bytes_accessed", 0.0)),
            argument_bytes=int(cost.get("argument_bytes", 0)),
            output_bytes=int(cost.get("output_bytes", 0)),
            temp_bytes=int(cost.get("temp_bytes", 0)),
            generated_code_bytes=int(cost.get("generated_code_bytes", 0)),
            lead_dim=int(cost.get("lead_dim", 0)),
        )
        return compiled, rec

    # -- save --------------------------------------------------------------

    def save(self, site: str, key, compiled, rec) -> bool:
        """Serialize one freshly-compiled executable, crash-safely
        (tmp + rename). Serialization being unsupported on this
        backend latches the jax-persistent-cache fallback instead; any
        other failure is logged and skipped (the store is an
        optimization, never load-bearing)."""
        path = self.entry_path(site, key)
        with self._lock:
            fallback = self._fallback
        if path is None or fallback:
            return False
        try:
            from jax.experimental import serialize_executable

            payload = pickle.dumps(serialize_executable.serialize(compiled))
        except Exception as e:  # noqa: BLE001 - export support is backend-optional: probe result decides the fallback, never crashes the dispatch
            enable = False
            with self._lock:
                if self._fallback is None:
                    self._fallback = True
                    enable = True
            if enable:
                log.warning(
                    "aot store: executable serialization unavailable "
                    "on this backend (%s); falling back to the JAX "
                    "persistent compilation cache under %s",
                    str(e).split("\n", 1)[0][:120], self.root,
                )
                self._enable_jax_cache()
            return False
        with self._lock:
            if self._fallback is None:
                self._fallback = False
        header = {
            "schema": _SCHEMA,
            "site": site,
            "tool": self.tool,
            "payload_sha256": hashlib.sha256(payload).hexdigest(),
            "cost": dict(rec.as_dict(), site=site),
        }
        hbytes = json.dumps(header, sort_keys=True).encode()
        try:
            fd, tmp = tempfile.mkstemp(
                dir=self.root, prefix=os.path.basename(path) + ".tmp."
            )
            renamed = False
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(_MAGIC)
                    f.write(struct.pack(">I", len(hbytes)))
                    f.write(hbytes)
                    f.write(payload)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
                renamed = True
            finally:
                if not renamed:
                    # the tmp file must not linger on ANY failure path
                    # (including an injected crash riding through);
                    # best-effort — the raising error is the real story
                    with suppress(OSError):
                        os.unlink(tmp)
        except OSError as e:
            log.warning(
                "aot store: save of %s failed (%s); entry skipped",
                site, str(e).split("\n", 1)[0][:120],
            )
            return False
        COUNTERS.inc("aot_store_save_total")
        return True

    # -- fallback ----------------------------------------------------------

    def _enable_jax_cache(self) -> None:
        """Best-effort enablement of JAX's persistent compilation cache
        rooted inside the store directory — the degraded mode for
        backends without executable export. Thresholds open wide so
        even sub-second compiles persist."""
        try:
            import jax

            cache_dir = os.path.join(self.root, "xla-cache")
            os.makedirs(cache_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            for knob, value in (
                ("jax_persistent_cache_min_compile_time_secs", 0),
                ("jax_persistent_cache_min_entry_size_bytes", -1),
            ):
                try:
                    jax.config.update(knob, value)
                except (AttributeError, ValueError):
                    # knob absent on this jax release: defaults apply
                    log.debug("aot store: jax knob %s unavailable", knob)
        except Exception as e:  # noqa: BLE001 - the fallback of the fallback is plain recompilation; log and move on
            log.warning(
                "aot store: persistent compilation cache unavailable "
                "(%s); artifacts will not persist",
                str(e).split("\n", 1)[0][:120],
            )

    def stats(self) -> dict:
        with self._lock:
            fallback = bool(self._fallback)
        return {
            "root": self.root,
            "tool": self.tool,
            "fallback": fallback,
            "entries": len(
                [n for n in os.listdir(self.root) if n.endswith(".aotx")]
            ),
        }


# ---------------------------------------------------------- process wiring

_STORE: Optional[ArtifactStore] = None
_STORE_LOCK = threading.Lock()
_ENV_CHECKED = False


def configure_store(path: Optional[str]) -> Optional[ArtifactStore]:
    """Arm (or disarm with None/'') the process-wide artifact store —
    the ``--aot-store DIR`` wiring. Returns the live store."""
    global _STORE, _ENV_CHECKED
    with _STORE_LOCK:
        _ENV_CHECKED = True
        if not path:
            _STORE = None
        else:
            _STORE = ArtifactStore(path)
        return _STORE


def current_store() -> Optional[ArtifactStore]:
    """The armed store, auto-configuring from ``SIMON_AOT_STORE`` on
    first consultation (subprocess surfaces need no flag plumbing)."""
    global _STORE, _ENV_CHECKED
    if _STORE is None and not _ENV_CHECKED:
        with _STORE_LOCK:
            if not _ENV_CHECKED:
                _ENV_CHECKED = True
                env = os.environ.get(STORE_ENV, "")
                if env:
                    _STORE = ArtifactStore(env)
    return _STORE


# ---------------------------------------------------------- obs blocks


def aot_store_block() -> dict:
    """Store counters for bench obs lines / trace artifacts / the
    doctor (hit_rate is the doctor-gated dimension)."""
    hits = COUNTERS.get("aot_store_hit_total")
    misses = COUNTERS.get("aot_store_miss_total")
    rejects = COUNTERS.get("aot_store_reject_total")
    saves = COUNTERS.get("aot_store_save_total")
    if not (hits or misses or rejects or saves):
        return {}
    return {
        "hits": hits,
        "misses": misses,
        "rejects": rejects,
        "saves": saves,
        "hit_rate": round(hits / max(1, hits + misses), 4),
    }


def incremental_block() -> dict:
    """Delta re-simulation counters (resim.py + the serve/twin/timeline
    wiring) for bench obs lines — suffix_fraction is the doctor-gated
    dimension: re-dispatched rows over rows the prefix reuse saved."""
    suffix = COUNTERS.get("incremental_suffix_pods_total")
    prefix = COUNTERS.get("incremental_prefix_reused_pods_total")
    if not (suffix or prefix):
        return {}
    return {
        "suffix_pods": suffix,
        "prefix_reused_pods": prefix,
        "suffix_fraction": round(suffix / max(1, suffix + prefix), 6),
        "resims": COUNTERS.get("incremental_resims_total"),
        "full_rebuilds": COUNTERS.get("incremental_full_rebuilds_total"),
        "fallbacks": COUNTERS.get("incremental_fallbacks_total"),
    }
