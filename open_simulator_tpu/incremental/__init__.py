"""Incremental execution: millisecond warm paths, zero-compile cold starts.

ROADMAP item 3, in two halves:

- ``store.py`` — a persistent, content-addressed on-disk store for the
  AOT-compiled executables the observatory already builds per
  shape-signature (obs/profile.py / obs/costs.py). A fresh ``simon
  serve`` / ``simon twin`` pointed at a warm store answers its first
  request with ZERO new XLA compiles; stale / corrupt / wrong-toolchain
  entries are refused loudly and recompiled.

- ``resim.py`` — delta re-simulation over the committed placement
  journal: a warm serve session keeps its cluster pods COMMITTED in a
  resident oracle (the "committed scan"), so a what-if request
  dispatches only its own few pods (the suffix) instead of re-scanning
  the whole roster, and a ``/v1/cluster-delta`` re-simulates only the
  journal suffix its conservative dependency rule says could change —
  placements stay byte-identical to a full re-scan (conformance-gated).
"""

from .resim import CommittedScan, SuffixDecision, suffix_for_delta  # noqa: F401
from .store import (  # noqa: F401
    ArtifactStore,
    aot_store_block,
    configure_store,
    current_store,
    incremental_block,
)
