"""Delta re-simulation: a placement-journal prefix index over the
committed scan.

A warm serve session answers what-if requests against a cluster whose
committed pods change rarely and by a handful at a time — yet every
tick used to re-scan the WHOLE roster (cluster pods active in every
scenario row). This module keeps the committed placements as a
resident journal:

- ``CommittedScan`` runs the roster through the existing engine path
  ONCE (``scheduler/core.Simulator._schedule_pods`` — the same
  begin_batch / scan_active / replay machinery as a standalone
  ``simulate()``), keeps the resulting oracle WARM, and records a
  per-pod journal row: how each roster position committed (bulk-simple
  / pinned / failed / dangling / side-effect) plus the node name and
  the per-class RequestSummary tables of the PR-3 bulk replay.
- What-if requests then dispatch ONLY their own pods (the suffix)
  against the committed oracle's dynamic state — the sequential-commit
  property makes this placement-identical to scanning cluster + request
  pods from scratch (exactly the multi-app contract of
  ``schedule_app``), and the serve conformance gates assert the bytes.
- A ``ClusterDelta`` re-simulates only the journal SUFFIX that its
  conservative dependency rule (``suffix_for_delta``) says could
  change: the prefix replays host-side from the journal (bulk
  scatter-add commits, no device work, no re-encode), and one
  suffix-sized scan re-decides the rest. Placements are byte-identical
  to a full re-scan (conformance-gated over seeded random delta
  streams, tests/test_incremental.py).

Conservatism (the suffix rule table, docs/PERFORMANCE.md): priority
tiers / preemption and side-effectful plugin classes (gpushare,
open-local storage, extenders) force the FULL suffix — their commit
order couples arbitrary positions, so "could change" is everything.
The rule is allowed to widen, never to narrow: a wrong-but-wide suffix
costs time, a wrong-but-narrow one would cost correctness.

The ``incremental.suffix`` chaos seam lives at the head of every
re-simulation; classified faults degrade to the full re-scan with
identical results (tests/test_chaos_matrix.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

from ..runtime import inject as _inject
from ..utils.trace import COUNTERS

# journal codes: how a roster position committed
S_BULK = 0      # simple class, bulk-replayable (PR-3 scatter-add)
S_PINNED = 1    # spec.nodeName pin to a known node (place_existing_pod)
S_FAILED = 2    # unschedulable; reason cached at its own step state
S_DANGLING = 3  # pinned to an unknown node; tracked, never scheduled
S_SIDE = 4      # placed through a side-effect class (GPU/storage/…)

_CODE_NAMES = {
    S_BULK: "bulk", S_PINNED: "pinned", S_FAILED: "failed",
    S_DANGLING: "dangling", S_SIDE: "side-effect",
}


def own_pod(p: dict) -> dict:
    """Shallow-clone the mutation surface of a pod dict (bind writes
    spec.nodeName / status.phase / metadata.annotations) — the serve
    Session idiom: roster dicts stay pristine for later encodes."""
    q = dict(p)
    q["spec"] = dict(p.get("spec") or {})
    meta = dict(p.get("metadata") or {})
    if meta.get("annotations") is not None:
        meta["annotations"] = dict(meta["annotations"])
    q["metadata"] = meta
    if isinstance(q.get("status"), dict):
        q["status"] = dict(q["status"])
    return q


@dataclass
class SuffixDecision:
    """Where re-simulation must begin. ``start == roster_len`` means
    nothing needs re-deciding; ``full`` forces position 0 with the
    journal prefix discarded."""

    start: int
    full: bool
    reason: str

    @property
    def trivial(self) -> bool:
        return not self.full and self.start < 0


def suffix_for_delta(
    kind: str,
    roster_len: int,
    *,
    positions=(),
    insert_position: Optional[int] = None,
    has_priority: bool = False,
    has_side_effects: bool = False,
) -> SuffixDecision:
    """The conservative dependency rule: given a delta's kind and the
    roster positions it touches, the earliest journal position whose
    feasible-node set or queue order could change.

    ============  =========================================================
    delta          suffix
    ============  =========================================================
    pod_evict /    from the evicted position — earlier pods committed
    pod_delete     against state the eviction cannot reach
    pod_arrive /   from the insertion position (min with the replaced
    pod_bind       position on re-arrival of a live key)
    node_drain     from the first position journaled ONTO a drained node
                   (losing a non-chosen node never flips an earlier
                   first-max winner); callers with daemonsets reload
                   the whole session instead (roster itself changes)
    node_join      FULL — any pod could have preferred the new node
    any, when the  FULL — priority tiers / preemption couple arbitrary
    roster carries positions; side-effect classes (gpushare, storage,
    priority or    extenders) thread allocator state through commit
    side effects   order
    ============  =========================================================
    """
    if has_priority:
        return SuffixDecision(0, True, "priority tiers force the full suffix")
    if has_side_effects:
        return SuffixDecision(
            0, True, "side-effect classes force the full suffix"
        )
    if kind == "node_join":
        return SuffixDecision(0, True, "node_join: any pod could prefer it")
    touched = [int(p) for p in positions if p is not None and p >= 0]
    if insert_position is not None:
        touched.append(int(insert_position))
    if not touched:
        return SuffixDecision(-1, False, f"{kind}: no journal position touched")
    start = min(touched)
    if start <= 0:
        return SuffixDecision(0, True, f"{kind}: suffix is the whole journal")
    return SuffixDecision(min(start, roster_len), False, f"{kind}")


class CommittedScan:
    """The committed roster, scanned once and kept warm: a resident
    oracle + engine over the committed state, the per-position journal,
    and the PR-3 bulk-commit tables that make prefix replay a
    scatter-add instead of a re-scan."""

    def __init__(self, nodes: List[dict], roster: List[dict],
                 _prefix_from: Optional["CommittedScan"] = None,
                 _prefix_len: int = 0):
        from ..utils.trace import phase

        self.nodes = nodes
        self.total = len(roster)
        self.codes = np.zeros(self.total, dtype=np.int8)
        self.node_names: List[Optional[str]] = [None] * self.total
        self.reasons: Dict[int, str] = {}
        self.cls_rows = np.full(self.total, -1, dtype=np.int64)
        self.failed = []  # UnscheduledPod, roster order
        # grown per-class commit tables (PR-3 bulk replay vocabulary);
        # suffix re-simulations append their batch's classes
        self.field_tbl = np.zeros((0, 7), dtype=np.int64)
        self.ports_of: list = []
        self.scalars_of: list = []
        # priority/preemption couple commit order to arbitrary earlier
        # positions (evicted victims requeue): a scan that saw either
        # can never seed a positional prefix replay
        self._ordering_coupled = False
        with phase("incremental/committed-scan"):
            self._build(roster, _prefix_from, _prefix_len)

    # -- construction --------------------------------------------------------

    def _build(self, roster, prefix_from, prefix_len):
        from ..scheduler.oracle import Oracle

        oracle = Oracle(self.nodes)
        start = 0
        if prefix_from is not None and prefix_len > 0:
            self._replay_prefix(oracle, roster, prefix_from, prefix_len)
            start = prefix_len
        self.oracle = oracle
        self.engine = self._scan_suffix(roster, start)
        COUNTERS.gauge("incremental_committed_pods", float(self.total))

    def _replay_prefix(self, oracle, roster, prev: "CommittedScan", n: int):
        """Host-only replay of journal positions [0, n) — the reused
        prefix: bulk scatter-add for simple runs, per-pod paths for
        pins and the cached failure reasons. No encode, no dispatch."""
        from ..scheduler.core import UnscheduledPod

        # COMPACT the inherited class tables to the rows the prefix
        # actually references: chained re-simulations would otherwise
        # grow field_tbl/ports_of/scalars_of by every suffix batch's
        # classes forever (a resident daemon on a steady delta stream
        # never full-rebuilds), leaking memory and making the vstack
        # per delta progressively slower
        codes = prev.codes[:n]
        old_rows = prev.cls_rows[:n]
        used = np.unique(old_rows[old_rows >= 0])
        if len(used):
            remap = np.full(int(used[-1]) + 1, -1, dtype=np.int64)
            remap[used] = np.arange(len(used))
            self.field_tbl = prev.field_tbl[used]
            self.ports_of = [prev.ports_of[int(o)] for o in used.tolist()]
            self.scalars_of = [
                prev.scalars_of[int(o)] for o in used.tolist()
            ]
            self.cls_rows[:n] = np.where(
                old_rows >= 0, remap[np.clip(old_rows, 0, None)], -1
            )
        self.codes[:n] = codes
        self.node_names[:n] = prev.node_names[:n]
        copies = [own_pod(roster[i]) for i in range(n)]
        node_index = oracle.node_index

        def bulk(a, b):
            if b <= a:
                return
            idx = np.fromiter(
                (node_index[self.node_names[i]] for i in range(a, b)),
                dtype=np.int64, count=b - a,
            )
            oracle.commit_simple_bulk(
                copies[a:b], idx, self.cls_rows[a:b],
                self.field_tbl, self.ports_of, self.scalars_of,
            )

        prev_i = 0
        for e in np.flatnonzero(codes != S_BULK).tolist():
            bulk(prev_i, e)
            prev_i = e + 1
            pod, code = copies[e], int(codes[e])
            if code == S_PINNED:
                oracle.place_existing_pod(pod)
            elif code == S_FAILED:
                self.reasons[e] = prev.reasons[e]
                self.failed.append(
                    UnscheduledPod(pod=pod, reason=prev.reasons[e])
                )
            elif code == S_DANGLING:
                pass  # tracked, never scheduled, absent from node status
            else:  # S_SIDE in a prefix replay: the caller's rule is wrong
                from ..runtime.errors import ConformanceError

                raise ConformanceError(
                    "side-effect journal entry inside a reused prefix — "
                    "suffix_for_delta must force the full suffix"
                )
        bulk(prev_i, n)
        COUNTERS.inc("incremental_prefix_reused_pods_total", n)

    def _scan_suffix(self, roster, start: int):
        """Scan roster[start:] through the real engine path against the
        oracle's current (prefix) state, then journal how every
        position committed. Returns the warm engine."""
        from ..scheduler.core import Simulator
        from ..scheduler.engine import TpuEngine

        suffix = [own_pod(p) for p in roster[start:]]
        sim = Simulator(engine="tpu")
        sim.oracle = self.oracle
        result = sim._schedule_pods(suffix, build_status=False)
        if result.preemptions or self.oracle.saw_priority:
            self._ordering_coupled = True
        COUNTERS.inc("incremental_suffix_pods_total", len(suffix))
        engine = sim._engine
        self._journal_window(roster, start, suffix, result, engine)
        if engine is None or engine.oracle is not self.oracle:
            engine = TpuEngine(self.oracle)
        return engine

    def _journal_window(self, roster, start, copies, result, engine):
        """Fill journal rows [start, start+len(copies)) from the commit
        outcome: the bound copies carry their node, the engine batch
        carries the class vocabulary for later bulk replays."""
        from ..scheduler.engine import build_bulk_tables

        failed_by_id = {id(up.pod): up for up in result.unscheduled_pods}
        self.failed.extend(result.unscheduled_pods)
        node_index = self.oracle.node_index
        cls_of = simple = bulk_ok = None
        offset = len(self.ports_of)
        if engine is not None and engine._batch is not None:
            cls_of = np.asarray(engine._last_class_of)
            simple = engine._last_simple
            field_tbl, ports_of, scalars_of, bulk_ok = build_bulk_tables(
                engine._batch, simple
            )
            self.field_tbl = (
                np.vstack([self.field_tbl, field_tbl])
                if len(self.field_tbl)
                else field_tbl
            )
            self.ports_of = list(self.ports_of) + list(ports_of)
            self.scalars_of = list(self.scalars_of) + list(scalars_of)
        # the engine batch covers the NON-dangling window pods in
        # order (core._scan_and_commit's pos_of contract), so walking
        # the copies while skipping dangling entries recovers each
        # pod's batch position — and with it its class row
        batch_pos = 0
        for k, pod in enumerate(copies):
            i = start + k
            up = failed_by_id.get(id(pod))
            name = (pod.get("spec") or {}).get("nodeName")
            pinned = bool((roster[i].get("spec") or {}).get("nodeName"))
            if name and name not in node_index:
                self.codes[i] = S_DANGLING
                self.node_names[i] = name
                continue  # dangling pods never entered the batch
            if up is not None:
                self.codes[i] = S_FAILED
                self.reasons[i] = up.reason
                batch_pos += 1
                continue
            self.node_names[i] = name
            if pinned:
                self.codes[i] = S_PINNED
            elif not name:
                # a non-failed, non-pinned pod with no binding —
                # unreachable by the commit contract; journal it as a
                # side-effect row so any later delta takes the full path
                self.codes[i] = S_SIDE
            elif cls_of is not None and batch_pos < len(cls_of):
                cls = int(cls_of[batch_pos])
                if simple[cls] and bulk_ok[cls]:
                    self.codes[i] = S_BULK
                    self.cls_rows[i] = offset + cls
                else:
                    self.codes[i] = S_SIDE
            else:
                self.codes[i] = S_SIDE
            batch_pos += 1

    # -- properties ----------------------------------------------------------

    @property
    def bulk_eligible(self) -> bool:
        """Whether the journal can seed a prefix replay: no
        side-effect rows (their commits thread allocator state the
        scatter-add cannot reproduce) and no priority/preemption
        ordering coupling (victims requeue out of roster order)."""
        return not self._ordering_coupled and not bool(
            (self.codes == S_SIDE).any()
        )

    @property
    def has_failures(self) -> bool:
        return bool(self.failed)

    # -- delta re-simulation -------------------------------------------------

    def resimulate(self, roster: List[dict], start: int) -> "CommittedScan":
        """Re-simulate journal positions [start, len(roster)) against
        the reused prefix; returns the NEW committed scan (self is
        untouched — the caller swaps on success). The chaos seam
        ``incremental.suffix`` fires here; the session degrades
        classified faults to :meth:`rebuild`."""
        _inject.fire("incremental.suffix", start=start)
        lied = _inject.value("incremental.suffix")
        if lied is not None or not self.bulk_eligible or start <= 0:
            reason = (
                "injected suffix lie distrusted"
                if lied is not None
                else ("side-effect journal rows" if not self.bulk_eligible
                      else "suffix is the whole journal")
            )
            from ..utils.trace import GLOBAL

            GLOBAL.note("incremental-full-rescan", reason)
            return self.rebuild(roster)
        start = min(int(start), len(roster))
        out = CommittedScan(
            self.nodes, roster, _prefix_from=self, _prefix_len=start
        )
        COUNTERS.inc("incremental_resims_total")
        return out

    def rebuild(self, roster: List[dict]) -> "CommittedScan":
        """The full re-scan (the conservative fallback every degraded
        path lands on): identical results, no reused prefix."""
        COUNTERS.inc("incremental_full_rebuilds_total")
        return CommittedScan(self.nodes, roster)

    # -- conformance ---------------------------------------------------------

    def state_digest(self) -> dict:
        """Canonical committed-state summary for the conformance gates:
        per-node pod keys in commit order, per-position journal, failed
        reasons. Two CommittedScans over equal roster/nodes must
        compare equal — the delta-resim == full-re-scan contract."""

        def key(p):
            m = p.get("metadata") or {}
            return f"{m.get('namespace') or 'default'}/{m.get('name', '')}"

        return {
            "journal": [
                (
                    _CODE_NAMES[int(self.codes[i])],
                    self.node_names[i]
                    if int(self.codes[i]) != S_FAILED
                    else self.reasons[i],
                )
                for i in range(self.total)
            ],
            "nodes": {
                ns.name: [key(p) for p in ns.pods] for ns in self.oracle.nodes
            },
            "failed": [(key(up.pod), up.reason) for up in self.failed],
        }
