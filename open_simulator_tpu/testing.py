"""Functional-option test fixture builders.

Parity with pkg/test (node.go, pod.go, deployment.go, replicaset.go,
statefulset.go, daemonset.go, job.go, cronjob.go): `make_fake_*`
constructors taking option callables, e.g.

    node = make_fake_node("n1", "32", "64Gi",
                          with_node_labels({"zone": "z1"}),
                          with_node_taints([...]))
"""

from __future__ import annotations

import json
from typing import Callable, List

Option = Callable[[dict], None]


def _check_positionals(*values):
    """Guard against an Option accidentally binding to a positional
    parameter (e.g. make_fake_pod("p", with_labels({...})) would bind
    the option to `namespace`)."""
    for v in values:
        if callable(v):
            raise TypeError(
                "option functions must come after namespace/cpu/memory/replicas; "
                f"got {v!r} bound to a positional parameter"
            )



# ------------------------------------------------------------------- nodes


def make_fake_node(name: str, cpu: str, memory: str, *opts: Option) -> dict:
    """110-pod capacity like MakeFakeNode (pkg/test/node.go:15-40)."""
    node = {
        "kind": "Node",
        "apiVersion": "v1",
        "metadata": {"name": name, "labels": {"kubernetes.io/hostname": name}, "annotations": {}},
        "status": {
            "allocatable": {"cpu": cpu, "memory": memory, "pods": "110"},
            "capacity": {"cpu": cpu, "memory": memory, "pods": "110"},
        },
    }
    for opt in opts:
        opt(node)
    return node


def with_node_labels(labels: dict) -> Option:
    def opt(node):
        node["metadata"].setdefault("labels", {}).update(labels)

    return opt


def with_node_taints(taints: List[dict]) -> Option:
    def opt(node):
        node.setdefault("spec", {})["taints"] = taints

    return opt


def with_node_local_storage(vgs: List[dict], devices: List[dict] = ()) -> Option:
    def opt(node):
        node["metadata"].setdefault("annotations", {})["simon/node-local-storage"] = json.dumps(
            {"vgs": list(vgs), "devices": list(devices)}
        )

    return opt


def with_node_gpu(count: int, total_memory: str, model: str = "V100") -> Option:
    def opt(node):
        for section in ("allocatable", "capacity"):
            node["status"].setdefault(section, {}).update(
                {
                    "alibabacloud.com/gpu-count": str(count),
                    "alibabacloud.com/gpu-mem": total_memory,
                }
            )
        node["metadata"].setdefault("labels", {})["alibabacloud.com/gpu-card-model"] = model

    return opt


def with_node_unschedulable() -> Option:
    def opt(node):
        node.setdefault("spec", {})["unschedulable"] = True

    return opt


# -------------------------------------------------------------------- pods


def _pod_template(name, namespace, cpu, memory):
    return {
        "metadata": {"name": name, "namespace": namespace, "labels": {}, "annotations": {}},
        "spec": {
            "containers": [
                {
                    "name": "c",
                    "image": f"image-{name}",
                    "resources": {"requests": {"cpu": cpu, "memory": memory}},
                }
            ]
        },
    }


def make_fake_pod(name: str, namespace: str = "default", cpu: str = "100m", memory: str = "100Mi", *opts: Option) -> dict:
    _check_positionals(namespace, cpu, memory)
    pod = {"kind": "Pod", "apiVersion": "v1", **_pod_template(name, namespace, cpu, memory)}
    for opt in opts:
        opt(pod)
    return pod


def with_labels(labels: dict) -> Option:
    def opt(obj):
        obj["metadata"].setdefault("labels", {}).update(labels)

    return opt


def with_annotations(annotations: dict) -> Option:
    def opt(obj):
        obj["metadata"].setdefault("annotations", {}).update(annotations)

    return opt


def _spec_of(obj: dict) -> dict:
    if obj.get("kind") == "Pod":
        return obj["spec"]
    if obj.get("kind") == "CronJob":
        return obj["spec"]["jobTemplate"]["spec"]["template"]["spec"]
    return obj["spec"]["template"]["spec"]


def with_tolerations(tolerations: List[dict]) -> Option:
    def opt(obj):
        _spec_of(obj)["tolerations"] = tolerations

    return opt


def with_node_selector(selector: dict) -> Option:
    def opt(obj):
        _spec_of(obj)["nodeSelector"] = selector

    return opt


def with_affinity(affinity: dict) -> Option:
    def opt(obj):
        _spec_of(obj)["affinity"] = affinity

    return opt


def with_priority(value: int) -> Option:
    """spec.priority — what the admission chain would stamp from a
    priorityClassName (scheduler/preemption.py)."""

    def opt(obj):
        _spec_of(obj)["priority"] = value

    return opt


def with_priority_class(name: str) -> Option:
    def opt(obj):
        _spec_of(obj)["priorityClassName"] = name

    return opt


def with_preemption_policy(policy: str) -> Option:
    def opt(obj):
        _spec_of(obj)["preemptionPolicy"] = policy

    return opt


def with_node_name(node_name: str) -> Option:
    def opt(obj):
        _spec_of(obj)["nodeName"] = node_name

    return opt


# --------------------------------------------------------------- workloads


def _workload(kind, api, name, namespace, replicas_field, replicas, cpu, memory):
    tpl = _pod_template(name, namespace, cpu, memory)
    tpl["metadata"] = {"labels": {"app": name}}
    obj = {
        "kind": kind,
        "apiVersion": api,
        "metadata": {"name": name, "namespace": namespace, "labels": {"app": name}},
        "spec": {
            "selector": {"matchLabels": {"app": name}},
            "template": tpl,
        },
    }
    if replicas_field:
        obj["spec"][replicas_field] = replicas
    return obj


def make_fake_deployment(name, namespace="default", replicas=1, cpu="100m", memory="100Mi", *opts: Option) -> dict:
    _check_positionals(namespace, replicas, cpu, memory)
    obj = _workload("Deployment", "apps/v1", name, namespace, "replicas", replicas, cpu, memory)
    for opt in opts:
        opt(obj)
    return obj


def make_fake_replica_set(name, namespace="default", replicas=1, cpu="100m", memory="100Mi", *opts: Option) -> dict:
    _check_positionals(namespace, replicas, cpu, memory)
    obj = _workload("ReplicaSet", "apps/v1", name, namespace, "replicas", replicas, cpu, memory)
    for opt in opts:
        opt(obj)
    return obj


def make_fake_stateful_set(name, namespace="default", replicas=1, cpu="100m", memory="100Mi", *opts: Option) -> dict:
    _check_positionals(namespace, replicas, cpu, memory)
    obj = _workload("StatefulSet", "apps/v1", name, namespace, "replicas", replicas, cpu, memory)
    for opt in opts:
        opt(obj)
    return obj


def make_fake_daemon_set(name, namespace="default", cpu="100m", memory="100Mi", *opts: Option) -> dict:
    _check_positionals(namespace, cpu, memory)
    obj = _workload("DaemonSet", "apps/v1", name, namespace, None, None, cpu, memory)
    for opt in opts:
        opt(obj)
    return obj


def make_fake_job(name, namespace="default", completions=1, cpu="100m", memory="100Mi", *opts: Option) -> dict:
    _check_positionals(namespace, completions, cpu, memory)
    obj = _workload("Job", "batch/v1", name, namespace, "completions", completions, cpu, memory)
    del obj["spec"]["selector"]
    for opt in opts:
        opt(obj)
    return obj


def make_fake_cron_job(name, namespace="default", completions=1, cpu="100m", memory="100Mi", *opts: Option) -> dict:
    _check_positionals(namespace, completions, cpu, memory)
    job = make_fake_job(name, namespace, completions, cpu, memory)
    obj = {
        "kind": "CronJob",
        "apiVersion": "batch/v1beta1",
        "metadata": {"name": name, "namespace": namespace, "labels": {"app": name}},
        "spec": {"schedule": "* * * * *", "jobTemplate": {"spec": job["spec"]}},
    }
    for opt in opts:
        opt(obj)
    return obj


def build_affinity_stress(
    n_nodes: int = 1000,
    n_sts: int = 100,
    replicas: int = 8,
    zones: int = 8,
    namespace: str = "stress",
):
    """The InterPodAffinity-heavy benchmark scenario (BASELINE.md:
    "100 StatefulSets + topology-spread").

    Returns (nodes, stateful_sets). Every StatefulSet carries
    - required pod anti-affinity against its own app label on the
      hostname topology (at most one replica per node),
    - a DoNotSchedule zone topology-spread constraint (maxSkew 1),
    - for odd indices, an additional ScheduleAnyway hostname spread
      (soft score path),
    - for every third one, preferred pod affinity to the previous
      StatefulSet's pods on the zone topology (cross-app score terms).
    """
    nodes = [
        make_fake_node(
            f"sn-{i:05d}",
            "32",
            "64Gi",
            with_node_labels({"zone": f"z{i % zones}"}),
        )
        for i in range(n_nodes)
    ]
    stss = []
    for s in range(n_sts):
        app = f"sts-{s:03d}"
        selector = {"matchLabels": {"app": app}}
        affinity = {
            "podAntiAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": [
                    {
                        "labelSelector": selector,
                        "topologyKey": "kubernetes.io/hostname",
                    }
                ]
            }
        }
        if s % 3 == 2:
            affinity["podAffinity"] = {
                "preferredDuringSchedulingIgnoredDuringExecution": [
                    {
                        "weight": 50,
                        "podAffinityTerm": {
                            "labelSelector": {
                                "matchLabels": {"app": f"sts-{s - 1:03d}"}
                            },
                            "topologyKey": "zone",
                        },
                    }
                ]
            }
        spread = [
            {
                "maxSkew": 1,
                "topologyKey": "zone",
                "whenUnsatisfiable": "DoNotSchedule",
                "labelSelector": selector,
            }
        ]
        if s % 2 == 1:
            spread.append(
                {
                    "maxSkew": 2,
                    "topologyKey": "kubernetes.io/hostname",
                    "whenUnsatisfiable": "ScheduleAnyway",
                    "labelSelector": selector,
                }
            )
        sts = make_fake_stateful_set(
            app,
            namespace,
            replicas,
            "500m",
            "1Gi",
            with_labels({"app": app}),
            with_affinity(affinity),
        )
        sts["spec"]["template"]["spec"]["topologySpreadConstraints"] = spread
        stss.append(sts)
    return nodes, stss
