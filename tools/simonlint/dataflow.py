"""Forward abstract interpretation over ``cfg.CFG``.

One generic worklist solver (``forward``) plus the three concrete
lattices the rules instantiate:

- **lock-held sets** (``LockAnalysis``): may-analysis over frozensets
  of canonical lock names; join = union. Feeds CONC002's
  blocking-while-locked check, the cross-function lock-order edge
  collection, and the self-deadlock check.
- **checked-since-loop-head** (``loop_unchecked_sources``): per-loop
  may-analysis of "this path has NOT consulted the budget since the
  loop head"; join = unchecked-dominates. A back-edge source that can
  be unchecked is an RT001 finding.
- **abstract value kinds** (``KindAnalysis``): variables mapped into
  the tiny lattice {JAX, NP, PYFLOAT} (absent = unknown); join drops
  disagreeing entries to unknown. Feeds JAX003's transfer/dtype
  checks.

All lattices are finite, so the fixpoint terminates; a generous
iteration bound guards against a builder bug turning into a hang.
"""

from __future__ import annotations

import ast
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from .cfg import CFG, Block, Event, event_exprs, iter_event_calls


def forward(
    cfg: CFG,
    init,
    transfer: Callable,
    join: Callable,
) -> Dict[int, object]:
    """Solve a forward dataflow problem; returns block-id -> state at
    block ENTRY. ``transfer(state, event) -> state`` must be pure;
    ``join(a, b)`` must be commutative/associative/idempotent."""
    entry_states: Dict[int, object] = {cfg.entry.bid: init}
    worklist: List[Block] = [cfg.entry]
    budget = max(64, len(cfg.blocks) * 64)
    while worklist and budget > 0:
        budget -= 1
        block = worklist.pop()
        state = entry_states[block.bid]
        for ev in block.events:
            state = transfer(state, ev)
        for succ in block.succs:
            if succ.bid not in entry_states:
                entry_states[succ.bid] = state
                worklist.append(succ)
            else:
                merged = join(entry_states[succ.bid], state)
                if merged != entry_states[succ.bid]:
                    entry_states[succ.bid] = merged
                    worklist.append(succ)
    return entry_states


def iter_event_states(
    cfg: CFG, entry_states: Dict[int, object], transfer: Callable
) -> Iterator[Tuple[Block, Event, object]]:
    """Replay the transfer over each reachable block, yielding
    (block, event, state-BEFORE-event) — the reporting pass every
    analysis shares after the fixpoint converges."""
    for block in cfg.blocks:
        if block.bid not in entry_states:
            continue  # unreachable
        state = entry_states[block.bid]
        for ev in block.events:
            yield block, ev, state
            state = transfer(state, ev)


def exit_state(
    cfg: CFG, entry_states: Dict[int, object], transfer: Callable, block: Block
):
    """State at the END of `block` (after all its events)."""
    state = entry_states[block.bid]
    for ev in block.events:
        state = transfer(state, ev)
    return state


# ------------------------------------------------------------------ locks


class LockAnalysis:
    """May-held lock sets: state = frozenset of canonical lock names."""

    init: frozenset = frozenset()

    @staticmethod
    def transfer(state: frozenset, ev: Event) -> frozenset:
        if ev.kind == "acquire":
            return state | {ev.lock}
        if ev.kind == "release":
            return state - {ev.lock}
        return state

    @staticmethod
    def join(a: frozenset, b: frozenset) -> frozenset:
        return a | b

    @classmethod
    def solve(cls, cfg: CFG) -> Dict[int, frozenset]:
        return forward(cfg, cls.init, cls.transfer, cls.join)


# ------------------------------------------------------- budget discipline


def loop_unchecked_sources(
    cfg: CFG,
    loop_node: ast.AST,
    consults: Callable[[Event], bool],
) -> List[Block]:
    """Back-edge source blocks of `loop_node` that some path reaches
    WITHOUT a budget consult since the loop head.

    State: "unchecked" / "checked" (plus the implicit bottom of an
    unreachable block). The loop head RESETS to unchecked (each
    iteration must re-consult); `consults(event)` promotes to checked;
    join lets unchecked dominate — exactly "exists a consult-free
    path"."""
    info = cfg.loops[loop_node]

    def transfer(state: str, ev: Event) -> str:
        if ev.kind == "loop_head" and ev.node is loop_node:
            state = "unchecked"
        if consults(ev):
            return "checked"
        return state

    def join(a: str, b: str) -> str:
        return "unchecked" if "unchecked" in (a, b) else "checked"

    entry_states = forward(cfg, "checked", transfer, join)
    out = []
    for src in info.back_sources:
        if src.bid not in entry_states:
            continue  # unreachable back edge
        if exit_state(cfg, entry_states, transfer, src) == "unchecked":
            out.append(src)
    return out


# ------------------------------------------------------------ value kinds

JAX = "jax"
NP = "np"
PYFLOAT = "pyfloat"

#: dotted-prefix -> kind for call results (alias-normalized names)
_CALL_KIND_PREFIXES = (
    ("jax.numpy.", JAX),
    ("jax.", JAX),
    ("numpy.", NP),
)


class KindAnalysis:
    """Variable -> abstract value kind. State is a dict-as-frozenset of
    (name, kind) pairs; absent = unknown. Join intersects (a variable
    keeps its kind only when every path agrees)."""

    def __init__(self, sf, seed: Optional[Dict[str, str]] = None):
        self.sf = sf
        self.init = frozenset((seed or {}).items())

    # -- expression kind ----------------------------------------------------

    def expr_kind(self, state: frozenset, expr: ast.AST) -> Optional[str]:
        env = dict(state)
        return self._kind(env, expr)

    def _kind(self, env: Dict[str, str], expr: ast.AST) -> Optional[str]:
        if isinstance(expr, ast.Constant):
            if isinstance(expr.value, float):
                return PYFLOAT
            return None
        if isinstance(expr, ast.Name):
            return env.get(expr.id)
        if isinstance(expr, ast.Call):
            dotted = self.sf.dotted_call_name(expr.func)
            for prefix, kind in _CALL_KIND_PREFIXES:
                if dotted.startswith(prefix):
                    return kind
            return None
        if isinstance(expr, ast.BinOp):
            lk = self._kind(env, expr.left)
            rk = self._kind(env, expr.right)
            if JAX in (lk, rk):
                return JAX
            if NP in (lk, rk):
                return NP
            if lk == rk:
                return lk
            return None
        if isinstance(expr, ast.Attribute):
            # np-array methods that preserve kind (x.astype, x.sum ...)
            return None
        return None

    # -- dataflow -----------------------------------------------------------

    def transfer(self, state: frozenset, ev: Event) -> frozenset:
        node = ev.node
        if ev.kind != "stmt" or not isinstance(
            node, (ast.Assign, ast.AnnAssign, ast.AugAssign)
        ):
            return state
        env = dict(state)
        value = node.value
        if value is None:  # bare annotation
            return state
        kind = self._kind(env, value)
        if isinstance(node, ast.AugAssign):
            # `acc += rhs` reads acc too: combine with the target's
            # current kind exactly like a BinOp (array kinds dominate a
            # scalar RHS), instead of letting the RHS overwrite it
            target_kind = (
                env.get(node.target.id)
                if isinstance(node.target, ast.Name)
                else None
            )
            if JAX in (kind, target_kind):
                kind = JAX
            elif NP in (kind, target_kind):
                kind = NP
            elif kind != target_kind:
                kind = None
        targets = (
            node.targets if isinstance(node, ast.Assign) else [node.target]
        )
        for t in targets:
            if isinstance(t, ast.Name):
                if kind is None:
                    env.pop(t.id, None)
                else:
                    env[t.id] = kind
            elif isinstance(t, (ast.Tuple, ast.List)):
                for elt in t.elts:
                    if isinstance(elt, ast.Name):
                        env.pop(elt.id, None)
        return frozenset(env.items())

    @staticmethod
    def join(a: frozenset, b: frozenset) -> frozenset:
        return a & b

    def solve(self, cfg: CFG) -> Dict[int, frozenset]:
        return forward(cfg, self.init, self.transfer, self.join)


__all__ = [
    "forward",
    "iter_event_states",
    "exit_state",
    "LockAnalysis",
    "loop_unchecked_sources",
    "KindAnalysis",
    "JAX",
    "NP",
    "PYFLOAT",
    "event_exprs",
    "iter_event_calls",
]
