"""simonlint — the repo's first-party multi-pass static analysis
framework (`make lint`, `python -m tools.simonlint`).

No third-party linter ships in this environment, so the lint gate is
built on the stdlib `ast` module. What began as a single-file
pyflakes-class checker (the old tools/lint.py) is now a framework:

- a shared project index (`project.py`): every source file parsed once,
  with parent links, scope chains, module-name resolution, and import
  alias maps that the rules share instead of re-deriving;
- an intra-package call-graph builder (`callgraph.py`) that resolves
  plain calls, `self.method()` calls, and imported-module attribute
  calls to their defining functions — the substrate for whole-program
  analyses like JAX001 trace-safety;
- a rule registry (`core.py`): each rule is a class registered under a
  stable id; `python -m tools.simonlint --list-rules` enumerates them;
- a dataflow layer: per-function control-flow graphs (`cfg.py`) with
  lock canonicalization and with/try-finally unwind modeling, a
  forward abstract-interpretation solver with the lock-held /
  budget-checked / value-kind lattices (`dataflow.py`), and one-level
  callee effect summaries (`effects.py`) — the substrate of CONC002,
  RT001, and JAX003;
- inline pragmas (`pragmas.py`): `# simonlint: disable=RULE[,RULE]` on
  the finding's line (or on the enclosing `def`/`class` line to cover a
  whole body). A pragma that suppresses nothing is itself reported
  (SL001) so dead suppressions cannot rot. Legacy `# noqa` lines keep
  working for the migrated rules;
- an incremental cache (`cache.py`, `.simonlint_cache/`): content-hash
  keyed, full-tree and per-file tiers, invalidated by any change to
  the simonlint sources themselves (`--no-cache` for a cold run);
- a baseline ratchet (`baseline.py`): `--baseline`/`--write-baseline`
  accept pre-existing findings for a newly enabled rule and fail only
  on new ones; entries that stop firing are reported stale (SL002);
- text, JSON, and SARIF output (`runner.py`, `sarif.py`), wired into
  `make lint` and CI (JSON + SARIF uploaded as artifacts, SARIF pushed
  to GitHub code scanning, cold runtime gated at 60 s).

Rule inventory (docs/STATIC_ANALYSIS.md holds the full table):

- pyflakes-class (rules/basic.py): F401 unused imports, F811 duplicate
  defs, B006 mutable defaults, E722 bare except, E711 None comparison,
  F541 placeholder-free f-strings, B011 assert-on-tuple
- runtime hygiene (rules/hygiene.py): BLE001 broad except, S110 silent
  except-pass, S113 I/O without timeout, T201 bare print — first-party
  runtime scope (open_simulator_tpu/), audited allowlists in
  allowlists.py
- JAX (rules/jax_trace.py, rules/jax_compile.py): JAX001 host side
  effects reachable inside traced code, JAX002 per-call `jax.jit`
  wrappers that defeat the compile cache / non-hashable static args
- concurrency (rules/concurrency.py, rules/lock_order.py): CONC001
  lock-discipline — fields guarded by `with self._lock` elsewhere must
  not be touched unlocked; CONC002 lock-order inversions, blocking
  calls under a lock, and self-deadlocks, via the lock-held dataflow
- dataflow (rules/jax_dtype.py, rules/deadline.py,
  rules/exceptions.py): JAX003 dtype/transfer drift in the engine
  directories, RT001 deadline discipline for budget-scoped while
  loops, EXC001 error-taxonomy enforcement at raise sites

Checks that need full runtime resolution (undefined names) stay out of
scope — `compileall` plus the test suite carry those.

Exit status 1 when any finding survives suppression (the CI gate).
"""

from __future__ import annotations

from .core import Finding, Rule, all_rules, get_rule, register
from .runner import DEFAULT_ROOTS, lint_file, lint_paths, lint_repo

__all__ = [
    "DEFAULT_ROOTS",
    "Finding",
    "Rule",
    "all_rules",
    "get_rule",
    "lint_file",
    "lint_paths",
    "lint_repo",
    "register",
]
