"""Inline suppression pragmas.

Two spellings are honored:

- ``# simonlint: disable=RULE[,RULE...]`` — the first-party form. On a
  finding's own line it suppresses that finding; on a ``def`` / ``class``
  header line it suppresses matching findings anywhere in that body
  (for caller-holds-lock helpers and documented hot-path reads, where a
  per-line pragma would repeat the same justification five times).
  Every pragma is accounted for: one that suppressed nothing is itself
  reported as **SL001 unused suppression**, so stale pragmas cannot
  accumulate after the code they excused is fixed.
- ``# noqa`` / ``# noqa: CODE[,CODE]`` — the legacy form the migrated
  rules (F401 ... T201) already use in the tree. Bare ``noqa``
  suppresses every rule on its line; with codes, only those. noqa
  pragmas are NOT usage-tracked (they predate the framework and some
  annotate tool output, e.g. conftest's E402 markers); new suppressions
  should use the simonlint form.

SL001 findings are themselves unsuppressible — a pragma whose only
effect is to hide "this pragma is unused" is definitionally unused.
"""

from __future__ import annotations

import io
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, List, Tuple

UNUSED_SUPPRESSION = "SL001"

_SIMONLINT_RE = re.compile(
    r"#\s*simonlint:\s*disable=([A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)"
)
_NOQA_RE = re.compile(r"#\s*noqa(?::\s*([A-Z0-9, ]+))?", re.IGNORECASE)


@dataclass
class LinePragmas:
    """Suppressions attached to one physical line."""

    #: rule ids from `# simonlint: disable=...`
    disable: Tuple[str, ...] = ()
    #: True for bare `# noqa`
    noqa_all: bool = False
    #: rule ids from `# noqa: CODE,...`
    noqa: Tuple[str, ...] = ()
    #: simonlint ids that actually suppressed a finding (usage ledger)
    used: set = field(default_factory=set)


def parse_pragmas(lines: List[str]) -> Dict[int, LinePragmas]:
    """1-based line -> LinePragmas, for lines carrying any pragma.

    Matched against real COMMENT tokens only (via `tokenize`), so a
    docstring or message string that merely MENTIONS a pragma — this
    framework's own sources are full of them — never suppresses
    anything. Tokenization errors (only possible on files that already
    fail to parse) degrade to no pragmas."""
    comments: Dict[int, str] = {}
    source = "\n".join(lines) + "\n"
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                comments[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return {}
    out: Dict[int, LinePragmas] = {}
    for i, comment in comments.items():
        lp = LinePragmas()
        m = _SIMONLINT_RE.search(comment)
        if m:
            lp.disable = tuple(
                s.strip() for s in m.group(1).split(",") if s.strip()
            )
        m = _NOQA_RE.search(comment)
        if m:
            codes = m.group(1)
            if codes:
                lp.noqa = tuple(
                    s.strip().upper() for s in codes.split(",") if s.strip()
                )
            else:
                lp.noqa_all = True
        if lp.disable or lp.noqa or lp.noqa_all:
            out[i] = lp
    return out


def _suppresses(lp: LinePragmas, rule: str, *, line_local: bool) -> bool:
    """Does this pragma line silence `rule`? noqa forms only apply on
    the finding's own line (the legacy contract); simonlint disables
    also apply from enclosing def/class headers."""
    if rule == UNUSED_SUPPRESSION:
        return False
    if rule in lp.disable:
        lp.used.add(rule)
        return True
    if line_local and (lp.noqa_all or rule in lp.noqa):
        return True
    return False


def apply_suppressions(findings, files, active_rules=None) -> List:
    """Drop suppressed findings, then report unused simonlint pragmas.

    `findings` is the full pre-suppression list; `files` the
    SourceFiles they came from (for pragma maps and scope lines).
    `active_rules` is the set of rule ids that actually RAN this
    invocation (None = all): a pragma for a rule that did not run
    cannot be proven unused and is never reported — otherwise a
    `--rules F401` subset run would flag every CONC001/JAX001 pragma
    in the tree. Returns the surviving findings plus SL001 entries,
    unsorted — the runner owns ordering."""
    from .core import Finding  # local import: core imports nothing from here

    by_rel = {sf.rel: sf for sf in files}
    kept = []
    for f in findings:
        sf = by_rel.get(f.rel)
        if sf is None:
            kept.append(f)
            continue
        lp = sf.pragmas.get(f.line)
        if lp is not None and _suppresses(lp, f.rule, line_local=True):
            continue
        # body-wide pragmas on enclosing def/class header lines
        node = _node_at(sf, f.line)
        suppressed = False
        if node is not None:
            for scope_line in sf.scope_lines(node):
                slp = sf.pragmas.get(scope_line)
                if slp is not None and _suppresses(
                    slp, f.rule, line_local=False
                ):
                    suppressed = True
                    break
        if not suppressed:
            kept.append(f)
    for sf in files:
        for line, lp in sorted(sf.pragmas.items()):
            for rule in lp.disable:
                if active_rules is not None and rule not in active_rules:
                    continue
                if rule not in lp.used:
                    kept.append(
                        Finding(
                            sf.path,
                            sf.rel,
                            line,
                            UNUSED_SUPPRESSION,
                            f"unused suppression: no {rule} finding is "
                            "silenced by this pragma — remove it (or fix "
                            "the rule id)",
                        )
                    )
    return kept


def _node_at(sf, line: int):
    """Any AST node on `line` (for scope-chain lookup). Cheap linear
    scan per finding; findings are rare on a healthy tree."""
    if sf.tree is None:
        return None
    import ast

    best = None
    for node in ast.walk(sf.tree):
        if getattr(node, "lineno", None) == line:
            return node
        # fall back to any node whose span covers the line (multi-line
        # statements report findings on sub-lines)
        end = getattr(node, "end_lineno", None)
        if (
            best is None
            and getattr(node, "lineno", None) is not None
            and end is not None
            and node.lineno <= line <= end
        ):
            best = node
    return best
