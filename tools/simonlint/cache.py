"""Incremental lint cache — re-analyze only what changed.

Two tiers, both keyed on CONTENT (sha256 of file bytes) plus a tool
digest (sha256 over the simonlint sources themselves, allowlists
included), so editing either the code or the linter invalidates
exactly what it must:

- **full-tree tier**: when the (file set, per-file digests, rule
  subset) triple matches the stored run, the stored post-suppression
  findings are returned without parsing anything — the repeat
  ``make lint`` on an unchanged tree.
- **per-file tier**: on a partial hit, unchanged files reuse their
  cached FILE-scoped findings (pre-suppression) and only changed files
  re-run the file rules. Project-scoped rules (JAX001, CONC002, RT001,
  JAX003, EXC001) always re-run — their facts cross file boundaries,
  so caching them per file would be unsound — and the
  pragma/suppression pass always runs fresh so SL001 accounting stays
  exact.

Storage: one JSON document at ``<root>/.simonlint_cache/cache.json``.
A corrupt or version-skewed cache degrades to a cold run, never an
error. ``--no-cache`` bypasses read AND write.
"""

from __future__ import annotations

import hashlib
import json
from pathlib import Path
from typing import Dict, List, Optional

CACHE_VERSION = 2

_FINDING_KEYS = ("rel", "line", "rule", "message")


def _tool_digest() -> str:
    h = hashlib.sha256()
    pkg = Path(__file__).resolve().parent
    for p in sorted(pkg.rglob("*.py")):
        if "__pycache__" in p.parts:
            continue
        h.update(str(p.relative_to(pkg)).encode())
        h.update(p.read_bytes())
    return h.hexdigest()


def file_digest(path: Path) -> str:
    return hashlib.sha256(Path(path).read_bytes()).hexdigest()


class LintCache:
    """One cache instance per lint invocation. ``stats`` is the
    observable contract the tests pin: full_hits / file_hits /
    file_misses."""

    def __init__(self, root: Path, enabled: bool = True):
        self.root = Path(root)
        self.enabled = enabled
        self.path = self.root / ".simonlint_cache" / "cache.json"
        self.tool_digest = _tool_digest() if enabled else ""
        self.stats = {"full_hits": 0, "file_hits": 0, "file_misses": 0}
        self._doc = self._load() if enabled else {}
        self._new_files: Dict[str, dict] = {}

    # -- storage ------------------------------------------------------------

    def _load(self) -> dict:
        try:
            doc = json.loads(self.path.read_text())
        except (OSError, ValueError):
            return {}
        if not isinstance(doc, dict):
            return {}
        if doc.get("version") != CACHE_VERSION:
            return {}
        if doc.get("tool_digest") != self.tool_digest:
            return {}  # the linter itself changed: everything stale
        return doc

    def save(self) -> None:
        if not self.enabled:
            return
        doc = {
            "version": CACHE_VERSION,
            "tool_digest": self.tool_digest,
            "files": {**self._doc.get("files", {}), **self._new_files},
            "full": self._doc.get("full"),
        }
        try:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            tmp = self.path.with_suffix(".tmp")
            tmp.write_text(json.dumps(doc))
            tmp.replace(self.path)
        except OSError:
            pass  # a read-only tree still lints, just never warm

    # -- full-tree tier ------------------------------------------------------

    def full_key(self, digests: Dict[str, str], rules_key: str) -> str:
        h = hashlib.sha256()
        h.update(self.tool_digest.encode())
        h.update(rules_key.encode())
        for rel in sorted(digests):
            h.update(rel.encode())
            h.update(digests[rel].encode())
        return h.hexdigest()

    def load_full(self, key: str) -> Optional[List[dict]]:
        if not self.enabled:
            return None
        full = self._doc.get("full")
        if isinstance(full, dict) and full.get("key") == key:
            findings = full.get("findings")
            if isinstance(findings, list):
                self.stats["full_hits"] += 1
                return findings
        return None

    def store_full(self, key: str, findings: List[dict]) -> None:
        if self.enabled:
            self._doc["full"] = {"key": key, "findings": findings}

    # -- per-file tier -------------------------------------------------------

    def load_file(self, rel: str, digest: str) -> Optional[List[dict]]:
        if not self.enabled:
            return None
        entry = self._doc.get("files", {}).get(rel)
        if isinstance(entry, dict) and entry.get("digest") == digest:
            findings = entry.get("findings")
            if isinstance(findings, list):
                self.stats["file_hits"] += 1
                return findings
        self.stats["file_misses"] += 1
        return None

    def store_file(self, rel: str, digest: str, findings: List[dict]) -> None:
        if self.enabled:
            self._new_files[rel] = {"digest": digest, "findings": findings}
