"""SARIF 2.1.0 rendering — the format GitHub code scanning ingests,
so CI-uploaded findings annotate PR diffs inline instead of living in
a log nobody opens.

Minimal but valid: one run, the registered rule inventory as
``tool.driver.rules`` (id + short/full description), one ``result``
per finding with a physical location. Framework-level findings (SL001
unused suppression, SL002 stale baseline entry) get synthesized rule
entries so every result's ruleId resolves.
"""

from __future__ import annotations

import json
from typing import List

from .core import Finding, all_rules

SARIF_VERSION = "2.1.0"
_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)

_META_RULES = {
    "E999": "syntax error",
    "SL001": "unused suppression — a pragma that silences nothing",
    "SL002": "stale baseline entry — the accepted finding no longer fires",
}


def render_sarif(findings: List[Finding]) -> str:
    rules = []
    seen = set()
    for rule in all_rules():
        seen.add(rule.id)
        rules.append(
            {
                "id": rule.id,
                "shortDescription": {"text": rule.title},
                "fullDescription": {"text": rule.rationale or rule.title},
                "defaultConfiguration": {"level": "error"},
            }
        )
    for rid, title in _META_RULES.items():
        if rid not in seen:
            rules.append(
                {
                    "id": rid,
                    "shortDescription": {"text": title},
                    "defaultConfiguration": {"level": "error"},
                }
            )
            seen.add(rid)
    index = {r["id"]: i for i, r in enumerate(rules)}
    results = []
    for f in findings:
        result = {
            "ruleId": f.rule,
            "level": "error",
            "message": {"text": f.message},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {
                            "uri": f.rel.replace("\\", "/"),
                            "uriBaseId": "%SRCROOT%",
                        },
                        "region": {"startLine": max(f.line, 1)},
                    }
                }
            ],
        }
        if f.rule in index:
            result["ruleIndex"] = index[f.rule]
        results.append(result)
    doc = {
        "$schema": _SCHEMA,
        "version": SARIF_VERSION,
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "simonlint",
                        "rules": rules,
                    }
                },
                "results": results,
            }
        ],
    }
    return json.dumps(doc, indent=2)
