"""Callee effect summaries — one-level interprocedural facts.

For every function the project index can see, a ``Summary`` of its
DIRECT effects (no transitive closure — facts propagate exactly one
call level, which bounds both cost and wrongness):

- ``locks``: canonical lock names it acquires (``with`` or
  ``.acquire()``),
- ``blocking``: labels of blocking operations it performs (fsync,
  sleep, sockets/HTTP, subprocess, journal appends, jit dispatches),
- ``consults_budget``: whether it calls ``Budget.check`` /
  ``.expired()`` / ``.remaining()`` on a budget-shaped receiver,
- ``raises``: alias-normalized dotted names of exceptions it raises.

``Effects.for_call`` resolves a Call node to its callee summary through
the shared ``callgraph.Resolver`` plus one extra step the resolver
does not do: methods invoked on MODULE-LEVEL SINGLETONS
(``COUNTERS.inc`` -> ``utils.trace.Counters.inc``), which is how the
serve/obs lock-order edges become visible.

Also here because every whole-program rule needs it: the project class
hierarchy (``class_index`` / ``taxonomy_classes``) that EXC001 uses to
decide whether a raised class is rooted in the runtime error taxonomy.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from .callgraph import Resolver
from .cfg import canonical_lock_name, is_lockish
from .project import ProjectIndex, SourceFile

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)

# ------------------------------------------------------------- blocking ops

#: alias-normalized dotted names that block on I/O or the device
BLOCKING_CALLS = {
    "os.fsync": "os.fsync",
    "os.fdatasync": "os.fdatasync",
    "time.sleep": "time.sleep",
    "urllib.request.urlopen": "urlopen",
    "socket.create_connection": "socket connect",
    "subprocess.run": "subprocess",
    "subprocess.call": "subprocess",
    "subprocess.check_call": "subprocess",
    "subprocess.check_output": "subprocess",
    "subprocess.Popen": "subprocess",
    "jax.block_until_ready": "device sync",
    "jax.device_get": "device transfer",
    "jax.device_put": "device transfer",
}

#: method name -> (receiver-substring requirement, label); receiver
#: substring "" matches any receiver
BLOCKING_METHODS = {
    "fsync": ("", "fsync"),
    "append": ("journal", "Journal.append (fsync'd)"),
    "wait": ("", "blocking wait"),
    "block_until_ready": ("", "device sync"),
}


def _receiver_text(func: ast.Attribute) -> str:
    parts = []
    node = func.value
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts)).lower()


def blocking_label(sf: SourceFile, call: ast.Call, jit_names: Set[str]) -> Optional[str]:
    """Label when this call is a known blocking operation (None
    otherwise). `jit_names` are the module-qualified names of known
    module-level jit wrappers (dispatching one is a device round-trip
    the caller should not take under a lock)."""
    dotted = sf.dotted_call_name(call.func)
    if dotted in BLOCKING_CALLS:
        return BLOCKING_CALLS[dotted]
    if dotted and dotted in jit_names:
        return f"jit dispatch ({dotted.rsplit('.', 1)[-1]})"
    if isinstance(call.func, ast.Attribute):
        attr = call.func.attr
        hit = BLOCKING_METHODS.get(attr)
        if hit is not None:
            needle, label = hit
            recv = _receiver_text(call.func)
            if needle in recv:
                return label
        # instance-cached jits: self._many_jit(...), cls._scan_jit(...)
        if attr.endswith("_jit"):
            return f"jit dispatch ({attr})"
    elif isinstance(call.func, ast.Name) and call.func.id.endswith("_jit"):
        return f"jit dispatch ({call.func.id})"
    return None


# --------------------------------------------------------- budget consults

_BUDGET_CONSULT_METHODS = {"check", "expired", "remaining"}


def _budgetish(expr: ast.AST) -> bool:
    """Does this receiver expression look like a Budget? (`budget`,
    `self._budget`, `req.budget`, `deadline_budget`, ...)"""
    name = None
    if isinstance(expr, ast.Name):
        name = expr.id
    elif isinstance(expr, ast.Attribute):
        name = expr.attr
    return name is not None and "budget" in name.lower()


def is_budget_consult(call: ast.Call) -> bool:
    return (
        isinstance(call.func, ast.Attribute)
        and call.func.attr in _BUDGET_CONSULT_METHODS
        and _budgetish(call.func.value)
    )


def mentions_budget(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, (ast.Name, ast.Attribute)) and _budgetish(sub):
            return True
    return False


# ----------------------------------------------------------------- summary


@dataclass
class Summary:
    locks: FrozenSet[str] = frozenset()
    blocking: Tuple[str, ...] = ()
    consults_budget: bool = False
    raises: FrozenSet[str] = frozenset()


class Effects:
    """Per-project effect summaries + the class hierarchy. Build once
    per lint invocation via ``get_effects(project)``."""

    def __init__(self, project: ProjectIndex):
        self.project = project
        self.resolver = Resolver(project)
        self.jit_names = self._module_jit_names()
        #: (rel, fn lineno) -> Summary of DIRECT effects
        self._direct: Dict[Tuple[str, int], Summary] = {}
        self._singletons = self._module_singletons()
        self.class_bases = self._class_index()
        for sf in project.files:
            if sf.tree is None:
                continue
            for node in ast.walk(sf.tree):
                if isinstance(node, _FUNC_NODES):
                    self._direct[(sf.rel, node.lineno)] = self._summarize(
                        sf, node
                    )

    # -- module-level discovery --------------------------------------------

    def _module_jit_names(self) -> Set[str]:
        """Module-qualified names bound at module level to a jit
        wrapper (``NAME = jax.jit(...)`` or ``NAME =
        wrap(jax.jit(...))``) — calling one is a device dispatch."""
        out: Set[str] = set()
        for sf in self.project.files:
            if sf.tree is None or sf.module is None:
                continue
            for stmt in sf.tree.body:
                if not isinstance(stmt, ast.Assign):
                    continue
                if not self._wraps_jit(sf, stmt.value):
                    continue
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        out.add(f"{sf.module}.{t.id}")
                        out.add(t.id)
        return out

    def _wraps_jit(self, sf: SourceFile, expr: ast.AST, depth: int = 0) -> bool:
        if depth > 3 or not isinstance(expr, ast.Call):
            return False
        if sf.dotted_call_name(expr.func) == "jax.jit":
            return True
        return any(
            self._wraps_jit(sf, a, depth + 1) for a in expr.args
        )

    def _module_singletons(self) -> Dict[str, Tuple[str, str]]:
        """module-qualified instance name -> (module, ClassName) for
        module-level ``NAME = ClassName(...)`` assignments whose class
        is defined in the same module."""
        out: Dict[str, Tuple[str, str]] = {}
        for sf in self.project.files:
            if sf.tree is None or sf.module is None:
                continue
            classes = {
                n.name for n in sf.tree.body if isinstance(n, ast.ClassDef)
            }
            for stmt in sf.tree.body:
                if not (
                    isinstance(stmt, ast.Assign)
                    and isinstance(stmt.value, ast.Call)
                    and isinstance(stmt.value.func, ast.Name)
                    and stmt.value.func.id in classes
                ):
                    continue
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        out[f"{sf.module}.{t.id}"] = (
                            sf.module,
                            stmt.value.func.id,
                        )
        return out

    # -- class hierarchy (EXC001) ------------------------------------------

    def _class_index(self) -> Dict[str, List[str]]:
        """dotted class name -> alias-normalized base names."""
        out: Dict[str, List[str]] = {}
        for sf in self.project.files:
            if sf.tree is None:
                continue
            mod = sf.module or sf.rel
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                bases = []
                for b in node.bases:
                    dotted = sf.dotted_call_name(b)
                    if dotted:
                        bases.append(dotted)
                out[f"{mod}.{node.name}"] = bases
        return out

    def taxonomy_classes(self, root_names: Set[str]) -> Set[str]:
        """Dotted names of classes transitively rooted in a class whose
        BARE name is in `root_names` (bare-name matching keeps fixture
        trees exercisable without replicating the package layout)."""
        roots = {
            dotted
            for dotted in self.class_bases
            if dotted.rsplit(".", 1)[-1] in root_names
        }
        taxo = set(roots)
        changed = True
        while changed:
            changed = False
            for dotted, bases in self.class_bases.items():
                if dotted in taxo:
                    continue
                for b in bases:
                    base_leaf = b.rsplit(".", 1)[-1]
                    if (
                        b in taxo
                        or base_leaf in root_names
                        or any(t.endswith("." + base_leaf) or t == base_leaf
                               for t in taxo)
                    ):
                        taxo.add(dotted)
                        changed = True
                        break
        return taxo

    # -- summaries ----------------------------------------------------------

    def _summarize(self, sf: SourceFile, fn: ast.AST) -> Summary:
        locks: Set[str] = set()
        blocking: List[str] = []
        consults = False
        raises: Set[str] = set()
        for node in self._own_nodes(fn):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    lock = canonical_lock_name(sf, item.context_expr)
                    if lock is not None:
                        locks.add(lock)
            elif isinstance(node, ast.Call):
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "acquire"
                ):
                    lock = canonical_lock_name(sf, node.func.value)
                    if lock is not None:
                        locks.add(lock)
                label = blocking_label(sf, node, self.jit_names)
                if label is not None and label not in blocking:
                    blocking.append(label)
                if is_budget_consult(node):
                    consults = True
            elif isinstance(node, ast.Raise) and node.exc is not None:
                exc = node.exc
                cls_expr = exc.func if isinstance(exc, ast.Call) else exc
                dotted = sf.dotted_call_name(cls_expr)
                if dotted:
                    raises.add(dotted)
        return Summary(
            locks=frozenset(locks),
            blocking=tuple(blocking),
            consults_budget=consults,
            raises=frozenset(raises),
        )

    @staticmethod
    def _own_nodes(fn: ast.AST):
        """Walk a function body EXCLUDING nested def/class bodies (they
        execute when called, not when this function runs)."""
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            yield node
            if isinstance(node, _FUNC_NODES + (ast.ClassDef, ast.Lambda)):
                continue
            stack.extend(ast.iter_child_nodes(node))

    # -- lookup -------------------------------------------------------------

    def blocking_label_for(self, sf: SourceFile, call: ast.Call) -> Optional[str]:
        return blocking_label(sf, call, self.jit_names)

    def direct(self, sf: SourceFile, fn: ast.AST) -> Summary:
        return self._direct.get((sf.rel, getattr(fn, "lineno", 0)), Summary())

    def for_call(self, sf: SourceFile, call: ast.Call) -> Optional[Summary]:
        """Summary of the function this call invokes, when resolvable
        (one level: the callee's DIRECT effects only)."""
        hit = self.resolver.resolve_call(sf, call)
        if hit is None:
            hit = self._resolve_singleton_method(sf, call)
        if hit is None:
            return None
        callee_sf, callee = hit
        return self.direct(callee_sf, callee)

    def _resolve_singleton_method(
        self, sf: SourceFile, call: ast.Call
    ) -> Optional[Tuple[SourceFile, ast.AST]]:
        """``COUNTERS.inc(...)`` -> Counters.inc in utils/trace.py: an
        attribute call on an imported module-level singleton."""
        func = call.func
        if not (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
        ):
            return None
        dotted = sf.imports.get(func.value.id)
        if dotted is None and sf.module is not None:
            dotted = f"{sf.module}.{func.value.id}"
        if dotted is None:
            return None
        hit = self._singletons.get(dotted)
        if hit is None:
            return None
        mod, cls_name = hit
        target_sf = self.project.by_module.get(mod)
        if target_sf is None or target_sf.tree is None:
            return None
        for node in target_sf.tree.body:
            if isinstance(node, ast.ClassDef) and node.name == cls_name:
                for meth in node.body:
                    if isinstance(meth, _FUNC_NODES) and meth.name == func.attr:
                        return target_sf, meth
        return None


def get_effects(project: ProjectIndex) -> Effects:
    """Per-invocation cached Effects (the index is immutable for the
    lifetime of one lint run)."""
    eff = getattr(project, "_simonlint_effects", None)
    if eff is None:
        eff = Effects(project)
        project._simonlint_effects = eff
    return eff


__all__ = [
    "Effects",
    "Summary",
    "get_effects",
    "blocking_label",
    "is_budget_consult",
    "mentions_budget",
    "is_lockish",
]
