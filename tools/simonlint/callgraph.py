"""Intra-package call graph + traced-root discovery.

Two jobs, both shared by whole-program rules:

1. **Root discovery** (`iter_traced_roots`): find every function that
   enters JAX tracing — the argument of ``jax.jit(...)`` /
   ``partial(jax.jit, ...)(...)`` / ``jax.vmap`` / ``jax.pmap``, a
   ``@jax.jit``-decorated def, or the kernel handed to
   ``pl.pallas_call(...)``. Arguments are resolved through one level of
   local aliasing (``sweep_fn = jax.vmap(self._scenario)`` then
   ``jax.jit(sweep_fn)`` roots ``_scenario``) because that is exactly
   how this codebase writes them.

2. **Call resolution** (`Resolver.resolve`): map a Call node inside a
   known function to the FunctionDef it invokes, when that target is
   first-party: same-module top-level functions, nested defs in the
   enclosing scope chain, ``self.method()`` on the enclosing class, and
   ``module_alias.func()`` through the import map to another indexed
   module. Anything unresolved returns None — the walker treats it as
   opaque (external) and only checks it against the host-effect table.

Heuristic by design: no data-flow through containers, no inheritance,
no decorators-as-wrappers. That bounds both false negatives (documented
in docs/STATIC_ANALYSIS.md) and analysis cost (one AST pass per file).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Iterator, Optional, Tuple

from .project import ProjectIndex, SourceFile

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)

#: call names (alias-normalized) whose first argument becomes traced
JIT_ENTRY_CALLS = {"jax.jit", "jax.vmap", "jax.pmap"}
#: pallas_call kernels are traced the same way; both the `pl.` alias
#: and a from-import of pallas_call normalize to these
PALLAS_CALLS = {"jax.experimental.pallas.pallas_call"}


def is_jit_name(dotted: str) -> bool:
    return dotted == "jax.jit"


def is_pallas_call(dotted: str) -> bool:
    return dotted in PALLAS_CALLS or dotted.endswith(".pallas_call") or dotted == "pallas_call"


@dataclass(frozen=True)
class TracedRoot:
    """One function entering JAX tracing, with its registration site."""

    sf: SourceFile          # file DEFINING the root function
    node: ast.AST           # FunctionDef / Lambda
    site_sf: SourceFile     # file of the jit/vmap/pallas_call site
    site_line: int
    via: str                # "jax.jit", "pallas_call", "@jax.jit", ...

    @property
    def name(self) -> str:
        return getattr(self.node, "name", "<lambda>")


class Resolver:
    """Resolve call/argument expressions to first-party FunctionDefs."""

    def __init__(self, project: ProjectIndex):
        self.project = project

    # -- expression -> function ---------------------------------------------

    def resolve_func_expr(
        self, sf: SourceFile, expr: ast.AST, scope: Optional[ast.AST]
    ) -> Optional[Tuple[SourceFile, ast.AST]]:
        """The FunctionDef an expression evaluates to, through local
        aliases and jit/vmap wrappers. `scope` is the enclosing
        FunctionDef (None at module scope)."""
        seen = 0
        while seen < 8:  # alias-chain bound; cycles impossible below it
            seen += 1
            if isinstance(expr, ast.Lambda):
                return sf, expr
            if isinstance(expr, ast.Call):
                dotted = sf.dotted_call_name(expr.func)
                if dotted in JIT_ENTRY_CALLS or is_pallas_call(dotted):
                    if expr.args:
                        expr = expr.args[0]
                        continue
                # functools.partial(f, ...) forwards to f
                if dotted in ("functools.partial", "partial") and expr.args:
                    expr = expr.args[0]
                    continue
                return None
            if isinstance(expr, ast.Name):
                resolved = self._resolve_name(sf, expr.id, scope)
                if isinstance(resolved, ast.AST):
                    return sf, resolved
                if resolved is not None:  # (sf, node) cross-module
                    return resolved
                # local alias: x = <expr> in the enclosing scope chain
                alias = self._local_assignment(scope, expr.id)
                if alias is not None:
                    expr = alias
                    continue
                return None
            if isinstance(expr, ast.Attribute):
                if (
                    isinstance(expr.value, ast.Name)
                    and expr.value.id == "self"
                ):
                    return self._resolve_self_method(sf, expr)
                dotted = sf.dotted_call_name(expr)
                if dotted:
                    hit = self.project.top_level_function(dotted)
                    if hit is not None:
                        return hit
                return None
            return None
        return None

    def _resolve_name(
        self, sf: SourceFile, name: str, scope: Optional[ast.AST]
    ):
        """nested def in the scope chain > module top-level def >
        from-imported first-party function."""
        node = scope
        while node is not None:
            for stmt in ast.walk(node):
                if isinstance(stmt, _FUNC_NODES) and stmt.name == name:
                    return stmt
            node = sf.enclosing_function_node(node)
        if sf.tree is not None:
            for stmt in sf.tree.body:
                if isinstance(stmt, _FUNC_NODES) and stmt.name == name:
                    return stmt
        target = sf.imports.get(name)
        if target:
            return self.project.top_level_function(target)
        return None

    def _local_assignment(
        self, scope: Optional[ast.AST], name: str
    ) -> Optional[ast.AST]:
        if scope is None:
            return None
        for stmt in ast.walk(scope):
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Name) and t.id == name:
                        return stmt.value
        return None

    def _resolve_self_method(
        self, sf: SourceFile, attr: ast.Attribute
    ) -> Optional[Tuple[SourceFile, ast.AST]]:
        cls = sf.enclosing_class(attr)
        if cls is None:
            return None
        for stmt in cls.body:
            if isinstance(stmt, _FUNC_NODES) and stmt.name == attr.attr:
                return sf, stmt
        return None

    # -- call site -> function ----------------------------------------------

    def resolve_call(
        self, sf: SourceFile, call: ast.Call
    ) -> Optional[Tuple[SourceFile, ast.AST]]:
        scope = sf.enclosing_function_node(call)
        return self.resolve_func_expr(sf, call.func, scope)


def iter_traced_roots(project: ProjectIndex) -> Iterator[TracedRoot]:
    """Every traced-function registration in runtime-scope files.
    Duplicate (function, via) pairs are collapsed to the first site."""
    resolver = Resolver(project)
    seen = set()
    for sf in project.files:
        if sf.tree is None or not sf.is_runtime_scope:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call):
                dotted = sf.dotted_call_name(node.func)
                via = None
                target_expr = None
                if dotted in JIT_ENTRY_CALLS and node.args:
                    via, target_expr = dotted, node.args[0]
                elif is_pallas_call(dotted) and node.args:
                    via, target_expr = "pallas_call", node.args[0]
                elif (
                    isinstance(node.func, ast.Call)
                    and sf.dotted_call_name(node.func.func)
                    in ("functools.partial", "partial")
                    and node.func.args
                    and sf.dotted_call_name(node.func.args[0]) == "jax.jit"
                    and node.args
                ):
                    # partial(jax.jit, ...)(fn)
                    via, target_expr = "partial(jax.jit)", node.args[0]
                if via is None:
                    continue
                scope = sf.enclosing_function_node(node)
                hit = resolver.resolve_func_expr(sf, target_expr, scope)
                if hit is None:
                    continue
                root_sf, fn = hit
                key = (root_sf.rel, getattr(fn, "lineno", 0), via)
                if key in seen:
                    continue
                seen.add(key)
                yield TracedRoot(root_sf, fn, sf, node.lineno, via)
            elif isinstance(node, _FUNC_NODES):
                for deco in node.decorator_list:
                    d = deco.func if isinstance(deco, ast.Call) else deco
                    dotted = sf.dotted_call_name(d)
                    is_partial_jit = (
                        isinstance(deco, ast.Call)
                        and sf.dotted_call_name(deco.func)
                        in ("functools.partial", "partial")
                        and deco.args
                        and sf.dotted_call_name(deco.args[0]) == "jax.jit"
                    )
                    if dotted == "jax.jit" or is_partial_jit:
                        key = (sf.rel, node.lineno, "@jax.jit")
                        if key not in seen:
                            seen.add(key)
                            yield TracedRoot(
                                sf, node, sf, node.lineno, "@jax.jit"
                            )
