"""Rule registry and the Finding record every rule emits.

A rule is a class with a stable ``id`` registered via ``@register``;
the runner instantiates the registry once per invocation and hands each
rule the shared project index (``project.ProjectIndex``) so no rule
re-parses a file the framework has already parsed.

Two granularities:

- ``scope = "file"``: ``check_file(ctx)`` runs once per source file
  with a ``FileContext`` (the parsed file + the project it belongs to).
  Most rules live here.
- ``scope = "project"``: ``check_project(project)`` runs once with the
  whole index — for cross-module analyses (JAX001 walks the package
  call graph from every jit root, which no single file can see).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path
from typing import TYPE_CHECKING, Dict, Iterable, List

if TYPE_CHECKING:  # import cycle: project.py imports nothing from here
    from .project import ProjectIndex, SourceFile


@dataclass(frozen=True)
class Finding:
    """One reported violation. ``rel`` is the repo-relative path (or
    the bare filename for out-of-tree files, e.g. test fixtures)."""

    path: Path
    rel: str
    line: int
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.rel}:{self.line}: {self.rule} {self.message}"

    def as_dict(self) -> dict:
        return {
            "file": self.rel,
            "line": self.line,
            "rule": self.rule,
            "message": self.message,
        }


@dataclass
class FileContext:
    """What a file-scoped rule sees: the parsed file plus the project
    index (for import resolution and runtime-scope decisions)."""

    sf: "SourceFile"
    project: "ProjectIndex"
    findings: List[Finding] = field(default_factory=list)

    def report(self, line: int, rule: str, message: str) -> None:
        self.findings.append(
            Finding(self.sf.path, self.sf.rel, line, rule, message)
        )


class Rule:
    """Base class: subclass, set ``id``/``title``/``scope``, implement
    the matching ``check_*`` method, and decorate with ``@register``."""

    id: str = ""
    title: str = ""
    #: one-line rationale shown by --list-rules (the full table with
    #: examples lives in docs/STATIC_ANALYSIS.md)
    rationale: str = ""
    scope: str = "file"  # "file" | "project"

    def check_file(self, ctx: FileContext) -> None:
        raise NotImplementedError

    def check_project(self, project: "ProjectIndex") -> Iterable[Finding]:
        raise NotImplementedError


_REGISTRY: Dict[str, Rule] = {}


def register(cls):
    """Class decorator: instantiate and index the rule by id. Duplicate
    ids are a programming error and fail loudly at import time."""
    rule = cls()
    if not rule.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if rule.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {rule.id!r}")
    _REGISTRY[rule.id] = rule
    return cls


def all_rules() -> List[Rule]:
    """Registered rules, stable-ordered by id (output determinism)."""
    _load_rules()
    return [_REGISTRY[k] for k in sorted(_REGISTRY)]


def get_rule(rule_id: str) -> Rule:
    _load_rules()
    return _REGISTRY[rule_id]


def _load_rules() -> None:
    # rules register on import; deferred so `import tools.simonlint.core`
    # alone (e.g. from a rule module) cannot cycle
    from . import rules  # noqa: F401
