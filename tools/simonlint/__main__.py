"""`python -m tools.simonlint` — the `make lint` / CI entry point.

Exit status 1 when any finding survives suppression, 0 on a clean
tree. `--format json` prints the machine-readable findings document;
`--out PATH` writes that document to a file regardless of the stdout
format (CI uploads it as a workflow artifact while keeping readable
logs)."""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from .core import all_rules
from .runner import (
    DEFAULT_ROOTS,
    lint_paths,
    render_json,
    render_text,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.simonlint",
        description="first-party static analysis (docs/STATIC_ANALYSIS.md)",
    )
    ap.add_argument(
        "paths",
        nargs="*",
        help=f"files/directories to lint (default: {' '.join(DEFAULT_ROOTS)})",
    )
    ap.add_argument(
        "--format",
        choices=("text", "json"),
        default="text",
        help="stdout format (default text)",
    )
    ap.add_argument(
        "--out",
        metavar="PATH",
        help="also write the JSON findings document to PATH",
    )
    ap.add_argument(
        "--rules",
        metavar="ID[,ID...]",
        help="restrict to a comma-separated subset of rule ids",
    )
    ap.add_argument(
        "--list-rules",
        action="store_true",
        help="print the rule inventory and exit",
    )
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in all_rules():
            print(f"{rule.id:8s} {rule.title}")
            print(f"         {rule.rationale}")
        # framework-level, not a registered rule: emitted by the
        # pragma accounting pass itself
        print("SL001    unused suppression")
        print(
            "         a `# simonlint: disable=` pragma that silences "
            "nothing is itself an error — suppressions cannot rot"
        )
        return 0

    rules = (
        [r.strip() for r in args.rules.split(",") if r.strip()]
        if args.rules
        else None
    )
    if rules:
        known = {r.id for r in all_rules()}
        unknown = [r for r in rules if r not in known]
        if unknown:
            print(f"unknown rule id(s): {', '.join(unknown)}", file=sys.stderr)
            return 2
    try:
        findings = lint_paths(args.paths or DEFAULT_ROOTS, rules=rules)
    except (OSError, UnicodeDecodeError) as e:
        # bad path / unreadable or undecodable file: a usage error
        # (2), distinct from "findings found" (1)
        print(f"simonlint: {e}", file=sys.stderr)
        return 2
    if args.out:
        Path(args.out).write_text(render_json(findings) + "\n")
    print(
        render_json(findings)
        if args.format == "json"
        else render_text(findings)
    )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
