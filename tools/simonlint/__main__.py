"""`python -m tools.simonlint` — the `make lint` / CI entry point.

Exit status 1 when any finding survives suppression (and the
baseline, when one is given), 0 on a clean tree. `--format
json|sarif` prints the machine-readable findings document; `--out
PATH` writes the JSON document and `--sarif-out PATH` the SARIF one
regardless of the stdout format (CI uploads both as artifacts while
keeping readable logs). The incremental cache is on by default
(`.simonlint_cache/`); `--no-cache` forces a cold run."""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from .baseline import apply_baseline, load_baseline, write_baseline
from .cache import LintCache
from .core import all_rules
from .project import repo_root
from .runner import (
    DEFAULT_ROOTS,
    lint_paths,
    render_json,
    render_text,
)
from .sarif import render_sarif


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.simonlint",
        description="first-party static analysis (docs/STATIC_ANALYSIS.md)",
    )
    ap.add_argument(
        "paths",
        nargs="*",
        help=f"files/directories to lint (default: {' '.join(DEFAULT_ROOTS)})",
    )
    ap.add_argument(
        "--format",
        choices=("text", "json", "sarif"),
        default="text",
        help="stdout format (default text)",
    )
    ap.add_argument(
        "--out",
        metavar="PATH",
        help="also write the JSON findings document to PATH",
    )
    ap.add_argument(
        "--sarif-out",
        metavar="PATH",
        help="also write the SARIF findings document to PATH",
    )
    ap.add_argument(
        "--rules",
        metavar="ID[,ID...]",
        help="restrict to a comma-separated subset of rule ids",
    )
    ap.add_argument(
        "--no-cache",
        action="store_true",
        help="bypass the incremental cache (.simonlint_cache/)",
    )
    ap.add_argument(
        "--baseline",
        metavar="PATH",
        help="accepted-findings baseline: fail only on findings not in "
        "it; stale entries are reported as SL002",
    )
    ap.add_argument(
        "--write-baseline",
        metavar="PATH",
        help="record the current findings as the accepted baseline and "
        "exit 0",
    )
    ap.add_argument(
        "--list-rules",
        action="store_true",
        help="print the rule inventory and exit",
    )
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in all_rules():
            print(f"{rule.id:8s} {rule.title}")
            print(f"         {rule.rationale}")
        # framework-level, not registered rules: emitted by the pragma
        # accounting pass and the baseline ratchet themselves
        print("SL001    unused suppression")
        print(
            "         a `# simonlint: disable=` pragma that silences "
            "nothing is itself an error — suppressions cannot rot"
        )
        print("SL002    stale baseline entry")
        print(
            "         a baseline entry whose finding no longer fires is "
            "itself an error — the ratchet only tightens"
        )
        return 0

    rules = (
        [r.strip() for r in args.rules.split(",") if r.strip()]
        if args.rules
        else None
    )
    if rules:
        known = {r.id for r in all_rules()}
        unknown = [r for r in rules if r not in known]
        if unknown:
            print(f"unknown rule id(s): {', '.join(unknown)}", file=sys.stderr)
            return 2
    try:
        cache = LintCache(repo_root(), enabled=not args.no_cache)
        findings = lint_paths(
            args.paths or DEFAULT_ROOTS, rules=rules, cache=cache
        )
    except (OSError, UnicodeDecodeError) as e:
        # bad path / unreadable or undecodable file: a usage error
        # (2), distinct from "findings found" (1)
        print(f"simonlint: {e}", file=sys.stderr)
        return 2
    if args.write_baseline:
        write_baseline(args.write_baseline, findings)
        # artifact flags still honored: a CI job recording a baseline
        # usually uploads the findings documents in the same run
        if args.out:
            Path(args.out).write_text(render_json(findings) + "\n")
        if args.sarif_out:
            Path(args.sarif_out).write_text(render_sarif(findings) + "\n")
        print(
            f"baseline written: {len(findings)} accepted finding(s) -> "
            f"{args.write_baseline}"
        )
        return 0
    if args.baseline:
        try:
            entries = load_baseline(args.baseline)
        except (OSError, ValueError) as e:
            print(f"simonlint: {e}", file=sys.stderr)
            return 2
        findings = apply_baseline(findings, entries, args.baseline)
        findings.sort(key=lambda f: (f.rel, f.line, f.rule))
    if args.out:
        Path(args.out).write_text(render_json(findings) + "\n")
    if args.sarif_out:
        Path(args.sarif_out).write_text(render_sarif(findings) + "\n")
    if args.format == "json":
        print(render_json(findings))
    elif args.format == "sarif":
        print(render_sarif(findings))
    else:
        print(render_text(findings))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
