"""Lint driver: build the project index once, run every registered
rule, apply pragmas, render text/JSON.

`lint_paths` is the API surface the tests drive (they point it at tmp
fixture trees with `root=` overriding the repo root so the runtime-
scope policy applies to fixtures); `lint_repo` is what
`python -m tools.simonlint` and `make lint` run.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import List, Optional, Sequence

from .core import FileContext, Finding, all_rules
from .pragmas import apply_suppressions
from .project import ProjectIndex, repo_root

#: what `make lint` covers — the same roots the old monolith walked
DEFAULT_ROOTS = (
    "open_simulator_tpu",
    "tools",
    "tests",
    "bench.py",
    "__graft_entry__.py",
)


def _expand(paths: Sequence, root: Path) -> List[Path]:
    out: List[Path] = []
    for p in paths:
        p = Path(p)
        if not p.is_absolute():
            p = root / p
        if p.is_dir():
            out.extend(sorted(p.rglob("*.py")))
        elif p.is_file():
            out.append(p)
        else:
            # a typo'd path must fail with a diagnostic, not a raw
            # read_text traceback whose exit code 1 looks like
            # "findings found" to scripts checking the gate
            raise FileNotFoundError(f"no such file or directory: {p}")
    return out


def lint_paths(
    paths: Sequence,
    root: Optional[Path] = None,
    rules: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """Lint an explicit set of files/directories. `root` anchors
    repo-relative names and the runtime-scope policy (defaults to the
    real repo root). `rules` optionally restricts to a subset of rule
    ids. Returns post-suppression findings, sorted."""
    root = Path(root) if root is not None else repo_root()
    project = ProjectIndex(_expand(paths, root), root)
    findings: List[Finding] = []
    active = [
        r for r in all_rules() if rules is None or r.id in set(rules)
    ]
    for sf in project.files:
        if sf.syntax_error is not None:
            e = sf.syntax_error
            findings.append(
                Finding(
                    sf.path,
                    sf.rel,
                    e.lineno or 0,
                    "E999",
                    f"syntax error: {e.msg}",
                )
            )
    file_rules = [r for r in active if r.scope == "file"]
    project_rules = [r for r in active if r.scope == "project"]
    for sf in project.files:
        if sf.tree is None:
            continue
        ctx = FileContext(sf, project)
        for rule in file_rules:
            rule.check_file(ctx)
        findings.extend(ctx.findings)
    for rule in project_rules:
        findings.extend(rule.check_project(project))
    findings = apply_suppressions(
        findings,
        project.files,
        active_rules=None if rules is None else {r.id for r in active},
    )
    findings.sort(key=lambda f: (f.rel, f.line, f.rule))
    return findings


def lint_repo(rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """The `make lint` entry: DEFAULT_ROOTS under the real repo root."""
    return lint_paths(DEFAULT_ROOTS, rules=rules)


def lint_file(path) -> List[tuple]:
    """Single-file compatibility shim with the old tools/lint.py
    signature: [(path, line, code, message)] tuples. Project-wide
    rules see only this one file."""
    findings = lint_paths([Path(path)])
    return [(f.path, f.line, f.rule, f.message) for f in findings]


# ------------------------------------------------------------- rendering


def render_text(findings: List[Finding]) -> str:
    lines = [f.render() for f in findings]
    lines.append(
        f"{len(findings)} finding(s)" if findings else "lint: clean"
    )
    return "\n".join(lines)


def render_json(findings: List[Finding]) -> str:
    doc = {
        "version": 1,
        "count": len(findings),
        "findings": [f.as_dict() for f in findings],
    }
    return json.dumps(doc, indent=2)
