"""Lint driver: build the project index once, run every registered
rule, apply pragmas, render text/JSON/SARIF.

`lint_paths` is the API surface the tests drive (they point it at tmp
fixture trees with `root=` overriding the repo root so the runtime-
scope policy applies to fixtures); `lint_repo` is what
`python -m tools.simonlint` and `make lint` run — with the incremental
cache (tools/simonlint/cache.py) on by default so an unchanged tree
answers from `.simonlint_cache/` and a partial edit re-runs file rules
only on the changed files (project-scoped rules always re-run; the
suppression pass always runs fresh so SL001 stays exact).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import List, Optional, Sequence

from .cache import LintCache, file_digest
from .core import FileContext, Finding, all_rules
from .pragmas import apply_suppressions
from .project import ProjectIndex, repo_root

#: what `make lint` covers — the same roots the old monolith walked
DEFAULT_ROOTS = (
    "open_simulator_tpu",
    "tools",
    "tests",
    "bench.py",
    "__graft_entry__.py",
)


def _expand(paths: Sequence, root: Path) -> List[Path]:
    out: List[Path] = []
    for p in paths:
        p = Path(p)
        if not p.is_absolute():
            p = root / p
        if p.is_dir():
            out.extend(sorted(p.rglob("*.py")))
        elif p.is_file():
            out.append(p)
        else:
            # a typo'd path must fail with a diagnostic, not a raw
            # read_text traceback whose exit code 1 looks like
            # "findings found" to scripts checking the gate
            raise FileNotFoundError(f"no such file or directory: {p}")
    return out


def _rel_of(path: Path, root: Path) -> str:
    try:
        return str(path.resolve().relative_to(root.resolve()))
    except ValueError:
        return path.name


def _finding_to_dict(f: Finding) -> dict:
    return {
        "path": str(f.path),
        "rel": f.rel,
        "line": f.line,
        "rule": f.rule,
        "message": f.message,
    }


def _finding_from_dict(d: dict) -> Finding:
    return Finding(
        Path(d["path"]), d["rel"], int(d["line"]), d["rule"], d["message"]
    )


def lint_paths(
    paths: Sequence,
    root: Optional[Path] = None,
    rules: Optional[Sequence[str]] = None,
    cache: Optional[LintCache] = None,
) -> List[Finding]:
    """Lint an explicit set of files/directories. `root` anchors
    repo-relative names and the runtime-scope policy (defaults to the
    real repo root). `rules` optionally restricts to a subset of rule
    ids. `cache` (a cache.LintCache) enables the incremental tiers.
    Returns post-suppression findings, sorted."""
    root = Path(root) if root is not None else repo_root()
    files = _expand(paths, root)

    digests = {}
    full_key = None
    if cache is not None and cache.enabled:
        digests = {_rel_of(p, root): file_digest(p) for p in files}
        rules_key = ",".join(sorted(rules)) if rules else "*"
        full_key = cache.full_key(digests, rules_key)
        stored = cache.load_full(full_key)
        if stored is not None:
            return [_finding_from_dict(d) for d in stored]

    project = ProjectIndex(files, root)
    findings: List[Finding] = []
    active = [
        r for r in all_rules() if rules is None or r.id in set(rules)
    ]
    for sf in project.files:
        if sf.syntax_error is not None:
            e = sf.syntax_error
            findings.append(
                Finding(
                    sf.path,
                    sf.rel,
                    e.lineno or 0,
                    "E999",
                    f"syntax error: {e.msg}",
                )
            )
    file_rules = [r for r in active if r.scope == "file"]
    project_rules = [r for r in active if r.scope == "project"]
    # the per-file tier only serves full-rule runs: its entries hold
    # the complete file-rule finding set for one content digest, which
    # a subset run could neither use nor refresh soundly
    use_file_tier = cache is not None and cache.enabled and rules is None
    for sf in project.files:
        if sf.tree is None:
            continue
        cached = (
            cache.load_file(sf.rel, digests.get(sf.rel, ""))
            if use_file_tier
            else None
        )
        if cached is not None:
            findings.extend(_finding_from_dict(d) for d in cached)
            continue
        ctx = FileContext(sf, project)
        for rule in file_rules:
            rule.check_file(ctx)
        findings.extend(ctx.findings)
        if use_file_tier:
            cache.store_file(
                sf.rel,
                digests.get(sf.rel, ""),
                [_finding_to_dict(f) for f in ctx.findings],
            )
    for rule in project_rules:
        findings.extend(rule.check_project(project))
    findings = apply_suppressions(
        findings,
        project.files,
        active_rules=None if rules is None else {r.id for r in active},
    )
    findings.sort(key=lambda f: (f.rel, f.line, f.rule))
    if cache is not None and cache.enabled and full_key is not None:
        cache.store_full(full_key, [_finding_to_dict(f) for f in findings])
        cache.save()
    return findings


def lint_repo(
    rules: Optional[Sequence[str]] = None, use_cache: bool = True
) -> List[Finding]:
    """The `make lint` entry: DEFAULT_ROOTS under the real repo root,
    incremental cache on."""
    cache = LintCache(repo_root(), enabled=use_cache)
    return lint_paths(DEFAULT_ROOTS, rules=rules, cache=cache)


def lint_file(path) -> List[tuple]:
    """Single-file compatibility shim with the old tools/lint.py
    signature: [(path, line, code, message)] tuples. Project-wide
    rules see only this one file."""
    findings = lint_paths([Path(path)])
    return [(f.path, f.line, f.rule, f.message) for f in findings]


# ------------------------------------------------------------- rendering


def render_text(findings: List[Finding]) -> str:
    lines = [f.render() for f in findings]
    lines.append(
        f"{len(findings)} finding(s)" if findings else "lint: clean"
    )
    return "\n".join(lines)


def render_json(findings: List[Finding]) -> str:
    doc = {
        "version": 1,
        "count": len(findings),
        "findings": [f.as_dict() for f in findings],
    }
    return json.dumps(doc, indent=2)
