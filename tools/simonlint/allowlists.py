"""Audited allowlists — the escape hatch that leaves a paper trail.

Every entry is keyed by (repo-relative path, enclosing function) so
line drift cannot rot it, and carries a one-line justification in the
comment above it. The test suite asserts every listed file still
exists (tests/test_simonlint.py). Unlike pragmas, allowlist entries are
not usage-checked — they cover whole functions, not lines — so prefer
a `# simonlint: disable=RULE` pragma (which IS usage-checked via
SL001) for single-line exemptions.
"""

from __future__ import annotations

from typing import Set, Tuple

Key = Tuple[str, str]

# --------------------------------------------------------------- BLE001/S110
# Broad handlers audited as legitimate last-resort degradations: each
# logs a warning and/or records a trace note, then falls back to a
# correct (slower) path — never a silent swallow. Anything new must
# catch specific exception types or earn an entry here with the same
# audit.
BROAD_EXCEPT_ALLOW: Set[Key] = {
    ("open_simulator_tpu/apply/applier.py", "_plan_with_probes"),
    ("open_simulator_tpu/apply/applier.py", "_sweep_min_count"),
    ("open_simulator_tpu/apply/interactive.py", "_make_evaluator"),
    # narrow-typed parse cascade (int -> float -> MISSING is the
    # template grammar, not a swallowed error) and best-effort tempfile
    # cleanup on close — audited silent-pass survivors
    ("open_simulator_tpu/models/chart.py", "_eval_atom"),
    ("open_simulator_tpu/models/kubeclient.py", "close"),
    # ladder executor: classifies via classify_device_error and either
    # re-raises typed or downgrades with a trace note — never swallows
    ("open_simulator_tpu/runtime/guard.py", "run_laddered"),
    # signal-handler restore at interpreter teardown: ValueError means
    # "not the main thread anymore", there is nothing left to restore
    ("open_simulator_tpu/runtime/budget.py", "sigint_to_budget"),
}

# ------------------------------------------------------------------- S113
# Audited call sites allowed without an explicit timeout: every other
# first-party I/O call names its timeout (runtime/retry.py holds the
# configurable defaults).
IO_TIMEOUT_ALLOW: Set[Key] = {
    # Popen has no timeout= (it does not wait); the spawn readiness
    # wait that follows is bounded by ReplicaProcess.ready_timeout_s
    ("open_simulator_tpu/fleet/replica.py", "_spawn_once"),
}

# ------------------------------------------------------------------- T201
# Files whose job IS terminal output — the CLI command surface.
# Everything else in open_simulator_tpu/ must route output through the
# report writer / logging / obs spans, or name its stream with file=.
PRINT_ALLOW_FILES: Set[str] = {
    "open_simulator_tpu/cli.py",
}
# Audited individual print sites. Currently empty: the non-CLI
# survivors all pass an explicit file= (interactive.py's shell writes
# to its injected fout).
PRINT_ALLOW: Set[Key] = set()

# ------------------------------------------------------------------ JAX002
# jit wrappers created inside a function body but provably compiled
# once: the creation is behind a cache-miss guard and the wrapper is
# stored somewhere the checker's assignment analysis cannot follow.
JAX002_ALLOW: Set[Key] = {
    # `@jax.jit def call(...)` is built once per _COMPILED_CACHE key
    # (the miss branch directly above) and stored via _Compiled(fn=call)
    # — a dataclass hop the local-escape analysis cannot see through
    ("open_simulator_tpu/ops/pallas_scan.py", "run_scan_pallas"),
}

# ------------------------------------------------------------------ JAX001
# Traced-reachable host calls audited as trace-safe. Currently empty:
# the guarded host path in ops/scan.features_of carries a def-line
# pragma instead (it is one function, and the pragma is usage-checked).
JAX001_ALLOW: Set[Key] = set()

# ----------------------------------------------------------------- CONC001
# Unlocked accesses to lock-guarded fields audited as safe. Currently
# empty: the documented benign races (memo fast path, hot-path enabled
# reads, caller-holds-lock helpers) carry usage-checked pragmas at the
# site instead.
CONC001_ALLOW: Set[Key] = set()

# ----------------------------------------------------------------- CONC002
# Functions exempt from the lock-order / blocking-under-lock dataflow.
# Currently empty: the one audited in-tree case (JsonlSink._emit keeps
# its per-line fsync under the sink's own single-purpose I/O lock)
# carries a usage-checked def-line pragma with the justification at
# the code instead.
CONC002_ALLOW: Set[Key] = set()

# ------------------------------------------------------------------- RT001
# Budget-scoped while loops audited as exempt from the
# check-on-every-path discipline. Prefer a usage-checked RT001 pragma
# at the loop over an entry here.
RT001_ALLOW: Set[Key] = set()

# ------------------------------------------------------------------ JAX003
# Engine-directory functions exempt from the dtype/transfer dataflow.
# Prefer a usage-checked JAX003 pragma at the site over an entry here
# (sweep.find_min_count_multi's one counted sync per shape bucket
# carries one).
JAX003_ALLOW: Set[Key] = set()

# ------------------------------------------------------------------ EXC001
# Whole modules whose JOB is parsing/validation: stdlib
# ValueError/TypeError raises there ARE the input-error surface
# (InputError is itself a ValueError; these modules sit below it and
# their internal `except ValueError` cascades must keep catching their
# own raises). Anything outside these files needs a per-function entry
# below or a typed taxonomy error.
EXC001_VALIDATION_FILES: Set[str] = {
    # the Go-compatible quantity grammar: parse errors are ValueErrors
    # by contract (validation.py wraps them into field-scoped errors)
    "open_simulator_tpu/utils/quantity.py",
    # Go math/rand reimplementation: argument-contract checks mirror
    # the stdlib's panics; callers treat them as programming errors
    "open_simulator_tpu/utils/gorand.py",
    # KubeSchedulerConfiguration parser: every raise is a config-file
    # diagnosis, wrapped by load_scheduler_config into one message
    "open_simulator_tpu/scheduler/schedconfig.py",
    # snapshot document validation (version/shape checks on load)
    "open_simulator_tpu/scheduler/snapshot.py",
    # --inject spec grammar: modifier parsing raises ValueError and
    # parse_spec's own `except ValueError` cascade wraps every one
    # into a clause-scoped InputError (the quantity.py pattern)
    "open_simulator_tpu/runtime/inject.py",
}

# Individual validation-boundary functions allowed to raise stdlib
# ValueError/TypeError: constructor argument checks and request/record
# parsers whose callers catch ValueError by contract.
EXC001_ALLOW: Set[Key] = {
    # HTTP request parsing: the handler catches ValueError -> 400
    ("open_simulator_tpu/serve/server.py", "parse_request_body"),
    ("open_simulator_tpu/serve/server.py", "_decode_app_yaml"),
    # constructor argument validation (the Python idiom; callers that
    # pass literals deserve the loud TypeError/ValueError)
    ("open_simulator_tpu/serve/coalescer.py", "__init__"),
    ("open_simulator_tpu/serve/sessions.py", "__init__"),
    ("open_simulator_tpu/runtime/budget.py", "__init__"),
    ("open_simulator_tpu/runtime/guard.py", "run_laddered"),
    ("open_simulator_tpu/resilience/chaos.py", "__init__"),
    ("open_simulator_tpu/scheduler/oracle.py", "__init__"),
    ("open_simulator_tpu/scheduler/plugins.py", "register"),
    ("open_simulator_tpu/testing.py", "_check_positionals"),
    # journal/decision-log record parsing: the raise IS the control
    # flow (caught as ValueError in the same function to classify a
    # torn tail vs interior damage)
    ("open_simulator_tpu/runtime/journal.py", "resume"),
    ("open_simulator_tpu/runtime/journal.py", "rewrite"),
    ("open_simulator_tpu/runtime/checkpoint.py", "load_checkpoint"),
    ("open_simulator_tpu/shadow/log.py", "read_decision_log"),
    ("open_simulator_tpu/shadow/log.py", "from_record"),
    # API-contract preconditions on the scan entry points (caller bug,
    # not recoverable input; ValueError mirrors numpy's own contract
    # errors these sit beside)
    ("open_simulator_tpu/ops/scan.py", "run_scan_masked"),
    ("open_simulator_tpu/ops/pallas_scan.py", "run_scan_pallas"),
    ("open_simulator_tpu/scheduler/engine.py", "scan_scenarios"),
    ("open_simulator_tpu/scheduler/oracle.py", "evict"),
    ("open_simulator_tpu/scheduler/oracle.py", "remove_pod_from_node"),
    # extenders config section validation (wrapped upstream into the
    # config-load diagnosis)
    ("open_simulator_tpu/scheduler/extender.py", "extenders_from_config_doc"),
    # CLI flag-literal parsing (argparse surfaces it as a usage error)
    ("open_simulator_tpu/cli.py", "_parse_taint"),
}
