"""Audited allowlists — the escape hatch that leaves a paper trail.

Every entry is keyed by (repo-relative path, enclosing function) so
line drift cannot rot it, and carries a one-line justification in the
comment above it. The test suite asserts every listed file still
exists (tests/test_simonlint.py). Unlike pragmas, allowlist entries are
not usage-checked — they cover whole functions, not lines — so prefer
a `# simonlint: disable=RULE` pragma (which IS usage-checked via
SL001) for single-line exemptions.
"""

from __future__ import annotations

from typing import Set, Tuple

Key = Tuple[str, str]

# --------------------------------------------------------------- BLE001/S110
# Broad handlers audited as legitimate last-resort degradations: each
# logs a warning and/or records a trace note, then falls back to a
# correct (slower) path — never a silent swallow. Anything new must
# catch specific exception types or earn an entry here with the same
# audit.
BROAD_EXCEPT_ALLOW: Set[Key] = {
    ("open_simulator_tpu/apply/applier.py", "_plan_with_probes"),
    ("open_simulator_tpu/apply/applier.py", "_sweep_min_count"),
    ("open_simulator_tpu/apply/interactive.py", "_make_evaluator"),
    # narrow-typed parse cascade (int -> float -> MISSING is the
    # template grammar, not a swallowed error) and best-effort tempfile
    # cleanup on close — audited silent-pass survivors
    ("open_simulator_tpu/models/chart.py", "_eval_atom"),
    ("open_simulator_tpu/models/kubeclient.py", "close"),
    # ladder executor: classifies via classify_device_error and either
    # re-raises typed or downgrades with a trace note — never swallows
    ("open_simulator_tpu/runtime/guard.py", "run_laddered"),
    # signal-handler restore at interpreter teardown: ValueError means
    # "not the main thread anymore", there is nothing left to restore
    ("open_simulator_tpu/runtime/budget.py", "sigint_to_budget"),
}

# ------------------------------------------------------------------- S113
# Audited call sites allowed without an explicit timeout. Currently
# empty: every first-party I/O call names its timeout
# (runtime/retry.py holds the configurable defaults).
IO_TIMEOUT_ALLOW: Set[Key] = set()

# ------------------------------------------------------------------- T201
# Files whose job IS terminal output — the CLI command surface.
# Everything else in open_simulator_tpu/ must route output through the
# report writer / logging / obs spans, or name its stream with file=.
PRINT_ALLOW_FILES: Set[str] = {
    "open_simulator_tpu/cli.py",
}
# Audited individual print sites. Currently empty: the non-CLI
# survivors all pass an explicit file= (interactive.py's shell writes
# to its injected fout).
PRINT_ALLOW: Set[Key] = set()

# ------------------------------------------------------------------ JAX002
# jit wrappers created inside a function body but provably compiled
# once: the creation is behind a cache-miss guard and the wrapper is
# stored somewhere the checker's assignment analysis cannot follow.
JAX002_ALLOW: Set[Key] = {
    # `@jax.jit def call(...)` is built once per _COMPILED_CACHE key
    # (the miss branch directly above) and stored via _Compiled(fn=call)
    # — a dataclass hop the local-escape analysis cannot see through
    ("open_simulator_tpu/ops/pallas_scan.py", "run_scan_pallas"),
}

# ------------------------------------------------------------------ JAX001
# Traced-reachable host calls audited as trace-safe. Currently empty:
# the guarded host path in ops/scan.features_of carries a def-line
# pragma instead (it is one function, and the pragma is usage-checked).
JAX001_ALLOW: Set[Key] = set()

# ----------------------------------------------------------------- CONC001
# Unlocked accesses to lock-guarded fields audited as safe. Currently
# empty: the documented benign races (memo fast path, hot-path enabled
# reads, caller-holds-lock helpers) carry usage-checked pragmas at the
# site instead.
CONC001_ALLOW: Set[Key] = set()
