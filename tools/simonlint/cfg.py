"""Per-function control-flow graphs — the substrate of the dataflow
rules (CONC002 / JAX003 / RT001).

One ``CFG`` per function: basic blocks of ordered **events**, edges for
branches, loop back-edges, exception paths, and ``finally`` chains.
Events are deliberately coarser than expressions and finer than
statements:

- ``stmt``    — one simple statement (or the *header* expression of a
  compound one: an ``if``/``while`` test, a ``for`` iterable). Rules
  scan the event's executed expressions via ``event_exprs`` — nested
  statement bodies are NOT part of the event (they have their own
  blocks), and nested ``def`` bodies are skipped entirely.
- ``acquire`` / ``release`` — a lock edge: ``with <lockish>:`` entry and
  exit, or an explicit ``.acquire()`` / ``.release()`` call statement.
  ``lock`` carries the canonical cross-module name (see
  ``canonical_lock_name``); the with-protocol's release-on-unwind is
  modeled (return / break / continue / raise inside a ``with`` emit the
  release before the abnormal edge).
- ``loop_head`` — the head of a ``while``/``for``; its block is the
  join point of the entry edge and every back-edge, which is what lets
  RT001 phrase "reaches the back-edge without a budget check" as a
  plain forward dataflow fact.

Exception flow is over-approximated the cheap way: every block created
inside a ``try`` body gets an edge to each of that try's handlers
(with the with-unwind releases for locks opened since the ``try``).
``finally`` bodies are lowered once; normal and abnormal paths both
route through them, and the finally exit conservatively reaches both
the continuation and the function exit. All three dataflow clients are
tolerant of this over-approximation by construction: CONC002 uses a
may-analysis (union join), RT001's unchecked-path analysis only gains
paths that also exist dynamically, and JAX003's kind lattice degrades
to "unknown" on a bad join.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)

def is_lockish(name: str) -> bool:
    """Heuristic lock detector: the codebase's locks all carry "lock"
    in the name (``_lock``, ``_breakers_lock``, ``_inflight_lock``,
    ``_REGISTRY_LOCK``)."""
    return "lock" in name.lower()


def canonical_lock_name(sf, expr: ast.AST) -> Optional[str]:
    """Cross-module canonical name of a lock expression, or None when
    the expression is not lock-shaped.

    - ``self._lock``          -> ``<module>.<Class>._lock``
    - module-level ``_lock``  -> ``<module>._lock`` (through the import
      alias map, so ``trace._lock`` in another file canonicalizes to
      the defining module)
    - ``mod_alias._lock``     -> ``<target module>._lock``
    """
    if isinstance(expr, ast.Attribute):
        if not is_lockish(expr.attr):
            return None
        base = expr.value
        if isinstance(base, ast.Name) and base.id == "self":
            cls = sf.enclosing_class(expr)
            mod = sf.module or sf.rel
            if cls is not None:
                return f"{mod}.{cls.name}.{expr.attr}"
            return f"{mod}.{expr.attr}"
        dotted = sf.dotted_call_name(expr)
        return dotted or None
    if isinstance(expr, ast.Name):
        if not is_lockish(expr.id):
            return None
        target = sf.imports.get(expr.id)
        if target:
            return target
        mod = sf.module or sf.rel
        return f"{mod}.{expr.id}"
    return None


@dataclass
class Event:
    kind: str  # "stmt" | "acquire" | "release" | "loop_head"
    node: ast.AST
    lock: Optional[str] = None


class Block:
    __slots__ = ("bid", "events", "succs")

    def __init__(self, bid: int):
        self.bid = bid
        self.events: List[Event] = []
        self.succs: List["Block"] = []

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"B{self.bid}->{[s.bid for s in self.succs]}"


@dataclass
class LoopInfo:
    head: Block
    break_target: Block
    #: blocks whose edge to `head` is a back-edge (fallthrough bottoms
    #: and `continue` sites)
    back_sources: List[Block] = field(default_factory=list)


@dataclass
class CFG:
    fn: ast.AST
    entry: Block
    exit: Block
    blocks: List[Block]
    loops: Dict[ast.AST, LoopInfo]


def event_exprs(ev: Event) -> List[ast.AST]:
    """The AST subtrees that actually EXECUTE at this event (header
    expressions for compound statements; the whole node for simple
    ones). Nested statement bodies and nested ``def`` bodies are
    excluded — they have their own events (or are separate CFGs)."""
    node = ev.node
    if ev.kind in ("acquire", "release"):
        return [node]
    if isinstance(node, (ast.If, ast.While)):
        return [node.test]
    if isinstance(node, (ast.For, ast.AsyncFor)):
        return [node.iter, node.target]
    if isinstance(node, (ast.With, ast.AsyncWith)):
        return [item.context_expr for item in node.items]
    if isinstance(node, ast.Try):
        return []
    if isinstance(node, _FUNC_NODES + (ast.ClassDef,)):
        # decorators/defaults run here; the body does not
        out: List[ast.AST] = list(node.decorator_list)
        if isinstance(node, _FUNC_NODES):
            out.extend(d for d in node.args.defaults)
            out.extend(d for d in node.args.kw_defaults if d is not None)
        return out
    if isinstance(node, ast.Return):
        return [node.value] if node.value is not None else []
    if isinstance(node, ast.Match):
        return [node.subject]
    return [node]


def iter_event_calls(ev: Event):
    """Every Call node executing at this event (nested defs excluded —
    ``event_exprs`` never yields a def body)."""
    for expr in event_exprs(ev):
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Call):
                yield sub


class _Builder:
    def __init__(self, sf, fn_node: ast.AST):
        self.sf = sf
        self.fn = fn_node
        self.blocks: List[Block] = []
        self.exit = self._raw_block()
        self.loops: Dict[ast.AST, LoopInfo] = {}
        #: (loop_node, head, break_target, with_depth)
        self.loop_stack: List[tuple] = []
        #: canonical lock names of lexically-open `with` items (None for
        #: non-lock withs)
        self.with_stack: List[Optional[str]] = []
        #: (handler_entry_blocks, with_depth, finally_entry|None)
        self.handler_stack: List[tuple] = []

    # -- plumbing -----------------------------------------------------------

    def _raw_block(self) -> Block:
        b = Block(len(self.blocks))
        self.blocks.append(b)
        return b

    def new_block(self) -> Block:
        """A block plus the conservative exception edge to the
        innermost enclosing try's handlers/finally (with with-unwind
        releases for locks opened since that try)."""
        b = self._raw_block()
        if self.handler_stack:
            entries, depth, fin = self.handler_stack[-1]
            unwind = self._unwind_block(depth)
            src = b
            if unwind is not None:
                b.succs.append(unwind)
                src = unwind
            for h in entries:
                src.succs.append(h)
            if not entries and fin is not None:
                src.succs.append(fin)
        return b

    def _unwind_block(self, to_depth: int) -> Optional[Block]:
        """Synthetic block releasing every with-held lock above
        `to_depth` (None when there is nothing to release)."""
        locks = [l for l in self.with_stack[to_depth:] if l is not None]
        if not locks:
            return None
        u = self._raw_block()
        for lock in reversed(locks):
            u.events.append(Event("release", self.fn, lock))
        return u

    def _abnormal_edge(self, cur: Block, target: Block, to_depth: int):
        """Route an abnormal exit (return/break/continue) to `target`,
        releasing with-held locks above `to_depth` on the way."""
        unwind = self._unwind_block(to_depth)
        if unwind is not None:
            cur.succs.append(unwind)
            unwind.succs.append(target)
            return unwind
        cur.succs.append(target)
        return cur

    def _innermost_finally(self) -> Optional[Block]:
        for entries, _depth, fin in reversed(self.handler_stack):
            if fin is not None:
                return fin
        return None

    # -- lowering -----------------------------------------------------------

    def build(self) -> CFG:
        entry = self.new_block()
        end = self.lower_body(list(self.fn.body), entry)
        if end is not None:
            end.succs.append(self.exit)
        return CFG(self.fn, entry, self.exit, self.blocks, self.loops)

    def lower_body(self, body: List[ast.stmt], cur: Block) -> Optional[Block]:
        for stmt in body:
            if cur is None:
                break  # unreachable tail (after return/raise)
            cur = self.lower_stmt(stmt, cur)
        return cur

    def lower_stmt(self, stmt: ast.stmt, cur: Block) -> Optional[Block]:
        if isinstance(stmt, ast.If):
            return self._lower_if(stmt, cur)
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            return self._lower_loop(stmt, cur)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            return self._lower_with(stmt, cur)
        if isinstance(stmt, ast.Try):
            return self._lower_try(stmt, cur)
        if isinstance(stmt, ast.Match):
            return self._lower_match(stmt, cur)
        if isinstance(stmt, ast.Return):
            cur.events.append(Event("stmt", stmt))
            target = self._innermost_finally() or self.exit
            self._abnormal_edge(cur, target, 0)
            return None
        if isinstance(stmt, ast.Raise):
            cur.events.append(Event("stmt", stmt))
            # the handler edge exists from block creation; add the
            # uncaught path (through finally when present)
            target = self._innermost_finally() or self.exit
            self._abnormal_edge(cur, target, 0)
            return None
        if isinstance(stmt, ast.Break):
            if self.loop_stack:
                _node, _head, brk, depth = self.loop_stack[-1]
                self._abnormal_edge(cur, brk, depth)
            else:  # pragma: no cover - syntactically invalid input
                cur.succs.append(self.exit)
            return None
        if isinstance(stmt, ast.Continue):
            if self.loop_stack:
                node, head, _brk, depth = self.loop_stack[-1]
                src = self._abnormal_edge(cur, head, depth)
                self.loops[node].back_sources.append(src)
            else:  # pragma: no cover - syntactically invalid input
                cur.succs.append(self.exit)
            return None
        # acquire()/release() call statements become lock events
        lock_ev = self._lock_call_event(stmt)
        if lock_ev is not None:
            cur.events.append(lock_ev)
            return cur
        cur.events.append(Event("stmt", stmt))
        return cur

    def _lock_call_event(self, stmt: ast.stmt) -> Optional[Event]:
        if not (isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call)):
            return None
        call = stmt.value
        if not (
            isinstance(call.func, ast.Attribute)
            and call.func.attr in ("acquire", "release")
        ):
            return None
        lock = canonical_lock_name(self.sf, call.func.value)
        if lock is None:
            return None
        return Event(call.func.attr, stmt, lock)

    def _lower_if(self, stmt: ast.If, cur: Block) -> Optional[Block]:
        cur.events.append(Event("stmt", stmt))  # test evaluation
        then_entry = self.new_block()
        cur.succs.append(then_entry)
        then_end = self.lower_body(stmt.body, then_entry)
        if stmt.orelse:
            else_entry = self.new_block()
            cur.succs.append(else_entry)
            else_end = self.lower_body(stmt.orelse, else_entry)
        else:
            else_end = cur
        if then_end is None and else_end is None:
            return None
        join = self.new_block()
        for end in (then_end, else_end):
            if end is not None:
                end.succs.append(join)
        return join

    def _lower_loop(self, stmt, cur: Block) -> Block:
        head = self.new_block()
        cur.succs.append(head)
        head.events.append(Event("loop_head", stmt))
        after = self.new_block()  # break target / loop exit join
        info = LoopInfo(head, after)
        self.loops[stmt] = info
        if stmt.orelse:
            else_entry = self.new_block()
            head.succs.append(else_entry)
            else_end = self.lower_body(stmt.orelse, else_entry)
            if else_end is not None:
                else_end.succs.append(after)
        else:
            head.succs.append(after)
        body_entry = self.new_block()
        head.succs.append(body_entry)
        self.loop_stack.append((stmt, head, after, len(self.with_stack)))
        body_end = self.lower_body(stmt.body, body_entry)
        self.loop_stack.pop()
        if body_end is not None:
            body_end.succs.append(head)
            info.back_sources.append(body_end)
        return after

    def _lower_with(self, stmt, cur: Block) -> Optional[Block]:
        cur.events.append(Event("stmt", stmt))  # context expr evaluation
        opened = 0
        for item in stmt.items:
            lock = canonical_lock_name(self.sf, item.context_expr)
            self.with_stack.append(lock)
            opened += 1
            if lock is not None:
                cur.events.append(Event("acquire", item.context_expr, lock))
        end = self.lower_body(stmt.body, cur)
        for _ in range(opened):
            lock = self.with_stack.pop()
            if lock is not None and end is not None:
                end.events.append(Event("release", stmt, lock))
        return end

    def _lower_try(self, stmt: ast.Try, cur: Block) -> Optional[Block]:
        cur.events.append(Event("stmt", stmt))
        fin_entry = fin_end = None
        if stmt.finalbody:
            fin_entry = self._raw_block()  # no self-exception edges
            fin_end = self.lower_body(stmt.finalbody, fin_entry)
        handler_entries = [self.new_block() for _ in stmt.handlers]
        self.handler_stack.append(
            (handler_entries, len(self.with_stack), fin_entry)
        )
        body_entry = self.new_block()
        cur.succs.append(body_entry)
        body_end = self.lower_body(stmt.body, body_entry)
        if body_end is not None and stmt.orelse:
            body_end = self.lower_body(stmt.orelse, body_end)
        self.handler_stack.pop()
        ends = [body_end]
        for handler, entry in zip(stmt.handlers, handler_entries):
            entry.events.append(Event("stmt", handler.type or handler))
            ends.append(self.lower_body(handler.body, entry))
        live = [e for e in ends if e is not None]
        if fin_entry is not None:
            for e in live:
                e.succs.append(fin_entry)
            if fin_end is None:
                return None
            # abnormal paths resume past the finally conservatively
            fin_end.succs.append(self.exit)
            if not live:
                return None
            join = self.new_block()
            fin_end.succs.append(join)
            return join
        if not live:
            return None
        join = self.new_block()
        for e in live:
            e.succs.append(join)
        return join

    def _lower_match(self, stmt: ast.Match, cur: Block) -> Optional[Block]:
        cur.events.append(Event("stmt", stmt))
        join = self.new_block()
        any_live = False
        for case in stmt.cases:
            entry = self.new_block()
            cur.succs.append(entry)
            end = self.lower_body(case.body, entry)
            if end is not None:
                end.succs.append(join)
                any_live = True
        cur.succs.append(join)  # no case matched
        return join if (any_live or stmt.cases is not None) else None


def build_cfg(sf, fn_node: ast.AST) -> CFG:
    """CFG of one FunctionDef (nested defs are NOT inlined — build
    their own CFGs; their bodies run when called, not here)."""
    return _Builder(sf, fn_node).build()


def iter_function_defs(sf):
    """Every function/method (incl. nested) in a parsed file."""
    if sf.tree is None:
        return
    for node in ast.walk(sf.tree):
        if isinstance(node, _FUNC_NODES):
            yield node
