"""Rule modules register themselves on import (core.register)."""

from . import basic  # noqa: F401
from . import concurrency  # noqa: F401
from . import deadline  # noqa: F401
from . import exceptions  # noqa: F401
from . import hygiene  # noqa: F401
from . import injection  # noqa: F401
from . import jax_compile  # noqa: F401
from . import jax_dtype  # noqa: F401
from . import jax_trace  # noqa: F401
from . import lock_order  # noqa: F401
