"""Pyflakes-class correctness checks (everywhere, including tests and
tools): unused imports, duplicate definitions, mutable defaults, bare
except, None comparison, placeholder-free f-strings, assert-on-tuple.

Ported rule-for-rule from the original single-file linter; behavior is
pinned by tests/test_simonlint.py (incl. the r5 regression where F811
once suppressed itself whenever the scope contained ANY `if`)."""

from __future__ import annotations

import ast

from ..core import FileContext, Rule, register


@register
class UnusedImports(Rule):
    id = "F401"
    title = "unused import"
    rationale = (
        "module-scope imports nothing references are dead weight and "
        "hide real dependency changes (__init__.py re-exports exempt)"
    )

    def check_file(self, ctx: FileContext) -> None:
        sf = ctx.sf
        if sf.path.name == "__init__.py":
            return  # __init__ re-exports are intentional
        imported: dict = {}
        for node in sf.tree.body:
            if isinstance(node, ast.Import):
                for a in node.names:
                    name = (a.asname or a.name).split(".")[0]
                    imported[name] = node.lineno
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                for a in node.names:
                    if a.name == "*":
                        continue
                    imported[a.asname or a.name] = node.lineno
        if not imported:
            return
        used: set = set()
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Name):
                used.add(node.id)
        # names referenced in __all__ strings count as used
        for node in sf.tree.body:
            if (
                isinstance(node, ast.Assign)
                and any(
                    isinstance(t, ast.Name) and t.id == "__all__"
                    for t in node.targets
                )
                and isinstance(node.value, (ast.List, ast.Tuple))
            ):
                for elt in node.value.elts:
                    if isinstance(elt, ast.Constant) and isinstance(
                        elt.value, str
                    ):
                        used.add(elt.value)
        for name, lineno in imported.items():
            if name not in used:
                ctx.report(lineno, self.id, f"'{name}' imported but unused")


@register
class DuplicateDefs(Rule):
    id = "F811"
    title = "redefinition in one scope"
    rationale = (
        "a duplicate def/class in one scope is the classic copy-paste "
        "bug (the second silently wins); conditional dispatch with an "
        "if/try BETWEEN the defs stays legal"
    )

    def check_file(self, ctx: FileContext) -> None:
        self._scope(ctx, ctx.sf.tree.body)
        for node in ast.walk(ctx.sf.tree):
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                self._scope(ctx, node.body)

    def _scope(self, ctx: FileContext, body) -> None:
        seen: dict = {}
        for idx, node in enumerate(body):
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                prev = seen.get(node.name)
                # a redefinition is a bug unless an If/Try stands
                # BETWEEN the two defs (conditional dispatch pattern) —
                # scanning the whole body would let any unrelated `if`
                # suppress the check
                if prev is not None and not any(
                    isinstance(n, (ast.If, ast.Try))
                    for n in body[prev[0] + 1 : idx]
                ):
                    ctx.report(
                        node.lineno,
                        self.id,
                        f"redefinition of '{node.name}' from line {prev[1]}",
                    )
                seen[node.name] = (idx, node.lineno)


@register
class MutableDefaults(Rule):
    id = "B006"
    title = "mutable default argument"
    rationale = (
        "a list/dict/set default is created once and shared across "
        "calls — mutation leaks between callers"
    )

    def check_file(self, ctx: FileContext) -> None:
        for node in ast.walk(ctx.sf.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for default in list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None
            ]:
                if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                    ctx.report(
                        default.lineno,
                        self.id,
                        f"mutable default argument in '{node.name}'",
                    )


@register
class BareExcept(Rule):
    id = "E722"
    title = "bare except"
    rationale = "an untyped handler catches SystemExit/KeyboardInterrupt too"

    def check_file(self, ctx: FileContext) -> None:
        for node in ast.walk(ctx.sf.tree):
            if isinstance(node, ast.ExceptHandler) and node.type is None:
                ctx.report(node.lineno, self.id, "bare 'except:'")


@register
class NoneComparison(Rule):
    id = "E711"
    title = "comparison to None with ==/!="
    rationale = "None identity must use is/is not (== can be overloaded)"

    def check_file(self, ctx: FileContext) -> None:
        for node in ast.walk(ctx.sf.tree):
            if not isinstance(node, ast.Compare):
                continue
            for op, comp in zip(node.ops, node.comparators):
                if isinstance(op, (ast.Eq, ast.NotEq)) and (
                    (isinstance(comp, ast.Constant) and comp.value is None)
                    or (
                        isinstance(node.left, ast.Constant)
                        and node.left.value is None
                    )
                ):
                    ctx.report(
                        node.lineno, self.id, "comparison to None with ==/!="
                    )


@register
class EmptyFString(Rule):
    id = "F541"
    title = "f-string without placeholders"
    rationale = "an f-prefix with no interpolation is usually a lost brace"

    def check_file(self, ctx: FileContext) -> None:
        for child in ast.iter_child_nodes(ctx.sf.tree):
            self._visit(ctx, child)

    def _visit(self, ctx: FileContext, node) -> None:
        if isinstance(node, ast.JoinedStr):
            if not any(
                isinstance(v, ast.FormattedValue) for v in node.values
            ):
                ctx.report(
                    node.lineno, self.id, "f-string without placeholders"
                )
            # do NOT recurse into the JoinedStr generically: a format
            # spec (":05d") is a placeholder-free JoinedStr child and
            # must not be flagged — only visit the formatted values'
            # expressions
            for v in node.values:
                if isinstance(v, ast.FormattedValue):
                    self._visit(ctx, v.value)
            return
        for child in ast.iter_child_nodes(node):
            self._visit(ctx, child)


@register
class AssertTuple(Rule):
    id = "B011"
    title = "assert on a non-empty tuple"
    rationale = "`assert (x, y)` is always true — the comma was meant as args"

    def check_file(self, ctx: FileContext) -> None:
        for node in ast.walk(ctx.sf.tree):
            if (
                isinstance(node, ast.Assert)
                and isinstance(node.test, ast.Tuple)
                and node.test.elts
            ):
                ctx.report(
                    node.lineno,
                    self.id,
                    "assert on a non-empty tuple is always true",
                )
