"""JAX003 — dtype drift and implicit host<->device transfers in the
engine directories (``ops/``, ``scheduler/``, ``parallel/``).

The engine's conformance contract is bit-exactness against the serial
oracle with x64 ENABLED (ops/__init__.py); its performance contract is
that warm paths stay transfer-free (the ``jax_transfer_bytes`` counter
and ROADMAP items 1/4 both gate on it). Three statically-visible ways
code drifts off both:

- **device -> host in a loop**: ``np.asarray(x)`` / ``np.array(x)``
  where the kind dataflow proves ``x`` is a JAX value, inside a
  ``for``/``while`` body — every iteration forces a blocking device
  sync. (One conversion at decode time is the normal pattern and stays
  legal; JAX001 separately polices conversions inside traced code.)
- **host -> device in a loop**: ``jnp.asarray(x)`` / ``jnp.array(x)``
  on a proven-numpy value inside a loop — a fresh host->device
  transfer per iteration; hoist the conversion.
- **weak Python floats into scan carries**: a bare float literal (or a
  variable the dataflow proves is a Python float) in the ``init`` of
  ``lax.scan`` — the carry dtype is then decided by promotion, not by
  the engine's layout, and a carry/output dtype mismatch re-traces or
  silently widens. Spell the dtype: ``jnp.asarray(0.0, dtype=...)``.
- **mixed np/jnp arithmetic in a loop**: a BinOp whose operands are
  proven JAX and proven numpy inside a loop — an implicit per-iteration
  transfer plus strong-dtype promotion (np scalars are strong; they
  override the jnp operand's dtype).

Value kinds come from the forward kind dataflow
(dataflow.KindAnalysis): ``jnp.*``/``jax.*`` call results are JAX,
``np.*`` results are numpy, float literals are Python floats; joins
drop disagreeing kinds to unknown, so only proven drift is reported.

Audited escapes: usage-checked ``# simonlint: disable=JAX003`` pragma
or allowlists.JAX003_ALLOW keyed (file, function).
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from .. import allowlists
from ..cfg import build_cfg, iter_function_defs
from ..core import Finding, Rule, register
from ..dataflow import JAX, NP, PYFLOAT, KindAnalysis, iter_event_states
from ..project import ProjectIndex, SourceFile

_SCOPED_DIRS = (
    "open_simulator_tpu/ops/",
    "open_simulator_tpu/scheduler/",
    "open_simulator_tpu/parallel/",
)

_NP_CONVERTERS = {"numpy.asarray", "numpy.array", "numpy.ascontiguousarray"}
_JNP_CONVERTERS = {"jax.numpy.asarray", "jax.numpy.array"}


def _in_scope(sf: SourceFile) -> bool:
    if not sf.is_runtime_scope:
        return False
    rel = sf.rel.replace("\\", "/")
    if rel.startswith("open_simulator_tpu/"):
        return rel.startswith(_SCOPED_DIRS)
    return True  # out-of-repo fixtures are live, like every other rule


@register
class DtypeTransferDrift(Rule):
    id = "JAX003"
    title = "dtype drift / implicit host<->device transfer in engine code"
    rationale = (
        "per-iteration np<->jnp conversions force transfers and syncs; "
        "weak Python floats in scan carries hand the carry dtype to "
        "promotion — both break the warm-path and conformance contracts"
    )
    scope = "project"

    def check_project(self, project: ProjectIndex) -> Iterable[Finding]:
        findings: List[Finding] = []
        for sf in project.files:
            if sf.tree is None or not _in_scope(sf):
                continue
            for fn in iter_function_defs(sf):
                if (sf.rel, fn.name) in allowlists.JAX003_ALLOW:
                    continue
                self._check_function(sf, fn, findings)
        return findings

    def _check_function(self, sf, fn, findings) -> None:
        analysis = KindAnalysis(sf)
        cfg = build_cfg(sf, fn)
        entry_states = analysis.solve(cfg)
        in_loop = _loop_membership(fn)
        reported = set()

        def report(line, msg):
            key = (line, msg)
            if key not in reported:
                reported.add(key)
                findings.append(Finding(sf.path, sf.rel, line, self.id, msg))

        for _block, ev, state in iter_event_states(
            cfg, entry_states, analysis.transfer
        ):
            for expr in _event_subtrees(ev):
                for node in ast.walk(expr):
                    if isinstance(node, ast.Call):
                        self._check_call(
                            sf, fn, analysis, state, node, in_loop, report
                        )
                    elif isinstance(node, ast.BinOp) and in_loop.get(
                        id(node)
                    ):
                        self._check_binop(
                            sf, fn, analysis, state, node, report
                        )

    # -- checks -------------------------------------------------------------

    def _check_call(self, sf, fn, analysis, state, call, in_loop, report):
        dotted = sf.dotted_call_name(call.func)
        if dotted in _NP_CONVERTERS and call.args:
            kind = analysis.expr_kind(state, call.args[0])
            if kind == JAX and in_loop.get(id(call)):
                report(
                    call.lineno,
                    f"np conversion of a device value inside a loop in "
                    f"'{fn.name}' — every iteration forces a blocking "
                    "device->host sync; pull the value to host once, "
                    "outside the loop",
                )
        elif dotted in _JNP_CONVERTERS and call.args:
            kind = analysis.expr_kind(state, call.args[0])
            if kind == NP and in_loop.get(id(call)):
                report(
                    call.lineno,
                    f"jnp conversion of a numpy value inside a loop in "
                    f"'{fn.name}' — a fresh host->device transfer per "
                    "iteration; hoist the conversion out of the loop",
                )
        elif dotted in ("jax.lax.scan", "lax.scan") and len(call.args) >= 2:
            self._check_scan_carry(sf, fn, analysis, state, call, report)

    def _check_scan_carry(self, sf, fn, analysis, state, call, report):
        init = call.args[1]
        elements = (
            list(init.elts) if isinstance(init, (ast.Tuple, ast.List)) else [init]
        )
        for elt in elements:
            weak = isinstance(elt, ast.Constant) and isinstance(
                elt.value, float
            )
            if not weak and isinstance(elt, ast.Name):
                weak = analysis.expr_kind(state, elt) == PYFLOAT
            if weak:
                report(
                    elt.lineno,
                    f"weak Python float in a lax.scan carry init in "
                    f"'{fn.name}' — the carry dtype is left to promotion "
                    "(re-trace or silent widening on mismatch); make it "
                    "explicit: jnp.asarray(x, dtype=...)",
                )

    def _check_binop(self, sf, fn, analysis, state, node, report):
        env = dict(state)
        lk = analysis._kind(env, node.left)
        rk = analysis._kind(env, node.right)
        if {lk, rk} == {JAX, NP}:
            report(
                node.lineno,
                f"arithmetic mixing a device value and a numpy value "
                f"inside a loop in '{fn.name}' — an implicit per-iteration "
                "host->device transfer with strong-dtype promotion; "
                "convert once outside the loop",
            )


def _event_subtrees(ev):
    from ..cfg import event_exprs

    return event_exprs(ev)


def _loop_membership(fn) -> dict:
    """id(node) -> True for every node lexically inside a for/while of
    this function (nested defs excluded — their loops are their own)."""
    out = {}

    def walk(node, in_loop):
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            child_in = in_loop or isinstance(
                child, (ast.For, ast.AsyncFor, ast.While)
            )
            out[id(child)] = child_in
            walk(child, child_in)

    out[id(fn)] = False
    walk(fn, False)
    return out
