"""EXC001 — error-taxonomy enforcement at ``raise`` sites.

The CLI exit-code contract (runtime/errors.py, docs/ROBUSTNESS.md)
only works if every way a plan can die maps to a typed error the
handlers can route: GuardError subclasses for execution failures,
InputError (a ValueError) for bad inputs, each with its exit code. A
stray ``raise RuntimeError(...)`` bypasses the whole taxonomy — it
renders as a traceback instead of a typed report, and callers cannot
catch it without catching everything.

Accepted at a ``raise`` site (runtime scope only):

- a first-party class transitively rooted in **GuardError** or
  **InputError** (bare-name roots, so fixture trees can define their
  own); the hierarchy comes from effects.Effects.class_bases;
- bare ``raise`` and ``raise <variable>`` (re-raise of a caught or
  constructed exception — untyped names are opaque by design);
- ``NotImplementedError`` (the abstract-interface marker);
- stdlib **ValueError/TypeError** at audited validation boundaries:
  the whole-file allowlist ``EXC001_VALIDATION_FILES`` (modules whose
  job is parsing/validation) or per-function ``EXC001_ALLOW``. These
  stay stdlib on purpose — a parser's internal ``except ValueError``
  cascade must keep catching its own raises, and constructor
  arg-validation is the Python idiom.

Everything else — ``RuntimeError``, ``KeyError``, bare ``Exception``,
first-party classes rooted outside the taxonomy — is a finding: root
the class in the taxonomy (multiple inheritance keeps compatibility,
e.g. ``class SampleRngOverflow(GuardError, RuntimeError)``), or use a
usage-checked ``# simonlint: disable=EXC001`` pragma with the
justification next to it.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set

from .. import allowlists
from ..core import Finding, Rule, register
from ..effects import get_effects
from ..project import ProjectIndex

TAXONOMY_ROOTS = {"GuardError", "InputError"}

#: stdlib exceptions allowed only via the validation allowlists
_VALIDATION_OK = {"ValueError", "TypeError"}
#: always acceptable
_ALWAYS_OK = {"NotImplementedError"}

_PY_BUILTIN_EXCEPTIONS = {
    "BaseException", "Exception", "ArithmeticError", "AssertionError",
    "AttributeError", "BufferError", "EOFError", "FloatingPointError",
    "ImportError", "IndexError", "KeyError", "KeyboardInterrupt",
    "LookupError", "MemoryError", "ModuleNotFoundError", "NameError",
    "NotImplementedError", "OSError", "IOError", "OverflowError",
    "RecursionError", "ReferenceError", "RuntimeError", "StopIteration",
    "StopAsyncIteration", "SyntaxError", "SystemError", "SystemExit",
    "TimeoutError", "TypeError", "UnboundLocalError", "UnicodeDecodeError",
    "UnicodeEncodeError", "UnicodeError", "ValueError", "ZeroDivisionError",
}


@register
class ErrorTaxonomy(Rule):
    id = "EXC001"
    title = "raise outside the runtime error taxonomy"
    rationale = (
        "untyped raises bypass the exit-code contract; execution errors "
        "root in GuardError, input errors in InputError, validation "
        "boundaries keep stdlib ValueError/TypeError via the audited "
        "allowlist"
    )
    scope = "project"

    def check_project(self, project: ProjectIndex) -> Iterable[Finding]:
        effects = get_effects(project)
        taxonomy: Set[str] = effects.taxonomy_classes(TAXONOMY_ROOTS)
        taxonomy_leaves = {t.rsplit(".", 1)[-1] for t in taxonomy}
        findings: List[Finding] = []
        for sf in project.files:
            if sf.tree is None or not sf.is_runtime_scope:
                continue
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Raise) or node.exc is None:
                    continue
                self._check_raise(
                    sf, node, taxonomy, taxonomy_leaves, findings
                )
        return findings

    def _check_raise(self, sf, node, taxonomy, taxonomy_leaves, findings):
        exc = node.exc
        cls_expr = exc.func if isinstance(exc, ast.Call) else exc
        dotted = sf.dotted_call_name(cls_expr)
        if not dotted:
            return  # dynamic (raise cls(...), raise e.with_traceback(...))
        leaf = dotted.rsplit(".", 1)[-1]
        if leaf in _ALWAYS_OK or leaf in TAXONOMY_ROOTS:
            return
        if dotted in taxonomy or leaf in taxonomy_leaves:
            return
        fn = sf.enclosing_function(node)
        if dotted in _PY_BUILTIN_EXCEPTIONS:
            if leaf in _VALIDATION_OK:
                if sf.rel in allowlists.EXC001_VALIDATION_FILES:
                    return
                if (sf.rel, fn) in allowlists.EXC001_ALLOW:
                    return
                findings.append(
                    Finding(
                        sf.path, sf.rel, node.lineno, self.id,
                        f"raise {leaf} in '{fn}' outside the audited "
                        "validation-boundary allowlist — raise InputError "
                        "(models/validation.py) for bad input, a GuardError "
                        "subclass (runtime/errors.py) for execution "
                        "failures, or audit the boundary in "
                        "tools/simonlint/allowlists.py EXC001_*",
                    )
                )
                return
            findings.append(
                Finding(
                    sf.path, sf.rel, node.lineno, self.id,
                    f"raise {leaf} in '{fn}' bypasses the error taxonomy "
                    "(runtime/errors.py) — callers cannot route it to an "
                    "exit code; use a GuardError/InputError subclass "
                    "(multiple inheritance keeps except-compatibility)",
                )
            )
            return
        if _is_first_party(dotted, sf):
            findings.append(
                Finding(
                    sf.path, sf.rel, node.lineno, self.id,
                    f"raise {leaf} in '{fn}': first-party exception not "
                    "rooted in the GuardError/InputError taxonomy "
                    "(runtime/errors.py) — re-root the class (multiple "
                    "inheritance keeps compatibility) or document the "
                    "escape with `# simonlint: disable=EXC001`",
                )
            )

    # fall through: unknown external name (yaml.YAMLError etc.) — opaque


def _is_first_party(dotted: str, sf) -> bool:
    """Is this class plausibly defined in the linted tree? True for
    names resolving into the package or defined in the same file /
    fixture tree (single-segment names that are classes here)."""
    if dotted.startswith("open_simulator_tpu."):
        return True
    head = dotted.split(".", 1)[0]
    if head == dotted:
        # unqualified: defined-or-imported name; treat as first-party
        # when a class of that name exists in this file
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef) and node.name == dotted:
                return True
        # or when the import map sent it to another first-party module
        target = sf.imports.get(dotted, "")
        return target.startswith("open_simulator_tpu.")
    return False
