"""CONC002 — lock-order inversions, blocking calls under a lock, and
self-deadlocks, via the lock-held dataflow.

`simon serve` holds several locks in one process (the coalescer queue
lock, the Counters/Trace registry locks, the span recorder lock, the
JSONL sink lock). Two failure modes no per-class rule (CONC001) can
see:

1. **Lock-order inversion**: thread 1 takes A then B, thread 2 takes B
   then A — a deadlock that only fires under contention. The rule
   computes may-held lock sets per function (forward dataflow over the
   CFG, ``with``/``acquire()`` both modeled, try/finally and
   with-unwind release included), collects every "acquired X while
   holding Y" edge project-wide — one interprocedural level deep, so
   ``COUNTERS.inc(...)`` under the coalescer lock contributes a
   ``Coalescer._lock -> Counters._lock`` edge — and reports every pair
   of sites whose edges point in opposite directions.
2. **Blocking call while a lock is held**: fsync, sleep, sockets/HTTP,
   subprocess, ``Journal.append`` (fsync'd), jit dispatches (a device
   round-trip), or a call whose one-level callee summary blocks. Every
   thread needing that lock then queues behind disk/network/device
   latency — the serve tail-latency bug class.

Also flagged: acquiring a lock already in the may-held set
(``threading.Lock`` is not reentrant — immediate self-deadlock).

Audited escapes: usage-checked ``# simonlint: disable=CONC002``
pragmas at the site (preferred), or allowlists.CONC002_ALLOW keyed
(file, function). The canonical acquisition order itself is documented
in docs/STATIC_ANALYSIS.md (lock-order policy).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Tuple

from .. import allowlists
from ..cfg import build_cfg, iter_event_calls, iter_function_defs
from ..core import Finding, Rule, register
from ..dataflow import LockAnalysis, iter_event_states
from ..effects import get_effects
from ..project import ProjectIndex


@register
class LockOrder(Rule):
    id = "CONC002"
    title = "lock-order inversion / blocking call under a lock"
    rationale = (
        "opposite-order nested acquisitions deadlock under contention; "
        "fsync/socket/subprocess/jit work under a lock serializes every "
        "thread behind the slow operation"
    )
    scope = "project"

    def check_project(self, project: ProjectIndex) -> Iterable[Finding]:
        effects = get_effects(project)
        findings: List[Finding] = []
        #: (held, acquired) -> [(sf, line, fn_name, via)]
        edges: Dict[Tuple[str, str], List[tuple]] = {}
        for sf in project.files:
            if sf.tree is None or not sf.is_runtime_scope:
                continue
            for fn in iter_function_defs(sf):
                self._scan_function(sf, fn, effects, edges, findings)
        findings.extend(self._inversions(edges))
        return findings

    # -- per-function dataflow ----------------------------------------------

    def _scan_function(self, sf, fn, effects, edges, findings) -> None:
        fn_name = fn.name
        if (sf.rel, fn_name) in allowlists.CONC002_ALLOW:
            return
        cfg = build_cfg(sf, fn)
        entry_states = LockAnalysis.solve(cfg)
        for _block, ev, held in iter_event_states(
            cfg, entry_states, LockAnalysis.transfer
        ):
            if ev.kind == "acquire":
                for h in sorted(held):
                    line = getattr(ev.node, "lineno", fn.lineno)
                    if h == ev.lock:
                        findings.append(
                            Finding(
                                sf.path,
                                sf.rel,
                                line,
                                self.id,
                                f"'{_leaf(ev.lock)}' acquired in "
                                f"'{fn_name}' while already held on some "
                                "path — threading.Lock is not reentrant "
                                "(self-deadlock)",
                            )
                        )
                    else:
                        edges.setdefault((h, ev.lock), []).append(
                            (sf, line, fn_name, "with")
                        )
                continue
            if ev.kind != "stmt" or not held:
                continue
            for call in iter_event_calls(ev):
                self._check_call_under_lock(
                    sf, fn_name, call, held, effects, edges, findings
                )

    def _check_call_under_lock(
        self, sf, fn_name, call, held, effects, edges, findings
    ) -> None:
        label = effects.blocking_label_for(sf, call)
        summary = None
        if label is None:
            summary = effects.for_call(sf, call)
            if summary is not None and summary.blocking:
                label = summary.blocking[0] + " (via callee)"
        if label is not None:
            findings.append(
                Finding(
                    sf.path,
                    sf.rel,
                    call.lineno,
                    self.id,
                    f"blocking operation [{label}] in '{fn_name}' while "
                    f"holding {_held_str(held)} — move the slow work "
                    "outside the lock (or document the audited exception "
                    "with `# simonlint: disable=CONC002`)",
                )
            )
        if summary is None:
            summary = effects.for_call(sf, call)
        if summary is not None:
            for acquired in summary.locks:
                for h in sorted(held):
                    if h == acquired:
                        findings.append(
                            Finding(
                                sf.path,
                                sf.rel,
                                call.lineno,
                                self.id,
                                f"call in '{fn_name}' re-acquires "
                                f"'{_leaf(acquired)}' already held here — "
                                "threading.Lock is not reentrant "
                                "(self-deadlock through the callee)",
                            )
                        )
                    else:
                        edges.setdefault((h, acquired), []).append(
                            (sf, call.lineno, fn_name, "call")
                        )

    # -- cross-function inversion detection ---------------------------------

    def _inversions(self, edges) -> List[Finding]:
        out: List[Finding] = []
        seen_pairs = set()
        for (a, b), sites in sorted(edges.items()):
            if (b, a) not in edges:
                continue
            pair = tuple(sorted((a, b)))
            if pair in seen_pairs:
                continue
            seen_pairs.add(pair)
            other_sf, _other_line, other_fn, _ = edges[(b, a)][0]
            for sf, line, fn_name, _via in sites:
                out.append(
                    Finding(
                        sf.path,
                        sf.rel,
                        line,
                        self.id,
                        f"lock-order inversion: '{_leaf(b)}' is acquired "
                        f"while holding '{_leaf(a)}' here in '{fn_name}', "
                        f"but '{_leaf(a)}' is acquired while holding "
                        f"'{_leaf(b)}' in {other_sf.rel} "
                        f"('{other_fn}') — pick one canonical order "
                        "(docs/STATIC_ANALYSIS.md lock-order policy)",
                    )
                )
            for sf, line, fn_name, _via in edges[(b, a)]:
                first_sf, _first_line, first_fn, _ = sites[0]
                out.append(
                    Finding(
                        sf.path,
                        sf.rel,
                        line,
                        self.id,
                        f"lock-order inversion: '{_leaf(a)}' is acquired "
                        f"while holding '{_leaf(b)}' here in '{fn_name}', "
                        f"but '{_leaf(b)}' is acquired while holding "
                        f"'{_leaf(a)}' in {first_sf.rel} "
                        f"('{first_fn}') — pick one canonical order "
                        "(docs/STATIC_ANALYSIS.md lock-order policy)",
                    )
                )
        return out


def _leaf(lock: str) -> str:
    parts = lock.rsplit(".", 2)
    return ".".join(parts[-2:]) if len(parts) >= 2 else lock


def _held_str(held) -> str:
    return " + ".join(f"'{_leaf(h)}'" for h in sorted(held))
