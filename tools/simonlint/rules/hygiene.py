"""Runtime-hygiene rules — first-party runtime scope only
(open_simulator_tpu/; tests, tools, bench.py and the graft entry are
exempt; out-of-repo fixture files are policed so tests can exercise the
rules directly — see project.SourceFile.is_runtime_scope).

- BLE001 broad `except Exception:` / `except BaseException:` — catch
  the specific expected errors so real bugs stay loud. Audited
  survivors (logged + trace-noted, never silent) live in
  allowlists.BROAD_EXCEPT_ALLOW.
- S110 silent `except ...: pass` — a swallowed exception must at least
  record why (trace note / log).
- S113 `urllib.request.urlopen` / `subprocess.run` (and friends)
  without an explicit `timeout=` — an unbounded external call can hang
  a whole plan; every I/O call site names its timeout
  (runtime/retry.py holds the configurable defaults).
- T201 bare `print()` (no explicit `file=`) in library code — library
  output goes through the report writer, the logging module, or the
  flight recorder (obs/), never straight to a stdout the embedding
  process may own (simon serve's HTTP replies, a driver parsing JSON).
  The CLI surface is the audited allowlist; a print that names its
  stream (`file=...`) is a report writer, not a stray.
"""

from __future__ import annotations

import ast

from .. import allowlists
from ..core import FileContext, Rule, register

# I/O entry points that hang forever without a timeout
IO_TIMEOUT_FUNCS = {
    "urllib.request.urlopen",
    "urlopen",
    "subprocess.run",
    "subprocess.call",
    "subprocess.check_call",
    "subprocess.check_output",
    "subprocess.Popen",
    "Popen",
}


def _handler_type_names(node: ast.ExceptHandler) -> list:
    types = []
    if isinstance(node.type, ast.Tuple):
        types = list(node.type.elts)
    elif node.type is not None:
        types = [node.type]
    return [t.id for t in types if isinstance(t, ast.Name)]


@register
class BroadExcept(Rule):
    id = "BLE001"
    title = "broad except in runtime code"
    rationale = (
        "except Exception/BaseException hides real bugs; audited "
        "last-resort degradations go in allowlists.BROAD_EXCEPT_ALLOW"
    )

    def check_file(self, ctx: FileContext) -> None:
        sf = ctx.sf
        if not sf.is_runtime_scope:
            return
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            fn = sf.enclosing_function(node)
            if (sf.rel, fn) in allowlists.BROAD_EXCEPT_ALLOW:
                continue
            broad = [
                n
                for n in _handler_type_names(node)
                if n in ("Exception", "BaseException")
            ]
            if broad:
                ctx.report(
                    node.lineno,
                    self.id,
                    f"broad 'except {broad[0]}:' in '{fn}' — catch the "
                    "specific expected errors (audited degradation paths "
                    "go in tools/simonlint/allowlists.py "
                    "BROAD_EXCEPT_ALLOW)",
                )


@register
class SilentExceptPass(Rule):
    id = "S110"
    title = "silent except: pass in runtime code"
    rationale = (
        "a swallowed exception must record why (trace note / log) or "
        "be narrowed away"
    )

    def check_file(self, ctx: FileContext) -> None:
        sf = ctx.sf
        if not sf.is_runtime_scope:
            return
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            fn = sf.enclosing_function(node)
            if (sf.rel, fn) in allowlists.BROAD_EXCEPT_ALLOW:
                continue
            if len(node.body) == 1 and isinstance(node.body[0], ast.Pass):
                ctx.report(
                    node.lineno,
                    self.id,
                    f"silent 'except: pass' in '{fn}' — record why the "
                    "exception is safe to swallow (trace note / log) or "
                    "narrow it away",
                )


@register
class IoWithoutTimeout(Rule):
    id = "S113"
    title = "I/O call without explicit timeout"
    rationale = (
        "urlopen/subprocess without timeout= can hang the whole plan; "
        "configurable defaults live in runtime/retry.py"
    )

    def check_file(self, ctx: FileContext) -> None:
        sf = ctx.sf
        if not sf.is_runtime_scope:
            return
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _raw_dotted(node.func)
            if name not in IO_TIMEOUT_FUNCS:
                continue
            if any(kw.arg == "timeout" for kw in node.keywords):
                continue
            fn = sf.enclosing_function(node)
            if (sf.rel, fn) in allowlists.IO_TIMEOUT_ALLOW:
                continue
            ctx.report(
                node.lineno,
                self.id,
                f"'{name}' without an explicit timeout= in '{fn}' — an "
                "unbounded external call can hang the plan (audited "
                "exceptions go in tools/simonlint/allowlists.py "
                "IO_TIMEOUT_ALLOW)",
            )


@register
class BarePrint(Rule):
    id = "T201"
    title = "bare print() in library code"
    rationale = (
        "library output goes through the report writer / logging / obs "
        "spans, or names its stream with file=; the CLI surface is "
        "allowlisted"
    )

    def check_file(self, ctx: FileContext) -> None:
        sf = ctx.sf
        if not sf.is_runtime_scope:
            return
        if sf.rel in allowlists.PRINT_ALLOW_FILES:
            return
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            if _raw_dotted(node.func) != "print":
                continue
            if any(kw.arg == "file" for kw in node.keywords):
                continue
            fn = sf.enclosing_function(node)
            if (sf.rel, fn) in allowlists.PRINT_ALLOW:
                continue
            ctx.report(
                node.lineno,
                self.id,
                f"bare print() in library code ('{fn}') — route through "
                "the report writer / logging / obs spans, or name the "
                "stream with file= (CLI surfaces go in "
                "tools/simonlint/allowlists.py PRINT_ALLOW_FILES)",
            )


def _raw_dotted(func: ast.AST) -> str:
    """Dotted name WITHOUT alias normalization — S113/T201 match the
    spelled call (`subprocess.run`, `urlopen`, `print`), same contract
    as the original linter."""
    parts = []
    while isinstance(func, ast.Attribute):
        parts.append(func.attr)
        func = func.value
    if isinstance(func, ast.Name):
        parts.append(func.id)
        return ".".join(reversed(parts))
    return ""
