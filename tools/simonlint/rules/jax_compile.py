"""JAX002 — recompile hazards: `jax.jit` wrappers that cannot hit a
warm compile cache.

Each `jax.jit(f)` call returns a NEW wrapper with its own compile
cache; a wrapper created per call (or per loop iteration) re-traces and
re-compiles every time, silently turning a warm serving path into a
cold one. The repo convention (ROADMAP item 4, PRs 4–5) is
module-level jits — created once per process, instrumented for
dispatch/recompile accounting (obs/profile.py) — and this rule makes
the convention machine-checked. The runtime counterpart is the
`jax_recompiles_total` counter and the CI recompile-regression guard
(docs/OBSERVABILITY.md); JAX002 catches the same defect before
anything runs.

Flagged (runtime scope only):

- `jax.jit(...)` / `partial(jax.jit, ...)` created inside a for/while
  loop — a fresh cache every iteration;
- `jax.jit(...)(args)` — created and invoked in one expression, a
  fresh cache every call;
- `jax.jit(...)` inside a function body whose wrapper is bound to a
  plain local (or returned directly) — it dies with the frame;
- `@jax.jit` on a def nested inside another function — re-decorated
  per enclosing call;
- a list/dict/set literal passed at a `static_argnums` position —
  static args are cache keys and must be hashable (TypeError at
  runtime).

NOT flagged (the audited caching idioms):

- module-level `jax.jit(...)` / `@jax.jit` on a top-level def;
- assignment to an attribute (`self._jit = jax.jit(...)` — instance
  cache) or a subscript (`cache[key] = jax.jit(...)`);
- assignment to a name declared `global` in the enclosing function
  (the module-singleton lazy-init idiom, scheduler/engine.py);
- wrapping through other calls on the way to such an assignment
  (`self._jit = profile.instrument_jit(jax.jit(f), "site")`).

Escapes the analysis cannot follow earn an allowlist entry
(allowlists.JAX002_ALLOW) with a justification comment.
"""

from __future__ import annotations

import ast

from .. import allowlists
from ..core import FileContext, Rule, register

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


def _is_jit_call(sf, node: ast.Call) -> bool:
    dotted = sf.dotted_call_name(node.func)
    if dotted == "jax.jit":
        return True
    # partial(jax.jit, ...) builds a deferred jit factory
    if dotted in ("functools.partial", "partial") and node.args:
        return sf.dotted_call_name(node.args[0]) == "jax.jit"
    return False


def _static_positions(node: ast.Call):
    """Literal static_argnums positions, when spelled as int/tuple."""
    for kw in node.keywords:
        if kw.arg != "static_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return [v.value]
        if isinstance(v, (ast.Tuple, ast.List)):
            out = []
            for elt in v.elts:
                if isinstance(elt, ast.Constant) and isinstance(
                    elt.value, int
                ):
                    out.append(elt.value)
            return out
    return []


@register
class RecompileHazard(Rule):
    id = "JAX002"
    title = "per-call jax.jit wrapper / non-hashable static arg"
    rationale = (
        "a jit created per call or per loop iteration re-compiles every "
        "time; module-level (or cached) jits are the convention the "
        "warm serve path depends on"
    )

    def check_file(self, ctx: FileContext) -> None:
        sf = ctx.sf
        if not sf.is_runtime_scope:
            return
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call) and _is_jit_call(sf, node):
                self._check_jit_site(ctx, node)
            elif isinstance(node, _FUNC_NODES):
                self._check_decorated(ctx, node)

    # -- jax.jit(...) expression sites --------------------------------------

    def _check_jit_site(self, ctx: FileContext, node: ast.Call) -> None:
        sf = ctx.sf
        parent = sf.parents.get(node)
        if isinstance(parent, _FUNC_NODES) and node in parent.decorator_list:
            return  # @partial(jax.jit, ...) — _check_decorated owns it
        fn = sf.enclosing_function(node)
        if (sf.rel, fn) in allowlists.JAX002_ALLOW:
            return
        self._check_static_args(ctx, node, fn)
        # in a loop: always a hazard, even at module scope
        for anc in sf.ancestors(node):
            if isinstance(anc, (ast.For, ast.While, ast.AsyncFor)):
                ctx.report(
                    node.lineno,
                    self.id,
                    f"jax.jit created inside a loop in '{fn}' — a fresh "
                    "compile cache every iteration; hoist it to module "
                    "level (or a guarded cache) per the module-level-jit "
                    "convention",
                )
                return
        if sf.enclosing_function_node(node) is None:
            return  # module level: the convention itself
        parent = sf.parents.get(node)
        # immediately invoked: jax.jit(f)(args)
        if isinstance(parent, ast.Call) and parent.func is node:
            ctx.report(
                node.lineno,
                self.id,
                f"jax.jit created and invoked in one expression in '{fn}' "
                "— a fresh compile cache (and a re-trace + re-compile) "
                "every call; create the jit once at module level or in a "
                "guarded cache (self._jit / global)",
            )
            return
        sink = self._assignment_sink(sf, node)
        if sink == "escapes":
            return
        verb = "returned directly" if sink == "return" else "bound to a local"
        ctx.report(
            node.lineno,
            self.id,
            f"jax.jit created inside '{fn}' and {verb} — the wrapper "
            "(and its compile cache) dies with the call frame; hoist to "
            "module level, or cache it (self._jit, a global declared in "
            "the function, or a cache dict)",
        )

    def _assignment_sink(self, sf, node: ast.Call) -> str:
        """Where does the fresh wrapper land? "escapes" = stored
        somewhere that outlives the frame (attribute / subscript /
        global-declared name), "return" = returned raw, "local" =
        plain local binding (or unknown)."""
        for anc in sf.ancestors(node):
            if isinstance(anc, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = (
                    anc.targets
                    if isinstance(anc, ast.Assign)
                    else [anc.target]
                )
                globals_declared = _global_names(
                    sf.enclosing_function_node(anc)
                )
                for t in targets:
                    if isinstance(t, (ast.Attribute, ast.Subscript)):
                        return "escapes"
                    if isinstance(t, ast.Name) and t.id in globals_declared:
                        return "escapes"
                return "local"
            if isinstance(anc, ast.Return):
                return "return"
            if isinstance(anc, _FUNC_NODES):
                return "local"
        return "local"

    def _check_static_args(
        self, ctx: FileContext, node: ast.Call, fn: str
    ) -> None:
        """Non-hashable literals at static_argnums positions of an
        immediately-invoked jit: static args are hash keys."""
        positions = _static_positions(node)
        if not positions:
            return
        parent = ctx.sf.parents.get(node)
        if not (isinstance(parent, ast.Call) and parent.func is node):
            return
        for pos in positions:
            if pos < len(parent.args) and isinstance(
                parent.args[pos], (ast.List, ast.Dict, ast.Set)
            ):
                ctx.report(
                    parent.args[pos].lineno,
                    self.id,
                    f"non-hashable literal at static_argnums position "
                    f"{pos} in '{fn}' — static args are compile-cache "
                    "keys and must be hashable (tuple, not list/dict/set)",
                )

    # -- @jax.jit decorators ------------------------------------------------

    def _check_decorated(self, ctx: FileContext, node) -> None:
        sf = ctx.sf
        if sf.enclosing_function_node(node) is None:
            return  # top-level @jax.jit def: the convention itself
        for deco in node.decorator_list:
            d = deco.func if isinstance(deco, ast.Call) else deco
            is_jit = sf.dotted_call_name(d) == "jax.jit"
            if isinstance(deco, ast.Call) and not is_jit:
                is_jit = _is_jit_call(sf, deco)
            if not is_jit:
                continue
            fn = sf.enclosing_function(node)
            if (sf.rel, fn) in allowlists.JAX002_ALLOW:
                continue
            ctx.report(
                node.lineno,
                self.id,
                f"@jax.jit on '{node.name}', nested inside '{fn}' — "
                "re-decorated (fresh compile cache) every enclosing "
                "call; hoist the jitted function to module level or "
                "cache the wrapper",
            )


def _global_names(func_node) -> set:
    if func_node is None:
        return set()
    out = set()
    for stmt in ast.walk(func_node):
        if isinstance(stmt, ast.Global):
            out.update(stmt.names)
    return out
