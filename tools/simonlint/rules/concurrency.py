"""CONC001 — lock discipline: fields guarded somewhere must be guarded
everywhere.

`simon serve` runs HTTP handler threads alongside one dispatcher
thread; the shared mutable state they touch (utils/trace.Counters,
utils/memo.IdentityMemo, serve/coalescer.Coalescer, obs/spans.Recorder,
obs/explain.ExplainRecorder) is guarded by a per-instance `_lock`. The
failure mode this rule targets is the asymmetric access: a field
consistently written under `with self._lock:` in five methods and then
read (or worse, read-modify-written) bare in a sixth — invisible to
review, intermittent under load, and exactly what the thread-safety
tests only catch when the interleaving cooperates.

Mechanics: in any class that defines `_lock` (a `self._lock = ...`
assignment, typically in __init__), every `self.<field>` access is
classified as inside or outside a `with self._lock:` block. A field
with at least one guarded access (outside __init__) is a GUARDED
field; any unguarded access to it (outside __init__/__new__, where the
instance is not yet shared) is flagged.

Intentional escapes are real and documented in this codebase — the
memo identity fast path, hot-path `enabled` reads, caller-holds-lock
helpers — and carry a usage-checked `# simonlint: disable=CONC001`
pragma (line- or def-level) with the justification next to the code it
excuses. Anything broader goes in allowlists.CONC001_ALLOW.

Known limits (docs/STATIC_ANALYSIS.md): only the literal `_lock` name
is recognized; accesses through aliases other than `self` and locks
taken via .acquire() are invisible; cross-class access (other.field)
is out of scope.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from .. import allowlists
from ..core import FileContext, Rule, register

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)
_EXEMPT_METHODS = {"__init__", "__new__", "__del__"}


def _defines_lock(cls: ast.ClassDef) -> bool:
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if (
                    isinstance(t, ast.Attribute)
                    and t.attr == "_lock"
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    return True
        elif isinstance(node, ast.AnnAssign):
            t = node.target
            if (
                isinstance(t, ast.Attribute)
                and t.attr == "_lock"
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
            ):
                return True
    return False


def _is_self_lock(expr: ast.AST) -> bool:
    return (
        isinstance(expr, ast.Attribute)
        and expr.attr == "_lock"
        and isinstance(expr.value, ast.Name)
        and expr.value.id == "self"
    )


@register
class LockDiscipline(Rule):
    id = "CONC001"
    title = "guarded field accessed outside the lock"
    rationale = (
        "a field accessed under `with self._lock:` anywhere must be "
        "accessed under it everywhere (outside __init__) — asymmetric "
        "access is the data race reviews miss"
    )

    def check_file(self, ctx: FileContext) -> None:
        sf = ctx.sf
        if not sf.is_runtime_scope:
            return
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef) and _defines_lock(node):
                self._check_class(ctx, node)

    def _check_class(self, ctx: FileContext, cls: ast.ClassDef) -> None:
        sf = ctx.sf
        #: field -> [(line, method, under_lock)]
        accesses: List[Tuple[str, int, str, bool]] = []
        guarded: Set[str] = set()
        guard_site: Dict[str, int] = {}
        for method in cls.body:
            if not isinstance(method, _FUNC_NODES):
                continue
            exempt = method.name in _EXEMPT_METHODS
            for field, line, under in self._method_accesses(method):
                if field == "_lock":
                    continue
                if under and not exempt:
                    guarded.add(field)
                    guard_site.setdefault(field, line)
                if not exempt:
                    accesses.append((field, line, method.name, under))
        for field, line, method_name, under in accesses:
            if under or field not in guarded:
                continue
            if (sf.rel, method_name) in allowlists.CONC001_ALLOW:
                continue
            ctx.report(
                line,
                self.id,
                f"'{cls.name}.{field}' is accessed under self._lock "
                f"elsewhere (e.g. line {guard_site[field]}) but touched "
                f"here in '{method_name}' without it — take the lock, or "
                "document the benign race with a "
                "`# simonlint: disable=CONC001` pragma",
            )

    def _method_accesses(self, method):
        """Yield (field, line, under_lock) for every self.<field>
        access in one method, nested defs included (they run on the
        caller's thread)."""
        #: nodes inside any `with self._lock:` body
        locked_spans: List[Tuple[int, int]] = []
        for node in ast.walk(method):
            if isinstance(node, (ast.With, ast.AsyncWith)) and any(
                _is_self_lock(item.context_expr) for item in node.items
            ):
                locked_spans.append(
                    (node.body[0].lineno, node.end_lineno or node.lineno)
                )

        def under_lock(line: int) -> bool:
            return any(lo <= line <= hi for lo, hi in locked_spans)

        for node in ast.walk(method):
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
            ):
                yield node.attr, node.lineno, under_lock(node.lineno)
