"""RT002 — every GuardError subtype must have registered injection-test
coverage.

The chaos matrix (tests/test_chaos_matrix.py, docs/ROBUSTNESS.md) is
only a guarantee while it is EXHAUSTIVE: a new taxonomy error that
ships without an injection cell is an untested degradation path — the
exact gap the matrix exists to close. This rule makes the coverage a
land-time invariant instead of a review-time hope.

Mechanics: the project's class hierarchy (effects.Effects.class_bases,
the EXC001 machinery) yields every class transitively rooted in a
bare-named **GuardError**. The coverage document is a module-level
``INJECTION_COVERAGE = {...}`` dict literal in the test tree whose
keys are taxonomy class names — the chaos matrix derives its
parametrized cells from the same dict and pins the ids to the live
cell tables (``test_registry_is_closed_over_cells``), so the static
check reads an honest document. Findings:

- a GuardError subtype missing from the registry (anchored at its
  ``class`` statement — the line the author is editing when they add
  the error);
- a registry key naming no live taxonomy class (a stale entry,
  anchored at the registry);
- no registry found at all while taxonomy classes exist.

Out-of-repo fixture trees (the lint test suite) exercise the rule
directly: any tree defining a bare-named GuardError root plays.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Tuple

from ..core import Finding, Rule, register
from ..effects import get_effects
from ..project import ProjectIndex

#: the registry variable the chaos matrix publishes
REGISTRY_NAME = "INJECTION_COVERAGE"

#: the taxonomy root (bare-name matching, like EXC001)
ROOT = "GuardError"


def _find_registry(
    project: ProjectIndex,
) -> Optional[Tuple[object, ast.Assign, Dict[str, int]]]:
    """Locate the module-level ``INJECTION_COVERAGE = {...}`` dict:
    (source file, assignment node, {key: line}). Last one wins if
    several exist (they should not)."""
    found = None
    for sf in project.files:
        if sf.tree is None:
            continue
        for node in sf.tree.body:
            if not isinstance(node, ast.Assign):
                continue
            targets = [
                t.id for t in node.targets if isinstance(t, ast.Name)
            ]
            if REGISTRY_NAME not in targets:
                continue
            if not isinstance(node.value, ast.Dict):
                continue
            keys: Dict[str, int] = {}
            for k in node.value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    keys[k.value] = k.lineno
            found = (sf, node, keys)
    return found


@register
class InjectionCoverage(Rule):
    id = "RT002"
    title = "GuardError subtype without registered injection-test coverage"
    rationale = (
        "a taxonomy error that ships without a chaos-matrix injection "
        "cell is an untested degradation path; register it in "
        "tests/test_chaos_matrix.py INJECTION_COVERAGE with a live cell"
    )
    scope = "project"

    def check_project(self, project: ProjectIndex) -> Iterable[Finding]:
        effects = get_effects(project)
        taxonomy = effects.taxonomy_classes({ROOT})
        if not taxonomy:
            return []  # no taxonomy in this tree: nothing to enforce
        # dotted -> leaf names, keeping the defining file/line so the
        # finding lands on the class statement
        leaf_sites: Dict[str, Tuple[object, int]] = {}
        for sf in project.files:
            if sf.tree is None:
                continue
            mod = sf.module or sf.rel
            for node in ast.walk(sf.tree):
                if (
                    isinstance(node, ast.ClassDef)
                    and f"{mod}.{node.name}" in taxonomy
                ):
                    leaf_sites[node.name] = (sf, node.lineno)
        findings: List[Finding] = []
        registry = _find_registry(project)
        if registry is None:
            sf, lineno = next(iter(leaf_sites.values()))
            findings.append(
                Finding(
                    sf.path, sf.rel, lineno, self.id,
                    f"taxonomy classes exist but no module-level "
                    f"{REGISTRY_NAME} dict was found in the tree — the "
                    "chaos matrix cannot certify injection coverage "
                    "(tests/test_chaos_matrix.py)",
                )
            )
            return findings
        reg_sf, node, keys = registry
        covered = {k for k, ids in _key_ids(node).items() if ids}
        for leaf, (sf, lineno) in sorted(leaf_sites.items()):
            if leaf not in covered:
                findings.append(
                    Finding(
                        sf.path, sf.rel, lineno, self.id,
                        f"taxonomy class '{leaf}' has no registered "
                        f"injection test — add a chaos-matrix cell and "
                        f"list its id under {REGISTRY_NAME}['{leaf}'] "
                        "(tests/test_chaos_matrix.py, "
                        "docs/ROBUSTNESS.md failure-mode matrix)",
                    )
                )
        for key, lineno in sorted(keys.items()):
            if key not in leaf_sites:
                findings.append(
                    Finding(
                        reg_sf.path, reg_sf.rel, lineno, self.id,
                        f"{REGISTRY_NAME} entry '{key}' names no class "
                        "in the GuardError taxonomy — stale registry "
                        "entries hide real gaps; remove or rename it",
                    )
                )
        return findings


def _key_ids(assign: ast.Assign) -> Dict[str, list]:
    """{key: [cell ids]} from the registry dict literal (non-literal
    values count as covered — the runtime closure test owns them)."""
    out: Dict[str, list] = {}
    value = assign.value
    if not isinstance(value, ast.Dict):
        return out
    for k, v in zip(value.keys, value.values):
        if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
            continue
        if isinstance(v, (ast.List, ast.Tuple)):
            out[k.value] = [
                e.value
                for e in v.elts
                if isinstance(e, ast.Constant)
            ]
        else:
            out[k.value] = ["<computed>"]
    return out
