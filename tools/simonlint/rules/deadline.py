"""RT001 — deadline discipline: budget-scoped ``while`` loops must
consult the Budget on EVERY path through an iteration.

The runtime contract (runtime/budget.py, docs/ROBUSTNESS.md): every
long loop in a guarded subsystem — probe search, chaos chunks, N+K
escalation, the serve dispatcher, the shadow tailer — calls
``budget.check(<boundary>)`` between units of work, so ``--deadline``
and SIGINT stop the run at a safe boundary instead of minutes later.
The bug class is the loop that checks on ONE branch (or not at all):
a retry path or escalation arm that keeps dispatching device scans
long after the deadline expired.

Mechanics: a function is **budget-scoped** when it mentions a
budget-shaped name (``budget``, ``self._budget``, ``req.budget``) or
calls a resolvable callee whose one-level summary consults a budget.
In each budget-scoped function, every ``while`` loop runs the
"checked-since-loop-head" dataflow (dataflow.loop_unchecked_sources):
the loop head resets to unchecked, consult events promote to checked,
and any back-edge source still reachable as unchecked is a finding.

What counts as a consult:

- ``<budgetish>.check/expired/remaining(...)`` anywhere in the event;
- an ``if``/``while`` test that MENTIONS the budget and whose body
  contains a consult (the ``if budget is not None: budget.check(...)``
  idiom: the no-budget branch is vacuously checked — there is nothing
  to consult);
- a call to a resolvable first-party callee whose summary consults
  (the loop may delegate its boundary to a helper).

``for`` loops are exempt (bounded iteration over materialized work —
the chunking helpers own their boundaries); so are functions with no
budget in reach (nothing to consult). Audited escapes use a
usage-checked ``# simonlint: disable=RT001`` pragma or
allowlists.RT001_ALLOW.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from .. import allowlists
from ..cfg import build_cfg, iter_event_calls, iter_function_defs
from ..core import Finding, Rule, register
from ..dataflow import loop_unchecked_sources
from ..effects import get_effects, is_budget_consult, mentions_budget
from ..project import ProjectIndex


@register
class DeadlineDiscipline(Rule):
    id = "RT001"
    title = "budget-scoped while loop missing a deadline check on a path"
    rationale = (
        "a loop that only checks the Budget on one branch keeps "
        "dispatching work after the deadline expired — every iteration "
        "path needs a safe boundary"
    )
    scope = "project"

    def check_project(self, project: ProjectIndex) -> Iterable[Finding]:
        effects = get_effects(project)
        findings: List[Finding] = []
        for sf in project.files:
            if sf.tree is None or not sf.is_runtime_scope:
                continue
            for fn in iter_function_defs(sf):
                if (sf.rel, fn.name) in allowlists.RT001_ALLOW:
                    continue
                # cheap gates first: a function with no while loop has
                # nothing to check, and one without a budget in reach
                # has nothing to check WITH — the call-resolution pass
                # only runs for the few loop-bearing candidates
                own = list(effects._own_nodes(fn))
                if not any(isinstance(n, ast.While) for n in own):
                    continue
                if not self._budget_scoped(sf, own, effects):
                    continue
                self._check_function(sf, fn, effects, findings)
        return findings

    # -- scoping ------------------------------------------------------------

    def _budget_scoped(self, sf, own, effects) -> bool:
        from ..effects import _budgetish

        for node in own:
            if isinstance(node, (ast.Name, ast.Attribute)) and _budgetish(
                node
            ):
                return True
            # a `budget` PARAMETER alone puts the function in scope —
            # an unused one is exactly the bug (it was passed to be
            # consulted)
            if isinstance(node, ast.arg) and "budget" in node.arg.lower():
                return True
        for node in own:
            if isinstance(node, ast.Call):
                summary = effects.for_call(sf, node)
                if summary is not None and summary.consults_budget:
                    return True
        return False

    # -- the per-loop dataflow ----------------------------------------------

    def _check_function(self, sf, fn, effects, findings) -> None:
        cfg = build_cfg(sf, fn)
        whiles = [n for n in cfg.loops if isinstance(n, ast.While)]
        if not whiles:
            return

        def consults(ev) -> bool:
            return self._event_consults(sf, ev, effects)

        for loop in whiles:
            unchecked = loop_unchecked_sources(cfg, loop, consults)
            if not unchecked:
                continue
            findings.append(
                Finding(
                    sf.path,
                    sf.rel,
                    loop.lineno,
                    self.id,
                    f"while loop in '{fn.name}' can complete an iteration "
                    "without consulting the Budget — add a "
                    "budget.check(<boundary>) reachable on every path "
                    "through the loop body (runtime/budget.py contract; "
                    "audited exceptions: `# simonlint: disable=RT001`)",
                )
            )

    def _event_consults(self, sf, ev, effects) -> bool:
        node = ev.node
        # guard idiom: a branch/loop test that mentions the budget and
        # whose body contains a consult — the budget-less arm is vacuous
        if (
            isinstance(node, (ast.If, ast.While))
            and mentions_budget(node.test)
            and self._subtree_consults(sf, node, effects)
        ):
            return True
        for call in iter_event_calls(ev):
            if is_budget_consult(call):
                return True
            summary = effects.for_call(sf, call)
            if summary is not None and summary.consults_budget:
                return True
        return False

    def _subtree_consults(self, sf, node, effects) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                if is_budget_consult(sub):
                    return True
                summary = effects.for_call(sf, sub)
                if summary is not None and summary.consults_budget:
                    return True
        return False
