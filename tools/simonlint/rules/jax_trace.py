"""JAX001 — trace-safety: host side effects reachable inside traced
code.

A function handed to `jax.jit` / `jax.vmap` / `partial(jax.jit, ...)`
/ `pl.pallas_call` runs ONCE at trace time; host-side effects inside it
either burn into the compiled program as constants (wall-clock reads,
RNG draws — silently wrong on every later dispatch), force a blocking
device sync (`.item()`, `float()` on a tracer, `np.asarray`), raise a
TracerError at the worst moment, or mutate host state (`self.x = ...`)
once instead of per call. The serial oracle and the scan must stay
bit-identical (tests/test_engine_conformance.py) — a stray
`np.random` or `time.time` inside the traced graph is exactly the kind
of divergence no dynamic test reliably catches.

The rule walks the intra-package call graph (tools/simonlint/
callgraph.py) from every traced root — including nested defs (a
`lax.scan` step function or pallas kernel body is traced with its
parent) — and flags:

- `time.*` calls (wall clock burned in at trace time)
- `random.*` / `np.random.*` (host RNG: one draw at trace time, same
  "random" number on every dispatch; jax.random is the traced-safe API)
- `print(...)` (fires once at trace time; use `jax.debug.print`)
- `.item()` / `float(tracer)` / `np.asarray` / `np.array` (forced
  host sync, or TracerError under jit)
- assignment to `self.<attr>` (host mutation happens once, at trace
  time, not per call)

Reads of host state (`self.features`, closures over numpy constants)
are trace-time constants by design and stay legal. Guarded host paths
(e.g. ops/scan.features_of, which bails to a pure value when it sees a
tracer) carry a usage-checked `# simonlint: disable=JAX001` pragma on
the def line; anything broader goes in allowlists.JAX001_ALLOW with a
justification.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .. import allowlists
from ..callgraph import Resolver, TracedRoot, iter_traced_roots
from ..core import Finding, Rule, register
from ..project import ProjectIndex, SourceFile

#: alias-normalized dotted prefixes whose every call is a host effect
HOST_EFFECT_PREFIXES = ("time.", "random.", "numpy.random.")
#: exact alias-normalized names
HOST_EFFECT_CALLS = {
    "print",
    "input",
    "breakpoint",
    "numpy.asarray",
    "numpy.array",
}
#: traced-safe exceptions under the prefixes (none today; placeholder
#: so e.g. time.monotonic_ns used for seeding COULD be carved out)
HOST_EFFECT_SAFE: Set[str] = set()


@register
class TraceSafety(Rule):
    id = "JAX001"
    title = "host side effect reachable inside traced code"
    rationale = (
        "host effects run once at trace time (stale constants, forced "
        "syncs, TracerErrors) — the scan/serial conformance contract "
        "cannot survive them"
    )
    scope = "project"

    def check_project(self, project: ProjectIndex) -> Iterable[Finding]:
        resolver = Resolver(project)
        findings: List[Finding] = []
        #: (rel, line, effect) -> already reported (roots overlap)
        reported: Set[Tuple[str, int, str]] = set()
        for root in iter_traced_roots(project):
            walker = _Walker(project, resolver, root, reported)
            findings.extend(walker.run())
        return findings


class _Walker:
    """BFS from one traced root through resolvable first-party calls,
    nested defs included."""

    MAX_DEPTH = 12

    def __init__(
        self,
        project: ProjectIndex,
        resolver: Resolver,
        root: TracedRoot,
        reported: Set[Tuple[str, int, str]],
    ):
        self.project = project
        self.resolver = resolver
        self.root = root
        self.reported = reported
        self.findings: List[Finding] = []
        self.visited: Set[Tuple[str, int]] = set()

    def run(self) -> List[Finding]:
        self._walk(self.root.sf, self.root.node, [self.root.name], 0)
        return self.findings

    def _walk(
        self, sf: SourceFile, fn_node: ast.AST, chain: List[str], depth: int
    ) -> None:
        key = (sf.rel, getattr(fn_node, "lineno", 0))
        if key in self.visited or depth > self.MAX_DEPTH:
            return
        self.visited.add(key)
        fn_name = getattr(fn_node, "name", "<lambda>")
        if (sf.rel, fn_name) in allowlists.JAX001_ALLOW:
            return
        #: local aliases of host-effect callables (`a = np.asarray`)
        local_alias: Dict[str, str] = {}
        body = (
            fn_node.body
            if isinstance(fn_node.body, list)
            else [fn_node.body]  # Lambda
        )
        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Assign):
                    self._check_self_mutation(sf, node, chain)
                    self._note_alias(sf, node, local_alias)
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    self._check_self_mutation(sf, node, chain)
                elif isinstance(node, ast.Call):
                    self._check_call(sf, node, chain, local_alias, depth)

    # -- effects ------------------------------------------------------------

    def _note_alias(
        self, sf: SourceFile, node: ast.Assign, local_alias: Dict[str, str]
    ) -> None:
        dotted = sf.dotted_call_name(node.value)
        if self._effect_name(dotted) is None:
            return
        for t in node.targets:
            if isinstance(t, ast.Name):
                local_alias[t.id] = dotted

    @staticmethod
    def _effect_name(dotted: str) -> Optional[str]:
        if not dotted or dotted in HOST_EFFECT_SAFE:
            return None
        if dotted in HOST_EFFECT_CALLS:
            return dotted
        for prefix in HOST_EFFECT_PREFIXES:
            if dotted.startswith(prefix):
                return dotted
        return None

    def _check_self_mutation(self, sf: SourceFile, node, chain) -> None:
        targets = (
            node.targets if isinstance(node, ast.Assign) else [node.target]
        )
        for t in targets:
            if (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
            ):
                self._report(
                    sf,
                    t.lineno,
                    f"self.{t.attr}",
                    chain,
                    f"mutation of self.{t.attr} inside traced code — "
                    "happens once at trace time, not per dispatch",
                )

    def _check_call(
        self,
        sf: SourceFile,
        node: ast.Call,
        chain: List[str],
        local_alias: Dict[str, str],
        depth: int,
    ) -> None:
        dotted = sf.dotted_call_name(node.func)
        # `a = np.asarray; a(x)` — flag through the local alias
        if (
            isinstance(node.func, ast.Name)
            and node.func.id in local_alias
        ):
            dotted = local_alias[node.func.id]
        effect = self._effect_name(dotted)
        if effect is not None:
            self._report(
                sf,
                node.lineno,
                effect,
                chain,
                f"host call `{effect}` inside traced code — runs once "
                "at trace time (stale constant / forced sync); use the "
                "jax.* equivalent or move it outside the traced region",
            )
            return
        # .item() on anything; float(tracer-ish)
        if isinstance(node.func, ast.Attribute) and node.func.attr == "item":
            self._report(
                sf,
                node.lineno,
                ".item()",
                chain,
                "`.item()` inside traced code — forces a device sync "
                "(or TracerError under jit)",
            )
            return
        if (
            dotted == "float"
            and node.args
            and not isinstance(node.args[0], ast.Constant)
        ):
            self._report(
                sf,
                node.lineno,
                "float()",
                chain,
                "`float()` on a traced value — forces a device sync "
                "(or TracerError under jit); keep it a jnp scalar",
            )
            return
        # descend into resolvable first-party callees
        hit = self.resolver.resolve_call(sf, node)
        if hit is None:
            return
        callee_sf, callee = hit
        if not callee_sf.is_runtime_scope:
            return
        self._walk(
            callee_sf,
            callee,
            chain + [getattr(callee, "name", "<lambda>")],
            depth + 1,
        )

    def _report(
        self, sf: SourceFile, line: int, effect: str, chain: List[str], msg
    ) -> None:
        key = (sf.rel, line, effect)
        if key in self.reported:
            return
        self.reported.add(key)
        root = self.root
        path = " -> ".join(chain[-4:])
        self.findings.append(
            Finding(
                sf.path,
                sf.rel,
                line,
                "JAX001",
                f"{msg} [traced from {root.via}({root.name}) at "
                f"{root.site_sf.rel}:{root.site_line}"
                + (f"; path {path}" if len(chain) > 1 else "")
                + "]",
            )
        )
