"""Baseline / ratchet — adopt a new rule without a flag-day cleanup.

``--write-baseline PATH`` records the current findings as ACCEPTED
debt; ``--baseline PATH`` then fails only on findings NOT in the
baseline. The ratchet is the same contract as unused pragmas (SL001):
an entry whose finding no longer fires is reported as **SL002 stale
baseline entry**, so the baseline can only shrink — fixed debt cannot
silently reappear, and the file cannot rot.

Matching is by (file, rule, message) — deliberately NOT by line
number, so unrelated edits above a finding do not un-baseline it; a
message carries enough context (function names, lock names) that two
distinct findings rarely collide, and when they do they are the same
debt. Each entry matches any number of identical findings.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import List, Tuple

from .core import Finding

BASELINE_VERSION = 1
STALE_BASELINE = "SL002"

Key = Tuple[str, str, str]


def write_baseline(path, findings: List[Finding]) -> None:
    doc = {
        "version": BASELINE_VERSION,
        "entries": [
            {"file": f.rel, "rule": f.rule, "message": f.message}
            for f in findings
        ],
    }
    Path(path).write_text(json.dumps(doc, indent=2) + "\n")


def load_baseline(path) -> List[dict]:
    doc = json.loads(Path(path).read_text())
    if not isinstance(doc, dict) or doc.get("version") != BASELINE_VERSION:
        raise ValueError(f"{path}: not a simonlint baseline (version 1)")
    entries = doc.get("entries")
    if not isinstance(entries, list):
        raise ValueError(f"{path}: baseline has no entries list")
    return entries


def apply_baseline(
    findings: List[Finding], entries: List[dict], baseline_path
) -> List[Finding]:
    """Drop baselined findings; append SL002 for stale entries."""
    accepted = {
        (str(e.get("file")), str(e.get("rule")), str(e.get("message")))
        for e in entries
        if isinstance(e, dict)
    }
    matched = set()
    kept = []
    for f in findings:
        key = (f.rel, f.rule, f.message)
        if key in accepted:
            matched.add(key)
        else:
            kept.append(f)
    rel = str(baseline_path)
    for key in sorted(accepted - matched):
        file, rule, message = key
        kept.append(
            Finding(
                Path(rel),
                rel,
                0,
                STALE_BASELINE,
                f"stale baseline entry: no current {rule} finding in "
                f"{file} matches {message!r} — the debt was paid, remove "
                "the entry (the ratchet only tightens)",
            )
        )
    return kept
