"""Shared AST + scope index — every file is parsed exactly once.

``SourceFile`` wraps one parsed module with the derived structure the
rules keep needing: parent links for upward walks, the enclosing
function of any node, per-file import alias maps (``np`` ->
``numpy``, ``scan_ops`` -> ``open_simulator_tpu.ops.scan``), and the
line pragmas. ``ProjectIndex`` holds every SourceFile keyed by path and
dotted module name, which is what lets cross-module analyses resolve
``scan_ops.run_scan_masked`` to the function node in ops/scan.py.

Scope policy (inherited from the old tools/lint.py): the runtime-
hygiene and JAX/concurrency rules police FIRST-PARTY RUNTIME code —
inside the repo that means ``open_simulator_tpu/`` (tests, tools,
bench.py and the graft entry are exempt); outside the repo (the lint
test suite's tmp fixtures) they are live so tests can exercise them
directly.
"""

from __future__ import annotations

import ast
import tokenize
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Tuple

from .pragmas import parse_pragmas

_EXEMPT_TOPDIRS = {"tests", "tools"}
_EXEMPT_FILES = {"bench.py", "__graft_entry__.py"}

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)
_SCOPE_NODES = _FUNC_NODES + (ast.ClassDef,)


def repo_root() -> Path:
    return Path(__file__).resolve().parent.parent.parent


class SourceFile:
    """One parsed source file plus the shared derived structure."""

    def __init__(self, path: Path, root: Optional[Path] = None):
        self.path = Path(path)
        self.root = Path(root) if root is not None else repo_root()
        # tokenize.open honors PEP 263 coding declarations, so a
        # legacy-encoded file compileall accepts does not crash the
        # gate with a UnicodeDecodeError
        with tokenize.open(self.path) as f:
            self.source = f.read()
        self.lines = self.source.splitlines()
        self.rel = self._relpath()
        self.module = self._module_name()
        self.syntax_error: Optional[SyntaxError] = None
        try:
            self.tree: Optional[ast.Module] = ast.parse(
                self.source, filename=str(self.path)
            )
        except SyntaxError as e:
            self.tree = None
            self.syntax_error = e
            self.parents = {}
            self.pragmas = {}
            self.imports = {}
            return
        self.parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
        self.pragmas = parse_pragmas(self.lines)
        #: alias -> dotted target. `import numpy as np` -> np: numpy;
        #: `from ..ops import scan as scan_ops` (in
        #: open_simulator_tpu.scheduler.engine) ->
        #: scan_ops: open_simulator_tpu.ops.scan; `from time import
        #: sleep` -> sleep: time.sleep. Function-local imports are
        #: included — this codebase imports inside functions to defer
        #: jax initialization, and alias resolution must still work
        #: there (collisions across functions are theoretical and
        #: resolve last-wins).
        self.imports: Dict[str, str] = {}
        self._collect_imports()

    # -- path / scope -------------------------------------------------------

    def _relpath(self) -> str:
        try:
            return str(self.path.resolve().relative_to(self.root.resolve()))
        except ValueError:
            return self.path.name

    def _module_name(self) -> Optional[str]:
        """Dotted module name for in-repo files (None out of tree)."""
        rel = Path(self.rel)
        if rel.is_absolute() or not self.rel.endswith(".py"):
            return None
        parts = list(rel.parts)
        parts[-1] = parts[-1][: -len(".py")]
        if parts[-1] == "__init__":
            parts.pop()
        return ".".join(parts) if parts else None

    @property
    def is_runtime_scope(self) -> bool:
        """True when the runtime-hygiene / JAX / concurrency rules
        apply (see module docstring for the policy)."""
        parts = Path(self.rel).parts
        if parts and parts[0] in _EXEMPT_TOPDIRS:
            return False
        if self.rel in _EXEMPT_FILES:
            return False
        return True

    # -- imports ------------------------------------------------------------

    def _collect_imports(self) -> None:
        pkg_parts = (self.module or "").split(".")[:-1] if self.module else []
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    alias = a.asname or a.name.split(".")[0]
                    target = a.name if a.asname else a.name.split(".")[0]
                    self.imports[alias] = target
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                base: Optional[str]
                if node.level:
                    # relative import: climb `level` packages from the
                    # containing package
                    up = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                    if node.level - 1 > len(pkg_parts):
                        up = []
                    base = ".".join(up)
                    if node.module:
                        base = f"{base}.{node.module}" if base else node.module
                else:
                    base = node.module
                if base is None:
                    continue
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.imports[a.asname or a.name] = f"{base}.{a.name}"

    def dotted_call_name(self, func: ast.AST) -> str:
        """Dotted name of a call target with the FIRST segment rewritten
        through the import alias map: ``np.random.seed`` ->
        ``numpy.random.seed``, ``scan_ops.run_scan_masked`` ->
        ``open_simulator_tpu.ops.scan.run_scan_masked``. Unresolvable
        shapes (subscripts, calls) return ""."""
        parts: List[str] = []
        while isinstance(func, ast.Attribute):
            parts.append(func.attr)
            func = func.value
        if not isinstance(func, ast.Name):
            return ""
        head = self.imports.get(func.id, func.id)
        parts.append(head)
        return ".".join(reversed(parts))

    # -- upward walks -------------------------------------------------------

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def enclosing_function(self, node: ast.AST) -> str:
        """Name of the innermost enclosing def ("<module>" at module
        scope) — the allowlist key the hygiene rules share."""
        for anc in self.ancestors(node):
            if isinstance(anc, _FUNC_NODES):
                return anc.name
        return "<module>"

    def enclosing_function_node(self, node: ast.AST):
        for anc in self.ancestors(node):
            if isinstance(anc, _FUNC_NODES):
                return anc
        return None

    def enclosing_class(self, node: ast.AST) -> Optional[ast.ClassDef]:
        for anc in self.ancestors(node):
            if isinstance(anc, ast.ClassDef):
                return anc
            if isinstance(anc, _FUNC_NODES):
                # a def between node and the class breaks method-hood
                # only if the class is further out; keep climbing — a
                # nested function inside a method still belongs to the
                # method's class for self-resolution purposes
                continue
        return None

    def scope_lines(self, node: ast.AST) -> List[int]:
        """Line numbers of every enclosing def/class HEADER (innermost
        first) — where body-wide pragmas may sit. A multi-line
        signature counts every header line (decorators excluded), so
        the pragma can ride the line with the closing colon."""
        out = []
        for anc in self.ancestors(node):
            if isinstance(anc, _SCOPE_NODES):
                body_start = anc.body[0].lineno if anc.body else anc.lineno
                header_end = max(anc.lineno, body_start - 1)
                out.extend(range(anc.lineno, header_end + 1))
        return out


class ProjectIndex:
    """Every SourceFile of one lint invocation, plus module lookup."""

    def __init__(self, paths: List[Path], root: Optional[Path] = None):
        self.root = Path(root) if root is not None else repo_root()
        self.files: List[SourceFile] = []
        self.by_path: Dict[Path, SourceFile] = {}
        self.by_module: Dict[str, SourceFile] = {}
        for p in paths:
            self.add(p)

    def add(self, path: Path) -> SourceFile:
        sf = SourceFile(path, self.root)
        self.files.append(sf)
        self.by_path[sf.path] = sf
        if sf.module:
            self.by_module[sf.module] = sf
        return sf

    def resolve_module(self, dotted: str) -> Optional[SourceFile]:
        """SourceFile for a dotted module name (packages resolve to
        their __init__ when indexed)."""
        return self.by_module.get(dotted)

    def top_level_function(
        self, dotted: str
    ) -> Optional[Tuple[SourceFile, ast.AST]]:
        """Resolve ``pkg.mod.func`` to (SourceFile, FunctionDef) when
        the module is in the index and defines the function at top
        level."""
        if "." not in dotted:
            return None
        mod_name, func_name = dotted.rsplit(".", 1)
        sf = self.by_module.get(mod_name)
        if sf is None or sf.tree is None:
            return None
        for node in sf.tree.body:
            if isinstance(node, _FUNC_NODES) and node.name == func_name:
                return sf, node
        return None
