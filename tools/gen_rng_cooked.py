"""Derive Go math/rand's `rngCooked` warm-up table without a Go toolchain.

Go bakes into math/rand/rng.go a 607-entry table: the ALFG(607, 273)
state after 7.8e12 burn-in steps from the cooked-free seed expansion
`srand(1)` (GOROOT/src/math/rand/gen_cooked.go — the generator program
whose output is the rngCooked literal; its burn-in loop count is the
constant 7.8e12).  The burn-in is a linear recurrence over Z_2^64:

    y[n] = y[n-607] + y[n-273]   (mod 2^64)

so instead of 7.8e12 sequential steps (~hours), jump: compute
g(t) = t^N mod f(t), f(t) = t^607 - t^334 - 1, by square-and-multiply
over Z_2^64[t] (f is monic, so reduction is well-defined despite
Z_2^64 not being a field), then evaluate the 607 consecutive terms
y[N]..y[N+606] as dot products against the initial window.

Array <-> sequence mapping (rng.go's feed/tap walk): feed starts at
334 and decrements each step, so y[m] is written to position
(333 - m) mod 607; after N steps position i holds
y[N + ((333 - N - i) mod 607)].

Verification is self-contained: with the derived table installed,
GoRand(seed=1) must reproduce Go's famous deterministic seed-1 stream
(rand.Int63() == 5577006791947779410, rand.Intn(100) -> 81 87 47 ...,
rand.Float64() == 0.6046602879796196) — 64+ bits of agreement that
cannot happen with a wrong table or wrong burn-in count.

Usage: python tools/gen_rng_cooked.py [out_path]
Writes 607 signed int64 literals (exactly Go's rng.go values), one per
line, default open_simulator_tpu/data/go_rng_cooked.txt.
"""

from __future__ import annotations

import sys

import numpy as np

LEN = 607
TAP = 273
FEED0 = LEN - TAP  # 334
MASK64 = (1 << 64) - 1
BURN_IN = 7_800_000_000_000  # gen_cooked.go's loop bound, 7.8e12


def srand_vec(seed: int = 1, shifts=(20, 10)) -> list[int]:
    """The burn-in program's srand(): the ORIGINAL Plan 9 lrand.c seed
    expansion — XOR folds at shifts 20/10/0, NOT the 40/20/0 of Go's
    rngSource.Seed.  (Go widened the shifts when porting; the baked
    table predates that, so reproducing it needs the original fold.
    Empirically pinned by the cross-product search in
    tools/search_rng_burnin.py: burn-in 20/10/0 + Seed 40/20/0 + Lehmer
    48271 + N=7.8e12 reproduces Go's documented seed-1 outputs; every
    other combination fails.)"""
    from open_simulator_tpu.utils.gorand import _seedrand

    a, b = shifts
    x = seed % ((1 << 31) - 1)
    if x < 0:
        x += (1 << 31) - 1
    if x == 0:
        x = 89482311
    vec = [0] * LEN
    for i in range(-20, LEN):
        x = _seedrand(x)
        if i >= 0:
            u = x << a
            x = _seedrand(x)
            u ^= x << b
            x = _seedrand(x)
            u ^= x
            vec[i] = u & MASK64
    return vec


def _reduce(c: np.ndarray) -> np.ndarray:
    """Reduce a coefficient array mod f(t) = t^607 - t^334 - 1, i.e.
    t^k -> t^(k-273) + t^(k-607) for k >= 607, highest degree first
    (folded coefficients can land back in the >=607 range)."""
    c = c.copy()
    for k in range(len(c) - 1, LEN - 1, -1):
        v = c[k]
        if v:
            c[k - TAP] += v  # k - 273 = (k - 607) + 334
            c[k - LEN] += v
            c[k] = 0
    return c[:LEN]


def _polymul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    # np.convolve on uint64 wraps mod 2^64 (C unsigned semantics)
    return _reduce(np.convolve(a, b))


def jump_coeffs(n: int) -> np.ndarray:
    """t^n mod f(t) over Z_2^64 by binary exponentiation."""
    result = np.zeros(LEN, dtype=np.uint64)
    result[0] = 1
    base = np.zeros(LEN, dtype=np.uint64)
    base[1] = 1
    while n:
        if n & 1:
            result = _polymul(result, base)
        base = _polymul(base, base)
        n >>= 1
    return result


def derive_cooked(burn_in: int = BURN_IN) -> list[int]:
    vec0 = srand_vec(1)
    # initial sequence window: y[k] = vec0[(333 - k) % 607]
    y = np.array([vec0[(FEED0 - 1 - k) % LEN] for k in range(LEN)], dtype=np.uint64)
    g = jump_coeffs(burn_in)
    # z[j] = y[burn_in + j] = sum_i g_j[i] * y[i]; g_{j+1} = t * g_j mod f
    z = np.zeros(LEN, dtype=np.uint64)
    for j in range(LEN):
        z[j] = np.dot(g, y)  # wraps mod 2^64
        g = np.roll(g, 1)
        top, g[0] = g[0], np.uint64(0)
        if top:
            g[FEED0] += top  # t^607 -> t^334 + 1
            g[0] += top
    # back to array layout: y[m] lives at position (333 - m) % 607, so
    # cooked[i] = y[burn_in + ((333 - burn_in - i) % 607)] — the window
    # rotates with the step count
    return [int(z[(FEED0 - 1 - burn_in - i) % LEN]) for i in range(LEN)]


def verify(cooked: list[int]) -> None:
    """Check the derived table reproduces Go's deterministic seed-1
    stream (values quoted in Go documentation/examples for the
    pre-1.20 unseeded global source)."""
    from open_simulator_tpu.utils.gorand import GoRand

    r = GoRand(seed=1, cooked=cooked)
    trip = [r.int63() for _ in range(3)]
    assert trip == [
        5577006791947779410,
        8674665223082153551,
        6129484611666145821,
    ], f"Int63 triple mismatch: {trip}"
    r = GoRand(seed=1, cooked=cooked)
    seq = [r.intn(100) for _ in range(10)]
    assert seq == [81, 87, 47, 59, 81, 18, 25, 40, 56, 0], f"Intn(100) mismatch: {seq}"
    r = GoRand(seed=1, cooked=cooked)
    f = r.int63() / (1 << 63)
    assert abs(f - 0.6046602879796196) < 1e-15, f"Float64 mismatch: {f}"


def main() -> None:
    out = sys.argv[1] if len(sys.argv) > 1 else "open_simulator_tpu/data/go_rng_cooked.txt"
    cooked = derive_cooked()
    verify(cooked)
    with open(out, "w") as fh:
        for v in cooked:
            sv = v - (1 << 64) if v >= (1 << 63) else v  # Go prints signed int64
            fh.write(f"{sv}\n")
    print(f"wrote {len(cooked)} entries to {out}; verification passed")


if __name__ == "__main__":
    main()
