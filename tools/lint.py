"""First-party AST linter (`make lint`).

No third-party linter ships in this environment, so the lint gate is a
small pyflakes-class checker built on the stdlib `ast`:

- F401 unused imports (module scope; `__init__.py` re-exports and
  `# noqa` lines are exempt)
- F811 duplicate function/class definitions in one scope
- B006 mutable default arguments (list/dict/set literals)
- E722 bare `except:`
- BLE001 broad `except Exception:` / `except BaseException:` in
  first-party runtime code (open_simulator_tpu/; tests and tools are
  exempt) — catch the specific expected errors so real bugs stay loud.
  Audited survivors (logged + trace-noted, never silent) are
  allowlisted by (file, enclosing function) in BROAD_EXCEPT_ALLOW
- S110 silent `except ...: pass` handlers in the same scope — a
  swallowed exception must at least record why (trace note / log)
- S113 `urllib.request.urlopen` / `subprocess.run` (and friends)
  without an explicit `timeout=` in first-party runtime code — an
  unbounded external call can hang a whole plan; every I/O call site
  names its timeout (runtime/retry.py holds the configurable
  defaults). Audited exceptions go in IO_TIMEOUT_ALLOW.
- T201 bare `print()` (no explicit `file=`) in library code under
  open_simulator_tpu/ — library output goes through the report
  writer, the logging module, or the flight recorder (obs/), never
  straight to a stdout the embedding process may own (simon serve's
  HTTP replies, a driver parsing JSON). The CLI surface itself is the
  audited allowlist (PRINT_ALLOW_FILES / PRINT_ALLOW); a print that
  names its stream (`file=...`) is a report writer, not a stray.
- E711 comparisons to None with ==/!=
- F541 f-strings without any placeholder
- B011/assert-tuple: `assert (x, y)` is always true
- W605 invalid escape sequences surface as SyntaxWarning at compile
  time and are promoted to errors by `compileall` in `make lint`

Checks that need full scope resolution (undefined names) are out of
scope — `compileall` plus the test suite carry those.

Exit status 1 when any finding is reported (CI gate).
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

ROOTS = ["open_simulator_tpu", "tools", "tests", "bench.py", "__graft_entry__.py"]

# Broad handlers audited as legitimate last-resort degradations: each
# logs a warning and/or records a trace note, then falls back to a
# correct (slower) path — never a silent swallow. Keyed by
# (repo-relative path, enclosing function) so line drift cannot rot
# the allowlist. Anything new must catch specific exception types or
# earn an entry here with the same audit.
BROAD_EXCEPT_ALLOW = {
    ("open_simulator_tpu/apply/applier.py", "_plan_with_probes"),
    ("open_simulator_tpu/apply/applier.py", "_sweep_min_count"),
    ("open_simulator_tpu/apply/interactive.py", "_make_evaluator"),
    # narrow-typed parse cascade (int -> float -> MISSING is the
    # template grammar, not a swallowed error) and best-effort tempfile
    # cleanup on close — audited silent-pass survivors
    ("open_simulator_tpu/models/chart.py", "_eval_atom"),
    ("open_simulator_tpu/models/kubeclient.py", "close"),
    # ladder executor: classifies via classify_device_error and either
    # re-raises typed or downgrades with a trace note — never swallows
    ("open_simulator_tpu/runtime/guard.py", "run_laddered"),
    # signal-handler restore at interpreter teardown: ValueError means
    # "not the main thread anymore", there is nothing left to restore
    ("open_simulator_tpu/runtime/budget.py", "sigint_to_budget"),
}

# I/O entry points that hang forever without a timeout; calls in
# first-party runtime code must pass `timeout=` explicitly (S113).
IO_TIMEOUT_FUNCS = {
    "urllib.request.urlopen",
    "urlopen",
    "subprocess.run",
    "subprocess.call",
    "subprocess.check_call",
    "subprocess.check_output",
    "subprocess.Popen",
    "Popen",
}

# Audited call sites allowed without an explicit timeout, keyed like
# BROAD_EXCEPT_ALLOW by (repo-relative path, enclosing function).
# Currently empty: every first-party I/O call names its timeout.
IO_TIMEOUT_ALLOW: set = set()

# T201: files whose job IS terminal output — the CLI command surface.
# Everything else in open_simulator_tpu/ must route output through the
# report writer / logging / obs spans, or name its stream with file=.
PRINT_ALLOW_FILES = {
    "open_simulator_tpu/cli.py",
}
# Audited individual call sites, keyed like BROAD_EXCEPT_ALLOW by
# (repo-relative path, enclosing function). Currently empty: the
# non-CLI survivors all pass an explicit file= (interactive.py's shell
# writes to its injected fout).
PRINT_ALLOW: set = set()

_REPO_ROOT = Path(__file__).resolve().parent.parent
_EXEMPT_TOPDIRS = {"tests", "tools"}
_EXEMPT_FILES = {"bench.py", "__graft_entry__.py"}


def _relpath(path: Path) -> str:
    try:
        return str(path.resolve().relative_to(_REPO_ROOT))
    except ValueError:
        return path.name


def _broad_except_applies(path: Path) -> bool:
    """The BLE001/S110 rules police first-party runtime code: inside
    the repo that means open_simulator_tpu/ (tests/tools/bench are
    exempt); outside the repo (the lint test suite's tmp files) the
    rules are live so they can be exercised directly."""
    rel = _relpath(path)
    parts = Path(rel).parts
    if parts and parts[0] in _EXEMPT_TOPDIRS:
        return False
    if rel in _EXEMPT_FILES:
        return False
    return True


def _is_noqa(source_lines, lineno: int) -> bool:
    if 1 <= lineno <= len(source_lines):
        return "noqa" in source_lines[lineno - 1]
    return False


class _Checker(ast.NodeVisitor):
    def __init__(self, path: Path, tree: ast.Module, source: str):
        self.path = path
        self.tree = tree
        self.lines = source.splitlines()
        self.findings: list = []
        self.is_init = path.name == "__init__.py"
        self.police_broad_except = _broad_except_applies(path)
        self.rel = _relpath(path)
        self._func_stack: list = []

    def report(self, lineno: int, code: str, msg: str):
        if not _is_noqa(self.lines, lineno):
            self.findings.append((self.path, lineno, code, msg))

    # -- unused imports (module scope only, conservative) --------------
    def check_unused_imports(self):
        if self.is_init:
            return  # __init__ re-exports are intentional
        imported: dict = {}
        for node in self.tree.body:
            if isinstance(node, ast.Import):
                for a in node.names:
                    name = (a.asname or a.name).split(".")[0]
                    imported[name] = node.lineno
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                for a in node.names:
                    if a.name == "*":
                        continue
                    imported[a.asname or a.name] = node.lineno
        if not imported:
            return
        used: set = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Name):
                used.add(node.id)
            elif isinstance(node, ast.Attribute):
                pass  # base Name is visited separately
        # names referenced in __all__ strings count as used
        for node in self.tree.body:
            if (
                isinstance(node, ast.Assign)
                and any(
                    isinstance(t, ast.Name) and t.id == "__all__"
                    for t in node.targets
                )
                and isinstance(node.value, (ast.List, ast.Tuple))
            ):
                for elt in node.value.elts:
                    if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                        used.add(elt.value)
        for name, lineno in imported.items():
            if name not in used:
                self.report(lineno, "F401", f"'{name}' imported but unused")

    # -- visitors ------------------------------------------------------
    def visit_scope_body(self, body, scope: str):
        seen: dict = {}
        for idx, node in enumerate(body):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                prev = seen.get(node.name)
                # a redefinition is a bug unless an If/Try stands
                # BETWEEN the two defs (conditional dispatch pattern) —
                # scanning the whole body would let any unrelated `if`
                # suppress the check
                if prev is not None and not any(
                    isinstance(n, (ast.If, ast.Try))
                    for n in body[prev[0] + 1 : idx]
                ):
                    self.report(
                        node.lineno,
                        "F811",
                        f"redefinition of '{node.name}' from line {prev[1]}",
                    )
                seen[node.name] = (idx, node.lineno)

    def visit_ClassDef(self, node):
        # duplicate METHOD definitions are the classic copy-paste bug
        # in test classes; check class bodies like any other scope
        self.visit_scope_body(node.body, node.name)
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        self._check_defaults(node)
        self.visit_scope_body(node.body, node.name)
        self._func_stack.append(node.name)
        self.generic_visit(node)
        self._func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def _check_defaults(self, node):
        for default in list(node.args.defaults) + [
            d for d in node.args.kw_defaults if d is not None
        ]:
            if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                self.report(
                    default.lineno,
                    "B006",
                    f"mutable default argument in '{node.name}'",
                )

    @staticmethod
    def _handler_type_names(node) -> list:
        types = []
        if isinstance(node.type, ast.Tuple):
            types = list(node.type.elts)
        elif node.type is not None:
            types = [node.type]
        return [t.id for t in types if isinstance(t, ast.Name)]

    def visit_ExceptHandler(self, node):
        if node.type is None:
            self.report(node.lineno, "E722", "bare 'except:'")
        if self.police_broad_except:
            ctx = self._func_stack[-1] if self._func_stack else "<module>"
            allowed = (self.rel, ctx) in BROAD_EXCEPT_ALLOW
            broad = [
                n
                for n in self._handler_type_names(node)
                if n in ("Exception", "BaseException")
            ]
            if broad and not allowed:
                self.report(
                    node.lineno,
                    "BLE001",
                    f"broad 'except {broad[0]}:' in '{ctx}' — catch the "
                    "specific expected errors (audited degradation paths "
                    "go in tools/lint.py BROAD_EXCEPT_ALLOW)",
                )
            if (
                not allowed
                and len(node.body) == 1
                and isinstance(node.body[0], ast.Pass)
            ):
                self.report(
                    node.lineno,
                    "S110",
                    f"silent 'except: pass' in '{ctx}' — record why the "
                    "exception is safe to swallow (trace note / log) or "
                    "narrow it away",
                )
        self.generic_visit(node)

    @staticmethod
    def _dotted_name(func) -> str:
        parts = []
        while isinstance(func, ast.Attribute):
            parts.append(func.attr)
            func = func.value
        if isinstance(func, ast.Name):
            parts.append(func.id)
            return ".".join(reversed(parts))
        return ""

    def visit_Call(self, node):
        # S113 + T201 police the same first-party runtime scope as BLE001
        if self.police_broad_except:
            name = self._dotted_name(node.func)
            if name in IO_TIMEOUT_FUNCS and not any(
                kw.arg == "timeout" for kw in node.keywords
            ):
                ctx = self._func_stack[-1] if self._func_stack else "<module>"
                if (self.rel, ctx) not in IO_TIMEOUT_ALLOW:
                    self.report(
                        node.lineno,
                        "S113",
                        f"'{name}' without an explicit timeout= in '{ctx}' "
                        "— an unbounded external call can hang the plan "
                        "(audited exceptions go in tools/lint.py "
                        "IO_TIMEOUT_ALLOW)",
                    )
            if (
                name == "print"
                and self.rel not in PRINT_ALLOW_FILES
                and not any(kw.arg == "file" for kw in node.keywords)
            ):
                ctx = self._func_stack[-1] if self._func_stack else "<module>"
                if (self.rel, ctx) not in PRINT_ALLOW:
                    self.report(
                        node.lineno,
                        "T201",
                        f"bare print() in library code ('{ctx}') — route "
                        "through the report writer / logging / obs spans, "
                        "or name the stream with file= (CLI surfaces go "
                        "in tools/lint.py PRINT_ALLOW_FILES)",
                    )
        self.generic_visit(node)

    def visit_Compare(self, node):
        for op, comp in zip(node.ops, node.comparators):
            if isinstance(op, (ast.Eq, ast.NotEq)) and (
                (isinstance(comp, ast.Constant) and comp.value is None)
                or (
                    isinstance(node.left, ast.Constant)
                    and node.left.value is None
                )
            ):
                self.report(
                    node.lineno, "E711", "comparison to None with ==/!="
                )
        self.generic_visit(node)

    def visit_JoinedStr(self, node):
        if not any(isinstance(v, ast.FormattedValue) for v in node.values):
            self.report(node.lineno, "F541", "f-string without placeholders")
        # do NOT generic_visit: a format spec (":05d") is itself a
        # placeholder-free JoinedStr child and must not be flagged
        for v in node.values:
            if isinstance(v, ast.FormattedValue):
                self.visit(v.value)

    def visit_Assert(self, node):
        if isinstance(node.test, ast.Tuple) and node.test.elts:
            self.report(
                node.lineno,
                "B011",
                "assert on a non-empty tuple is always true",
            )
        self.generic_visit(node)


def lint_file(path: Path) -> list:
    source = path.read_text()
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError as e:
        return [(path, e.lineno or 0, "E999", f"syntax error: {e.msg}")]
    checker = _Checker(path, tree, source)
    checker.check_unused_imports()
    checker.visit_scope_body(tree.body, "<module>")
    checker.visit(tree)
    return checker.findings


def main() -> int:
    repo = Path(__file__).resolve().parent.parent
    findings = []
    for root in ROOTS:
        p = repo / root
        files = [p] if p.is_file() else sorted(p.rglob("*.py"))
        for f in files:
            findings.extend(lint_file(f))
    for path, lineno, code, msg in findings:
        print(f"{path.relative_to(repo)}:{lineno}: {code} {msg}")
    if findings:
        print(f"{len(findings)} finding(s)")
        return 1
    print("lint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
