"""Measure HBM->VMEM tile-streaming cost for a node-blocked scan step.

The fused scan kernel keeps all persistent node-state tiles resident
in VMEM; past the ~13 MB budget the plan rejects (see tools/
vmem_map.py for where that lands per scenario flavor). The candidate
mitigation is node-axis blocking: state lives in HBM and every pod
step streams it through VMEM in (B, 128) blocks. Its floor cost is
pure HBM bandwidth: steps x state_bytes. This microbenchmark measures
the ACHIEVED bandwidth of exactly that access pattern — a Pallas
kernel whose grid walks pod steps, double-buffering DMA copies of
node blocks into VMEM scratch and reducing them on the VPU — so the
design note can quote a measured number instead of a datasheet one.

Usage: python tools/stream_bench.py  (runs on the real TPU; exits
quietly with a note on CPU-only hosts)
"""

from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def stream_kernel(state_ref, out_ref, scratch, sem, *, n_blocks, block_rows):
    """One grid step = one pod step: stream every (block_rows, 128)
    block of the state through VMEM scratch (double-buffered) and fold
    a max-reduce — the shape of a blocked feasibility+score pass."""

    def get_copy(slot, b):
        return pltpu.make_async_copy(
            state_ref.at[pl.ds(b * block_rows, block_rows), :],
            scratch.at[slot],
            sem.at[slot],
        )

    step = pl.program_id(0)

    @pl.when(step == 0)
    def _():
        out_ref[...] = jnp.zeros((1, 128), jnp.int32)

    get_copy(0, 0).start()
    acc = jnp.full((1, 128), -(2**31) + 1, jnp.int32)

    def body(b, acc):
        slot = jax.lax.rem(b, 2)
        get_copy(slot, b).wait()

        @pl.when(b + 1 < n_blocks)
        def _():
            get_copy(1 - slot, b + 1).start()

        tile = scratch[slot]
        return jnp.maximum(acc, jnp.max(tile, axis=0, keepdims=True))

    acc = jax.lax.fori_loop(0, n_blocks, body, acc)
    # accumulate across steps so no step's streaming can be elided
    out_ref[...] = out_ref[...] + acc


def run(state_mb: float, steps: int, block_rows: int = 256) -> float:
    rows = int(state_mb * 2**20) // (128 * 4)
    rows = (rows // block_rows) * block_rows
    n_blocks = rows // block_rows
    state = jnp.asarray(
        np.random.randint(0, 1 << 20, (rows, 128), dtype=np.int32)
    )

    kernel = functools.partial(
        stream_kernel, n_blocks=n_blocks, block_rows=block_rows
    )
    call = pl.pallas_call(
        kernel,
        grid=(steps,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((1, 128), lambda s: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((1, 128), jnp.int32),
        scratch_shapes=[
            pltpu.VMEM((2, block_rows, 128), jnp.int32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    jitted = jax.jit(call)
    np.asarray(jitted(state))  # compile + full sync (the relay's
    # block_until_ready returns before device completion; a host fetch
    # is the only reliable barrier)
    t0 = time.perf_counter()
    np.asarray(jitted(state))
    dt = time.perf_counter() - t0
    gb = rows * 128 * 4 * steps / 1e9
    return gb / dt


def main() -> None:
    if jax.devices()[0].platform not in ("tpu",):
        print("no TPU backend; streaming bench skipped")
        return
    for mb in (8, 16, 32, 64):
        steps = max(1, int(2000 * 16 / mb))  # ~constant total bytes
        bw = run(mb, steps)
        print(f"state {mb:3d} MB, {steps} steps: {bw:7.1f} GB/s achieved")


if __name__ == "__main__":
    sys.exit(main())
