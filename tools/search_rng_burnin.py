"""Search for gen_cooked.go's exact burn-in count + srand shift variant.

The first two Int63 outputs of Go's seed-1 stream are documented
ground truth (5577006791947779410, 8674665223082153551).  Each only
touches 4 entries of the cooked table:

  out1 = ((s[333]^c[333]) + (s[606]^c[606])) & mask63
  out2 = ((s[332]^c[332]) + (s[605]^c[605])) & mask63

where s is the seed expansion sans cooked XOR and c[i] =
y[N + ((333 - N - i) % 607)].  So a candidate (N, variant) costs one
modexp (shared-prefix powers cached) + 4 dot products.

RESULT (2026-07-30): burn-in srand shifts 20/10/0 (the original Plan 9
lrand.c fold), Seed expansion shifts 40/20/0 (Go's rngSource.Seed),
Lehmer 48271/44488/3399 for both, N = 7.8e12 exactly — confirmed by
out1+out2 (126 bits) and by the derived table's first two entries
matching rng.go's literals. The burn-in and Seed variants DIFFER;
searching only matching pairs finds nothing.

Usage: python tools/search_rng_burnin.py
"""

from __future__ import annotations

import sys

import numpy as np

sys.path.insert(0, ".")

from tools.gen_rng_cooked import LEN, FEED0, _polymul

MASK64 = (1 << 64) - 1
MASK63 = (1 << 63) - 1
OUT1 = 5577006791947779410
OUT2 = 8674665223082153551


def srand_vec_shifts(seed: int, shifts) -> list[int]:
    from open_simulator_tpu.utils.gorand import _seedrand

    a, b = shifts
    x = seed
    vec = [0] * LEN
    for i in range(-20, LEN):
        x = _seedrand(x)
        if i >= 0:
            u = x << a
            x = _seedrand(x)
            u ^= x << b
            x = _seedrand(x)
            u ^= x
            vec[i] = u & MASK64
    return vec


_POW2 = {}


def t_pow(n: int) -> np.ndarray:
    """t^n mod f via cached binary powers."""
    result = np.zeros(LEN, dtype=np.uint64)
    result[0] = 1
    k = 0
    base = np.zeros(LEN, dtype=np.uint64)
    base[1] = 1
    while n:
        if k not in _POW2:
            _POW2[k] = base if k == 0 else _polymul(_POW2[k - 1], _POW2[k - 1])
        if n & 1:
            result = _polymul(result, _POW2[k])
        n >>= 1
        k += 1
    return result


def probe(n: int, y: np.ndarray, s: list[int]) -> bool:
    g = t_pow(n)
    def cooked_at(i: int) -> int:
        j = (FEED0 - 1 - n - i) % LEN
        return int(np.dot(_polymul(t_pow(j), g) if j else g, y))
    c333, c606 = cooked_at(333), cooked_at(606)
    o1 = (((s[333] ^ c333) + (s[606] ^ c606)) & MASK64) & MASK63
    if o1 != OUT1:
        return False
    c332, c605 = cooked_at(332), cooked_at(605)
    o2 = (((s[332] ^ c332) + (s[605] ^ c605)) & MASK64) & MASK63
    return o2 == OUT2


def main() -> None:
    variants = {"40/20/0": (40, 20), "20/10/0": (20, 10)}
    candidates = []
    base = 7_800_000_000_000
    for n in [base, base - 1, base + 1, base - 607, base + 607,
              78_000_000_000, 780_000_000_000, 78_000_000_000_000,
              7_800_000_000, 3_900_000_000_000, 15_600_000_000_000,
              1_000_000_000_000, 10_000_000_000_000]:
        candidates.append(n)
    for name, shifts in variants.items():
        sv = srand_vec_shifts(1, shifts)
        y = np.array([sv[(FEED0 - 1 - k) % LEN] for k in range(LEN)], dtype=np.uint64)
        for n in candidates:
            if probe(n, y, sv):
                print(f"MATCH: burn_in={n} shifts={name}")
                return
        print(f"no match among {len(candidates)} candidates for shifts={name}")


if __name__ == "__main__":
    import warnings

    warnings.filterwarnings("ignore")
    main()
