"""Map the fused kernel's VMEM cliff (VERDICT r3 weak #4 / next #4).

The Pallas scan keeps every persistent (R, 128) node-state tile in
VMEM and rejects the plan when the tile budget exceeds ~13 MB
(pallas_scan.build_plan); past that point the batch drops to the XLA
scan (~10x). This tool bisects, per bench scenario flavor, the
maximum node count whose plan still fits, and prints the tile count
at the edge — the numbers quoted in docs/PERFORMANCE.md.

Plan building is host-only: no TPU needed, and SIMON_PALLAS_FORCE=1
makes should_use() irrelevant (build_plan is called directly).

Usage: python tools/vmem_map.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("SIMON_BACKEND_PROBE", "0")


def build_at(n_nodes: int, flavor: str):
    import bench
    from open_simulator_tpu.ops import pallas_scan
    from open_simulator_tpu.ops.encode import (
        encode_batch,
        encode_cluster,
        encode_dynamic,
        features_of_batch,
    )
    from open_simulator_tpu.scheduler.oracle import Oracle

    if flavor == "default":
        nodes, pods = bench.build_scenario()
    elif flavor == "mixed":
        nodes, pods = bench.build_scenario(port_frac=0.01, scalar_frac=0.01)
    elif flavor == "affinity":
        nodes, pods = bench.build_affinity_scenario(n_nodes=2000, replicas=20)
    elif flavor == "gpushare":
        nodes, pods = bench.build_gpushare_scenario(n_nodes=1000, n_pods=2000)
    else:
        raise ValueError(flavor)
    # resize the node axis by cloning/truncating the built nodes
    base = nodes
    nodes = []
    i = 0
    while len(nodes) < n_nodes:
        src = base[i % len(base)]
        if i < len(base):
            nodes.append(src)
        else:
            clone = {
                "metadata": {
                    "name": f"x-{i:06d}",
                    "labels": dict((src.get("metadata") or {}).get("labels") or {}),
                },
                "spec": dict(src.get("spec") or {}),
                "status": src.get("status"),
            }
            nodes.append(clone)
        i += 1
    oracle = Oracle(nodes)
    cluster = encode_cluster(oracle)
    batch = encode_batch(oracle, cluster, pods[: min(len(pods), 2000)])
    dyn = encode_dynamic(oracle, cluster)
    features = features_of_batch(cluster, batch)
    plan = pallas_scan.build_plan(cluster, batch, dyn, features)
    return plan, pallas_scan.last_reject()


def max_nodes(flavor: str, lo: int = 1000, hi: int = 600_000) -> tuple:
    """Largest node count whose plan builds, by bisection."""
    plan, rej = build_at(lo, flavor)
    if plan is None:
        return 0, rej
    while hi - lo > max(lo // 50, 256):  # ~2% resolution
        mid = (lo + hi) // 2
        plan, rej = build_at(mid, flavor)
        if plan is None and rej and "VMEM" in rej:
            hi = mid
        elif plan is None:
            return lo, rej  # rejected for a non-VMEM reason: report it
        else:
            lo = mid
    return lo, None


def main() -> None:
    for flavor in ("default", "mixed", "gpushare", "affinity"):
        n, rej = max_nodes(flavor)
        note = f" (stopped: {rej})" if rej else ""
        print(f"{flavor:10s} max nodes on the fused kernel ~= {n:,}{note}")


if __name__ == "__main__":
    main()
